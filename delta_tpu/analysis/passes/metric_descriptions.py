"""Metric-descriptions pass — migrated from ``tests/test_telemetry.py``.

Every cataloged metric must carry a non-empty one-line ``DESCRIPTIONS``
entry (the ``/metrics`` ``# HELP`` text), and ``DESCRIPTIONS`` must not
accumulate entries for metrics that no longer exist — the catalog and its
documentation move together.

``metric-undocumented``      a catalog entry with no (or an empty) HELP line
``metric-stale-description`` a DESCRIPTIONS entry for an un-cataloged name
``metric-multiline-description``  a HELP text containing a newline (breaks
                             the Prometheus exposition)
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from delta_tpu.analysis.core import AnalysisContext, AnalysisPass, Finding
from delta_tpu.analysis.passes.metric_catalog import catalog_sets

__all__ = ["MetricDescriptionsPass"]


def _descriptions(sf) -> Optional[Dict[str, Tuple[str, int]]]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name) or t.id != "DESCRIPTIONS":
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        out: Dict[str, Tuple[str, int]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                text = v.value if (isinstance(v, ast.Constant)
                                   and isinstance(v.value, str)) else ""
                out[k.value] = (text, k.lineno)
        return out
    return None


class MetricDescriptionsPass(AnalysisPass):
    name = "metric-descriptions"
    description = ("every cataloged metric has a one-line # HELP "
                   "description; none stale")
    rules = ("metric-undocumented", "metric-stale-description",
             "metric-multiline-description")

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        cat_file = ctx.find_suffix("obs/metric_names.py")
        if cat_file is None:
            return []
        sets = catalog_sets(cat_file)
        descs = _descriptions(cat_file)
        if sets is None or descs is None:
            return []
        cataloged: Dict[str, int] = {}
        for entries in sets.values():
            cataloged.update(entries)
        out: List[Finding] = []
        for name, line in sorted(cataloged.items()):
            text = descs.get(name, ("", 0))[0]
            if not text.strip():
                out.append(Finding(
                    "metric-undocumented", cat_file.rel, line,
                    f"catalog entry '{name}' has no # HELP description in "
                    f"obs/metric_names.DESCRIPTIONS"))
        for name, (text, line) in sorted(descs.items()):
            if name not in cataloged:
                out.append(Finding(
                    "metric-stale-description", cat_file.rel, line,
                    f"DESCRIPTIONS entry '{name}' documents an "
                    f"un-cataloged metric"))
            elif "\n" in text:
                out.append(Finding(
                    "metric-multiline-description", cat_file.rel, line,
                    f"DESCRIPTIONS entry '{name}' is multi-line — breaks "
                    f"the Prometheus exposition"))
        return out
