"""Predicate pushdown synthesis — sound min/max rewrites for arithmetic,
string, and temporal predicates over the stats environment.

``ops/pruning.skipping_predicate`` handles the directly min/max-evaluable
shapes (``col op literal``, IN, null tests, StartsWith); everything else
used to rewrite to UNKNOWN, so ``price * qty > 1000`` or
``substr(id, 1, 4) = 'us-w'`` paid full scans even on perfectly laid-out
tables — and the workload journal proved it (``neverPruned`` fingerprints
with reason "shape"). Following "Optimal Predicate Pushdown Synthesis"
(PAPERS.md), this module synthesizes *can-match* over-approximations for
three families:

* **arithmetic** — interval arithmetic over per-column ``[min.c, max.c]``
  bounds for Add/Sub/Mul/Div/Mod/Neg. Single-column chains invert exactly
  (``price * 2 + 10 >= L`` → ``price >= (L-10)/2``), so the rewrite stays a
  plain lane comparison the resident device planner lowers to ranges and
  serves from HBM. Multi-column trees expand to endpoint-candidate
  comparisons: ``UB(price·qty) > L`` ≡ *any* of the four endpoint products
  ``> L`` (interval multiplication; a negative factor flips the interval
  implicitly because all four endpoint combinations participate). The
  candidates evaluate in float64 (int64 products can overflow Arrow's
  wrapping kernels; float64 overflow saturates monotonically) against an
  OUTWARD-relaxed literal, so rounding can only KEEP extra files, never
  drop a match. Division by an expression whose interval may contain zero
  is UNKNOWN; ``x % c`` bounds to ``[-|c|, |c|]`` (covers both Python int
  and fmod sign conventions); arithmetic with a NULL literal can never
  match and rewrites to FALSE.
* **string** — prefix-preserving ops: ``substr(c, 1, k) op lit`` (prefix
  truncation is monotone non-strict in code-point order, so
  ``substr_k(min.c) <= substr_k(x) <= substr_k(max.c)``), ``LIKE``
  patterns via their longest literal prefix (→ the StartsWith rule), and
  wildcard-free LIKE → Eq. Inherits the file tier's truncated-bounds
  conservatism: stats lanes the engine cannot trust (binary / absent)
  evaluate NULL and keep.
* **conditional / abs / col-vs-col** — ``abs(x) op v`` decomposes exactly
  into its two signed comparisons (Or for the upper tests, And for the
  lower); ``coalesce``/``CASE WHEN`` compare via the disjunction of their
  branch values' can-matches (conditions ignored — over-approximate, never
  unsound); ``a < b`` between two data columns excludes when
  ``min.a >= max.b`` — gated to integer/decimal/temporal lanes because
  float lanes are NaN-blind and string bounds may be truncated (the same
  conservatism as the NOT flip).
* **temporal / cast** — monotone shapes only: numeric widening casts
  (identity up to float64 rounding, covered by the relaxation),
  integer-truncation casts (``|x - trunc(x)| < 1`` → bounds padded by one
  unit), ``year(c)`` and ``to_date(ts)`` (truncations, monotone
  non-strict), and ``date_add/date_sub(c, n)`` (shift inverted exactly at
  synthesis time). Narrowing or non-monotone shapes — ``month``/``day``/
  ``hour``, string→numeric casts (string order is not numeric order) —
  stay UNKNOWN.

Soundness contract (the same Kleene story both pruning tiers share): a
rewrite may evaluate to False only when NO row of the file/row-group can
satisfy the original predicate; any unknowable input — missing stats, a
NULL branch, a failed type gate, an arithmetic error — yields NULL = keep.
Every rule needs the column's declared type (``types`` maps lowercased
names to schema DataTypes): without it, string columns could leak into
arithmetic (Python would happily concatenate ``min.a + min.b``) or a
string→long cast could be mistaken for monotone. ``types=None`` disables
synthesis entirely. The property harness in ``tests/test_synthesis.py``
drives seeded random predicates over random tables asserting a synthesized
prune never drops a file or row group containing a matching row.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

from delta_tpu.expr import ir
from delta_tpu.schema.types import (
    ByteType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    ShortType,
    StringType,
    TimestampType,
)

__all__ = ["synthesize", "shape", "can_exclude", "classify_family",
           "schema_types", "UNKNOWN"]

UNKNOWN = ir.Literal(None)

_NUM_TYPES = (ByteType, ShortType, IntegerType, LongType, FloatType,
              DoubleType, DecimalType)
_TEMPORAL_TYPES = (DateType, TimestampType)

#: Relative literal relaxation covering float64 rounding of synthesized
#: arithmetic chains (a few ulps per op; 1e-9 over-covers by ~1e6x — the
#: cost is keeping a boundary file pruning could have dropped, never the
#: reverse).
_REL_EPS = 1e-9

#: Candidate-set size cap for the interval expansion — a deeper Mul nest
#: would square it; past the cap the rewrite is UNKNOWN (keep).
_MAX_CANDS = 8

_Base = Callable[[ir.Expression], ir.Expression]


def schema_types(metadata) -> Dict[str, DataType]:
    """Lowercased column name → declared DataType, the type gate every
    synthesis rule needs (see module docstring)."""
    return {f.name.lower(): f.data_type for f in metadata.schema.fields}


# ---------------------------------------------------------------------------
# Shared shape/fingerprint helpers (canonical home; obs/journal delegates)
# ---------------------------------------------------------------------------


def shape(expr: ir.Expression) -> str:
    """Normalized op shape of an IR expression: class names lowered, column
    names kept (lowercased), literals abstracted to ``?`` — so ``v = 5`` and
    ``v = 9`` share the fingerprint ``eq(v,?)`` while ``price * qty > 1000``
    keeps its arithmetic structure (``gt(mul(price,qty),?)``). Named
    functions render as their FUNCTION name (``substr(id,?,?)``), not the
    ``Func`` class — which function it is decides whether the shape is
    synthesizable, and the advisor's stale-history recognizer matches on
    these tokens. (Pre-r12 journal entries carry the old ``func(...)``
    rendering; the recognizer accepts both.)"""
    if isinstance(expr, ir.Column):
        return expr.name.lower()
    if isinstance(expr, ir.Literal):
        return "?"
    name = (expr.name if isinstance(expr, ir.Func)
            else type(expr).__name__.lower())
    kids = ",".join(shape(c) for c in expr.children)
    return f"{name}({kids})"


def can_exclude(rewritten: ir.Expression) -> bool:
    """Can a skipping rewrite ever evaluate to False — i.e. actually exclude
    a file/row group? ``skipping_predicate`` returns ``Literal(None)``
    (= keep) for unsupported shapes, but And/Or recurse, so an unsupported
    disjunction comes back as ``Or(NULL, NULL)``, not a bare NULL root.
    Three-valued logic: an OR excludes only when BOTH branches can, an AND
    through either; a constant leaf never depends on stats."""
    if isinstance(rewritten, ir.Literal):
        # Literal(False) CAN exclude (e.g. `col = NULL` matches nothing);
        # NULL / TRUE leaves never do
        return rewritten.value is False
    if isinstance(rewritten, ir.And):
        return can_exclude(rewritten.left) or can_exclude(rewritten.right)
    if isinstance(rewritten, ir.Or):
        return can_exclude(rewritten.left) and can_exclude(rewritten.right)
    return True


_FAMILY_STRING = ("substr", "substring")
_FAMILY_TEMPORAL = ("year", "to_date", "date_add", "date_sub")
_FAMILY_ARITH_FUNCS = ("abs",)
_CMP_CLASSES = (ir.Eq, ir.Ne, ir.Lt, ir.Le, ir.Gt, ir.Ge)


def classify_family(expr: ir.Expression) -> str:
    """Coarse rewrite-family label for attribution (``ScanReport.
    rewritesFired`` / the advisor's mining): string > arithmetic >
    conditional > cast > colcol > not > other, by the ops present anywhere
    in the conjunct."""
    has_string = has_arith = has_cond = has_cast = has_colcol = False
    has_not = False
    for e in expr.walk():
        if isinstance(e, (ir.Like, ir.StartsWith)) or (
                isinstance(e, ir.Func) and e.name in _FAMILY_STRING):
            has_string = True
        elif isinstance(e, (ir.Add, ir.Sub, ir.Mul, ir.Div, ir.Mod, ir.Neg)) \
                or (isinstance(e, ir.Func) and e.name in _FAMILY_ARITH_FUNCS):
            has_arith = True
        elif isinstance(e, (ir.Coalesce, ir.CaseWhen)):
            has_cond = True
        elif isinstance(e, ir.Cast) or (
                isinstance(e, ir.Func) and e.name in _FAMILY_TEMPORAL):
            has_cast = True
        elif isinstance(e, _CMP_CLASSES) and isinstance(e.left, ir.Column) \
                and isinstance(e.right, ir.Column):
            has_colcol = True
        elif isinstance(e, ir.Not):
            has_not = True
    if has_string:
        return "string"
    if has_arith:
        return "arithmetic"
    if has_cond:
        return "conditional"
    if has_cast:
        return "cast"
    if has_colcol:
        return "colcol"
    if has_not:
        return "not"
    return "other"


# ---------------------------------------------------------------------------
# Internal control flow
# ---------------------------------------------------------------------------


class _Unknown(Exception):
    """No sound rewrite for this shape — caller keeps (UNKNOWN)."""


class _Never(Exception):
    """The predicate can never be True (NULL operand, division by a zero
    literal) — caller may rewrite to FALSE (exclude everything)."""


def _as_num(v: Any) -> Any:
    """Literal value as a Python number; bools/strings/None are not
    arithmetic operands here."""
    if v is None:
        raise _Never
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise _Unknown
    return v


def _relaxed(v: float, direction: int) -> float:
    """Move a comparison literal OUTWARD (direction -1 = down, +1 = up) by
    the float-rounding slack, so an inexact candidate chain can only keep
    extra files. Non-finite bounds pass through (inf - inf is a trap)."""
    try:
        f = float(v)
    except OverflowError:
        return math.inf if v > 0 else -math.inf
    if not math.isfinite(f):
        return f
    return f + direction * max(abs(f), 1.0) * _REL_EPS


def _fold(e: ir.Expression) -> ir.Expression:
    """Fold a negated numeric literal (the parser's unary minus) into a
    plain literal so the exact inversion path sees it as a constant."""
    if isinstance(e, ir.Neg) and isinstance(e.child, ir.Literal):
        v = e.child.value
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return ir.Literal(-v)
    return e


def _or_all(parts: List[ir.Expression]) -> ir.Expression:
    out = parts[0]
    for p in parts[1:]:
        out = ir.Or(out, p)
    return out


def _min(c: str) -> ir.Expression:
    return ir.Column(f"min.{c}")


def _max(c: str) -> ir.Expression:
    return ir.Column(f"max.{c}")


# ---------------------------------------------------------------------------
# Single-column inversion (exact; resident/device-lowerable output)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _Bounds:
    """The inverted constraint ``col ∈ (lo, hi)`` accumulated while peeling
    a monotone chain; None = unbounded on that side. ``exact`` drops when a
    transform can round (then the emitted literals relax outward)."""

    lo: Optional[Any] = None
    hi: Optional[Any] = None
    lo_strict: bool = False
    hi_strict: bool = False
    exact: bool = True

    @staticmethod
    def from_cmp(t, v) -> "_Bounds":
        if t is ir.Gt:
            return _Bounds(lo=v, lo_strict=True)
        if t is ir.Ge:
            return _Bounds(lo=v)
        if t is ir.Lt:
            return _Bounds(hi=v, hi_strict=True)
        if t is ir.Le:
            return _Bounds(hi=v)
        if t is ir.Eq:
            return _Bounds(lo=v, hi=v)
        raise _Unknown

    def negate(self) -> "_Bounds":
        """x → -x: bounds swap and negate (exact)."""
        return _Bounds(
            lo=None if self.hi is None else -self.hi,
            hi=None if self.lo is None else -self.lo,
            lo_strict=self.hi_strict, hi_strict=self.lo_strict,
            exact=self.exact)

    def shift(self, d) -> "_Bounds":
        """x → x + d was peeled off: bounds shift by d."""
        exact = self.exact and isinstance(d, int)

        def add(v):
            if v is None:
                return None
            if not (isinstance(v, int) and isinstance(d, int)):
                nonlocal exact
                exact = False
            return v + d

        return replace(self, lo=add(self.lo), hi=add(self.hi), exact=exact)

    def scale_down(self, c) -> "_Bounds":
        """x → x * c was peeled off (c ≠ 0): bounds divide by c, order
        flipping for negative c."""
        b = self.negate().scale_down(-c) if c < 0 else self
        if c < 0:
            return b
        exact = b.exact

        def div(v):
            nonlocal exact
            if v is None:
                return None
            if isinstance(v, int) and isinstance(c, int) and v % c == 0:
                return v // c
            exact = False
            try:
                return v / c
            except OverflowError:
                raise _Unknown
        return replace(b, lo=div(b.lo), hi=div(b.hi), exact=exact)

    def scale_up(self, c) -> "_Bounds":
        """x → x / c was peeled off (c ≠ 0): bounds multiply by c. Never
        exact — the original evaluates FLOAT division of the row value, so
        its rounding must be covered by the relaxation either way."""
        b = self.negate().scale_up(-c) if c < 0 else self
        if c < 0:
            return b

        def mul(v):
            return None if v is None else v * c
        return replace(b, lo=mul(b.lo), hi=mul(b.hi), exact=False)

    def pad_unit(self) -> "_Bounds":
        """x → trunc(x) was peeled off: ``|x - trunc(x)| < 1`` widens both
        bounds by one unit (strictness drops — already a relaxation)."""
        return _Bounds(
            lo=None if self.lo is None else self.lo - 1,
            hi=None if self.hi is None else self.hi + 1,
            exact=self.exact)


def _emit_bounds(col: ir.Column, b: _Bounds, base: _Base) -> ir.Expression:
    """Lower the inverted constraint to base lane comparisons. Exact bounds
    keep their strictness (and int-ness: the resident range lowering stays
    exact); inexact ones relax outward and drop to non-strict."""
    if (b.exact and b.lo is not None and b.hi is not None
            and b.lo == b.hi and not b.lo_strict and not b.hi_strict):
        return base(ir.Eq(col, ir.Literal(b.lo)))
    parts: List[ir.Expression] = []
    if b.lo is not None:
        if b.exact:
            op = ir.Gt if b.lo_strict else ir.Ge
            parts.append(base(op(col, ir.Literal(b.lo))))
        else:
            parts.append(base(ir.Ge(col, ir.Literal(_relaxed(b.lo, -1)))))
    if b.hi is not None:
        if b.exact:
            op = ir.Lt if b.hi_strict else ir.Le
            parts.append(base(op(col, ir.Literal(b.hi))))
        else:
            parts.append(base(ir.Le(col, ir.Literal(_relaxed(b.hi, +1)))))
    if not parts:
        raise _Unknown
    out = parts[0]
    for p in parts[1:]:
        out = ir.And(out, p)
    return out


_WIDENING_CASTS = ("float", "double", "decimal")
_TRUNC_CASTS = ("byte", "short", "integer", "long")


def _invert_chain(e: ir.Expression, b: _Bounds,
                  pcols: FrozenSet[str], types: Dict[str, DataType],
                  base: _Base) -> ir.Expression:
    """Peel a single-column monotone chain, transforming the bound at each
    step; raises _Unknown on multi-column shapes (interval path takes over)
    and _Never when no row can match."""
    while True:
        if isinstance(e, ir.Column):
            if e.name.lower() in pcols:
                raise _Unknown  # partition columns have no stats lanes
            if not isinstance(types.get(e.name.lower()), _NUM_TYPES):
                raise _Unknown
            return _emit_bounds(e, b, base)
        if isinstance(e, ir.Neg):
            b, e = b.negate(), e.child
            continue
        if isinstance(e, (ir.Add, ir.Sub, ir.Mul, ir.Div)):
            l, r = _fold(e.left), _fold(e.right)
            lit = r if isinstance(r, ir.Literal) else (
                l if isinstance(l, ir.Literal) else None)
            if lit is None:
                raise _Unknown  # two expression operands: interval path
            other = l if lit is r else r
            c = _as_num(lit.value)
            if isinstance(e, ir.Add):
                b = b.shift(-c)
            elif isinstance(e, ir.Sub):
                # x - c cmp B ⇒ x cmp B + c; c - x cmp B ⇒ -x cmp B - c
                b = b.shift(c) if lit is r else b.shift(-c).negate()
            elif isinstance(e, ir.Mul):
                if c == 0:
                    # 0 * x ≡ 0 for every non-null row: constant verdict
                    raise _Unknown if _zero_satisfies(b) else _Never
                b = b.scale_down(c)
            else:  # Div
                if lit is l:
                    raise _Unknown  # c / x: sign of x unknowable statically
                if c == 0:
                    raise _Never  # x / 0 is NULL: never matches
                b = b.scale_up(c)
            e = other
            continue
        if isinstance(e, ir.Cast):
            name = (e.data_type.name
                    if not isinstance(e.data_type, DecimalType) else "decimal")
            # the chain must bottom out in a NUMERIC column (checked at the
            # Column leaf) for any of these to be monotone
            if name in _TRUNC_CASTS:
                b = b.pad_unit()
            elif name in _WIDENING_CASTS:
                b = replace(b, exact=False)  # float64 rounding
            else:
                raise _Unknown
            e = e.child
            continue
        raise _Unknown


def _zero_satisfies(b: _Bounds) -> bool:
    if b.lo is not None and (0 < b.lo or (0 == b.lo and b.lo_strict)):
        return False
    if b.hi is not None and (0 > b.hi or (0 == b.hi and b.hi_strict)):
        return False
    return True


# ---------------------------------------------------------------------------
# Multi-column interval expansion (float64 candidates; host + jaxeval)
# ---------------------------------------------------------------------------


def _cast_f64(e: ir.Expression) -> ir.Expression:
    return ir.Cast(e, DoubleType())


def _interval(e: ir.Expression, pcols: FrozenSet[str],
              types: Dict[str, DataType]
              ) -> Tuple[List[ir.Expression], List[ir.Expression]]:
    """(lo_candidates, hi_candidates) over stats lanes such that for every
    non-null row value v of ``e``: min(lo) <= v <= max(hi), and every
    candidate's value lies within [min(lo), max(hi)] (the invariant interval
    composition needs). Candidates evaluate in float64."""
    if isinstance(e, ir.Literal):
        v = _as_num(e.value)
        try:
            lit = ir.Literal(float(v))
        except OverflowError:
            raise _Unknown
        return [lit], [lit]
    if isinstance(e, ir.Column):
        if e.name.lower() in pcols:
            raise _Unknown
        if not isinstance(types.get(e.name.lower()), _NUM_TYPES):
            raise _Unknown
        return [_cast_f64(_min(e.name))], [_cast_f64(_max(e.name))]
    if isinstance(e, ir.Neg):
        lo, hi = _interval(e.child, pcols, types)
        return [ir.Neg(h) for h in hi], [ir.Neg(l) for l in lo]
    if isinstance(e, ir.Add):
        alo, ahi = _interval(e.left, pcols, types)
        blo, bhi = _interval(e.right, pcols, types)
        if len(alo) * len(blo) > _MAX_CANDS or len(ahi) * len(bhi) > _MAX_CANDS:
            raise _Unknown
        return ([ir.Add(x, y) for x in alo for y in blo],
                [ir.Add(x, y) for x in ahi for y in bhi])
    if isinstance(e, ir.Sub):
        alo, ahi = _interval(e.left, pcols, types)
        blo, bhi = _interval(e.right, pcols, types)
        if len(alo) * len(bhi) > _MAX_CANDS or len(ahi) * len(blo) > _MAX_CANDS:
            raise _Unknown
        return ([ir.Sub(x, y) for x in alo for y in bhi],
                [ir.Sub(x, y) for x in ahi for y in blo])
    if isinstance(e, ir.Mul):
        alo, ahi = _interval(e.left, pcols, types)
        blo, bhi = _interval(e.right, pcols, types)
        a_m = _members(alo, ahi)
        b_m = _members(blo, bhi)
        if len(a_m) * len(b_m) > _MAX_CANDS:
            raise _Unknown
        prods = [ir.Mul(x, y) for x in a_m for y in b_m]
        # the four (or more) endpoint products: the interval's lo is their
        # min and hi their max — one candidate set serves both sides, and a
        # negative factor's flip falls out of taking all combinations
        return prods, list(prods)
    if isinstance(e, ir.Div):
        divisor = _fold(e.right)
        if not isinstance(divisor, ir.Literal):
            raise _Unknown  # divisor interval may cross zero: UNKNOWN
        c = _as_num(divisor.value)
        if c == 0:
            raise _Never
        lo, hi = _interval(e.left, pcols, types)
        lit = ir.Literal(float(c))
        if c < 0:
            lo, hi = hi, lo
        return ([ir.Div(x, lit) for x in lo], [ir.Div(x, lit) for x in hi])
    if isinstance(e, ir.Mod):
        divisor = _fold(e.right)
        if not isinstance(divisor, ir.Literal):
            raise _Unknown
        c = _as_num(divisor.value)
        if c == 0:
            raise _Never
        # int %: result in [0, |c|) or (-|c|, 0] by divisor sign; float
        # fmod: sign follows the DIVIDEND — [-|c|, |c|] covers every
        # combination the engine's Mod can produce. Gate the dividend like
        # any operand (types/partition checks) even though its bounds drop.
        _interval(e.left, pcols, types)
        return [ir.Literal(-abs(float(c)))], [ir.Literal(abs(float(c)))]
    if isinstance(e, ir.Cast):
        name = (e.data_type.name
                if not isinstance(e.data_type, DecimalType) else "decimal")
        lo, hi = _interval(e.child, pcols, types)
        if name in _TRUNC_CASTS:
            one = ir.Literal(1.0)
            return ([ir.Sub(x, one) for x in lo], [ir.Add(x, one) for x in hi])
        if name in _WIDENING_CASTS:
            return lo, hi  # float64 rounding is inside the relaxation
        raise _Unknown
    if isinstance(e, ir.Func) and e.name == "abs" and len(e.children) == 1:
        lo, hi = _interval(e.children[0], pcols, types)
        m = _members(lo, hi)
        if len(m) + 1 > _MAX_CANDS:
            raise _Unknown
        wrapped = [ir.Func("abs", [x]) for x in m]
        # the child interval may span zero, where |v| bottoms out at 0 even
        # though every |endpoint| is large — the 0 lower candidate is what
        # keeps the composed interval sound. The endpoint achieving the
        # child's min (resp. max) is a member, so max(|members|) covers the
        # true upper bound.
        return [ir.Literal(0.0)] + wrapped, wrapped
    raise _Unknown


def _members(lo: List[ir.Expression], hi: List[ir.Expression]) -> List[ir.Expression]:
    out: List[ir.Expression] = []
    seen = set()
    for x in lo + hi:
        if x not in seen:
            seen.add(x)
            out.append(x)
    return out


def _cand_side(cands: List[ir.Expression], cmp_cls,
               lit: ir.Literal) -> Any:
    """One Or-side of the interval comparison, with constant candidates
    (Mod bounds, folded literals) resolved statically: returns True (the
    side is trivially satisfied — no exclusion possible through it), False
    (no candidate can satisfy it — the side excludes everything), or the
    Or expression over the non-constant candidates."""
    branches: List[ir.Expression] = []
    for c in cands:
        if isinstance(c, ir.Literal) and isinstance(c.value, float):
            ok = (c.value >= lit.value if cmp_cls is ir.Ge
                  else c.value <= lit.value)
            if ok:
                return True
            continue
        branches.append(cmp_cls(c, lit))
    if not branches:
        return False
    return _or_all(branches)


def _interval_cmp(t, expr_side: ir.Expression, lit_value: Any,
                  pcols: FrozenSet[str],
                  types: Dict[str, DataType]) -> ir.Expression:
    v = _as_num(lit_value)
    lo, hi = _interval(expr_side, pcols, types)
    lo_lit = ir.Literal(_relaxed(v, +1))   # LB <= v+eps tests
    hi_lit = ir.Literal(_relaxed(v, -1))   # UB >= v-eps tests
    if t in (ir.Gt, ir.Ge):
        # can-match: UB >= v (strictness absorbed by the relaxation); UB is
        # max(hi) so "any candidate >= v-eps"
        side = _cand_side(hi, ir.Ge, hi_lit)
    elif t in (ir.Lt, ir.Le):
        side = _cand_side(lo, ir.Le, lo_lit)
    elif t is ir.Eq:
        a = _cand_side(lo, ir.Le, lo_lit)
        b = _cand_side(hi, ir.Ge, hi_lit)
        if a is False or b is False:
            raise _Never
        if a is True:
            side = b
        elif b is True:
            side = a
        else:
            side = ir.And(a, b)
    else:
        raise _Unknown
    if side is True:
        raise _Unknown  # trivially satisfiable: nothing to exclude on
    if side is False:
        raise _Never
    return side


# ---------------------------------------------------------------------------
# Branch combinators + abs / conditional / col-vs-col rules
# ---------------------------------------------------------------------------


def _or_branches(thunks: List[Callable[[], ir.Expression]]) -> ir.Expression:
    """can-match of a disjunction of can-matches. A _Never branch is False
    and drops out; _Unknown propagates (one might-match branch makes the
    whole OR unbounded — nothing stats can exclude); every branch impossible
    → _Never."""
    parts: List[ir.Expression] = []
    for th in thunks:
        try:
            parts.append(th())
        except _Never:
            continue
    if not parts:
        raise _Never
    return _or_all(parts)


def _and_branches(thunks: List[Callable[[], ir.Expression]]) -> ir.Expression:
    """can-match conjunction: And(UNKNOWN, X) over-approximates soundly to
    X alone, a _Never branch propagates (the conjunction is impossible),
    all-UNKNOWN → _Unknown."""
    parts: List[ir.Expression] = []
    for th in thunks:
        try:
            parts.append(th())
        except _Unknown:
            continue
    if not parts:
        raise _Unknown
    out = parts[0]
    for p in parts[1:]:
        out = ir.And(out, p)
    return out


def _synth_abs(t, child: ir.Expression, lit: ir.Literal,
               pcols: FrozenSet[str], types: Dict[str, DataType],
               base: _Base) -> ir.Expression:
    """Exact logical decomposition of ``abs(x) op v``: the upper tests split
    into ``x > v OR x < -v``, the lower into ``x < v AND x > -v``, each side
    re-synthesized recursively — strictly stronger than the interval path
    for the lower/equality shapes, where abs's 0 lower candidate makes the
    interval trivially satisfiable."""
    v = _as_num(lit.value)

    def sub(cmp_cls, bound):
        return lambda: _synthesize(cmp_cls(child, ir.Literal(bound)),
                                   pcols, types, base)

    if t in (ir.Gt, ir.Ge):
        if v < 0 or (t is ir.Ge and v == 0):
            raise _Unknown  # trivially true for every non-null row
        return _or_branches([sub(t, v), sub(_CMP_FLIP[t], -v)])
    if t in (ir.Lt, ir.Le):
        if v < 0 or (t is ir.Lt and v == 0):
            raise _Never  # |x| below a non-positive bound: impossible
        return _and_branches([sub(t, v), sub(_CMP_FLIP[t], -v)])
    if t is ir.Eq:
        if v < 0:
            raise _Never
        if v == 0:
            return _synthesize(ir.Eq(child, ir.Literal(v)), pcols, types, base)
        return _or_branches([sub(ir.Eq, v), sub(ir.Eq, -v)])
    raise _Unknown


def _synth_branches(t, e: ir.Expression, lit: ir.Literal,
                    pcols: FrozenSet[str], types: Dict[str, DataType],
                    base: _Base) -> ir.Expression:
    """can-match for ``coalesce(...) op lit`` / ``CASE WHEN ... op lit``: a
    row's value is always one of the branch values (CaseWhen conditions and
    coalesce nullness ignored — a sound over-approximation), so the OR of
    per-branch can-matches covers every row. A literal branch resolves
    statically: satisfying → some row may take it and match (_Unknown — no
    stats lane can rule it out); NULL or non-satisfying → drops out."""
    if isinstance(e, ir.Coalesce):
        vals = list(e.children)
    else:  # CaseWhen children: (c1, v1, ..., default)
        vals = [e.children[2 * i + 1] for i in range(e.n_branches)]
        vals.append(e.children[-1])
    thunks: List[Callable[[], ir.Expression]] = []
    for b in vals:
        b = _fold(b)
        if isinstance(b, ir.Literal):
            if b.value is None:
                continue  # comparison against NULL can't match
            try:
                ok = t(b, lit).eval({})
            except Exception:  # noqa: BLE001 — incomparable literal pair
                raise _Unknown from None
            if ok is True:
                raise _Unknown
            continue
        thunks.append(lambda bb=b: _synthesize(t(bb, lit), pcols, types, base))
    if not thunks:
        raise _Never
    return _or_branches(thunks)


#: Col-vs-col comparisons trust BOTH lanes' min/max to bound actual row
#: values — the same hazard the NOT flip gates: float lanes are blind to
#: NaN rows, and string lanes may carry truncated bounds whose max
#: under-reports. Integer-family + decimal + (same-type) temporal only.
_COLCOL_SAFE_NUM = (ByteType, ShortType, IntegerType, LongType, DecimalType)


def _synth_colcol(t, l: ir.Column, r: ir.Column,
                  pcols: FrozenSet[str],
                  types: Dict[str, DataType]) -> ir.Expression:
    """``a < b`` can match only when ``min.a < max.b`` (some pair of row
    values can land in order), ``a = b`` only when the two stat intervals
    intersect. NULL/absent lanes evaluate NULL = keep (Kleene)."""
    la, ra = l.name.lower(), r.name.lower()
    if la in pcols or ra in pcols:
        raise _Unknown  # partition columns have no stats lanes
    ta, tb = types.get(la), types.get(ra)
    ok = ((isinstance(ta, _COLCOL_SAFE_NUM) and isinstance(tb, _COLCOL_SAFE_NUM))
          or (isinstance(ta, DateType) and isinstance(tb, DateType))
          or (isinstance(ta, TimestampType) and isinstance(tb, TimestampType)))
    if not ok:
        raise _Unknown
    if la == ra:
        if t in (ir.Lt, ir.Gt):
            raise _Never  # a < a matches no row
        raise _Unknown  # a <= a / a = a: true for every non-null row
    if t is ir.Lt:
        return ir.Lt(_min(l.name), _max(r.name))
    if t is ir.Le:
        return ir.Le(_min(l.name), _max(r.name))
    if t is ir.Gt:
        return ir.Gt(_max(l.name), _min(r.name))
    if t is ir.Ge:
        return ir.Ge(_max(l.name), _min(r.name))
    if t is ir.Eq:
        return ir.And(ir.Le(_min(l.name), _max(r.name)),
                      ir.Ge(_max(l.name), _min(r.name)))
    raise _Unknown


# ---------------------------------------------------------------------------
# String + temporal monotone wraps
# ---------------------------------------------------------------------------


def _wrap_cmp(t, wrap: Callable[[ir.Expression], ir.Expression],
              col: str, lit: ir.Literal) -> ir.Expression:
    """can-match for ``w(col) op lit`` with w monotone NON-STRICT:
    ``w(min.c) <= w(x) <= w(max.c)``, so an upper test needs only the max
    lane and a lower test only the min lane; strictness survives (if
    ``w(max) <= lit`` definitely, no row has ``w(x) > lit``)."""
    if t is ir.Eq:
        return ir.And(ir.Le(wrap(_min(col)), lit), ir.Ge(wrap(_max(col)), lit))
    if t in (ir.Gt, ir.Ge):
        return t(wrap(_max(col)), lit)
    if t in (ir.Lt, ir.Le):
        return t(wrap(_min(col)), lit)
    raise _Unknown


def _synth_substr(t, f: ir.Func, lit: ir.Literal,
                  types: Dict[str, DataType],
                  pcols: FrozenSet[str], base: _Base) -> ir.Expression:
    args = f.children
    if not (args and isinstance(args[0], ir.Column)):
        raise _Unknown
    col = args[0]
    if col.name.lower() in pcols:
        raise _Unknown
    if not isinstance(types.get(col.name.lower()), StringType):
        raise _Unknown
    if lit.value is None:
        raise _Never
    if not isinstance(lit.value, str):
        raise _Unknown
    pos = args[1] if len(args) > 1 else None
    if not (isinstance(pos, ir.Literal) and isinstance(pos.value, int)
            and not isinstance(pos.value, bool) and pos.value in (0, 1)):
        raise _Unknown  # only position-1 prefixes are monotone
    if len(args) == 2:
        # substr(c, 1) is the identity: the base rules take it whole
        return base(t(col, lit))
    k = args[2]
    if not (isinstance(k, ir.Literal) and isinstance(k.value, int)
            and not isinstance(k.value, bool) and k.value >= 0):
        raise _Unknown

    def wrap(x: ir.Expression) -> ir.Expression:
        return ir.Func("substr", [x, ir.Literal(1), ir.Literal(k.value)])

    return _wrap_cmp(t, wrap, col.name, lit)


def _synth_temporal(t, f: ir.Func, lit: ir.Literal,
                    types: Dict[str, DataType],
                    pcols: FrozenSet[str], base: _Base) -> ir.Expression:
    args = f.children
    if not (args and isinstance(args[0], ir.Column)):
        raise _Unknown
    col = args[0]
    if col.name.lower() in pcols:
        raise _Unknown
    dt = types.get(col.name.lower())
    if not isinstance(dt, _TEMPORAL_TYPES):
        raise _Unknown
    if lit.value is None:
        raise _Never
    if f.name == "year" and len(args) == 1:
        if isinstance(lit.value, bool) or not isinstance(lit.value, int):
            raise _Unknown

        def wrap(x: ir.Expression) -> ir.Expression:
            # date stats arrive as ISO strings (file tier) or date/datetime
            # objects (footer tier); Cast(DateType) normalizes both to
            # epoch days, which _epoch_day_field takes
            return ir.Func("year", [ir.Cast(x, DateType())])

        return _wrap_cmp(t, wrap, col.name, lit)
    if f.name == "to_date" and len(args) == 1:
        if not isinstance(lit.value, str):
            raise _Unknown
        if isinstance(dt, DateType):
            # identity on a date column — the base col-op-lit rules apply
            return base(t(col, lit))

        def wrap(x: ir.Expression) -> ir.Expression:
            # engine timestamp stats are fixed-width ISO strings, whose
            # prefix-10 parse is monotone; footer stats arrive as datetime
            # objects (_to_date truncates) — both land on dates
            return ir.Func("to_date", [x])

        return _wrap_cmp(t, wrap, col.name, lit)
    if f.name in ("date_add", "date_sub") and len(args) == 2:
        n = args[1]
        if not (isinstance(n, ir.Literal) and isinstance(n.value, int)
                and not isinstance(n.value, bool)):
            raise _Unknown
        lit_date = ir.Func.FUNCS["to_date"](lit.value)
        if lit_date is None:
            raise _Unknown
        sign = -1 if f.name == "date_add" else 1
        shifted = ir.Func.FUNCS["date_add"](lit_date, sign * n.value)
        shifted_lit = ir.Literal(shifted.isoformat())
        if isinstance(dt, DateType):
            # strict monotone shift over DATE values: invert exactly onto
            # the raw column; an ISO string literal compares correctly
            # against string or date-valued stats through _coerce_pair
            return base(t(col, shifted_lit))
        # TimestampType: _date_add TRUNCATES the datetime to a date first
        # (ir._as_date), so the composite is day-truncating, NOT strict
        # monotone — an exact inversion onto the raw timestamp would prune
        # files whose rows fall later inside the matching day. Use the
        # same monotone non-strict wrap as to_date, with the shifted bound.

        def wrap(x: ir.Expression) -> ir.Expression:
            return ir.Func("to_date", [x])

        return _wrap_cmp(t, wrap, col.name, shifted_lit)
    raise _Unknown


def _synth_like(e: ir.Like, types: Dict[str, DataType],
                pcols: FrozenSet[str], base: _Base) -> ir.Expression:
    if not (isinstance(e.left, ir.Column) and isinstance(e.right, ir.Literal)):
        raise _Unknown
    col, pat = e.left, e.right.value
    if col.name.lower() in pcols:
        raise _Unknown
    if not isinstance(types.get(col.name.lower()), StringType):
        raise _Unknown
    if pat is None:
        raise _Never
    if not isinstance(pat, str):
        raise _Unknown
    wild = [i for i, ch in enumerate(pat) if ch in "%_"]
    if not wild:
        return base(ir.Eq(col, ir.Literal(pat)))
    prefix = pat[: wild[0]]
    if not prefix:
        raise _Unknown
    # every match carries the literal prefix: the StartsWith rule is a
    # sound (weaker) can-match for the whole pattern
    return base(ir.StartsWith(col, ir.Literal(prefix)))


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


_CMP_FLIP = {ir.Lt: ir.Gt, ir.Le: ir.Ge, ir.Gt: ir.Lt, ir.Ge: ir.Le,
             ir.Eq: ir.Eq}


def synthesize(e: ir.Expression, partition_cols: FrozenSet[str],
               types: Dict[str, DataType], base: _Base) -> ir.Expression:
    """Sound can-match rewrite for a predicate leaf the base skipping rules
    return UNKNOWN for; ``Literal(None)`` (keep) when no rule applies.
    ``base`` is the plain-shape rewriter (``ops.pruning.skipping_predicate``
    without synthesis) the inversion/prefix rules delegate to."""
    try:
        return _synthesize(e, partition_cols, types, base)
    except _Never:
        return ir.Literal(False)
    except _Unknown:
        return UNKNOWN
    except Exception:  # noqa: BLE001 — synthesis must never fail a scan
        return UNKNOWN


def _synthesize(e: ir.Expression, pcols: FrozenSet[str],
                types: Dict[str, DataType], base: _Base) -> ir.Expression:
    t = type(e)
    if t is ir.Like:
        return _synth_like(e, types, pcols, base)
    if t is ir.In:
        branches: List[ir.Expression] = []
        for o in e.options:
            if not isinstance(o, ir.Literal):
                raise _Unknown
            if o.value is None:
                continue  # a NULL option can never make the IN true
            branches.append(_synthesize(ir.Eq(e.value, o), pcols, types, base))
        if not branches:
            raise _Never
        return _or_all(branches)
    if t in _CMP_FLIP:
        l, r = _fold(e.left), _fold(e.right)
        if isinstance(l, ir.Literal) and not isinstance(r, ir.Literal):
            t = _CMP_FLIP[t]
            l, r = r, l
        if isinstance(l, ir.Column) and isinstance(r, ir.Column):
            return _synth_colcol(t, l, r, pcols, types)
        if not isinstance(r, ir.Literal) or isinstance(l, ir.Literal):
            raise _Unknown
        if isinstance(l, ir.Func) and l.name in _FAMILY_STRING:
            return _synth_substr(t, l, r, types, pcols, base)
        if isinstance(l, ir.Func) and l.name in _FAMILY_TEMPORAL:
            return _synth_temporal(t, l, r, types, pcols, base)
        if isinstance(l, ir.Func) and l.name == "abs" and len(l.children) == 1:
            try:
                return _synth_abs(t, l.children[0], r, pcols, types, base)
            except _Unknown:
                pass  # the interval path below is abs-aware
        if isinstance(l, (ir.Coalesce, ir.CaseWhen)):
            return _synth_branches(t, l, r, pcols, types, base)
        v = _as_num(r.value)
        try:
            return _invert_chain(l, _Bounds.from_cmp(t, v), pcols, types, base)
        except _Unknown:
            pass
        return _interval_cmp(t, l, v, pcols, types)
    raise _Unknown
