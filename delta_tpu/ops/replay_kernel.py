"""Device log replay: last-writer-wins reconciliation as a sharded sort.

The reference replays the action log with a per-partition hash map
(`actions/InMemoryLogReplay.scala:43-65`, driven by a 50-way Spark
repartition, `Snapshot.scala:88-111`). A hash map is the wrong shape for a
TPU; the same semantics vectorize as:

    sort rows by (path_id, seq)  →  the last row of each path run wins
    alive = winner AND is_add

which is one `lax.sort` (bitonic on TPU) plus elementwise ops — fully fused by
XLA. Sharding: rows are bucketed by ``path_id % n_shards`` (each path's whole
history lands on one shard, so per-shard replay is exact) and the per-shard
kernels run under `shard_map`; aggregate counts come back via `psum` over ICI.
This is the "sharded log-replay" component called out in SURVEY §2.8.

Tombstone expiry (`minFileRetentionTimestamp`) applies to *removes retained as
tombstones*, not to which add survives — handled by a mask on remove rows.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from delta_tpu.utils.jaxcompat import enable_x64, shard_map
from delta_tpu.ops.state_export import ReplayArrays
from delta_tpu.parallel.mesh import P, STATE_AXIS, shard_count

__all__ = [
    "ReplayResult",
    "replay_alive_mask",
    "replay_sharded",
    "ReplayStats",
    "winner_mask_device",
    "replay_columns",
]


class ReplayStats(NamedTuple):
    num_files: jnp.ndarray  # int32 scalar
    total_size: jnp.ndarray  # int64/float scalar
    num_tombstones: jnp.ndarray  # int32 scalar


class ReplayResult(NamedTuple):
    alive: jnp.ndarray  # bool per input row: surviving AddFile
    tombstone: jnp.ndarray  # bool per input row: retained RemoveFile
    stats: ReplayStats


@functools.partial(jax.jit, static_argnames=())
def _replay_kernel(path_id, seq, is_add, size, deletion_ts, min_retention_ts):
    """Single-shard replay. Padding rows use path_id == -1 (never win)."""
    valid = path_id >= 0
    # Sort by (path, seq): bitonic sort on TPU, one pass.
    idx = jnp.arange(path_id.shape[0], dtype=jnp.int32)
    s_path, s_seq, s_idx = jax.lax.sort((path_id, seq, idx), num_keys=2)
    # Winner = last row of each equal-path run.
    next_differs = jnp.concatenate(
        [s_path[1:] != s_path[:-1], jnp.ones((1,), bool)]
    )
    s_valid = s_path >= 0
    winner_sorted = next_differs & s_valid
    # Scatter back to input order.
    winner = jnp.zeros_like(is_add).at[s_idx].set(winner_sorted)
    alive = winner & is_add & valid
    tombstone = winner & ~is_add & valid & (deletion_ts > min_retention_ts)
    stats = ReplayStats(
        num_files=jnp.sum(alive, dtype=jnp.int32),
        total_size=jnp.sum(jnp.where(alive, size, 0)),
        num_tombstones=jnp.sum(tombstone, dtype=jnp.int32),
    )
    return alive, tombstone, stats


def _next_pow2(n: int) -> int:
    p = 8
    while p < n:
        p *= 2
    return p


def _pad(col: np.ndarray, cap: int, fill) -> np.ndarray:
    out = np.full(cap, fill, dtype=col.dtype)
    out[: len(col)] = col
    return out


def replay_alive_mask(arrays: ReplayArrays, min_retention_ts: int = 0) -> ReplayResult:
    """Single-device replay of an action stream (bench + small tables).

    Inputs are padded to the next power of two so XLA compiles one kernel per
    size bucket, not per log length."""
    n = arrays.num_rows
    cap = _next_pow2(n)
    # x64 scoped to the kernel: seq keys, sizes and retention timestamps are
    # genuine 64-bit lanes, but the process-global dtype default stays intact.
    with enable_x64():
        alive, tombstone, stats = _replay_kernel(
            jnp.asarray(_pad(arrays.path_id, cap, np.int32(-1))),
            jnp.asarray(_pad(arrays.seq, cap, np.int64(0))),
            jnp.asarray(_pad(arrays.is_add, cap, False)),
            jnp.asarray(_pad(arrays.size, cap, np.int64(0))),
            jnp.asarray(_pad(arrays.deletion_timestamp, cap, np.int64(0))),
            jnp.asarray(min_retention_ts, jnp.int64),
        )
    return ReplayResult(alive[:n], tombstone[:n], stats)


@jax.jit
def _winner_bits_kernel(path_id):
    """Last-row-of-each-path-run mask from the path column alone.

    Row order is the replay order (``log/columnar.SegmentColumns`` layout
    invariant), so the implicit iota is the sort tiebreaker — no seq column
    ever ships to the device. Input: one int32 lane (padding = -1); output:
    the winner mask packed to bits (n/8 bytes). Sized for the realistic
    deployment constraint that host↔device link latency/bandwidth — not the
    O(n log n) bitonic sort — dominates this kernel."""
    n = path_id.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    s_path, s_idx = jax.lax.sort((path_id, idx), num_keys=2)
    next_differs = jnp.concatenate([s_path[1:] != s_path[:-1], jnp.ones((1,), bool)])
    winner_sorted = next_differs & (s_path >= 0)
    winner = jnp.zeros((n,), bool).at[s_idx].set(winner_sorted)
    return jnp.packbits(winner)


def winner_mask_device(path_id: np.ndarray) -> np.ndarray:
    """Device last-writer-wins winner mask for a replay-ordered action stream.

    Ships one int32 column up, one bitmask down; everything else
    (alive/tombstone masks, aggregates) is cheap host numpy on the result."""
    n = len(path_id)
    cap = _next_pow2(n)
    padded = np.full(cap, -1, np.int32)
    padded[:n] = path_id
    bits = np.asarray(_winner_bits_kernel(jnp.asarray(padded)))
    return np.unpackbits(bits, count=n).astype(bool)


def replay_columns(cols, min_retention_ts: int = 0, device: bool = True) -> ReplayResult:
    """Replay a :class:`delta_tpu.log.columnar.SegmentColumns` stream.

    The winner computation runs on device (``device=True``) or as the host
    scatter fallback; alive/tombstone masks and the aggregate stats are
    elementwise host numpy either way (they are O(n) band-limited and would
    only add transfer latency on device)."""
    winner = winner_mask_device(cols.path_id) if device else None
    alive, tombstone = cols.replay(min_retention_ts, winner=winner)
    stats = ReplayStats(
        num_files=np.int32(alive.sum()),
        total_size=np.int64(cols.size[alive].sum()),
        num_tombstones=np.int32(tombstone.sum()),
    )
    return ReplayResult(alive, tombstone, stats)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer: decorrelates shard choice from path-id locality
    (sequential dictionary codes would otherwise stripe shards unevenly
    whenever n_shards shares factors with the id assignment pattern)."""
    z = x.astype(np.uint64)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _bucket_by_path(arrays: ReplayArrays, n_shards: int):
    """Host-side bucketing: row → shard ``mix(path_id) % n_shards`` (every
    action for a path lands on one shard), padded to equal per-shard length.
    Fully vectorized — one argsort + one scatter per column, no Python loop
    over shards (a true single-path hot spot still cannot be split: replay
    correctness requires a path's whole history on one shard; the mixer only
    protects against accidental clustering). Returns stacked (n_shards, cap)
    arrays + the flat destination map for unscattering."""
    bucket = (_mix64(arrays.path_id) % np.uint64(n_shards)).astype(np.int64)
    order = np.argsort(bucket, kind="stable")
    counts = np.bincount(bucket, minlength=n_shards)
    cap = _next_pow2(int(counts.max()) if len(counts) else 1)
    # position of each (ordered) row within its shard slab
    starts = np.cumsum(counts) - counts
    within = np.arange(len(order), dtype=np.int64) - np.repeat(starts, counts)
    dest = bucket[order] * cap + within  # flat index into (n_shards*cap)

    def stack(col, fill):
        out = np.full(n_shards * cap, fill, dtype=col.dtype)
        out[dest] = col[order]
        return out.reshape(n_shards, cap)

    cols = (
        stack(arrays.path_id, np.int32(-1)),
        stack(arrays.seq, np.int64(0)),
        stack(arrays.is_add, False),
        stack(arrays.size, np.int64(0)),
        stack(arrays.deletion_timestamp, np.int64(0)),
    )
    return cols, order, dest


def replay_sharded(
    arrays: ReplayArrays, mesh: Mesh, min_retention_ts: int = 0
) -> ReplayResult:
    """Replay sharded over a device mesh.

    Equivalent of `Snapshot.scala:88-111`'s repartition+replay: each shard
    owns a hash range of paths, replays independently, and the aggregate
    state counts are reduced with `psum` over ICI.
    """
    n = shard_count(mesh)
    (path_id, seq, is_add, size, del_ts), order, dest = _bucket_by_path(arrays, n)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(STATE_AXIS), P(STATE_AXIS), P(STATE_AXIS), P(STATE_AXIS), P(STATE_AXIS)),
        out_specs=(P(STATE_AXIS), P(STATE_AXIS), P(), P(), P()),
    )
    def shard_replay(pid, sq, add, sz, dts):
        alive, tombstone, stats = _replay_kernel(
            pid[0], sq[0], add[0], sz[0], dts[0],
            jnp.asarray(min_retention_ts, dtype=sq.dtype),
        )
        num = jax.lax.psum(stats.num_files, STATE_AXIS)
        tot = jax.lax.psum(stats.total_size, STATE_AXIS)
        ntomb = jax.lax.psum(stats.num_tombstones, STATE_AXIS)
        return alive[None], tombstone[None], num, tot, ntomb

    with enable_x64():
        alive_sh, tomb_sh, num, tot, ntomb = jax.jit(shard_replay)(
            path_id, seq, is_add, size, del_ts
        )

    # Unscatter: stacked (n, cap) → original row order, one gather each.
    alive = np.zeros(arrays.num_rows, bool)
    tombstone = np.zeros(arrays.num_rows, bool)
    alive[order] = np.asarray(alive_sh).reshape(-1)[dest]
    tombstone[order] = np.asarray(tomb_sh).reshape(-1)[dest]
    return ReplayResult(
        jnp.asarray(alive),
        jnp.asarray(tombstone),
        ReplayStats(num, tot, ntomb),
    )
