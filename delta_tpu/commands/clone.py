"""SHALLOW CLONE — a new table whose log references the source's data files.

Beyond-reference command (the 0.9 reference has none; modern Delta ships
``CREATE TABLE t SHALLOW CLONE s [VERSION AS OF v]``). The clone commits the
source snapshot's Protocol + Metadata (fresh table id) and one ``AddFile``
per live source file with the path made ABSOLUTE, so the clone reads the
source's Parquet in place; writes to the clone produce new files under the
clone's own directory, and the source is never modified. Deletion-vector
sidecars are absolutized the same way. Vacuum on the clone only walks the
clone's directory, so referenced source files are never collected by it
(vacuuming the SOURCE can break clones — the same caveat real shallow
clones carry).
"""
from __future__ import annotations

import os
import urllib.parse
from dataclasses import replace
from typing import Dict, Optional, Union

from delta_tpu.commands import operations as ops
from delta_tpu.protocol.actions import Metadata, Protocol
from delta_tpu.utils import errors

__all__ = ["CloneCommand"]


def Clone(source_path: str, source_version: int) -> ops.Operation:
    return ops.Operation(
        "CLONE",
        {"source": source_path, "sourceVersion": source_version,
         "isShallow": True},
        ["sourceTableSize", "sourceNumOfFiles", "numClonedFiles"],
    )


class CloneCommand:
    def __init__(self, source_log, target_path: str,
                 version: Optional[int] = None,
                 timestamp: Optional[Union[str, int]] = None):
        self.source_log = source_log
        self.target_path = target_path
        self.version = version
        self.timestamp = timestamp
        self.metrics: Dict[str, int] = {}

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.utility.clone", path=self.target_path):
            return self._run_impl()

    def _run_impl(self) -> int:
        from delta_tpu.log.deltalog import DeltaLog

        src = self.source_log
        snapshot = src.snapshot_for(self.version, self.timestamp)
        if snapshot.version < 0:
            raise errors.not_a_delta_table(src.data_path, "CLONE")

        target = DeltaLog.for_table(self.target_path)
        if target.update().version >= 0:
            raise errors.DeltaAnalysisError(
                f"Cannot clone into {self.target_path}: a Delta table "
                "already exists there"
            )

        src_root = os.path.abspath(src.data_path)

        def absolutize(rel: str) -> str:
            if "://" in rel or os.path.isabs(rel):
                return rel
            return urllib.parse.quote(
                os.path.join(src_root, urllib.parse.unquote(rel)),
                safe="/:@!$&'()*+,;=-._~",
            )

        def body(txn) -> int:
            import uuid

            if txn.read_version != -1:
                # a table appeared at the target between the pre-check and
                # this transaction: never merge two tables silently
                raise errors.DeltaAnalysisError(
                    f"Cannot clone into {self.target_path}: a Delta table "
                    "already exists there"
                )
            meta: Metadata = replace(snapshot.metadata, id=str(uuid.uuid4()))
            txn.update_metadata(meta)
            # the clone must carry at least the SOURCE's protocol: config
            # alone under-derives it (e.g. DV files outliving an unset DV
            # property, or an explicit upgrade_protocol on the source)
            src_p = snapshot.protocol
            derived = txn.new_protocol
            reader = max(src_p.min_reader_version,
                         derived.min_reader_version if derived else 0)
            writer = max(src_p.min_writer_version,
                         derived.min_writer_version if derived else 0)
            feats = set(src_p.reader_features or ()) | set(
                src_p.writer_features or ()
            )
            if derived is not None:
                feats |= set(derived.reader_features or ())
                feats |= set(derived.writer_features or ())
            txn.new_protocol = Protocol(
                reader, writer,
                tuple(sorted(feats)) if reader >= 3 else None,
                tuple(sorted(feats)) if writer >= 7 else None,
            )
            actions = []
            total_size = 0
            for f in snapshot.all_files:
                dv = f.deletion_vector
                if dv and dv.get("storageType") == "u":
                    dv = dict(dv, pathOrInlineDv=os.path.join(
                        src_root, dv["pathOrInlineDv"]
                    ))
                actions.append(replace(
                    f, path=absolutize(f.path), data_change=True,
                    deletion_vector=dv,
                ))
                total_size += f.size or 0
            self.metrics.update(
                sourceTableSize=total_size,
                sourceNumOfFiles=len(actions),
                numClonedFiles=len(actions),
            )
            txn.report_metrics(**self.metrics)
            return txn.commit(
                actions, Clone(src.data_path, snapshot.version)
            )

        return target.with_new_transaction(body)
