"""Benchmarks for the 5 BASELINE.md harness configs, end to end.

Every number is wall-clock through the public engine APIs — Parquet IO,
expression evaluation, log commit and all — not kernel-only. Baselines are
honest same-machine host implementations, labeled per config:

  1 batch overwrite + filtered read      vs raw pyarrow parquet write+read
  2 MERGE upsert 1M→10M store_sales      vs the engine's own host-Arrow join
    (headline: GB/sec)                      path (devicePath.enabled=false)
  3 Z-ORDER OPTIMIZE + point query       vs the same query pre-OPTIMIZE
  4 streaming tail of a 1k-commit log    vs snapshot-rebuild-per-batch
  5 checkpoint replay, 10k versions      vs sequential dict replay (both
    (JSON decode included)                  including JSON action decode)
  6/6p hot-table batched scan planning    vs batched numpy over resident
    (1M files x 256 queries; 6p = the       float64 mirrors (strongest host)
    partitioned variant)
  7 replay winner scale probe            vs host numpy scatter
  8 steady-state resident MERGE probe    vs strongest host membership path
    (10M/30M/100M target keys)             on resident key mirrors
  2x north-star-scale MERGE              cold vs steady-state engine merge
    (100M rows, 10 GB class)               (resident-lane CDC shape)
  12 device-resident residual scan        vs the Arrow host residual path
    (host/cold/warm legs, identity          (deviceResidual.mode=off); CPU-
    asserted per query)                     only hosts skip-record the claim
  13 shadow optimizer end to end          first-round absolute numbers; the
    (journal->trace, 2-candidate what-if   scorecard verdicts (confirmed
     scorecard, 10x/100x SLO capacity)     winner, refuted loser) and the
                                           fired SLO objective are asserted
                                           in-config
  14 sharded execution plane 1-vs-8       plan leg in an 8-device subprocess
    (shard_map scan planning, workers=8    ("14w"); identity asserted per
     OPTIMIZE, probe-restricted MERGE)     leg; CPU-only hosts skip-record
                                           the throughput claim but keep the
                                           measured numbers + LPT skew gate

Prints ONE JSON line: the headline metric (config 2 MERGE GB/sec) with the
required {metric, value, unit, vs_baseline} keys plus an ``all`` field
holding every config's numbers. BENCH_SCALE (default 1.0) scales row counts
for quick local runs.

Budget discipline (ISSUE 6): the run must exit rc=0 inside the driver's
wall. BENCH_BUDGET_S (default 3000s) is the soft total; each config also
runs under a SIGALRM deadline (BENCH_CONFIG_DEADLINE_S, default 480s;
headline config 2 gets 900s, 2x 540s, 8 600s) — a breach records a skip
entry and the run continues, so every completed config's artifact is
always captured. Config errors likewise record-and-continue.
"""
import json
import os

# must precede the first pyarrow import: jemalloc (the default) returns
# freed pages to the OS aggressively, so every bench phase re-faults its
# working set; mimalloc retains, giving steadier wall-clock
os.environ.setdefault("ARROW_DEFAULT_MEMORY_POOL", "mimalloc")

import shutil
import sys
import tempfile
import time

import numpy as np

from delta_tpu.utils.jaxcompat import enable_x64

SCALE = float(os.environ.get("BENCH_SCALE", "1.0"))


def _rows(n):
    return max(int(n * SCALE), 1000)


def _dir_bytes(path):
    total = 0
    for root, _dirs, files in os.walk(path):
        if "_delta_log" in root:
            continue
        for f in files:
            if f.endswith(".parquet"):
                total += os.path.getsize(os.path.join(root, f))
    return total


def _timed(fn):
    t0 = time.perf_counter()
    out = fn()
    return time.perf_counter() - t0, out


# -- config 1: batch overwrite + filtered read -------------------------------


def bench_overwrite_read(workdir):
    import pyarrow as pa
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu import DeltaLog

    n = _rows(2_000_000)
    rng = np.random.RandomState(3)
    data = pa.table({
        "id": np.arange(n, dtype=np.int64),
        "v": rng.randint(0, 1000, n).astype(np.int64),
        "name": pa.array(np.char.add("u", rng.randint(0, 99999, n).astype(str))),
    })
    path = os.path.join(workdir, "c1")
    log = DeltaLog.for_table(path)
    # Fault layer is strictly zero-overhead when no plan is configured:
    # maybe_wrap must return the store UNCHANGED (no wrapper object at all),
    # and the bench must never accidentally run with injection enabled.
    from delta_tpu.storage import faults as _faults

    assert _faults.plan_from_conf() is None, (
        "bench must run without a fault plan (delta.tpu.faults.plan is set)")
    assert _faults.maybe_wrap(log._base_store) is log._base_store, (
        "fault layer must install NO wrapper when delta.tpu.faults.plan is unset")
    assert not isinstance(getattr(log.store, "base", log.store),
                          _faults.FaultInjectingLogStore), (
        "DeltaLog store stack must not contain a fault injector by default")
    WriteIntoDelta(log, "append", data).run()

    def engine_roundtrip():
        WriteIntoDelta(log, "overwrite", data).run()
        t = DeltaTable.for_path(path)
        out = t.to_arrow(filters=["v < 100"])
        return out.num_rows

    engine_roundtrip()  # warm device kernel compiles (XLA caches per shape)
    eng_s, eng_rows = _timed(engine_roundtrip)

    # baseline: raw pyarrow — the floor any engine pays for the same IO
    raw = os.path.join(workdir, "c1_raw.parquet")

    def raw_roundtrip():
        pq.write_table(data, raw)
        t = pq.read_table(raw)
        return t.filter(pc.less(t.column("v"), 100)).num_rows

    trials = [_timed(raw_roundtrip) for _ in range(2)]
    raw_s, raw_rows = min(trials, key=lambda x: x[0])
    assert eng_rows == raw_rows, (eng_rows, raw_rows)

    # publish table.health.* gauges so this config's telemetry snapshot
    # carries layout health (small-file debt, stats coverage) per round
    from delta_tpu.obs.doctor import doctor

    doctor(path)
    # run the workload-journal advisor once: journal.* counters land in the
    # snapshot and the --compare gate prices journaling overhead on the
    # scan path of THIS config against the prior round
    from delta_tpu.obs.advisor import advise

    advise(path)
    return {
        "metric": "overwrite_plus_filtered_read_2M_rows",
        "value": round(eng_s, 3),
        "unit": "s",
        "vs_baseline": round(raw_s / eng_s, 2),
        "baseline": "raw pyarrow parquet write+read+filter (no log, no txn)",
    }


# -- config 2: MERGE upsert (headline) ---------------------------------------


def _store_sales(n, rng):
    import pyarrow as pa

    keys = rng.permutation(n * 2)[:n].astype(np.int64)
    return pa.table({
        "ss_item_sk": keys,
        "ss_customer_sk": rng.randint(0, 1_000_000, n).astype(np.int64),
        "ss_sold_date_sk": rng.randint(2450000, 2452000, n).astype(np.int64),
        "ss_store_sk": rng.randint(0, 500, n).astype(np.int64),
        "ss_quantity": rng.randint(1, 100, n).astype(np.int64),
        "ss_sales_price": rng.rand(n).astype(np.float64) * 100,
        "ss_ext_discount_amt": rng.rand(n).astype(np.float64) * 10,
        "ss_net_paid": rng.rand(n).astype(np.float64) * 90,
    })


def bench_merge_upsert(workdir):
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.utils.config import conf

    n_target, n_source = _rows(10_000_000), _rows(1_000_000)
    rng = np.random.RandomState(7)
    target = _store_sales(n_target, rng)
    path = os.path.join(workdir, "c2")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", target).run()
    # the engine's default MERGE policy on this table: deletion vectors
    # (rows marked, only changed rows written). The baseline mode pins the
    # reference-shaped full-rewrite path via the session kill switch below.
    from delta_tpu.commands.alter import set_table_properties

    set_table_properties(log, {"delta.tpu.enableDeletionVectors": "true"})

    # source: half updates (existing keys), half inserts (fresh keys)
    existing = np.asarray(target.column("ss_item_sk"))[
        rng.choice(n_target, n_source // 2, replace=False)
    ]
    fresh = np.arange(n_target * 2, n_target * 2 + (n_source - n_source // 2),
                      dtype=np.int64)
    src_keys = np.concatenate([existing, fresh])
    rng.shuffle(src_keys)
    source = _store_sales(n_source, np.random.RandomState(11))
    source = source.set_column(0, "ss_item_sk", pa.array(src_keys))

    copies = {
        name: os.path.join(workdir, f"c2_{name}")
        for name in ("warm", "dev2", "host1", "host2", "forced")
    }
    for p in copies.values():
        # hardlink copies: delta table files are immutable (writes always
        # create new files), so linking shares the data without queuing
        # ~2GB of writeback that would pollute the timed trials below
        shutil.copytree(path, p, copy_function=os.link)
    gb = (_dir_bytes(path) + source.nbytes) / 1e9

    def run_merge(table_path, mode, src_tab=None, resident=False):
        from delta_tpu import DeltaLog as DL

        DL.clear_cache()
        lg = DL.for_table(table_path)
        # baseline ("off") = the reference's algorithm on this host: Arrow
        # hash join + whole-file rewrite (MergeIntoCommand.scala:456-561).
        # Engine modes keep the deletion-vector policy (changed rows only).
        with conf.set_temporarily(**{
            "delta.tpu.merge.devicePath.mode": mode,
            "delta.tpu.deletionVectors.enabled": mode != "off",
            # the resident-key lane is exercised by its own legs below; the
            # cold trials stay cold (no background build skewing them)
            "delta.tpu.merge.residentKeys.enabled": resident,
        }):
            cmd = MergeIntoCommand(
                lg, source if src_tab is None else src_tab,
                "t.ss_item_sk = s.ss_item_sk",
                [MergeClause("update", assignments=None)],
                [MergeClause("insert", assignments=None)],
                source_alias="s", target_alias="t",
            )
            cmd.run()
        assert cmd.metrics["numTargetRowsUpdated"] == n_source // 2
        assert cmd.metrics["numTargetRowsInserted"] == n_source - n_source // 2
        return cmd

    run_merge(copies["warm"], "force")  # warm the device-kernel compiles
    # headline: auto mode (the engine's link-aware executor routing) vs the
    # host-pinned baseline. Trials INTERLEAVE modes (auto, host, auto, host)
    # so page-cache/writeback drift hits both modes equally; min of 2 per
    # mode damps the allocator/page-fault noise single trials show here.
    def drain():
        # drain page-cache writeback so each trial starts from a quiet
        # disk — otherwise earlier trials' dirty pages throttle later ones
        os.sync()

    auto_trials, host_trials = [], []
    drain(); auto_trials.append(_timed(lambda: run_merge(path, "auto")))
    drain(); host_trials.append(_timed(lambda: run_merge(copies["host1"], "off")))
    drain(); auto_trials.append(_timed(lambda: run_merge(copies["dev2"], "auto")))
    drain(); host_trials.append(_timed(lambda: run_merge(copies["host2"], "off")))
    auto_s, auto_cmd = min(auto_trials, key=lambda x: x[0])
    host_s, host_cmd = min(host_trials, key=lambda x: x[0])

    # per-round sources against an evolving table: updates hit original
    # keys (always present), inserts use disjoint fresh ranges per round
    import pyarrow as _pa

    def mk_source(round_i):
        ex = np.asarray(target.column("ss_item_sk"))[
            np.random.RandomState(17 + round_i).choice(
                n_target, n_source // 2, replace=False)]
        fr = np.arange(n_target * (3 + round_i),
                       n_target * (3 + round_i) + (n_source - n_source // 2),
                       dtype=np.int64)
        keys = np.concatenate([ex, fr])
        np.random.RandomState(23 + round_i).shuffle(keys)
        s = _store_sales(n_source, np.random.RandomState(29 + round_i))
        return s.set_column(0, "ss_item_sk", _pa.array(keys))

    # the fused device pipeline, cold then warm on ONE table copy:
    # device_cold = first forced merge (per-file key decode streams onto the
    # slab while later files decode, probe, and the slab REGISTERS in the
    # KeyCache); device_forced = second forced merge against the now-hot
    # table (cache hit: tail advance + probe, no upload, no key decode) —
    # the steady state the fused MERGE tentpole targets
    drain()
    cold_s, cold_cmd = _timed(lambda: run_merge(
        copies["forced"], "force", resident=True))
    assert cold_cmd._device_join is not None, "forced device join did not run"
    drain()
    forced_s, forced_cmd = _timed(lambda: run_merge(
        copies["forced"], "force", src_tab=mk_source(8), resident=True))
    assert forced_cmd._device_join is not None, "warm forced join did not run"
    warm_cache_hit = forced_cmd._join_path == "resident"

    # resident-key steady state (the CDC loop): the warm copy was merged
    # once already; build its key lane (reported separately — in production
    # it builds in the background after the first eligible merge), then a
    # second merge probes from HBM, shipping only source keys
    from delta_tpu import DeltaLog as DL
    from delta_tpu.commands.merge import MergeIntoCommand as MIC
    from delta_tpu.expr import ir as _ir
    from delta_tpu.ops.key_cache import KeyCache

    DL.clear_cache()
    lg = DL.for_table(copies["warm"])
    snapw = lg.update()
    t_exprs = [_ir.Column("ss_item_sk")]
    sig = MIC._key_signature(t_exprs)
    build_s, entry = _timed(lambda: KeyCache.instance().get(
        snapw, sig, ["ss_item_sk"], t_exprs))
    assert entry is not None
    up_s, _ = _timed(entry.ensure_resident)
    build_s += up_s
    # rounds 1-2 warm the kernel compiles for this shape bucket (probe +
    # tail-advance scatters; first machine contact — the persistent XLA
    # cache makes later processes skip them); rounds 3-4 are the steady
    # state being measured
    run_merge(copies["warm"], "force", src_tab=mk_source(0), resident=True)
    run_merge(copies["warm"], "force", src_tab=mk_source(1), resident=True)
    res_trials = []
    for i in (2, 3):
        drain()
        res_trials.append(_timed(lambda i=i: run_merge(
            copies["warm"], "force", src_tab=mk_source(i), resident=True)))
    resident_s, res_cmd = min(res_trials, key=lambda x: x[0])
    assert res_cmd._join_path == "resident", res_cmd._join_path
    # what auto picks with the lane resident (honest link-model verdict)
    drain()
    res_auto_s, res_auto_cmd = _timed(lambda: run_merge(
        copies["warm"], "auto", src_tab=mk_source(4), resident=True))

    from delta_tpu.parallel import link
    from delta_tpu.utils import telemetry as _tel

    lp = link.profile()
    return {
        "metric": "tpcds_store_sales_merge_upsert_1M_into_10M",
        "value": round(gb / auto_s, 3),
        "unit": "GB/s",
        "vs_baseline": round(host_s / auto_s, 2),
        "baseline": "reference-shaped path on the same machine: host Arrow "
                    "hash-join + whole-file rewrite (deletion vectors off)",
        "auto_s": round(auto_s, 2),
        "host_s": round(host_s, 2),
        "gb": round(gb, 3),
        "auto_used_device": auto_cmd._device_join is not None,
        "auto_join_path": auto_cmd._join_path,
        "auto_router": dict(auto_cmd._router),
        "auto_phases": dict(auto_cmd.phase_ms),
        "host_phases": dict(host_cmd.phase_ms),
        # the pinned-device legs on ONE copy: cold = fused slab pipeline
        # (decode streams onto HBM, probe, slab registers); forced = the
        # second merge against the hot table (KeyCache hit — no upload, no
        # key decode). On PCIe/DMA-attached chips the auto router engages
        # the same path; on this tunnel the cold upload is the honest cost.
        "device_cold_s": round(cold_s, 2),
        "device_cold_phases": dict(cold_cmd.phase_ms),
        "device_cold_path": cold_cmd._join_path,
        "device_forced_s": round(forced_s, 2),
        "device_forced_phases": dict(forced_cmd.phase_ms),
        "device_forced_cache_hit": warm_cache_hit,
        # steady-state CDC legs: target key lane HBM-resident, probe ships
        # only source keys (ops/key_cache)
        "device_resident_s": round(resident_s, 2),
        "device_resident_phases": dict(res_cmd.phase_ms),
        "resident_build_s": round(build_s, 2),
        "resident_auto_s": round(res_auto_s, 2),
        "resident_auto_path": res_auto_cmd._join_path,
        "resident_auto_router": dict(res_auto_cmd._router),
        # the production observables for the same decisions
        # (delta.merge.router events feed these counters)
        "router_counters": {
            **_tel.counters("merge.device"), **_tel.counters("merge.keyCache"),
        },
        "link_MBps": {"up": round(lp.up_mbps, 1), "down": round(lp.down_mbps, 1),
                      "latency_ms": round(lp.latency_s * 1000, 1)},
    }


# -- config 3: Z-ORDER OPTIMIZE + data-skipping point query ------------------


def bench_zorder_point_query(workdir):
    from delta_tpu import DeltaLog
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.exec.scan import scan_files

    n = _rows(4_000_000)
    rng = np.random.RandomState(5)
    data = _store_sales(n, rng)
    path = os.path.join(workdir, "c3")
    log = DeltaLog.for_table(path)
    # write in 8 chunks → 8 files with interleaved key ranges (worst case)
    step = n // 8
    for i in range(8):
        WriteIntoDelta(log, "append", data.slice(i * step, step)).run()

    key = int(np.asarray(data.column("ss_item_sk"))[12345])
    date = int(np.asarray(data.column("ss_sold_date_sk"))[12345])
    pred = f"ss_item_sk = {key} AND ss_sold_date_sk = {date}"

    def point_query():
        DeltaLog.clear_cache()
        t = DeltaTable.for_path(path)
        scan = scan_files(t.delta_log.update(), [pred])
        out = t.to_arrow(filters=[pred])
        return len(scan.files), out.num_rows

    point_query()  # warm pruning-kernel compiles
    pre_s, (pre_files, pre_rows) = _timed(point_query)
    opt_s, _ = _timed(
        OptimizeCommand(log, z_order_by=["ss_item_sk", "ss_sold_date_sk"],
                        target_rows=step).run
    )
    point_query()  # re-warm: the post-OPTIMIZE file count is a new shape
    post_s, (post_files, post_rows) = _timed(point_query)
    assert pre_rows == post_rows
    return {
        "metric": "zorder_point_query_4M_rows",
        "value": round(post_s * 1000, 1),
        "unit": "ms",
        "vs_baseline": round(pre_s / post_s, 2),
        "baseline": "same point query before Z-ORDER OPTIMIZE (files scanned "
                    f"{pre_files}->{post_files})",
        "optimize_s": round(opt_s, 2),
    }


# -- config 10: predicate pushdown synthesis ---------------------------------


def bench_pushdown(workdir):
    """2M-row table, arithmetic + string + cast predicate suite: files and
    row groups pruned, bytes skipped, and planning ms with predicate
    synthesis ON vs OFF (`delta.tpu.read.predicateSynthesis`), result
    identity asserted on every query. Headline: planning-bytes-skipped
    (file tier + row-group tier) ratio on/off — these shapes paid full
    scans before the synthesis layer, so OFF skips ~nothing."""
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.obs import scan_report
    from delta_tpu.utils.config import conf as _c

    n = _rows(2_000_000)
    ids = np.arange(n, dtype=np.int64)
    rng = np.random.RandomState(11)
    regions = np.array(["us-w", "us-e", "eu-c", "eu-w",
                        "ap-s", "ap-n", "sa-e", "af-s"])
    # region index correlates with row order → prefixes cluster per file,
    # like a region-loaded ingest; prices sorted → tight per-file bounds
    region_ix = (ids * len(regions)) // n
    sym = np.char.add(np.char.add(regions[region_ix], "-"),
                      np.char.zfill(ids.astype("U10"), 10))
    base_us = 1_600_000_000_000_000
    data = pa.table({
        "id": ids,
        "price": ids,
        "qty": rng.randint(1, 8, n).astype(np.int64),
        "sym": pa.array(sym),
        "ts": pa.array(base_us + ids * 60_000_000, pa.timestamp("us")),
    })
    path = os.path.join(workdir, "c10")
    log = DeltaLog.for_table(path)
    with _c.set_temporarily(**{
        "delta.tpu.write.targetFileRows": max(n // 16, 1000),
        "delta.tpu.write.rowGroupRows": max(n // 128, 500),
    }):
        WriteIntoDelta(log, "append", data).run()
    total_bytes = _dir_bytes(path)
    hi = int(0.97 * n)
    day = (base_us + int(0.98 * n) * 60_000_000) // 86_400_000_000
    import datetime as _dt

    day_s = (_dt.date(1970, 1, 1) + _dt.timedelta(days=int(day))).isoformat()
    queries = [
        ("arith_mul", f"price * qty > {hi * 7}"),
        ("arith_chain", f"price * 2 + 10 >= {2 * hi}"),
        ("arith_div", f"(price - {n // 2}) / 4 >= {int(0.115 * n)}"),
        ("string_substr", "substr(sym, 1, 4) = 'af-s'"),
        ("string_like", "sym like 'us-w000000%'"),
        ("cast_double", f"cast(price as double) * 1.5 >= {1.5 * hi}"),
        ("temporal_to_date", f"to_date(ts) = '{day_s}'"),
        ("not_cmp", f"not (price < {hi})"),
    ]
    t = DeltaTable.for_path(path)
    t.to_arrow(filters=[queries[0][1]])  # warm footers + compiles

    def run_suite(enabled):
        out = {}
        with _c.set_temporarily(**{
            "delta.tpu.read.predicateSynthesis": enabled,
        }):
            for name, q in queries:
                t0 = time.perf_counter()
                result = t.to_arrow(filters=[q])
                wall_s = time.perf_counter() - t0
                rep = scan_report.last_scan_report()
                out[name] = {
                    "rows": result.num_rows,
                    "id_sum": int(np.asarray(result.column("id")).sum()),
                    "files_pruned": rep.files_pruned,
                    "rowgroups_pruned": rep.row_groups_pruned,
                    "rowgroups_late_skipped": rep.row_groups_late_skipped,
                    # planning-skipped = file tier (compressed bytes never
                    # read) + row-group PLANNER tier (groups never opened);
                    # late materialization is decode-time, not planning
                    "bytes_skipped": (total_bytes - rep.bytes_read)
                    + rep.bytes_skipped_planned,
                    "planning_ms": rep.phase_ms.get("planning", 0),
                    "wall_ms": round(wall_s * 1000, 1),
                    "rewrites_fired": len(rep.rewrites_fired),
                }
        return out

    off = run_suite(False)
    on = run_suite(True)
    for name, _q in queries:
        # result identity on every query: synthesis may only change what
        # decodes, never what returns
        assert on[name]["rows"] == off[name]["rows"], name
        assert on[name]["id_sum"] == off[name]["id_sum"], name
    skipped_on = sum(v["bytes_skipped"] for v in on.values())
    skipped_off = sum(v["bytes_skipped"] for v in off.values())
    ratio = skipped_on / max(skipped_off, 1)
    plan_on = sorted(v["planning_ms"] for v in on.values())
    plan_off = sorted(v["planning_ms"] for v in off.values())
    return {
        "metric": "pushdown_synthesis_bytes_skipped_ratio",
        "value": round(ratio, 1),
        "unit": "x",
        "vs_baseline": round(ratio, 1),
        "baseline": "same suite with delta.tpu.read.predicateSynthesis="
                    "false (pre-synthesis engine: these shapes never prune)",
        "rows": n,
        "bytes_skipped_on": skipped_on,
        "bytes_skipped_off": skipped_off,
        "files_pruned_on": sum(v["files_pruned"] for v in on.values()),
        "files_pruned_off": sum(v["files_pruned"] for v in off.values()),
        "rowgroups_pruned_on": sum(v["rowgroups_pruned"] for v in on.values()),
        "rowgroups_pruned_off": sum(v["rowgroups_pruned"]
                                    for v in off.values()),
        "rewrites_fired": sum(v["rewrites_fired"] for v in on.values()),
        "planning_ms_on_p50": plan_on[len(plan_on) // 2],
        "planning_ms_off_p50": plan_off[len(plan_off) // 2],
        "queries": {name: {"on": on[name], "off": off[name]}
                    for name, _q in queries},
        # direction-aware sub-metrics for the --compare gate
        "gate": {
            "bytes_skipped_ratio": {"value": round(ratio, 1), "unit": "x"},
            "files_pruned_on": {
                "value": sum(v["files_pruned"] for v in on.values()),
                "unit": "files"},
            "rowgroups_pruned_on": {
                "value": sum(v["rowgroups_pruned"] for v in on.values()),
                "unit": "rowgroups"},
            "planning_ms_on_p50": {
                "value": plan_on[len(plan_on) // 2], "unit": "ms"},
        },
    }


# -- config 12: device-resident hot-column scan cache ------------------------


def bench_device_scan(workdir):
    """2M-row table, residual-only predicate suite (every value scattered so
    footer stats prune NOTHING — the hot-column residual shape): three legs
    over the same queries, result identity asserted per query across all of
    them.

      host  — deviceResidual.mode=off: the Arrow host residual path
      cold  — mode=force on an empty ColumnCache: pays predicate-column
              decode + device upload + first-shape jit compiles
      warm  — mode=force again: every lane resident (columnCache.hits > 0,
              misses == 0), mask is one jitted pass per file

    Headline: warm-device speedup vs the host leg. On a CPU-only host
    (JAX_PLATFORMS=cpu, no accelerator) the speedup claim is skip-recorded
    (value -1, unit "skipped") — the legs still run so identity and the
    columnCache.* counter story are captured in the artifact."""
    import jax
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.obs import scan_report
    from delta_tpu.ops.column_cache import ColumnCache
    from delta_tpu.utils import telemetry
    from delta_tpu.utils.config import conf as _c

    n = _rows(2_000_000)
    ids = np.arange(n, dtype=np.int64)
    A = 982_451_653  # prime > n: (i*A) % n is a permutation → scattered
    scattered = (ids * A) % n
    cats = np.array(["us-w", "us-e", "eu-c", "eu-w",
                     "ap-s", "ap-n", "sa-e", "af-s"])
    rng = np.random.RandomState(23)
    base_us = 1_577_836_800_000_000  # 2020-01-01 UTC
    span_us = 4 * 365 * 86_400_000_000  # ~4 years of timestamps
    data = pa.table({
        "id": ids,
        "price": scattered,
        "qty": rng.randint(1, 9, n).astype(np.int64),
        "cat": pa.array(cats[ids % len(cats)]),
        "ts": pa.array(base_us + scattered * (span_us // n),
                       pa.timestamp("us")),
    })
    path = os.path.join(workdir, "c12")
    log = DeltaLog.for_table(path)
    with _c.set_temporarily(**{
        "delta.tpu.write.targetFileRows": max(n // 8, 1000),
        "delta.tpu.write.rowGroupRows": max(n // 64, 500),
    }):
        WriteIntoDelta(log, "append", data).run()
    queries = [
        ("string_eq", "cat = 'eu-c'"),
        ("string_in", "cat in ('us-w', 'ap-s', 'af-s')"),
        ("num_scatter", f"price >= {int(0.9 * n)}"),
        ("arith", f"price * 2 + qty > {int(1.8 * n)}"),
        ("conj", "cat = 'us-w' and qty >= 6"),
        ("temporal_year", "year(ts) = 2021"),
        ("low_sel", f"price < {max(n // 100, 1)}"),
    ]
    tab = DeltaTable.for_path(path)
    with _c.set_temporarily(**{"delta.tpu.read.deviceResidual.mode": "off"}):
        tab.to_arrow(filters=[queries[0][1]])  # warm footers for every leg

    def run_leg(mode):
        out = {}
        c0 = telemetry.counters("columnCache")
        d0 = telemetry.counters("scan.device")
        t_leg = time.perf_counter()
        with _c.set_temporarily(**{
            "delta.tpu.read.deviceResidual.mode": mode,
        }):
            for name, q in queries:
                t0 = time.perf_counter()
                result = tab.to_arrow(filters=[q])
                wall_s = time.perf_counter() - t0
                rep = scan_report.last_scan_report()
                out[name] = {
                    "rows": result.num_rows,
                    "id_sum": int(np.asarray(result.column("id")).sum()),
                    "wall_ms": round(wall_s * 1000, 1),
                    "device_residual": rep.device_residual,
                    "bytes_device_survivor": rep.bytes_device_survivor,
                    "rowgroups_device_skipped": rep.row_groups_device_skipped,
                }
        total_s = time.perf_counter() - t_leg
        c1 = telemetry.counters("columnCache")
        d1 = telemetry.counters("scan.device")
        counters = {k: c1.get(k, 0) - c0.get(k, 0)
                    for k in set(c0) | set(c1)}
        counters.update({k: d1.get(k, 0) - d0.get(k, 0)
                         for k in set(d0) | set(d1)})
        return {"total_s": round(total_s, 3), "queries": out,
                "counters": {k: v for k, v in sorted(counters.items()) if v}}

    host = run_leg("off")
    ColumnCache.reset()  # cold leg starts from an empty cache, honestly
    cold = run_leg("force")
    warm = run_leg("force")
    for name, _q in queries:
        # identity on every query, every leg: the device mask may only
        # change where rows decode, never what returns
        for leg, tag in ((cold, "cold"), (warm, "warm")):
            assert leg["queries"][name]["rows"] == \
                host["queries"][name]["rows"], (name, tag)
            assert leg["queries"][name]["id_sum"] == \
                host["queries"][name]["id_sum"], (name, tag)
        assert warm["queries"][name]["device_residual"] == "device", name
    # the cache story the headline rests on: cold decodes, warm serves
    assert cold["counters"].get("columnCache.misses", 0) > 0
    assert warm["counters"].get("columnCache.hits", 0) > 0
    assert warm["counters"].get("columnCache.misses", 0) == 0
    assert warm["counters"].get("scan.device.engaged", 0) == len(queries)
    speedup = host["total_s"] / max(warm["total_s"], 1e-9)
    platform = jax.devices()[0].platform
    accelerated = platform not in ("cpu",)
    result = {
        "metric": "device_scan_warm_speedup",
        "value": round(speedup, 2) if accelerated else -1,
        "unit": "x" if accelerated else "skipped",
        "vs_baseline": round(speedup, 2) if accelerated else 0,
        "baseline": "same suite with delta.tpu.read.deviceResidual.mode=off "
                    "(the Arrow host residual path)",
        "rows": n,
        "platform": platform,
        "warm_speedup_measured": round(speedup, 2),
        "legs": {"host": host, "cold": cold, "warm": warm},
        "gate": {
            "host_total_s": {"value": host["total_s"], "unit": "s"},
            "warm_total_s": {"value": warm["total_s"], "unit": "s"},
            "warm_cache_hits": {
                "value": warm["counters"].get("columnCache.hits", 0),
                "unit": "hits"},
        },
    }
    if not accelerated:
        result["note"] = (
            f"no accelerator (platform={platform}): warm-device speedup "
            "claim skip-recorded; all three legs still ran with per-query "
            "result identity asserted and columnCache.* counters captured")
    else:
        result["gate"]["warm_speedup"] = {"value": round(speedup, 2),
                                          "unit": "x"}
    return result


# -- config 4: streaming tail of a 1k-commit log -----------------------------


def bench_streaming_tail(workdir):
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.streaming.source import DeltaSource

    n_commits = max(int(1000 * SCALE), 100)
    path = os.path.join(workdir, "c4")
    log = DeltaLog.for_table(path)
    rng = np.random.RandomState(9)
    for i in range(n_commits):
        WriteIntoDelta(log, "append", pa.table({
            "id": np.arange(i * 10, i * 10 + 10, dtype=np.int64),
            "v": rng.randint(0, 100, 10).astype(np.int64),
        })).run()

    def tail_all():
        DeltaLog.clear_cache()
        src = DeltaSource(DeltaLog.for_table(path), max_files_per_trigger=100,
                          starting_version=0)
        off = src.initial_offset()
        total = batches = 0
        while True:
            end = src.latest_offset(off)
            if end is None:
                break
            total += src.get_batch(off, end).num_rows
            off = end
            batches += 1
        return total, batches

    tail_s, (rows_read, n_batches) = _timed(tail_all)
    assert rows_read == n_commits * 10

    # baseline: rebuild the snapshot at each batch boundary (what a
    # non-incremental consumer pays), same batch count
    def naive():
        from delta_tpu.exec.scan import scan_to_table

        total = 0
        seen = 0
        for b in range(n_batches):
            DeltaLog.clear_cache()
            hi = min((b + 1) * 100, n_commits) - 1
            snap = DeltaLog.for_table(path).get_snapshot_at(hi)
            t = scan_to_table(snap)
            total += t.num_rows - seen
            seen = t.num_rows
        return total

    naive_s, naive_rows = min((_timed(naive) for _ in range(2)), key=lambda x: x[0])
    assert naive_rows == rows_read

    # CDC-tailing leg (the BASELINE config names it): the change feed of the
    # same 1k-commit log streamed through DeltaCDFSource
    def tail_cdf():
        from delta_tpu.streaming.source import DeltaCDFSource

        DeltaLog.clear_cache()
        src = DeltaCDFSource(DeltaLog.for_table(path),
                             max_files_per_trigger=100, starting_version=0)
        off = src.initial_offset()
        total = 0
        while True:
            end = src.latest_offset(off)
            if end is None:
                break
            total += src.get_batch(off, end).num_rows
            off = end
        return total

    cdf_s, cdf_rows = _timed(tail_cdf)
    assert cdf_rows == rows_read  # append-only log: every row is an insert
    return {
        "metric": "streaming_tail_1k_commit_log",
        "value": round(n_commits / tail_s, 1),
        "unit": "commits/s",
        "vs_baseline": round(naive_s / tail_s, 2),
        "baseline": "snapshot rebuild + full rescan per micro-batch",
        "cdf_commits_per_s": round(n_commits / cdf_s, 1),
    }


# -- config 5: checkpoint replay, 10k versions -------------------------------


def bench_checkpoint_replay(workdir):
    """End-to-end snapshot state reconstruction from a cold on-disk log:
    checkpoint Parquet at the midpoint + a JSON commit tail, both paths
    reading the same files. Device path = columnar decode (log/columnar.py)
    + the slim winner kernel; baseline = the reference-shaped sequential
    object replay (checkpoint rows + per-line JSON decode into a dict)."""
    from delta_tpu.log import checkpoints as ckpt_mod
    from delta_tpu.log.columnar import decode_segment
    from delta_tpu.ops import replay_kernel
    from delta_tpu.protocol import filenames
    from delta_tpu.protocol.actions import AddFile, action_from_json
    from delta_tpu.storage.logstore import get_log_store

    n_versions, per_commit, n_paths = max(int(10_000 * SCALE), 500), 20, 50_000
    ckpt_v = n_versions // 2
    rng = np.random.RandomState(7)
    log_path = os.path.join(workdir, "c5", "_delta_log")
    store = get_log_store(log_path)

    active = {}
    for v in range(n_versions):
        lines = []
        for _ in range(per_commit):
            p = f"part-{rng.randint(n_paths):05d}-{v}.parquet"
            if rng.rand() < 0.85:
                sz = int(rng.randint(1, 1 << 24))
                lines.append(json.dumps({"add": {
                    "path": p, "partitionValues": {}, "size": sz,
                    "modificationTime": v, "dataChange": True}}))
                active[p] = sz
            else:
                lines.append(json.dumps({"remove": {
                    "path": p, "deletionTimestamp": v * 1000, "dataChange": True}}))
                active.pop(p, None)
        store.write(f"{log_path}/{filenames.delta_file(v)}", lines)
        if v == ckpt_v:
            ckpt_actions = [AddFile(path=p, size=s, modification_time=0,
                                    data_change=False) for p, s in active.items()]
            ckpt_mod.write_checkpoint(store, log_path, v, ckpt_actions)

    ckpt_paths = [f"{log_path}/{filenames.checkpoint_file_single(ckpt_v)}"]
    deltas = [f"{log_path}/{filenames.delta_file(v)}" for v in range(ckpt_v + 1, n_versions)]

    def host_end_to_end():
        state = {}
        for a in ckpt_mod.read_checkpoint_actions(store, ckpt_paths):
            d = a.__class__.__name__
            if d == "AddFile":
                state[a.path] = a.size
        for p in deltas:
            for line in store.read_iter(p):
                a = action_from_json(line)
                d = a.__class__.__name__
                if d == "AddFile":
                    state[a.path] = a.size
                elif d == "RemoveFile":
                    state.pop(a.path, None)
        return len(state)

    host_s, host_n = min((_timed(host_end_to_end) for _ in range(2)), key=lambda x: x[0])
    assert host_n == len(active)

    phases = {}

    def device_end_to_end():
        t0 = time.perf_counter()
        cols = decode_segment(store, ckpt_paths, deltas)
        t1 = time.perf_counter()
        r = replay_kernel.replay_columns(cols, min_retention_ts=0, device=True)
        t2 = time.perf_counter()
        phases["decode_ms"] = round((t1 - t0) * 1000, 1)
        phases["device_winner_ms"] = round((t2 - t1) * 1000, 1)
        return int(r.stats.num_files)

    # warm the jit cache, then min-of-3 to damp tunnel-latency jitter
    device_end_to_end()
    runs = [_timed(device_end_to_end) for _ in range(3)]
    dev_s = min(s for s, _ in runs)
    dev_n = runs[0][1]
    assert host_n == dev_n, (host_n, dev_n)

    # host-winner variant (no device round trip) for the breakdown
    cols = decode_segment(store, ckpt_paths, deltas)
    hw_s = min(_timed(lambda: replay_kernel.replay_columns(
        cols, min_retention_ts=0, device=False))[0] for _ in range(3))
    return {
        "metric": "checkpoint_replay_10k_versions_200k_actions",
        "value": round(dev_s * 1000, 1),
        "unit": "ms",
        "vs_baseline": round(host_s / dev_s, 2),
        "baseline": "sequential object replay incl. checkpoint Parquet read "
                    "+ per-line JSON decode (reference Snapshot.scala shape)",
        "host_baseline_ms": round(host_s * 1000, 1),
        "phases": dict(phases, host_winner_ms=round(hw_s * 1000, 2)),
    }


# -- config 6: hot-table batched scan planning (device-resident state) -------


def bench_hot_plan(workdir, partitioned=False):
    """The query-server shape: a 1M-file table's scan lanes resident in HBM
    (`ops/state_cache`), serving batches of 256 point-range plans. Baseline =
    the strongest host implementation (vectorized numpy over the same float64
    mirrors, batched); the reference-shaped per-query path (materialize
    AddFiles + re-evaluate stats per query, `DataSkippingReader`'s shape) is
    also sampled for scale. The win condition VERDICT r3 set: the device
    engages under AUTO routing and beats the host."""
    import json as _json

    from delta_tpu import DeltaLog
    from delta_tpu.exec.scan import plan_scans
    from delta_tpu.log import checkpoints as ckpt_mod
    from delta_tpu.ops.state_cache import DeviceStateCache
    from delta_tpu.protocol import filenames
    from delta_tpu.protocol.actions import AddFile, Metadata, Protocol
    from delta_tpu.schema.types import DoubleType, LongType, StructType
    from delta_tpu.storage.logstore import get_log_store
    from delta_tpu.utils.config import conf

    n_files = max(int(1_000_000 * SCALE), 20_000)
    n_queries = 256
    rng = np.random.RandomState(13)
    table_path = os.path.join(workdir, "c6p" if partitioned else "c6")
    log_path = os.path.join(table_path, "_delta_log")
    store = get_log_store(log_path)

    schema = StructType()
    for c in range(4):
        schema = schema.add(f"c{c}", DoubleType() if c % 2 else LongType())
    part_cols = []
    days = []
    if partitioned:
        # the reference's primary pruning path: a date-partitioned layout
        # (DeltaLog.scala:500-547 rewritePartitionFilters shapes)
        from delta_tpu.schema.types import StringType

        schema = schema.add("day", StringType())
        part_cols = ["day"]
        import datetime as _dt

        n_days = 732
        day0 = _dt.date(2020, 1, 1)
        days = [(day0 + _dt.timedelta(days=d)).isoformat()
                for d in range(n_days)]
    meta = Metadata(schema_string=schema.to_json(),
                    partition_columns=part_cols)
    proto = Protocol(1, 2)
    store.write(f"{log_path}/{filenames.delta_file(0)}",
                [proto.json(), meta.json()])

    # 1M files, each covering a narrow range per column (a well-clustered
    # table: point queries match a handful of files)
    base = {f"c{c}": np.sort(rng.rand(n_files) * 1e6) if c % 2 else
            np.sort(rng.randint(0, 1 << 40, n_files).astype(np.int64))
            for c in range(4)}
    width = {f"c{c}": 1e6 / n_files * 8 if c % 2 else max((1 << 40) // n_files * 8, 1)
             for c in range(4)}
    adds = []
    for i in range(n_files):
        mins = {c: (float(v[i]) if c in ("c1", "c3") else int(v[i])) for c, v in base.items()}
        maxs = {c: (float(v[i] + width[c]) if c in ("c1", "c3") else int(v[i] + width[c]))
                for c, v in base.items()}
        stats = _json.dumps({"numRecords": 10000, "minValues": mins,
                             "maxValues": maxs,
                             "nullCount": {c: 0 for c in base}})
        pv = {"day": days[i * len(days) // n_files]} if partitioned else {}
        adds.append(AddFile(path=f"part-{i:07d}.parquet", size=1 << 20,
                            modification_time=0, data_change=False, stats=stats,
                            partition_values=pv))
    ckpt_mod.write_checkpoint(store, log_path, 0, [proto, meta] + adds)

    DeltaLog.clear_cache()
    DeviceStateCache.reset()
    log = DeltaLog.for_table(table_path)
    t0 = time.perf_counter()
    snap = log.update()
    snap.num_of_files  # force state reconstruction
    decode_s = time.perf_counter() - t0

    # queries: point ranges on 2 columns (a dashboard's WHERE shapes);
    # partitioned tables mix partition equality/ranges with stat ranges
    qs = []
    for k in range(n_queries):
        i = rng.randint(n_files)
        lo0 = int(base["c0"][i])
        lo1 = float(base["c1"][i])
        if partitioned and k % 2 == 0:
            d = days[i * len(days) // n_files]
            if k % 4 == 0:
                qs.append([f"day = '{d}' AND c0 >= {lo0}"])
            else:
                qs.append([f"day >= '{d}' AND day <= '{days[min(i * len(days) // n_files + 3, len(days) - 1)]}'"])
        else:
            qs.append([f"c0 >= {lo0} AND c0 <= {lo0 + int(width['c0'])} "
                       f"AND c1 >= {lo1:.6f} AND c1 <= {lo1 + width['c1']:.6f}"])

    from delta_tpu.parallel import link

    link.profile()  # backend + tunnel warm-up: not a per-table cost
    t0 = time.perf_counter()
    entry = DeviceStateCache.instance().get(snap)
    assert entry is not None
    parse_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    entry.ensure_resident()
    upload_s = time.perf_counter() - t0
    build_s = parse_s + upload_s

    def run(mode):
        with conf.set_temporarily(**{"delta.tpu.stateCache.devicePlan.mode": mode}):
            return plan_scans(snap, qs, k=256)

    from delta_tpu.parallel import link

    link.profile()  # process-wide calibration, not a per-batch cost
    run("force")  # warm the plan-kernel compile
    dev_s = min(_timed(lambda: run("force"))[0] for _ in range(3))
    host_s = min(_timed(lambda: run("off"))[0] for _ in range(3))
    auto_s, auto_plans = min(
        (_timed(lambda: run("auto")) for _ in range(2)), key=lambda x: x[0])
    auto_via = auto_plans[0].via

    # parity spot-check: the device's f32 verdict may keep an extra boundary
    # file (conservative rounding) but never drop one the host keeps
    dev_plans, host_plans = run("force"), run("off")
    for d, h in zip(dev_plans[:16], host_plans[:16]):
        assert set(h.paths) <= set(d.paths)
        assert d.count <= h.count + 4, (d.count, h.count)

    # reference-shaped per-query sample: files_for_scan on materialized
    # AddFiles (the all_files dataclass path), 2 queries, extrapolated
    from delta_tpu.exec.scan import scan_files

    sample_n = 2
    with conf.set_temporarily(**{"delta.tpu.stateCache.enabled": False,
                                 "delta.tpu.stateCache.serveScans": False}):
        ref_s, _ = _timed(lambda: [scan_files(snap, q) for q in qs[:sample_n]])
    ref_extrapolated_s = ref_s / sample_n * n_queries

    # steady-state: a new commit tails in incrementally (no rebuild)
    new_add = AddFile(path="part-new.parquet", size=1 << 20, modification_time=1,
                      data_change=True,
                      partition_values={"day": days[-1]} if partitioned else {},
                      stats=_json.dumps({"numRecords": 1, "minValues": {"c0": 1},
                                         "maxValues": {"c0": 2},
                                         "nullCount": {c: 0 for c in base}}))
    store.write(f"{log_path}/{filenames.delta_file(1)}", [new_add.json()])
    DeviceStateCache.instance().get(log.update())  # first apply warms the jits
    from dataclasses import replace as _dc_replace

    new_add2 = _dc_replace(new_add, path="part-new2.parquet")
    store.write(f"{log_path}/{filenames.delta_file(2)}", [new_add2.json()])
    snap2 = log.update()
    tail_s, entry2 = _timed(lambda: DeviceStateCache.instance().get(snap2))
    assert entry2 is entry and entry2.version == 2, "tail must apply incrementally"

    # serving-envelope coverage: a MIXED workload (ranges, ORs, INs, null
    # tests, unknown columns, strings) — what fraction serves resident?
    mixed = []
    for j in range(64):
        i = rng.randint(n_files)
        lo0 = int(base["c0"][i])
        shapes = [
            [f"c0 >= {lo0} AND c0 <= {lo0 + int(width['c0'])}"],     # range
            [f"c0 = {lo0} OR c0 = {lo0 + 9999}"],                    # OR
            [f"c0 IN ({lo0}, {lo0 + 7}, {lo0 + 77})"],               # IN
            ["c1 IS NULL"],                                          # null test
            ["c3 >= 0.5 AND c1 >= 0.1"],                             # wide range
            ["c1 IS NOT NULL"],                              # null-count test
        ]
        mixed.append(shapes[j % len(shapes)])
    mixed_plans = plan_scans(log.update(), mixed, k=64)
    resident_served = sum(1 for p_ in mixed_plans if p_.via != "scan")
    per_q_device_ms = dev_s / n_queries * 1000
    return {
        "metric": ("hot_table_batched_scan_planning_1M_files_256_queries"
                   + ("_partitioned" if partitioned else "")),
        "value": round(dev_s * 1000, 1),
        "unit": "ms",
        "vs_baseline": round(host_s / dev_s, 2),
        "baseline": "strongest host path on the same machine: batched "
                    "vectorized numpy over resident float64 mirrors",
        "auto_used_device": auto_via == "device",
        "auto_ms": round(auto_s * 1000, 1),
        "host_resident_ms": round(host_s * 1000, 1),
        "device_ms": round(dev_s * 1000, 1),
        "per_query_device_ms": round(per_q_device_ms, 3),
        "reference_shaped_extrapolated_s": round(ref_extrapolated_s, 1),
        "vs_reference_shaped": round(ref_extrapolated_s / dev_s, 1),
        "state_decode_s": round(decode_s, 2),
        "cache_build_s": round(build_s, 2),
        "cache_build_parse_s": round(parse_s, 2),
        "cache_build_upload_s": round(upload_s, 2),
        "incremental_tail_apply_ms": round(tail_s * 1000, 1),
        "mixed_workload_resident_pct": round(100.0 * resident_served / len(mixed), 1),
        "n_files": n_files,
    }


# -- config 7: replay scale probe (device crossover calibration) -------------


def bench_replay_scale(workdir):
    """Where does the device replay winner kernel cross over the host
    scatter? Three legs per size, measured the same way (min of 3):

      host      — the numpy scatter winner (SegmentColumns.winner_mask)
      upload    — winner_mask_device: ship the path column, kernel, bits back
      resident  — the column already in HBM (ops/state_cache steady state):
                  kernel + live-prefix bits download only

    The honest record VERDICT r3 asked for: the routing thresholds in
    parallel/link.py are checked against live per-row numbers, and the
    crossover (or its absence, on a link where uploads dominate) is stated
    per leg rather than assumed."""
    import jax
    import jax.numpy as jnp

    from delta_tpu.ops import replay_kernel

    rng = np.random.RandomState(3)
    sizes = [int(n * SCALE) for n in (1_000_000, 4_000_000, 16_000_000)]
    sizes = [max(s, 100_000) for s in sizes]
    results = []
    crossover_upload = crossover_resident = None
    for n in sizes:
        n_paths = max(n // 10, 1)
        path_id = rng.randint(0, n_paths, n).astype(np.int32)

        def host_winner():
            last = np.full(n_paths, -1, np.int64)
            last[path_id] = np.arange(n)
            mask = np.zeros(n, bool)
            mask[last[last >= 0]] = True
            return mask

        host_ms = min(_timed(host_winner)[0] for _ in range(3)) * 1000

        replay_kernel.winner_mask_device(path_id)  # warm compile per shape
        up_ms = min(
            _timed(lambda: replay_kernel.winner_mask_device(path_id))[0]
            for _ in range(3)
        ) * 1000

        cap = replay_kernel._next_pow2(n)
        padded = np.full(cap, -1, np.int32)
        padded[:n] = path_id
        dev = jax.device_put(padded)
        jax.block_until_ready(dev)

        def resident_winner():
            bits = replay_kernel._winner_bits_kernel(dev)
            return np.asarray(bits[: (n + 7) // 8])

        resident_winner()
        res_ms = min(_timed(resident_winner)[0] for _ in range(3)) * 1000
        del dev
        results.append({
            "actions": n,
            "host_ms": round(host_ms, 2),
            "device_upload_ms": round(up_ms, 1),
            "device_resident_ms": round(res_ms, 1),
        })
        if crossover_upload is None and up_ms < host_ms:
            crossover_upload = n
        if crossover_resident is None and res_ms < host_ms:
            crossover_resident = n

    from delta_tpu.parallel import link

    lp = link.profile()
    biggest = results[-1]
    return {
        "metric": "replay_winner_scale_probe",
        "value": biggest["device_resident_ms"],
        "unit": "ms",
        "vs_baseline": round(
            biggest["host_ms"] / biggest["device_resident_ms"], 2
        ),
        "baseline": f"host numpy scatter winner at {biggest['actions']} actions",
        "sweep": results,
        "crossover_actions_upload": crossover_upload,
        "crossover_actions_resident": crossover_resident,
        "link_MBps": {"up": round(lp.up_mbps, 1), "down": round(lp.down_mbps, 1),
                      "latency_ms": round(lp.latency_s * 1000, 1)},
        "note": "upload leg is link-bound on tunneled chips (crossover may "
                "not exist); the resident leg is the steady state the "
                "state cache serves",
    }


# -- config 2x: north-star-scale MERGE (10 GB class) -------------------------


def bench_merge_scale(workdir):
    """VERDICT r4 #3: push the MERGE bench toward BASELINE.json's stated
    shape (100 GB TPC-DS store_sales). Sized to fit the driver budget
    (ISSUE 6 satellite: r5's 100M-row leg was what blew the round to
    rc=124): default 40M rows ≈ 4 GB class, raisable via BENCH_2X_ROWS;
    a store_sales target merged with a 1/10th source through the engine's
    AUTO paths (deletion vectors + resident key lane). Two successive
    merges measure cold (builds the resident lane post-commit) and steady
    state (probes HBM residency, advances the tail). Timed once each —
    min-of-N would double a ~minutes-long config; the ±band is stated
    instead. The reference-shaped full-rewrite host baseline is NOT re-run
    at this scale; config 2 carries that comparison and config 8 carries
    the 100M-key host-vs-device probe."""
    import resource

    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.alter import set_table_properties
    from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.utils.config import conf

    base_rows = int(float(os.environ.get("BENCH_2X_ROWS", "40000000")))
    n_target = max(int(base_rows * SCALE), 2_000_000)
    n_source = max(n_target // 10, 200_000)
    rng = np.random.RandomState(17)
    path = os.path.join(workdir, "c2x")
    log = DeltaLog.for_table(path)
    t0 = time.perf_counter()
    target = _store_sales(n_target, rng)
    WriteIntoDelta(log, "append", target).run()
    set_table_properties(log, {"delta.tpu.enableDeletionVectors": "true"})
    build_s = time.perf_counter() - t0
    gb = _dir_bytes(path) / 1e9
    target_keys = np.asarray(target.column("ss_item_sk"))
    del target

    def mk_source(seed, fresh_base):
        r = np.random.RandomState(seed)
        existing = target_keys[r.choice(n_target, n_source // 2, replace=False)]
        fresh = np.arange(fresh_base, fresh_base + (n_source - n_source // 2),
                          dtype=np.int64)
        keys = np.concatenate([existing, fresh])
        r.shuffle(keys)
        src = _store_sales(n_source, np.random.RandomState(seed + 1))
        return src.set_column(0, "ss_item_sk", pa.array(keys))

    def run_merge(src):
        DeltaLog.clear_cache()
        lg = DeltaLog.for_table(path)
        with conf.set_temporarily(**{
            "delta.tpu.merge.devicePath.mode": "auto",
            "delta.tpu.deletionVectors.enabled": True,
            "delta.tpu.merge.residentKeys.enabled": True,
        }):
            cmd = MergeIntoCommand(
                lg, src, "t.ss_item_sk = s.ss_item_sk",
                [MergeClause("update", assignments=None)],
                [MergeClause("insert", assignments=None)],
                source_alias="s", target_alias="t",
            )
            cmd.run()
        assert cmd.metrics["numTargetRowsUpdated"] == n_source // 2
        assert cmd.metrics["numTargetRowsInserted"] == n_source - n_source // 2
        return cmd

    src1 = mk_source(31, n_target * 4)
    cold_s, cold = _timed(lambda: run_merge(src1))
    del src1

    # steady state needs the resident key lane UP: wait for the background
    # build the cold merge kicked off (a projected read of every file's
    # keys — ~a minute of IO at this scale), then ship it to HBM and sort
    # it explicitly so the timed leg measures the steady probe, not the
    # one-time residency cost (reported separately here)
    import jax

    from delta_tpu.ops.key_cache import KeyCache

    with conf.set_temporarily(**{
            "delta.tpu.keyCache.maxBytes": str(8 << 30)}):
        t0 = time.perf_counter()
        entry = None
        while time.perf_counter() - t0 < 300:
            with KeyCache.instance()._lock:
                cands = [e for (k, e) in KeyCache.instance()._entries.items()
                         if k[0] == log.log_path]
            if cands:
                entry = cands[0]
                break
            time.sleep(2)
        build_wait_s = time.perf_counter() - t0
        residency_upload_s = probe_warm_s = None
        if entry is not None:
            t0 = time.perf_counter()
            entry.ensure_resident()
            with entry._lock:
                entry._ensure_sorted()
            jax.block_until_ready(entry._dev["sorted_keys"])
            np.asarray(entry._dev["sorted_keys"][:8])  # force completion
            residency_upload_s = time.perf_counter() - t0
            # absorb the per-shape probe compile outside the timed leg
            t0 = time.perf_counter()
            warm = entry.probe_async(
                np.zeros(n_source, np.int64), np.ones(n_source, bool))
            if warm is not None:
                try:
                    warm.result()
                except Exception:
                    pass
            probe_warm_s = time.perf_counter() - t0
            # the tunnel's bandwidth DEGRADES under sustained traffic and
            # recovers after idle (parallel/link.py); the residency ship is
            # a one-time event in the steady state being measured, so let
            # the link recover before the timed leg rather than charging
            # its hangover to every subsequent merge (bounded: the
            # per-config deadline is the hard stop)
            time.sleep(20)
        src2 = mk_source(37, n_target * 5)
        steady_s, steady = _timed(lambda: run_merge(src2))
        src_gb = src2.nbytes / 1e9
        del src2
    peak_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    return {
        "metric": "merge_upsert_100M_rows_10GB_class",
        "value": round((gb + src_gb) / cold_s, 3),
        "unit": "GB/s",
        "vs_baseline": round(steady_s / cold_s, 2),
        "baseline": "the second (steady-state) engine merge on the same "
                    "table — an honest scale record, not a win claim: on "
                    "this 1-vCPU host + degrading tunnel the 100M-row "
                    "merge is bound by host decode/apply and the one-time "
                    "residency ship, so the steady leg can measure SLOWER "
                    "than cold (see notes; config 8 isolates the probe "
                    "itself, which does win at this scale)",
        "rows_target": n_target,
        "rows_source": n_source,
        "table_gb": round(gb, 2),
        "table_build_s": round(build_s, 1),
        "cold_merge_s": round(cold_s, 1),
        "steady_merge_s": round(steady_s, 1),
        "resident_build_wait_s": round(build_wait_s, 1),
        "residency_upload_s": (round(residency_upload_s, 1)
                               if residency_upload_s is not None else None),
        "probe_compile_warm_s": (round(probe_warm_s, 1)
                                 if probe_warm_s is not None else None),
        "cold_join_path": cold._join_path,
        "steady_join_path": steady._join_path,
        "cold_phases_ms": {k: round(v, 0) for k, v in cold.phase_ms.items()},
        "steady_phases_ms": {k: round(v, 0) for k, v in steady.phase_ms.items()},
        "peak_rss_gb": round(peak_gb, 1),
        "note": "timed once per leg (~minutes each at this scale; host "
                "noise band ±30% applies); the reference-shaped host "
                "baseline is carried at 1/10th scale by config 2 and the "
                "100M-key probe comparison by config 8. Where time goes at "
                "10x scale: the join/decode/apply phases are host-bound "
                "(1 vCPU) and grow superlinearly once the working set "
                "passes the page cache; the ~0.5 GB residency ship "
                "(int32-narrowed) both costs minutes on this tunnel AND "
                "degrades it for the leg that follows, so AUTO routing "
                "correctly keeps later merges on the host here — on an "
                "attached chip the same ship is sub-second",
    }


# -- config 8: steady-state resident MERGE membership probe ------------------


def bench_resident_probe(workdir):
    """The data-plane shape VERDICT r4 demanded: the MERGE membership probe
    from warm HBM residency (`ops/key_cache` sorted-slab steady state),
    isolated — source keys up, head + compacted O(matched) pairs down (the
    fused join) — swept over target sizes, with a full phase breakdown and
    the attached-chip extrapolation.

    Baselines are the STRONGEST host paths on the same machine, both given
    resident decoded key mirrors for free (no Parquet decode charged):
      host_searchsorted — sort the 1M source, binary-search all N targets
      host_isin_table   — np.isin(kind='table') bool-lookup over the range
    The engine's real host join additionally pays a per-merge key decode
    (link.HOST_KEY_DECODE_S_PER_ROW, measured); reported as a modeled line.

    Honesty notes: the 10M entry pays the real tiled upload (build_s);
    larger slabs are materialized device-side from the same congruential
    permutation the host mirrors use (identical content, skipping an
    upload this tunnel cannot sustain — a one-time cost in production,
    reported at the 10M point)."""
    import jax
    import jax.numpy as jnp

    from delta_tpu.ops import key_cache as kc
    from delta_tpu.ops.join_kernel import _bucket
    from delta_tpu.ops.key_cache import ResidentJoinKeys
    from delta_tpu.parallel import link

    M_SRC = max(int(1_000_000 * SCALE), 100_000)
    sizes = sorted({max(int(n * SCALE), 1_000_000)
                    for n in (10_000_000, 30_000_000, 100_000_000)})
    A = 982_451_653  # prime > any n here: (i*A) % n is a permutation

    def keyfn_host(n):
        return ((np.arange(n, dtype=np.int64) * A) % n) * 2

    def mk_entry(n, real_upload):
        e = ResidentJoinKeys("bench", "mid", 0, f"bench-{n}", ["k"])
        keys = keyfn_host(n)
        e.h_keys = keys
        e.h_valid = np.ones(n, bool)
        e.h_nullok = np.ones(n, bool)
        e.h_min, e.h_max = 0, 2 * (n - 1)
        e.num_rows, e.capacity = n, _bucket(n)
        step = 2_097_152
        e.slabs = {f"f{i}": (off, min(step, n - off))
                   for i, off in enumerate(range(0, n, step))}
        build_s = None
        if real_upload:
            t0 = time.perf_counter()
            e.ensure_resident()
            build_s = time.perf_counter() - t0
        else:
            cap = e.capacity
            with enable_x64():
                iota = jnp.arange(cap, dtype=jnp.int64)
                dk = jnp.where(iota < n, ((iota * A) % n) * 2, 0)
                dvv = iota < n
                jax.block_until_ready((dk, dvv))
            e._dev = {"keys": dk, "valid": dvv}
            e._sort_stale = True
        with e._lock:  # first sort: absorbs the per-shape compile
            e._ensure_sorted()
        jax.block_until_ready(e._dev["sorted_keys"])
        t0 = time.perf_counter()  # steady-state re-sort (the advance cost)
        with e._lock:
            e._sort_stale = True
            e._dev.pop("sorted_keys", None)
            e._dev.pop("perm", None)
            e._ensure_sorted()
        jax.block_until_ready(e._dev["sorted_keys"])
        sort_s = time.perf_counter() - t0
        return e, keys, build_s, sort_s

    def sources(n, keys):
        half = M_SRC // 2
        rng = np.random.RandomState(41)
        # clustered: hits form a contiguous KEY range (a CDC upsert touching
        # one id band) — the shape the coarse-fine hot-block download serves;
        # misses are odd keys (absent). The slab holds every even key < 2n.
        k0 = (n // 3) * 2
        hits_c = np.arange(k0, k0 + 2 * half, 2, dtype=np.int64)
        miss = rng.randint(0, n, M_SRC - half).astype(np.int64) * 2 + 1
        clustered = np.concatenate([hits_c, miss])
        rng.shuffle(clustered)
        # uniform: hits scattered over the whole key space (dense blocks,
        # the device-unsort + full-mask download path)
        rows_u = rng.choice(n, half, replace=False)
        uniform = np.concatenate([keys[rows_u], miss])
        rng.shuffle(uniform)
        return {"clustered": clustered, "uniform": uniform}

    lp = link.profile()
    sweep = []
    for n in sizes:
        real_upload = n <= 12_000_000
        try:
            e, keys, build_s, sort_s = mk_entry(n, real_upload)
        except Exception as ex:  # HBM/link failure: record and continue
            sweep.append({"targets": n, "skipped": str(ex)[:120]})
            continue
        srcs = sources(n, keys)
        entry_res = {"targets": n, "m_source": M_SRC,
                     "build_upload_s": round(build_s, 2) if build_s else None,
                     "device_sort_s": round(sort_s, 3)}
        for label, s_keys in srcs.items():
            s_ok = np.ones(len(s_keys), bool)
            trials = 3 if n <= 40_000_000 else 2

            # host winners on resident mirrors
            def host_ss():
                ss = np.sort(s_keys)
                ix = np.searchsorted(ss, keys)
                ix[ix == len(ss)] = len(ss) - 1
                return ss[ix] == keys

            def host_tab():
                return np.isin(keys, s_keys, kind="table")

            h_ss = min(_timed(host_ss)[0] for _ in range(trials))
            try:
                h_tab = min(_timed(host_tab)[0] for _ in range(trials))
            except TypeError:  # numpy without kind=
                h_tab = float("inf")
            host_best = min(h_ss, h_tab)

            # device steady state through the public API (warm first)
            e.probe_async(s_keys, s_ok).result()
            dev_total = min(
                _timed(lambda: e.probe_async(s_keys, s_ok).result())[0]
                for _ in range(trials))

            # phase decomposition (replicates probe_async internals)
            s_enc = s_keys.astype(np.int32)
            cap_s = _bucket(len(s_enc))
            s_in = np.full(cap_s, np.iinfo(np.int32).max - 1, np.int32)
            s_in[: len(s_enc)] = s_enc
            up_s = min(_timed(lambda: jax.block_until_ready(
                jax.device_put(s_in)))[0] for _ in range(trials))
            s_dev = jax.device_put(s_in)
            jax.block_until_ready(s_dev)
            dev_h = e._dev

            def kernel_only():
                with enable_x64():
                    out = kc._probe_sorted_kernel()(
                        dev_h["sorted_keys"], dev_h["sorted_valid"],
                        jnp.asarray(np.int32(n)), s_dev)
                np.asarray(out[0][:2])  # force completion (tiny fetch)
                return out

            head_dev, t_match_dev, s_first_dev = kernel_only()
            k_s = min(_timed(kernel_only)[0] for _ in range(trials))
            head_s, head = _timed(lambda: np.asarray(head_dev))
            _multi, overflow, mc, _sm = kc._decode_head(
                head, cap_s, len(s_keys))
            assert not overflow, "probe overflow on a bench shape"

            def pairs_fetch():
                # the fused path's O(matched) pair download (physical row +
                # first-match source row, compacted on device)
                if mc == 0:
                    return None
                out_cap = kc._next_pow2(mc, floor=64)
                return np.asarray(kc._pair_compact_kernel()(
                    t_match_dev, s_first_dev, dev_h["perm"], out_cap))

            pairs_fetch()
            fine_s = min(_timed(pairs_fetch)[0] for _ in range(trials))
            resident_source_s = k_s + head_s + fine_s

            # the engine's real host join additionally decodes target keys
            host_engine_modeled = host_best + n * link.HOST_KEY_DECODE_S_PER_ROW
            s_bytes = cap_s // 8
            # attached-chip terms: same measured kernel, PCIe-class link
            attached = k_s + (4 * len(s_keys)) / 12e9 + 2 * 0.0002 \
                + (mc * 8 + s_bytes) / 12e9
            # the MERGE router's decision for this shape (the cost model
            # in commands/merge.py:_launch_resident_probe, live link terms)
            auto_device_s = link.resident_probe_device_s(n, len(s_keys), lp)
            auto_host_s = ((n + len(s_keys)) * link.HOST_JOIN_S_PER_ROW
                           + n * link.HOST_KEY_DECODE_S_PER_ROW)
            entry_res[label] = {
                "auto_routes_device": bool(auto_device_s < auto_host_s),
                "host_best_ms": round(host_best * 1000, 1),
                "host_searchsorted_ms": round(h_ss * 1000, 1),
                "host_isin_table_ms": round(h_tab * 1000, 1)
                if h_tab != float("inf") else None,
                "host_engine_modeled_ms": round(host_engine_modeled * 1000, 1),
                "device_total_ms": round(dev_total * 1000, 1),
                "device_resident_source_ms": round(resident_source_s * 1000, 1),
                "attached_chip_extrapolated_ms": round(attached * 1000, 2),
                "phases_ms": {
                    "upload": round(up_s * 1000, 1),
                    "kernel": round(k_s * 1000, 1),
                    "head_fetch": round(head_s * 1000, 1),
                    "pairs_fetch": round(fine_s * 1000, 1),
                },
                "matched_pairs": int(mc),
                "device_beats_host_resident": bool(dev_total < host_best),
                "attached_beats_host_resident": bool(attached < host_best),
            }
        del e
        sweep.append(entry_res)

    # headline: the largest measured shape's clustered leg
    top = next((s for s in reversed(sweep) if "clustered" in s), None)
    if top is None:
        return {"metric": "resident_merge_probe_steady_state", "value": -1,
                "unit": "ms", "vs_baseline": 0, "sweep": sweep}
    c = top["clustered"]
    return {
        "metric": "resident_merge_probe_steady_state",
        "value": c["device_total_ms"],
        "unit": "ms",
        "vs_baseline": round(c["host_best_ms"] / c["device_total_ms"], 2),
        "baseline": f"strongest host membership path on resident mirrors at "
                    f"{top['targets']} target keys (clustered hits)",
        "sweep": sweep,
        "link_MBps": {"up": round(lp.up_mbps, 1),
                      "down": round(lp.down_mbps, 1),
                      "latency_ms": round(lp.latency_s * 1000, 1)},
        "note": "device_total is the public probe_async round trip (source "
                "upload + fused sorted-slab kernel + head + compacted "
                "O(matched) pair fetch); attached_chip_extrapolated "
                "re-prices only the link terms at PCIe 12 GB/s + 0.2 ms",
    }


# -- config 11: fleet observability plane ------------------------------------


def bench_fleet(workdir):
    """Config 11: K registered tables x a skewed (one-hot-table) commit +
    scan workload. Measures what the fleet plane costs and what it serves:

    * scraper steady-state overhead — the same workload with the
      ``delta-obs-scraper`` daemon OFF vs ON (hot 100ms interval, SLO
      evaluation riding every scrape), and the same pair again under a
      telemetry blackout, where the ON leg must cost ≈0 (the blackout
      guarantee: a ticking scraper does no registry work);
    * /fleet and /slo route latency (p50/p95 over N GETs) with the rings
      warm and a live doctor sweep per /fleet request.
    """
    import http.client

    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.obs import fleet, slo, timeseries
    from delta_tpu.obs.server import ObsServer
    from delta_tpu.utils.config import conf

    K = 6
    ops_per_leg = max(int(400 * min(SCALE, 2.0)), 40)
    base = os.path.join(workdir, "fleet")
    rng = np.random.RandomState(7)

    def ids(n, start=0):
        import pyarrow as pa

        return pa.table({"id": np.arange(start, start + n).astype("int64")})

    tables = []
    for i in range(K):
        path = f"{base}/t{i}"
        tables.append(DeltaTable.create(path, data=ids(2000)))

    # skew: table 0 takes ~half the traffic (the hot-table case the SLO
    # attribution exists for)
    picks = np.where(rng.rand(ops_per_leg) < 0.5, 0,
                     rng.randint(1, K, ops_per_leg))

    def leg():
        # overwrite, not append: a leg must not grow the tables and bias
        # the next leg's scan/commit cost (the on-vs-off comparison needs
        # identical work per leg)
        for j, i in enumerate(picks):
            t = tables[int(i)]
            if j % 3 == 0:
                t.write(ids(50, start=10_000 + 50 * j), mode="overwrite")
            else:
                t.to_arrow(filters=[f"id < {50 + (j % 200)}"])

    leg()  # warm caches/JITs so the off leg isn't paying one-time costs
    timeseries.reset()
    slo.reset()
    # interleaved min-of-2 per leg (config 9's idiom): off/on/off/on, so
    # drift affects both legs alike and host noise is floored by the min
    def on_leg():
        with conf.set_temporarily(
                **{"delta.tpu.obs.scrape.intervalMs": 100}):
            timeseries.start_scraper()
            try:
                return _timed(leg)[0]
            finally:
                timeseries.stop_scraper()

    # ABBA order: the log tail grows a little every leg, so a fixed
    # off-then-on order would bill that drift entirely to the ON side
    offs, ons = [], []
    offs.append(_timed(leg)[0]); ons.append(on_leg())
    ons.append(on_leg()); offs.append(_timed(leg)[0])
    off_s, on_s = min(offs), min(ons)
    scrapes_on = timeseries.scrape_count()
    overhead_pct = (on_s / off_s - 1.0) * 100.0

    # blackout pair: the scraper daemon ticking over a disabled registry.
    # Rings reset first so the leg's own counts are what gets asserted —
    # the ON leg above legitimately filled them
    timeseries.reset()
    slo.reset()
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        dark_offs, dark_ons = [], []
        dark_offs.append(_timed(leg)[0]); dark_ons.append(on_leg())
        dark_ons.append(on_leg()); dark_offs.append(_timed(leg)[0])
        dark_off_s, dark_on_s = min(dark_offs), min(dark_ons)
        dark_scrapes = timeseries.scrape_count()
        dark_series = len(timeseries.series_snapshot()["counters"])
    blackout_overhead_pct = (dark_on_s / dark_off_s - 1.0) * 100.0

    # route latency with the rings warm and the registry full
    with conf.set_temporarily(
            **{"delta.tpu.obs.scrape.intervalMs": 100}):
        timeseries.start_scraper()
        srv = ObsServer(port=0)
        try:
            def get(route):
                c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                               timeout=30)
                try:
                    c.request("GET", route)
                    r = c.getresponse()
                    assert r.status == 200, route
                    return r.read()
                finally:
                    c.close()

            get("/fleet")  # warm the sweep path once
            n_req = 30
            fleet_ms = sorted(
                _timed(lambda: get("/fleet"))[0] * 1000
                for _ in range(n_req))
            slo_ms = sorted(
                _timed(lambda: get("/slo"))[0] * 1000
                for _ in range(n_req))
            fleet_doc = json.loads(get("/fleet"))
        finally:
            srv.stop()
            timeseries.stop_scraper()

    assert fleet_doc["tables"] >= K
    ranked = fleet_doc["sweep"]["entries"]

    def pct(samples, q):
        # upper-rounded index: p95 over 30 samples is the 29th, not ~p91
        import math

        return round(samples[min(len(samples) - 1,
                                 math.ceil(q * len(samples)) - 1)], 2)

    p50 = pct(fleet_ms, 0.50)
    return {
        "metric": "fleet_route_p50_ms",
        "value": p50,
        "unit": "ms",
        "vs_baseline": 0,
        "baseline": "no prior fleet plane: first-round absolute numbers",
        "tables": K,
        "ops_per_leg": ops_per_leg,
        "route_fleet_ms": {"p50": p50, "p95": pct(fleet_ms, 0.95)},
        "route_slo_ms": {"p50": pct(slo_ms, 0.50),
                         "p95": pct(slo_ms, 0.95)},
        "scraper": {
            "off_s": round(off_s, 3), "on_s": round(on_s, 3),
            "overhead_pct": round(overhead_pct, 2),
            "scrapes_during_leg": scrapes_on,
        },
        "blackout": {
            "off_s": round(dark_off_s, 3), "on_s": round(dark_on_s, 3),
            "overhead_pct": round(blackout_overhead_pct, 2),
            "scrapes": dark_scrapes, "series": dark_series,
            "inert": _assert_blackout_inert(dark_scrapes, dark_series),
        },
        "sweep_ranked_tables": len(ranked),
        "gate": {
            "route_slo_p50_ms": {
                "value": pct(slo_ms, 0.50), "unit": "ms"},
            "sweep_tables": {"value": len(ranked), "unit": "tables"},
        },
        "note": "overhead legs share one warmed workload fn in ABBA "
                "order (min-of-2 per side) and run the scraper at 100ms "
                "— 100x hotter than the 10s default; measured on/off "
                "deltas land within this host's ±15% wall-clock noise "
                "band in BOTH directions across rounds, i.e. the "
                "steady-state cost is not distinguishable from zero at "
                "this cadence (and is ~1/100th of whatever it is at the "
                "default 10s). blackout inert=true is the structural "
                "assertion: zero scrapes recorded AND zero series "
                "retained while the daemon ticked through the dark leg",
    }


def _assert_blackout_inert(scrapes, series):
    # the blackout guarantee is ASSERTED, not just recorded: a scraper that
    # does registry work under blackout must fail the config (the wall-
    # clock delta stays recorded-only — it is host-noise-bound)
    assert scrapes == 0 and series == 0, (
        f"blackout leg not inert: scrapes={scrapes} series={series}")
    return True


# -- config 13: shadow optimizer — what-if replay + SLO capacity burn --------


def bench_shadow(workdir):
    """Config 13: the shadow optimizer end to end at bench scale.

    Journals a clustered-vs-unclustered workload (files clustered on
    ``a``, ``v`` permuted inside every file; selective ``v`` point scans
    plus file-pruned ``a`` range scans), reconstructs the trace from the
    journal (every literal rehydrated from the reservoir — zero
    synthesis), then times one ``shadow_run`` over two candidates:

    * ``ZORDER:v`` under fine row groups — the rewrite that genuinely wins
      (point scans prune nearly every group) → must score ``confirmed``;
    * ``ROW_GROUP_ROWS:4194304`` — recoarsen/compact, which destroys the
      file-tier ``a`` clustering for zero gain → must score ``refuted``
      on the measured read-side loss.

    Both verdicts are ASSERTED, not just recorded: a scoring regression
    that lets the bad rewrite through (or refutes the good one) fails the
    config. The capacity leg replays the zipf hot-key storm scenario at
    10x and 100x against the live scraper/SLO plane and asserts the
    ``scanPlanningP99`` objective fires at BOTH compressions, then resets
    the rings. Headline = shadow_run wall (trace replay x 3: baseline +
    2 sandboxed candidate rewrites)."""
    import pyarrow as pa

    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.obs import journal, slo, timeseries
    from delta_tpu.replay import (Candidate, build_trace, capacity_replay,
                                  shadow_run, zipf_hot_key_storm)
    from delta_tpu.utils.config import conf

    rows_total = _rows(2_000_000)
    per_file = max(rows_total // 4, 2000)
    rng = np.random.RandomState(5)
    path = os.path.join(workdir, "shadow_t")

    def part(base):
        return pa.table({
            "id": np.arange(base, base + per_file).astype("int64"),
            "a": np.arange(base, base + per_file).astype("int64"),
            "v": rng.permutation(per_file).astype("int64"),
        })

    # every scan keeps its own literal (the default 3-sample reservoir
    # would collapse later same-shape scans onto the first literal)
    with conf.set_temporarily(**{"delta.tpu.journal.literalSamples": 16}):
        t = DeltaTable.create(path, data=part(0))
        for i in range(1, 4):
            t.write(part(i * per_file), mode="append")
        for i in range(6):
            t.to_arrow(filters=[f"v = {i * 13}"])  # selective: 1 hit/file
        for _ in range(4):
            t.to_arrow(filters=[f"a < {per_file // 20}"])  # file-pruned
    journal.flush()

    build_s, trace = _timed(lambda: build_trace(t.delta_log))
    # every literal must come out of the reservoir — a synthesis fallback
    # here means the reservoir stamping regressed
    assert trace.synthesized_literals == 0, trace.to_dict()
    assert trace.counts()["scan"] == 10

    sandbox_root = os.path.join(workdir, "shadow_sandboxes")
    os.makedirs(sandbox_root, exist_ok=True)
    cands = [Candidate("ZORDER", {"columns": ["v"]}),
             Candidate("ROW_GROUP_ROWS", {"rows": 4_194_304})]
    # candidate rewrites land under fine row groups; the baseline clone
    # keeps the live table's coarse layout — the granularity the ZORDER
    # win is measured against
    with conf.set_temporarily(**{
            "delta.tpu.write.rowGroupRows": 8192,
            "delta.tpu.replay.sandboxDir": sandbox_root}):
        shadow_s, card = _timed(lambda: shadow_run(
            t.delta_log, trace=trace, candidates=cands))

    top = card.top
    assert (top["candidate"]["label"] == "ZORDER:v"
            and top["verdict"] == "confirmed" and top["score"] > 0), card.to_dict()
    [bad] = [r for r in card.candidates
             if r["candidate"]["label"] == "ROW_GROUP_ROWS:4194304"]
    assert bad["verdict"] == "refuted" and bad["score"] < 0, bad
    assert os.listdir(sandbox_root) == []  # sandbox never leaks clones

    # capacity leg: same storm, two compressions, same objective fired.
    # The replay deliberately writes into the live rings; reset after.
    storm = zipf_hot_key_storm(path=path)
    caps = {}
    with conf.set_temporarily(**{"delta.tpu.obs.slo.minObservations": 4}):
        for speed in (10.0, 100.0):
            slo.reset()
            timeseries.reset()
            wall, rep = _timed(lambda s=speed: capacity_replay(
                storm, speed=s, now_ms=1_000_000_000_000))
            assert rep["objectives"] == ["scanPlanningP99"], rep
            caps[f"{int(speed)}x"] = {
                "wall_s": round(wall, 3),
                "events": rep["events"],
                "scrapes": rep["scrapes"],
                "simulated_ms": rep["simulatedMs"],
                "original_ms": rep["originalMs"],
                "objectives": rep["objectives"],
            }
    slo.reset()
    timeseries.reset()

    return {
        "metric": "shadow_run_s",
        "value": round(shadow_s, 3),
        "unit": "s",
        "vs_baseline": 0,
        "baseline": "no prior shadow optimizer: first-round absolute numbers",
        "rows": rows_total,
        "files": 4,
        "scans_journaled": 10,
        "trace": {"build_s": round(build_s, 3),
                  "scans": trace.counts()["scan"],
                  "synthesized_literals": trace.synthesized_literals},
        "scorecard": {
            "top": top["candidate"]["label"],
            "top_verdict": top["verdict"],
            "top_score": top["score"],
            "top_deltas": top["deltas"],
            "bad": bad["candidate"]["label"],
            "bad_verdict": bad["verdict"],
            "bad_score": bad["score"],
            "candidates": len(card.candidates),
        },
        "capacity": caps,
        "gate": {
            "trace_build_ms": {"value": round(build_s * 1000, 1),
                               "unit": "ms"},
            "capacity_10x_ms": {"value": round(caps["10x"]["wall_s"] * 1000,
                                               1), "unit": "ms"},
            "confirmed_candidates": {
                "value": sum(1 for r in card.candidates
                             if r["verdict"] == "confirmed"),
                "unit": "candidates"},
        },
        "note": "shadow_run wall covers trace replay x3 (baseline clone + "
                "2 candidate rewrites: a full ZORDER of the table under "
                "8192-row groups and a full recoarsen compaction) in a "
                "throwaway sandbox. Verdicts are structural assertions: "
                "ZORDER:v confirmed on measured bytes no longer read + "
                "newly skipped, the recoarsen refuted on the measured "
                "file-pruning loss, and the 10x/100x capacity replays "
                "must fire scanPlanningP99 — any flip fails the config",
    }


# -- config 9: sustained-contention commit path (group commit) ---------------


def bench_commit_contention(workdir):
    """Config 9: K writer threads x M commits each against one table —
    mostly blind appends plus a conflicting-DML fraction (non-blind
    read-then-add txns) — three interleaved trials of grouping + async
    incremental checkpointing OFF (the baseline leg) then ON, latency
    samples pooled per leg. Records throughput and pooled p50/p99 commit
    latency per leg; headline = p99 commit-latency improvement (higher is
    better). The ungrouped leg pays the per-writer list/read-tail/CAS
    cycle and the every-10th-commit synchronous checkpoint stall that
    ISSUE 9 targets."""
    import threading

    from delta_tpu import DeltaLog
    from delta_tpu.commands import operations as ops_mod
    from delta_tpu.log import checkpointer
    from delta_tpu.protocol.actions import AddFile, Metadata
    from delta_tpu.schema.types import LongType, StructType
    from delta_tpu.utils import errors as errors_mod
    from delta_tpu.utils.config import conf

    K = int(os.environ.get("BENCH_CONTENTION_WRITERS", "16"))
    M = int(os.environ.get("BENCH_CONTENTION_COMMITS", "40"))
    conflict_every = 5  # every 5th commit per writer is non-blind

    schema = StructType().add("id", LongType()).add("v", LongType())

    # contention is a LOCK/LISTING/BATCHING phenomenon: on a shared CI
    # filesystem (virtio-9p here) other tenants' fsync bursts inject
    # multi-second stalls into random commits of either leg, swamping the
    # leg comparison with noise that has nothing to do with the commit
    # path. A RAM-backed dir keeps the measured tail the engine's own.
    base = workdir
    if os.access("/dev/shm", os.W_OK):
        base = tempfile.mkdtemp(prefix="delta_tpu_bench_c9_", dir="/dev/shm")

    def _leg(name, grouped):
        path = os.path.join(base, f"c9_{name}")
        log = DeltaLog.for_table(path)
        txn = log.start_transaction()
        txn.update_metadata(Metadata(schema_string=schema.to_json()))
        txn.commit([], ops_mod.ManualUpdate())

        latencies = [[] for _ in range(K)]
        conflicts = [0] * K
        barrier = threading.Barrier(K + 1)

        def writer(w):
            barrier.wait()
            for i in range(M):
                try:
                    t = log.start_transaction()
                    add = AddFile(
                        f"w{w}-{i:05d}.parquet", {}, 4096, 1, True,
                        stats='{"numRecords":128,"minValues":{"id":0},'
                              '"maxValues":{"id":127},"nullCount":{"id":0}}',
                    )
                    if i % conflict_every == conflict_every - 1:
                        t.filter_files()  # records the read: non-blind txn
                    # time the commit() call only — the list/read-tail/
                    # conflict-check/CAS cycle grouping amortizes; the
                    # read-side snapshot listing in start_transaction is
                    # identical in both legs and would only dilute the leg
                    # comparison with shared noise
                    t0 = time.perf_counter()
                    t.commit([add], ops_mod.Write("Append"))
                    latencies[w].append(time.perf_counter() - t0)
                except errors_mod.DeltaConcurrentModificationException:
                    conflicts[w] += 1

        overrides = {
            "delta.tpu.commit.group.enabled": grouped,
            "delta.tpu.commit.group.maxWaitMs": 3,
            "delta.tpu.checkpoint.async": grouped,
            "delta.tpu.checkpoint.incremental": grouped,
        }
        with conf.set_temporarily(**overrides):
            threads = [threading.Thread(target=writer, args=(w,))
                       for w in range(K)]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            # async builds drain OUTSIDE the timed window: that is the
            # design (they are off the commit's critical path), but the
            # work must still complete inside this config's deadline
            checkpointer.flush()
        return {"lats": [x for per in latencies for x in per],
                "conflicts": sum(conflicts), "wall_s": wall}

    def _pooled(runs):
        """Aggregate one leg's interleaved trials: percentiles over the
        POOLED latency samples (a single trial's p99 rides on ~3 tail
        samples and is noisy on a shared box; pooling triples the tail),
        throughput over the summed walls."""
        lats = sorted(x for r in runs for x in r["lats"])
        ok = len(lats)
        wall = sum(r["wall_s"] for r in runs)

        def _pct(p):
            return lats[min(ok - 1, int(p * ok))] * 1000 if ok else -1.0

        def _trial_p99(r):
            s = sorted(r["lats"])
            return round(s[min(len(s) - 1, int(0.99 * len(s)))] * 1000, 2) \
                if s else -1.0

        return {
            "commits_ok": ok,
            "conflicts": sum(r["conflicts"] for r in runs),
            "wall_s": round(wall, 3),
            "throughput_cps": round(ok / wall, 1) if wall > 0 else -1.0,
            "p50_ms": round(_pct(0.50), 2),
            "p99_ms": round(_pct(0.99), 2),
            "trial_p99_ms": [_trial_p99(r) for r in runs],
        }

    # three interleaved off/on trials: interleaving decorrelates machine
    # drift from the leg comparison, pooling stabilizes the tail estimate
    try:
        trials = [(_leg(f"off{i}", grouped=False),
                   _leg(f"on{i}", grouped=True))
                  for i in range(3)]
    finally:
        if base is not workdir:
            shutil.rmtree(base, ignore_errors=True)
    ungrouped = _pooled([t[0] for t in trials])
    grouped = _pooled([t[1] for t in trials])
    speedup = (round(ungrouped["p99_ms"] / grouped["p99_ms"], 2)
               if grouped["p99_ms"] > 0 else -1.0)
    return {
        "metric": f"commit_p99_speedup_grouped_vs_ungrouped_{K}w",
        "value": speedup,
        "unit": "x",
        "vs_baseline": speedup,
        "baseline": "same workload, grouping + async checkpointing off",
        "writers": K,
        "commits_per_writer": M,
        "conflict_fraction": round(1.0 / conflict_every, 2),
        "ungrouped": ungrouped,
        "grouped": grouped,
        # sub-metrics the --compare gate walks direction-aware
        # (tools/bench_diff): p99 regresses when it GROWS, throughput when
        # it SHRINKS
        "gate": {
            "grouped_p99_ms": {"value": grouped["p99_ms"], "unit": "ms"},
            "grouped_throughput": {"value": grouped["throughput_cps"],
                                   "unit": "commits/s"},
            "p99_speedup": {"value": speedup, "unit": "x"},
        },
    }


# -- config 14: sharded scan planning + distributed OPTIMIZE/MERGE -----------


def bench_sharded_scan_worker():
    """Hidden worker for config 14 (``14w`` — subprocess only, the full
    sweep skips ``*w`` keys): 256-query batched scan planning on resident
    lanes, single-device vs shard_map-sharded over the mesh, identity vs the
    host planner asserted per query. Runs in its OWN process because the
    device count is fixed at first backend init — the parent forces an
    8-virtual-device CPU mesh via XLA_FLAGS without perturbing its own
    topology (or real accelerators, where the flag is inert)."""
    import jax

    from delta_tpu.expr.parser import parse_expression
    from delta_tpu.ops import pruning
    from delta_tpu.ops.state_cache import ResidentState, extract_ranges
    from delta_tpu.utils.config import conf as _c

    n_files = 6000  # capacity 8192: lanes shard into whole 1024-file blocks
    n_q = 256
    reps = 5
    rng = np.random.RandomState(14)
    cols = ["a", "b", "c", "d"]
    lo = rng.rand(len(cols), n_files) * 1000.0
    hi = lo + rng.rand(len(cols), n_files) * 50.0
    entry = ResidentState(
        "bench://c14", "mid", 0, cols, [f"f{i}" for i in range(n_files)],
        {"min": lo, "max": hi, "size": np.ones(n_files, np.int64)},
    )
    ranges = []
    for i in range(n_q):
        c = cols[i % len(cols)]
        a0 = (i * 37) % 950
        pred = pruning.skipping_predicate(
            parse_expression(f"{c} >= {a0} AND {c} <= {a0 + 40}"),
            frozenset())
        r = extract_ranges(pred, cols)
        assert r is not None
        ranges.append(r)
    host = entry.plan_ranges(ranges, k=n_files, use_device=False)

    def leg(enabled):
        # existing residency wins shard planning, so re-place per leg
        entry.drop_device()
        with _c.set_temporarily(**{
            "delta.tpu.distributed.plan.enabled": enabled,
            "delta.tpu.distributed.plan.mode": "force",
            "delta.tpu.stateCache.devicePlan.mode": "force",
        }):
            plans = entry.plan_ranges(ranges, k=n_files, use_device=True)
            shards = entry.resident_shards
            t0 = time.perf_counter()
            for _ in range(reps):
                plans = entry.plan_ranges(ranges, k=n_files, use_device=True)
            wall = (time.perf_counter() - t0) / reps
        # identity per query: the sharded coarse cull + host fine pass must
        # return EXACTLY the single-route plan rows
        for hp, dp in zip(host, plans):
            assert list(dp.rows) == list(hp.rows), "sharded plan != host"
        return wall, shards

    single_s, s1 = leg(False)
    sharded_s, s8 = leg(True)
    assert s1 == 1, s1
    ratio = single_s / max(sharded_s, 1e-9)
    platform = jax.devices()[0].platform
    accelerated = platform not in ("cpu",)
    return {
        "metric": "sharded_plan_throughput_vs_single",
        "value": round(ratio, 2) if accelerated else -1,
        "unit": "x" if accelerated else "skipped",
        "vs_baseline": round(ratio, 2),
        "platform": platform,
        "n_devices": len(jax.devices()),
        "shards": s8,
        "plan_single_s": round(single_s, 4),
        "plan_sharded_s": round(sharded_s, 4),
        "throughput_ratio": round(ratio, 3),
        "efficiency": round(ratio / max(s8, 1), 4),
        "queries": n_q,
        "files": n_files,
        "identity": True,
    }


def bench_sharded_scan(workdir):
    """Config 14 — the sharded execution plane, 1-vs-8 (ISSUE 18).

    Three legs, each under its own deadline, record-and-continue:

      plan     — subprocess (``bench.py 14w``) on a forced 8-virtual-device
                 mesh: batched scan planning single-device vs shard_map-
                 sharded lanes, identity vs the host planner asserted
      optimize — in-process: the same partitioned table compacted with
                 workers=1 vs workers=8 (LPT seed + work stealing), row
                 identity and file-topology identity asserted, per-worker
                 timings and steals recorded
      merge    — in-process: probe-restricted MERGE vs probe-off on clone
                 tables, result identity asserted, probe speedup measured

    Headline: sharded-vs-single planning throughput at 8 shards. On a
    CPU-only host the 8 "devices" are one physical CPU, so the throughput
    claim is skip-recorded (value -1, unit "skipped") — the measured
    numbers and the deterministic LPT zipf-balance gate still ride the
    artifact, and ``--compare`` walks the gate sub-metrics direction-aware.
    """
    import subprocess

    import jax
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.exec.scan import scan_to_table
    from delta_tpu.parallel.distributed import bytes_skew, lpt_assign
    from delta_tpu.utils.config import conf as _c

    legs = {}

    def _leg(name, budget_s, fn):
        t0 = time.perf_counter()
        try:
            legs[name] = fn(budget_s)
            legs[name]["wall_s"] = round(time.perf_counter() - t0, 3)
        except subprocess.TimeoutExpired:
            legs[name] = {"skipped": f"leg deadline {budget_s:.0f}s breached"}
        except Exception as e:  # noqa: BLE001 — per-leg record-and-continue
            legs[name] = {"error": f"{type(e).__name__}: {e}"[:300]}

    # plan leg runs in a subprocess: the forced 8-device mesh must not leak
    # into the parent's jax (device count is fixed at first backend init)
    def _plan(budget_s):
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "14w"],
            capture_output=True, text=True, timeout=budget_s, env=env)
        if proc.returncode != 0:
            return {"error": (proc.stderr or proc.stdout)[-300:]}
        return json.loads(proc.stdout.strip().splitlines()[-1])

    _leg("plan", 240, _plan)

    rows_per = max(_rows(400_000) // 32, 1000)

    def _mk(path, rng):
        log = DeltaLog.for_table(path)
        for p in range(8):
            for f in range(4):
                base = (p * 4 + f) * rows_per
                WriteIntoDelta(log, "append", pa.table({
                    "id": np.arange(base, base + rows_per, dtype=np.int64),
                    "part": pa.array([f"p{p}"] * rows_per),
                    "v": rng.rand(rows_per),
                }), partition_columns=["part"]).run()
        return log

    def _optimize(budget_s):
        seq = _mk(os.path.join(workdir, "c14_seq"), np.random.RandomState(3))
        par = _mk(os.path.join(workdir, "c14_par"), np.random.RandomState(3))
        c1 = OptimizeCommand(seq, min_file_size=1 << 30, workers=1)
        t1, _ = _timed(c1.run)
        c8 = OptimizeCommand(par, min_file_size=1 << 30, workers=8)
        t8, _ = _timed(c8.run)
        # worker count must be invisible: same rows, same file topology
        a = scan_to_table(seq.update()).sort_by("id")
        b = scan_to_table(par.update()).sort_by("id")
        assert a.equals(b), "parallel OPTIMIZE diverged from sequential"
        assert c1.metrics["numRemovedFiles"] == \
            c8.metrics["numRemovedFiles"] == 32
        assert c1.metrics["numAddedFiles"] == c8.metrics["numAddedFiles"]
        rep = c8.shard_report
        return {
            "rows": 32 * rows_per,
            "workers1_s": round(t1, 3),
            "workers8_s": round(t8, 3),
            "speedup": round(t1 / max(t8, 1e-9), 2),
            "groups": len(rep.results),
            "steals": rep.steals,
            "skew": round(rep.skew, 4),
            "per_worker": rep.timings(),
        }

    _leg("optimize", 150, _optimize)

    def _merge(budget_s):
        mrows = max(_rows(160_000) // 32, 1000)

        def mk(path):
            log = DeltaLog.for_table(path)
            for i in range(32):
                base = i * mrows
                WriteIntoDelta(log, "append", pa.table({
                    "id": np.arange(base, base + mrows, dtype=np.int64),
                    "v": np.arange(base, base + mrows, dtype=np.float64),
                })).run()
            return log

        # 2 updates landing in 2 of the 32 files + 1 insert past the range
        src = pa.table({
            "id": pa.array([7, 3 * mrows + 11, 32 * mrows + 5], pa.int64()),
            "v": pa.array([-1.0, -2.0, -3.0]),
        })
        up = MergeClause("update", assignments=None)
        ins = MergeClause("insert", assignments=None)
        off_log = mk(os.path.join(workdir, "c14_moff"))
        with _c.set_temporarily(
            **{"delta.tpu.distributed.merge.probe.enabled": False}
        ):
            m_off = MergeIntoCommand(off_log, src, "t.id = s.id", [up], [ins],
                                     source_alias="s", target_alias="t")
            t_off, _ = _timed(m_off.run)
        on_log = mk(os.path.join(workdir, "c14_mon"))
        m_on = MergeIntoCommand(on_log, src, "t.id = s.id", [up], [ins],
                                source_alias="s", target_alias="t")
        t_on, _ = _timed(m_on.run)
        a = scan_to_table(off_log.update()).sort_by("id")
        b = scan_to_table(on_log.update()).sort_by("id")
        assert a.to_pylist() == b.to_pylist(), "probe changed MERGE results"
        assert m_on.metrics["numTargetRowsUpdated"] == 2
        assert m_on.metrics["numTargetRowsInserted"] == 1
        assert m_on.metrics["numTargetFilesRemoved"] <= 2
        return {
            "files": 32,
            "probe_off_s": round(t_off, 3),
            "probe_on_s": round(t_on, 3),
            "probe_speedup": round(t_off / max(t_on, 1e-9), 2),
            "files_removed": m_on.metrics["numTargetFilesRemoved"],
            "probe_ms": m_on.phase_ms.get("probe_ms"),
        }

    _leg("merge", 90, _merge)

    # the LPT balance gate is deterministic (pure function of the zipf
    # population), so --compare can hold it to the skew unit regardless of
    # host speed: growth past threshold = a load-balance regression
    zipf = [1_000_000 // (i + 1) + 1 for i in range(100_000)]
    lpt_skew = bytes_skew(zipf, lpt_assign(zipf, 8))
    strided_skew = bytes_skew(
        zipf, [list(range(h, 100_000, 8)) for h in range(8)])

    plan = legs.get("plan", {})
    ratio = plan.get("throughput_ratio")
    ok = isinstance(ratio, (int, float)) and ratio > 0
    platform = jax.devices()[0].platform
    accelerated = platform not in ("cpu",)
    if accelerated and ok:
        # the scaling-efficiency acceptance where hardware allows it
        assert ratio >= 2.0, f"8-shard planning only {ratio:.2f}x single"
    result = {
        "metric": "sharded_plan_throughput_8shard_vs_single",
        "value": round(ratio, 2) if (accelerated and ok) else -1,
        "unit": "x" if (accelerated and ok) else "skipped",
        "vs_baseline": round(ratio, 2) if ok else 0,
        "platform": platform,
        "legs": legs,
        "lpt_zipf": {"strided_skew": round(strided_skew, 3),
                     "lpt_skew": round(lpt_skew, 5)},
        "gate": {
            "lpt_zipf_skew": {"value": round(lpt_skew, 5), "unit": "skew"},
            "scaling_efficiency": {
                "value": (round(plan.get("efficiency", -1.0), 4)
                          if (accelerated and ok) else -1),
                "unit": "x" if (accelerated and ok) else "skipped",
            },
        },
    }
    if not accelerated:
        result["note"] = (
            "skipped: CPU-only host — the 8-shard mesh is one physical CPU, "
            "so the throughput claim needs real devices; measured numbers "
            "and the balance gate are recorded in legs/gate")
    return result


def bench_trace_overhead(workdir):
    """Config 15 — distributed-tracing overhead on the sharded OPTIMIZE leg
    (ISSUE 19).

    The same partitioned compaction (pool path: job/worker/item spans) runs
    under three postures, reps interleaved so clock drift lands on every
    variant equally:

      sampled    — ``trace.sampleRate=1`` + a spool dir: every span is
                   serialized and appended to the JSONL spool
      unsampled  — ``trace.sampleRate=0`` + a spool dir: head sampling says
                   no; the claim is the sink never runs AND the spool dir
                   is never created
      disabled   — telemetry off entirely: the floor the others compare to

    Headline: the tracing plane's marginal cost when sampled — the median
    of the per-rep ``sampled/unsampled`` wall ratios (pairing adjacent runs
    cancels the slow drift that dominates run-to-run noise at this scale).
    ``unsampled/disabled`` is the context number: the whole telemetry plane
    vs blackout, of which tracing-off must add nothing. The inertness
    claims are hard-asserted (rate 0 must write NOTHING); the timing claims
    ride a findings-style gate — ``0`` means both hold (sampled-on < 5%,
    unsampled-vs-disabled within the disabled variant's own rep spread),
    and any regression reads as new findings for ``--compare``.
    """
    import statistics

    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.obs import trace_store
    from delta_tpu.utils.config import conf as _c

    rows_per = max(_rows(240_000) // 24, 500)
    reps = 6

    def _mk(path):
        log = DeltaLog.for_table(path)
        for p in range(8):
            for f in range(3):
                base = (p * 3 + f) * rows_per
                WriteIntoDelta(log, "append", pa.table({
                    "id": np.arange(base, base + rows_per, dtype=np.int64),
                    "part": pa.array([f"p{p}"] * rows_per),
                    "v": np.arange(base, base + rows_per, dtype=np.float64),
                }), partition_columns=["part"]).run()
        return log

    spools = {v: os.path.join(workdir, f"c15_spool_{v}")
              for v in ("sampled", "unsampled")}
    variants = {
        "sampled": {"delta.tpu.trace.dir": spools["sampled"],
                    "delta.tpu.trace.sampleRate": 1.0},
        "unsampled": {"delta.tpu.trace.dir": spools["unsampled"],
                      "delta.tpu.trace.sampleRate": 0.0},
        "disabled": {"delta.tpu.telemetry.enabled": False},
    }
    times = {v: [] for v in variants}
    # rep -1 is an untimed warm-up sweep: the first compaction pays JIT and
    # first-touch caches, and must not land on whichever variant runs first
    for rep in range(-1, reps):
        for v, knobs in variants.items():
            log = _mk(os.path.join(workdir, f"c15_{v}_{rep}"))
            cmd = OptimizeCommand(log, min_file_size=1 << 30, workers=4)
            with _c.set_temporarily(**knobs):
                t, _ = _timed(cmd.run)
            if rep >= 0:
                times[v].append(t)
            assert cmd.metrics["numRemovedFiles"] == 24
    trace_store.reset()

    spooled = len(trace_store.read_spools(spools["sampled"]))
    # the knobs must be provably inert: rate 0 writes NOTHING — the sink
    # never ran, so the spool directory was never even created
    assert spooled > 0, "sampled variant spooled no spans"
    assert not os.path.exists(spools["unsampled"]), \
        "sampleRate=0 still touched the spool"

    med = {v: statistics.median(ts) for v, ts in times.items()}
    # paired ratios: within one rep the variants run back to back, so the
    # slow drift (freq scaling, background load) divides out of the ratio
    on_pct = (statistics.median(
        s / u for s, u in zip(times["sampled"], times["unsampled"])
    ) - 1.0) * 100.0
    off_pct = (statistics.median(
        u / d for u, d in zip(times["unsampled"], times["disabled"])
    ) - 1.0) * 100.0
    # noise floor: the disabled variant's own interquartile spread (≥ 2%)
    d_sorted = sorted(times["disabled"])
    q = max(reps // 4, 1)
    noise_pct = max((d_sorted[-1 - q] - d_sorted[q]) / med["disabled"]
                    * 100.0, 2.0)
    violations = int(on_pct >= 5.0) + int(abs(off_pct) > noise_pct)
    return {
        "metric": "trace_overhead_sampled_pct",
        "value": round(max(on_pct, 0.0), 2),
        "unit": "pct",
        "vs_baseline": round(on_pct, 2),
        "reps": reps,
        "rows": 24 * rows_per,
        "files_compacted": 24,
        "median_s": {v: round(t, 4) for v, t in med.items()},
        "times_s": {v: [round(t, 4) for t in ts]
                    for v, ts in times.items()},
        "sampled_on_overhead_pct": round(on_pct, 2),
        "sampled_off_overhead_pct": round(off_pct, 2),
        "noise_pct": round(noise_pct, 2),
        "spans_spooled_sampled": spooled,
        "gate": {
            "trace_overhead_claims_violated": {
                "value": violations, "unit": "findings"},
        },
    }


def bench_dist_faults(workdir):
    """Config 16 — the price of fault tolerance on the sharded plane
    (ISSUE 20).

    Three legs, each under its own deadline, record-and-continue:

      retry       — the same partitioned compaction clean vs under 4
                    scripted transient ``dist.itemExec`` faults: every
                    fault retries to success (zero quarantine), row and
                    file-topology identity asserted, the fault run's
                    overhead over clean measured
      speculation — a seeded straggler workload on ``run_sharded`` with
                    speculative re-dispatch on vs off: the supervisor's
                    rescue must beat waiting out the wedged attempt
                    (hard-asserted — this is the config's headline)
      recovery    — 2-host posed OPTIMIZE where host 1 crashes mid-slice
                    after publishing its lease; the coordinator reconciles
                    the orphan — end state identical to a single-process
                    run, recovery overhead over that solo run measured

    Headline: speculation speedup vs no-speculation on the straggler leg.
    The gate rides two sub-metrics: ``dist_fault_identity_violations``
    (findings — any leg that errors or diverges from its fault-free
    reference) and ``recovery_overhead_pct`` (pct — what the crash +
    lease recovery cost over the solo compaction).
    """
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.optimize import OptimizeCommand
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.exec.scan import scan_to_table
    from delta_tpu.parallel import distributed as dist_mod
    from delta_tpu.parallel import leases
    from delta_tpu.parallel.executor import run_sharded
    from delta_tpu.storage.faults import FaultPlan, SimulatedCrash
    from delta_tpu.utils import telemetry
    from delta_tpu.utils.config import conf as _c

    legs = {}

    def _leg(name, budget_s, fn):
        t0 = time.perf_counter()
        try:
            legs[name] = fn(budget_s)
            legs[name]["wall_s"] = round(time.perf_counter() - t0, 3)
        except Exception as e:  # noqa: BLE001 — per-leg record-and-continue
            legs[name] = {"error": f"{type(e).__name__}: {e}"[:300]}

    rows_per = max(_rows(96_000) // 32, 500)

    def _mk(path, rng):
        log = DeltaLog.for_table(path)
        for p in range(8):
            for f in range(4):
                base = (p * 4 + f) * rows_per
                WriteIntoDelta(log, "append", pa.table({
                    "id": np.arange(base, base + rows_per, dtype=np.int64),
                    "part": pa.array([f"p{p}"] * rows_per),
                    "v": rng.rand(rows_per),
                }), partition_columns=["part"]).run()
        return log

    def _rows_files(log):
        snap = DeltaLog.for_table(log.data_path).update()
        return (sorted(scan_to_table(snap, [], ["id"])
                       .column("id").to_pylist()), snap.num_of_files)

    fast_retry = {"delta.tpu.distributed.retry.baseDelayMs": 1,
                  "delta.tpu.distributed.retry.maxDelayMs": 10}

    def _retry(budget_s):
        # untimed warm-up: the first compaction pays JIT and first-touch
        # caches, and must not land on the clean side of the overhead ratio
        warm = _mk(os.path.join(workdir, "c16_warm"),
                   np.random.RandomState(5))
        OptimizeCommand(warm, min_file_size=1 << 30, workers=4).run()
        clean = _mk(os.path.join(workdir, "c16_clean"),
                    np.random.RandomState(7))
        faulted = _mk(os.path.join(workdir, "c16_fault"),
                      np.random.RandomState(7))
        c_clean = OptimizeCommand(clean, min_file_size=1 << 30, workers=4)
        t_clean, _ = _timed(c_clean.run)
        plan = FaultPlan(script=[("dist.itemExec", "transient")] * 4)
        with _c.set_temporarily(**fast_retry,
                                **{"delta.tpu.faults.plan": plan}):
            c_fault = OptimizeCommand(faulted, min_file_size=1 << 30,
                                      workers=4, on_failure="quarantine")
            t_fault, _ = _timed(c_fault.run)
        assert not plan.script, "scripted faults never fired"
        # every transient retried to success: no quarantine, and the fault
        # run's table is indistinguishable from the clean run's
        assert c_fault.metrics["numQuarantinedGroups"] == 0
        rep = c_fault.shard_report
        assert rep.retried >= 4
        a, a_files = _rows_files(clean)
        b, b_files = _rows_files(faulted)
        assert a == b and a_files == b_files, \
            "faulted OPTIMIZE diverged from clean"
        return {
            "rows": 32 * rows_per,
            "faults_injected": 4,
            "retried": rep.retried,
            "quarantined": len(rep.quarantined),
            "clean_s": round(t_clean, 3),
            "faulted_s": round(t_fault, 3),
            "retry_overhead_pct": round(
                (t_fault / max(t_clean, 1e-9) - 1.0) * 100.0, 2),
            "identity_ok": True,
        }

    _leg("retry", 120, _retry)

    def _speculation(budget_s):
        # the straggler is an injected `slow` fault at dist.itemExec: one
        # scripted 1.2s stall inside whichever item attempt fires first,
        # well past the 60ms priced timeout. The script is consumed once,
        # so the speculative re-dispatch of the stuck item runs clean —
        # the same one-straggler schedule on both sides of the comparison.
        straggle_s = 1.2
        items = list(range(8))
        want = [i * 10 for i in items]

        def fn(i):
            time.sleep(0.02)
            return i * 10

        knobs = {"delta.tpu.distributed.itemTimeoutMs": 60,
                 "delta.tpu.distributed.supervisor.intervalMs": 5,
                 "delta.tpu.distributed.speculation.slackFactor": 1.0}

        def run_once(spec_on, lbl):
            plan = FaultPlan(script=[("dist.itemExec", "slow")],
                             slow_ms=straggle_s * 1e3)
            with _c.set_temporarily(
                    **knobs,
                    **{"delta.tpu.faults.plan": plan,
                       "delta.tpu.distributed.speculation.enabled": spec_on}):
                t, rep = _timed(
                    lambda: run_sharded(items, fn, workers=4, label=lbl))
            assert not plan.script, "the scripted straggler never fired"
            return t, rep

        t_none, rep_none = run_once(False, "bench-nospec")
        t_spec, rep_spec = run_once(True, "bench-spec")
        assert rep_none.results == want and rep_spec.results == want
        assert rep_none.speculated == 0
        assert rep_spec.speculated >= 1 and rep_spec.rescued >= 1
        # the acceptance: rescuing the straggler must beat waiting it out
        assert t_spec < t_none, \
            f"speculation ({t_spec:.2f}s) did not beat " \
            f"no-speculation ({t_none:.2f}s)"
        return {
            "items": len(items),
            "straggle_s": straggle_s,
            "speculation_off_s": round(t_none, 3),
            "speculation_on_s": round(t_spec, 3),
            "speedup": round(t_none / max(t_spec, 1e-9), 2),
            "speculated": rep_spec.speculated,
            "rescued": rep_spec.rescued,
            "identity_ok": True,
        }

    _leg("speculation", 60, _speculation)

    def _posed(log, proc, **kw):
        cmd = OptimizeCommand(log, min_file_size=1 << 30, workers=4,
                              distribute=True, **kw)
        orig = dist_mod.process_info
        dist_mod.process_info = lambda: (proc, 2)
        try:
            cmd.run()
        finally:
            dist_mod.process_info = orig
        return cmd

    def _recovery(budget_s):
        solo = _mk(os.path.join(workdir, "c16_solo"),
                   np.random.RandomState(11))
        crash_path = os.path.join(workdir, "c16_crash")
        crashed = _mk(crash_path, np.random.RandomState(11))
        c_solo = OptimizeCommand(solo, min_file_size=1 << 30, workers=4)
        t_solo, _ = _timed(c_solo.run)
        ref_rows, ref_files = _rows_files(solo)

        base_recovered = telemetry.counters("dist").get(
            "dist.slice.recovered", 0)
        # host 1 dies on its first group rewrite, lease already published
        plan = FaultPlan(script=[("dist.itemExec", "crash_before_publish")])
        with _c.set_temporarily(**fast_retry,
                                **{"delta.tpu.faults.plan": plan}):
            try:
                _posed(crashed, proc=1)
            except SimulatedCrash:
                pass
            else:
                raise AssertionError("host 1 survived its scripted crash")
        assert len(leases.read_leases(crashed.log_path)) == 1
        past = time.time() - 120  # age the orphan's heartbeat past the ttl
        for p, _b, _m in leases.read_leases(crashed.log_path):
            os.utime(p, (past, past))

        DeltaLog.clear_cache()
        crashed = DeltaLog.for_table(crash_path)
        with _c.set_temporarily(
                **{"delta.tpu.distributed.lease.settleMs": 20}):
            t_recover, _ = _timed(lambda: _posed(crashed, proc=0))

        got_rows, got_files = _rows_files(crashed)
        recovered = telemetry.counters("dist").get(
            "dist.slice.recovered", 0) - base_recovered
        assert got_rows == ref_rows and got_files == ref_files, \
            "recovered table diverged from the solo run"
        assert recovered == 1, f"expected 1 recovered slice, got {recovered}"
        assert leases.read_leases(crashed.log_path) == []
        return {
            "rows": 32 * rows_per,
            "solo_s": round(t_solo, 3),
            "crash_recover_s": round(t_recover, 3),
            "recovery_overhead_pct": round(
                (t_recover / max(t_solo, 1e-9) - 1.0) * 100.0, 2),
            "slices_recovered": recovered,
            "identity_ok": True,
        }

    _leg("recovery", 150, _recovery)

    violations = sum(1 for leg in legs.values()
                     if not leg.get("identity_ok"))
    spec = legs.get("speculation", {})
    speedup = spec.get("speedup")
    ok = isinstance(speedup, (int, float)) and speedup > 0
    rec_pct = legs.get("recovery", {}).get("recovery_overhead_pct")
    return {
        "metric": "dist_speculation_speedup_vs_none",
        "value": round(speedup, 2) if ok else -1,
        "unit": "x" if ok else "error",
        "vs_baseline": round(speedup, 2) if ok else 0,
        "legs": legs,
        "gate": {
            "dist_fault_identity_violations": {
                "value": violations, "unit": "findings"},
            "recovery_overhead_pct": {
                "value": (max(round(rec_pct, 2), 0.0)
                          if isinstance(rec_pct, (int, float)) else -1),
                "unit": "pct",
            },
        },
    }


def _emit(results):
    headline = results.get("2") or next(iter(results.values()))
    print(json.dumps({
        "metric": headline["metric"],
        "value": headline["value"],
        "unit": headline["unit"],
        "vs_baseline": headline["vs_baseline"],
        "all": results,
    }), flush=True)


def _reset_engine_state():
    """Per-config isolation — and the cleanup a mid-config deadline abort
    relies on: a SIGALRM can fire anywhere, so the next config must never
    inherit half-built caches or log handles."""
    try:
        from delta_tpu import DeltaLog
        from delta_tpu.ops.key_cache import KeyCache
        from delta_tpu.ops.state_cache import DeviceStateCache

        from delta_tpu.ops.column_cache import ColumnCache

        DeltaLog.clear_cache()
        KeyCache.reset()
        DeviceStateCache.reset()
        ColumnCache.reset()
        from delta_tpu.obs import journal

        journal.reset()
        from delta_tpu.log import checkpointer

        checkpointer.reset()
        from delta_tpu import autopilot

        autopilot.reset()
        from delta_tpu.obs import fleet, slo, timeseries, trace_store

        timeseries.reset()
        slo.reset()
        fleet.reset()
        trace_store.reset()
    except Exception:
        pass


class ConfigDeadline(BaseException):
    """Raised by the SIGALRM handler: one config exceeded its deadline.
    BaseException, not Exception — the engine's defensive `except
    Exception` handlers (device-finalize host fallback, telemetry guards)
    must not swallow the deadline and leave the config running unbounded
    (the same reasoning that made PR 5's SimulatedCrash a BaseException)."""


def _parse_argv(argv):
    """(only, compare_path, threshold): positional config selector plus the
    regression-gate flags (``--compare BENCH_rN.json`` diffs this run
    against a prior round via tools/bench_diff and exits non-zero on
    regression past ``--compare-threshold`` percent)."""
    only = compare = None
    threshold = 20.0
    args = list(argv)
    while args:
        a = args.pop(0)
        if a == "--compare":
            if not args:
                sys.exit("bench.py: --compare requires a BENCH_*.json path")
            compare = args.pop(0)
        elif a == "--compare-threshold":
            if not args:
                sys.exit("bench.py: --compare-threshold requires a percent")
            try:
                threshold = float(args.pop(0))
            except ValueError:
                sys.exit("bench.py: --compare-threshold must be numeric")
        elif a.startswith("-"):
            # a typo'd gate flag must NOT fall through to the config
            # selector — it would match no config, run nothing, and pass
            # the regression gate vacuously
            sys.exit(f"bench.py: unknown flag {a!r}")
        else:
            only = a
    return only, compare, threshold


def main():
    import signal

    only, compare_path, compare_threshold = _parse_argv(sys.argv[1:])
    workdir = tempfile.mkdtemp(prefix="delta_tpu_bench_")
    # priority order: the headline and the device-win configs land first,
    # so a driver-side timeout still records the story; the long auxiliary
    # scale configs (2x, 7) run last under the soft budget below
    configs = {
        "2": lambda: bench_merge_upsert(workdir),
        "9": lambda: bench_commit_contention(workdir),
        "6": lambda: bench_hot_plan(workdir),
        "6p": lambda: bench_hot_plan(workdir, partitioned=True),
        "10": lambda: bench_pushdown(workdir),
        "11": lambda: bench_fleet(workdir),
        "13": lambda: bench_shadow(workdir),
        "14": lambda: bench_sharded_scan(workdir),
        "15": lambda: bench_trace_overhead(workdir),
        "16": lambda: bench_dist_faults(workdir),
        "12": lambda: bench_device_scan(workdir),
        "8": lambda: bench_resident_probe(workdir),
        "5": lambda: bench_checkpoint_replay(workdir),
        "3": lambda: bench_zorder_point_query(workdir),
        "4": lambda: bench_streaming_tail(workdir),
        "1": lambda: bench_overwrite_read(workdir),
        "2x": lambda: bench_merge_scale(workdir),
        "7": lambda: bench_replay_scale(workdir),
        # *w keys are subprocess-only workers (config 14's plan leg spawns
        # "14w" with a forced 8-device mesh); the full sweep skips them
        "14w": lambda: bench_sharded_scan_worker(),
    }
    results: dict = {}
    emitted = {"done": False}

    def bail(signum, frame):  # pragma: no cover - signal path
        if results and not emitted["done"]:
            emitted["done"] = True
            results["_partial"] = f"terminated by signal {signum}"
            _emit(results)
        sys.exit(1)

    signal.signal(signal.SIGTERM, bail)

    def _alarm(signum, frame):  # pragma: no cover - signal path
        raise ConfigDeadline()

    signal.signal(signal.SIGALRM, _alarm)
    # rc must be 0 with every claim driver-captured (ISSUE 6 satellite:
    # r5 hit the DRIVER's timeout — rc 124 — and lost its artifacts): the
    # soft budget leaves headroom under the driver's wall, and a PER-CONFIG
    # deadline skips-and-records any config that would blow it
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "3000"))
    default_deadline = float(os.environ.get("BENCH_CONFIG_DEADLINE_S", "480"))
    per_config_deadline = {"2": 900.0, "2x": 540.0, "8": 600.0, "9": 420.0,
                           "14": 540.0, "16": 360.0}
    t_start = time.perf_counter()
    # deadline forensics: configs run with the flight recorder armed, so a
    # SIGALRM unwinding through the open span stack leaves an incident file
    # (spans + counters at the moment of the breach) — a timed-out config
    # is a diagnosable artifact, not just `"skipped": true` in the JSON
    from delta_tpu.obs import flight_recorder
    from delta_tpu.utils.config import conf as _conf

    flight_recorder.install()
    incident_dir = os.environ.get(
        "BENCH_INCIDENT_DIR",
        str(_conf.get("delta.tpu.obs.incidentDir")
            or os.path.join(os.getcwd(), "bench_incidents")),
    )
    def run_with_telemetry(fn):
        """Per-config isolation: reset the registry, run, attach a compact
        internal-metrics snapshot (top counters + phase-histogram summaries)
        so BENCH_*.json trajectories carry attributable phase deltas, not
        just wall-clock."""
        from delta_tpu.utils import telemetry

        telemetry.reset_all()
        out = fn()
        try:
            if isinstance(out, dict):
                # skip-rate counters always ride along: BENCH rounds track
                # row-group pruning effectiveness next to latency; router
                # audit + device-memory gauges carry the new cost-model
                # ledger per round
                out["telemetry"] = telemetry.bench_snapshot(
                    include=("scan.rowgroups", "scan.bytes.skipped",
                             "scan.bytes.deviceSkipped",
                             "scan.bytes.deviceSurvivor", "scan.device",
                             "columnCache", "scan.rewrites", "footerCache",
                             "table.health", "router", "device.hbm",
                             "journal", "advisor", "fleet", "slo", "dist",
                             "obs.scrape", "obs.server.clientAborts"),
                )
        except Exception:  # noqa: BLE001 — metrics must never fail the bench
            pass
        return out

    def _gate(results):
        """Mechanical regression gate (satellite): diff this run against a
        prior round's JSON and fail the process on regression, so perf
        claims in PRs are checkable instead of prose. Reports on stderr —
        stdout keeps the one-JSON-line contract."""
        if not compare_path:
            return
        from tools.bench_diff import compare

        with open(compare_path, encoding="utf-8") as f:
            prior = json.load(f)
        regressions = compare(results, prior, compare_threshold)
        for r in regressions:
            print(f"REGRESSION: {r.describe()}", file=sys.stderr)
        if regressions:
            sys.exit(3)
        print(f"bench gate OK vs {compare_path} "
              f"(threshold {compare_threshold:g}%)", file=sys.stderr)

    try:
        if only:
            results = {only: run_with_telemetry(configs[only])}
            emitted["done"] = True  # one-line contract: bail() must not re-emit
            print(json.dumps(results[only]))
            _gate(results)
            return
        for k, fn in configs.items():
            if k.endswith("w"):
                continue  # hidden subprocess-only worker configs
            elapsed = time.perf_counter() - t_start
            remaining = budget_s - elapsed
            if remaining < 60:
                results[k] = {
                    "metric": f"config_{k}", "value": -1, "unit": "skipped",
                    "vs_baseline": 0,
                    "note": f"skipped: soft budget BENCH_BUDGET_S="
                            f"{budget_s:.0f}s exhausted at {elapsed:.0f}s",
                }
                continue
            deadline = min(per_config_deadline.get(k, default_deadline),
                           remaining)
            t_cfg = time.perf_counter()
            signal.alarm(max(int(deadline), 1))
            try:
                with _conf.set_temporarily(
                    **{"delta.tpu.obs.incidentDir": incident_dir}
                ):
                    try:
                        results[k] = run_with_telemetry(fn)
                    except ConfigDeadline as dexc:
                        # the alarm unwound through the config's open spans
                        # with the recorder armed: an incident file already
                        # exists (fullest stack, deduped on the exception);
                        # a deadline outside any span records one here
                        inc = None
                        if not getattr(dexc, "_delta_incident_recorded",
                                       False):
                            from delta_tpu.utils.telemetry import UsageEvent

                            ev = UsageEvent(
                                f"bench.config.{k}.deadline",
                                int(time.time() * 1000),
                                tags={"config": k},
                                data={"deadlineS": deadline},
                            )
                            inc = flight_recorder.record_incident(ev, dexc)
                        else:
                            files = flight_recorder.incident_files(
                                incident_dir)
                            inc = files[-1] if files else None
                        results[k] = {
                            "metric": f"config_{k}", "value": -1,
                            "unit": "skipped", "vs_baseline": 0,
                            "note": f"skipped: per-config deadline "
                                    f"{deadline:.0f}s breached after "
                                    f"{time.perf_counter() - t_cfg:.0f}s",
                            "incident": inc,
                        }
            except Exception as e:  # record-and-continue: rc stays 0 and
                # every other config's artifact is still driver-captured
                results[k] = {
                    "metric": f"config_{k}", "value": -1, "unit": "error",
                    "vs_baseline": 0,
                    "note": f"{type(e).__name__}: {e}"[:300],
                }
            finally:
                signal.alarm(0)
                _reset_engine_state()
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    # the static-analysis gate rides the bench artifact (ISSUE 10): finding
    # counts land as a config entry (unit "findings" is lower-is-better in
    # tools/bench_diff, so --compare fails a round that grew findings) and
    # as the cataloged analysis.findings gauge in the telemetry snapshot
    try:
        from delta_tpu import analysis as _analysis
        from delta_tpu.utils import telemetry as _telemetry

        _report = _analysis.analyze_repo()
        _analysis.publish_metrics(_report)
        results["analysis"] = {
            "metric": "analysis_findings", "value": len(_report.findings),
            "unit": "findings", "vs_baseline": 0,
            "counts": _report.counts(),
            "waived": len(_report.suppressed),
            "baselined": len(_report.baselined),
            "telemetry": _telemetry.bench_snapshot(include=("analysis",)),
        }
    except Exception as e:  # noqa: BLE001 — the gate must not eat the bench
        results["analysis"] = {
            "metric": "analysis_findings", "value": -1, "unit": "error",
            "vs_baseline": 0, "note": f"{type(e).__name__}: {e}"[:300],
        }
    emitted["done"] = True
    _emit(results)
    _gate(results)


if __name__ == "__main__":
    main()
