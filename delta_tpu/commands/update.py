"""UPDATE command — conditional column rewrite.

Mirrors `commands/UpdateCommand.scala:45-269`: find candidate files by
predicate scan, rewrite each touched file projecting
``CASE WHEN cond THEN new_expr ELSE old END`` per updated column
(`buildUpdatedColumns :232`), commit remove+add. The projection is one
vectorized pass per column (Arrow kernels) instead of per-row codegen.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.commands import operations as ops
from delta_tpu.commands.dml_common import (
    POSITION_COL,
    Timer,
    candidate_files,
    dv_enabled,
    dv_mark_from_mask,
    read_candidates,
)
from delta_tpu.exec import cdf
from delta_tpu.exec import write as write_exec
from delta_tpu.expr import ir
from delta_tpu.expr.parser import parse_expression, parse_predicate
from delta_tpu.expr.vectorized import evaluate
from delta_tpu.protocol.actions import Action
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = ["UpdateCommand"]


class UpdateCommand:
    def __init__(
        self,
        delta_log,
        set_exprs: Dict[str, Union[str, ir.Expression]],
        condition: Optional[Union[str, ir.Expression]] = None,
    ):
        if not set_exprs:
            raise DeltaAnalysisError("UPDATE requires at least one SET assignment")
        self.delta_log = delta_log
        self.set_exprs = {
            col: parse_expression(e) if isinstance(e, str) else e
            for col, e in set_exprs.items()
        }
        self.condition = (
            parse_predicate(condition) if isinstance(condition, str) else condition
        )
        self.metrics: Dict[str, int] = {}

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.dml.update", path=self.delta_log.data_path):
            return self.delta_log.with_new_transaction(self._body)

    def _body(self, txn) -> int:
        metadata = txn.metadata
        schema_cols = {f.name.lower(): f.name for f in metadata.schema.fields}
        # updating a partition column is allowed: write_files is partition-
        # aware, so rewritten rows land in their new partition directories
        for col in self.set_exprs:
            if col.lower() not in schema_cols:
                raise errors.update_column_not_found(col)

        timer = Timer()
        if self.condition is not None:
            from delta_tpu.schema.char_varchar import pad_char_literals

            self.condition = pad_char_literals(self.condition, metadata)
        use_dv = dv_enabled(metadata)
        use_cdf = cdf.cdf_enabled(metadata)
        cdf_blocks = []
        candidates = candidate_files(txn, self.condition)
        touched = read_candidates(
            self.delta_log.data_path, candidates, metadata, self.condition,
            with_positions=use_dv,
            # DV mode only touches matched rows, so match-free row groups
            # can skip decode; the rewrite path must read files whole
            prune_row_groups=use_dv,
        )
        scan_ms = timer.lap_ms()

        removes: List[Action] = []
        adds: List[Action] = []
        updated_rows = 0
        for tf in touched:
            n_match = pc.sum(tf.mask).as_py() or 0
            if not n_match:
                continue
            updated_rows += n_match
            if use_dv:
                # old versions of the matched rows get DV-marked; only the
                # NEW versions are written — untouched rows stay in place
                rm, re_add = dv_mark_from_mask(
                    self.delta_log.data_path, tf.add, tf.table, tf.mask
                )
                removes.append(rm)
                if re_add is not None:
                    adds.append(re_add)
                matched = tf.table.filter(tf.mask).drop_columns([POSITION_COL])
                all_true = pa.chunked_array(
                    [pa.array([True] * matched.num_rows)]
                )
                rewritten = self._apply_updates(matched, all_true, metadata)
                if use_cdf:
                    cdf_blocks.append(("update_preimage", matched))
                    cdf_blocks.append(("update_postimage", rewritten))
            else:
                removes.append(tf.add.remove())
                rewritten = self._apply_updates(tf.table, tf.mask, metadata)
                if use_cdf:
                    cdf_blocks.append(
                        ("update_preimage", tf.table.filter(tf.mask))
                    )
                    cdf_blocks.append(
                        ("update_postimage", rewritten.filter(tf.mask))
                    )
            adds.extend(
                write_exec.write_files(
                    self.delta_log.data_path, rewritten, metadata, data_change=True
                )
            )
        cdc_actions: List[Action] = []
        if cdf_blocks:
            cdc_actions = list(
                cdf.write_change_data(
                    self.delta_log.data_path, cdf_blocks, metadata
                )
            )
        self.metrics.update(
            numRemovedFiles=len(removes),
            numAddedFiles=len(adds),
            numUpdatedRows=updated_rows,
            scanTimeMs=scan_ms,
            rewriteTimeMs=timer.lap_ms(),
        )
        txn.report_metrics(**self.metrics)
        op = ops.Update(predicate=self.condition.sql() if self.condition else None)
        version = txn.commit(removes + adds + cdc_actions, op)
        # workload journal: DML entry (mode + rewrite metrics) for the
        # layout advisor (buffered; inert under blackout)
        from delta_tpu.obs import journal as journal_mod

        journal_mod.record_dml(
            self.delta_log.log_path, "update",
            mode="dv" if use_dv else "rewrite", version=version,
            metrics=dict(self.metrics),
        )
        if not use_dv and removes:
            # whole-file rewrite (not a DV mark): bump the resident
            # key-cache epoch — stale slabs must never serve a
            # post-rewrite MERGE (DV-mode diffs advance incrementally);
            # same bump for the scan column cache
            from delta_tpu.ops.column_cache import ColumnCache
            from delta_tpu.ops.key_cache import KeyCache

            KeyCache.instance().bump_epoch(self.delta_log.log_path)
            ColumnCache.instance().bump_epoch(self.delta_log.log_path)
        return version

    def _apply_updates(self, table: pa.Table, mask, metadata) -> pa.Table:
        cols = []
        names = []
        lower_set = {c.lower(): e for c, e in self.set_exprs.items()}
        for name in table.column_names:
            expr = lower_set.get(name.lower())
            old = table.column(name)
            if expr is None:
                cols.append(old)
            else:
                new = evaluate(expr, table)
                try:
                    new = pc.cast(new, old.type, safe=False)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                    raise errors.update_expression_type_mismatch(name, new.type, old.type)
                cols.append(pc.if_else(mask, new, old))
            names.append(name)
        out = pa.table(cols, names=names)
        # generated columns whose referenced base columns were assigned must
        # be recomputed, not copied (stale values fail write-time checks)
        from delta_tpu.schema import generated as generated_mod

        return generated_mod.recompute_stale(
            out, metadata.schema, list(self.set_exprs), mask=mask
        )
