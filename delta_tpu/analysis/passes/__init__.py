"""Pass registry. ``all_passes()`` is the one list the CLI, the tier-1
test and the bench wiring share — a new pass registers here and nowhere
else."""
from __future__ import annotations

from typing import List

from delta_tpu.analysis.core import AnalysisPass
from delta_tpu.analysis.passes.config_registry import ConfigRegistryPass
from delta_tpu.analysis.passes.crash_safety import CrashSafetyPass
from delta_tpu.analysis.passes.lock_discipline import LockDisciplinePass
from delta_tpu.analysis.passes.metric_catalog import MetricCatalogPass
from delta_tpu.analysis.passes.metric_descriptions import \
    MetricDescriptionsPass
from delta_tpu.analysis.passes.pool_naming import PoolNamingPass
from delta_tpu.analysis.passes.telemetry_spans import TelemetrySpansPass

__all__ = ["all_passes"]


def all_passes() -> List[AnalysisPass]:
    return [
        LockDisciplinePass(),
        CrashSafetyPass(),
        ConfigRegistryPass(),
        PoolNamingPass(),
        TelemetrySpansPass(),
        MetricCatalogPass(),
        MetricDescriptionsPass(),
    ]
