"""MERGE behavioral matrix — ported from the reference's MergeIntoSuiteBase
(`core/src/test/scala/org/apache/spark/sql/delta/MergeIntoSuiteBase.scala`,
2,922 LoC) high-value cases: NULL-key semantics, star expansion with
extra/missing/reordered source columns, per-clause conditions referencing
both sides, clause ordering, self-merge, and schema evolution
(`deltaMerge.scala:224-424`). Every case runs on both executors (device
kernel forced / host Arrow join) via the ``executor`` fixture."""
import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.utils.config import conf
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    DeltaUnsupportedOperationError,
)


@pytest.fixture(params=["device", "host"])
def executor(request):
    mode = "force" if request.param == "device" else "off"
    with conf.set_temporarily(**{"delta.tpu.merge.devicePath.mode": mode}):
        yield request.param


def _write(path, data, **kw):
    log = DeltaLog.for_table(str(path))
    WriteIntoDelta(log, "append", pa.table(data) if isinstance(data, dict) else data,
                   **kw).run()
    return log


def _rows(log, sort="id"):
    from delta_tpu.exec.scan import scan_to_table

    t = scan_to_table(log.update())
    if sort and sort in t.column_names:
        t = t.sort_by(sort)
    return t.to_pylist()


def _merge(log, source, cond, matched=(), not_matched=(), **kw):
    cmd = MergeIntoCommand(
        log, pa.table(source) if isinstance(source, dict) else source, cond,
        matched, not_matched, **kw
    )
    cmd.run()
    return cmd


UP = MergeClause("update", assignments=None)
INS = MergeClause("insert", assignments=None)
ALIAS = dict(source_alias="s", target_alias="t")


# -- basic shapes -----------------------------------------------------------


def test_update_only(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2, 3], "v": [10, 20, 30]})
    cmd = _merge(log, {"id": [2, 4], "v": [99, 99]}, "t.id = s.id", [UP], [], **ALIAS)
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 2, "v": 99}, {"id": 3, "v": 30}]
    assert cmd.metrics["numTargetRowsUpdated"] == 1
    assert cmd.metrics["numTargetRowsInserted"] == 0


def test_insert_only(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    cmd = _merge(log, {"id": [2, 3], "v": [0, 30]}, "t.id = s.id", [], [INS], **ALIAS)
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 2, "v": 20}, {"id": 3, "v": 30}]
    assert cmd.metrics["numTargetRowsInserted"] == 1


def test_delete_only(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2, 3], "v": [10, 20, 30]})
    cmd = _merge(log, {"id": [1, 3]}, "t.id = s.id", [MergeClause("delete")], [],
                 **ALIAS)
    assert _rows(log) == [{"id": 2, "v": 20}]
    assert cmd.metrics["numTargetRowsDeleted"] == 2


def test_upsert_update_and_insert(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    _merge(log, {"id": [2, 3], "v": [21, 31]}, "t.id = s.id", [UP], [INS], **ALIAS)
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 2, "v": 21}, {"id": 3, "v": 31}]


def test_update_delete_insert_three_clauses(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2, 3], "v": [10, 20, 30]})
    _merge(
        log, {"id": [1, 2, 4], "v": [-1, 99, 40]}, "t.id = s.id",
        [MergeClause("delete", condition="s.v < 0"), UP],
        [INS], **ALIAS,
    )
    assert _rows(log) == [{"id": 2, "v": 99}, {"id": 3, "v": 30}, {"id": 4, "v": 40}]


def test_empty_source_is_noop(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    cmd = _merge(log, pa.table({"id": pa.array([], pa.int64()),
                                "v": pa.array([], pa.int64())}),
                 "t.id = s.id", [UP], [INS], **ALIAS)
    assert _rows(log) == [{"id": 1, "v": 10}]
    assert cmd.metrics["numTargetRowsUpdated"] == 0
    assert cmd.metrics["numTargetRowsInserted"] == 0


def test_empty_target_inserts_all(tmp_path, executor):
    path = str(tmp_path / "t")
    log = DeltaLog.for_table(path)
    WriteIntoDelta(log, "append", pa.table(
        {"id": pa.array([], pa.int64()), "v": pa.array([], pa.int64())})).run()
    _merge(log, {"id": [1, 2], "v": [10, 20]}, "t.id = s.id", [UP], [INS], **ALIAS)
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 2, "v": 20}]


# -- clause conditions & ordering -------------------------------------------


def test_matched_condition_references_both_sides(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    _merge(
        log, {"id": [1, 2], "v": [5, 50]}, "t.id = s.id",
        [MergeClause("update", condition="s.v > t.v", assignments=None)],
        [], **ALIAS,
    )
    # only id=2 satisfies s.v > t.v
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 2, "v": 50}]


def test_matched_clause_order_first_wins(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    _merge(
        log, {"id": [1, 2], "v": [100, 200]}, "t.id = s.id",
        [
            MergeClause("update", condition="t.v = 10",
                        assignments={"v": "s.v + 1"}),
            MergeClause("update", assignments={"v": "s.v + 2"}),
        ],
        [], **ALIAS,
    )
    # id=1 hits clause 1 (101), id=2 falls through to clause 2 (202)
    assert _rows(log) == [{"id": 1, "v": 101}, {"id": 2, "v": 202}]


def test_conditional_insert(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    _merge(
        log, {"id": [2, 3], "v": [20, 30]}, "t.id = s.id", [],
        [MergeClause("insert", condition="s.v > 25", assignments=None)],
        **ALIAS,
    )
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 3, "v": 30}]


def test_only_last_clause_may_omit_condition(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    with pytest.raises(DeltaAnalysisError):
        MergeIntoCommand(
            log, pa.table({"id": [1], "v": [1]}), "t.id = s.id",
            [MergeClause("update", assignments=None),
             MergeClause("delete")], [], **ALIAS,
        )


def test_update_expression_uses_both_sides(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    _merge(
        log, {"id": [1, 2], "v": [1, 2]}, "t.id = s.id",
        [MergeClause("update", assignments={"v": "t.v + s.v"})], [], **ALIAS,
    )
    assert _rows(log) == [{"id": 1, "v": 11}, {"id": 2, "v": 22}]


# -- NULL-key matrix (MergeIntoSuiteBase "Merge with null keys") -------------


def test_null_source_keys_insert_not_update(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    src = pa.table({"id": pa.array([1, None], pa.int64()),
                    "v": pa.array([100, 999], pa.int64())})
    cmd = _merge(log, src, "t.id = s.id", [UP], [INS], **ALIAS)
    assert cmd.metrics["numTargetRowsUpdated"] == 1
    assert cmd.metrics["numTargetRowsInserted"] == 1
    assert _rows(log) == [{"id": 1, "v": 100}, {"id": 2, "v": 20},
                          {"id": None, "v": 999}]


def test_null_target_keys_never_match(tmp_path, executor):
    log = _write(tmp_path / "t", pa.table({
        "id": pa.array([None, 2], pa.int64()),
        "v": pa.array([0, 20], pa.int64())}))
    cmd = _merge(log, {"id": [2, 3], "v": [21, 31]}, "t.id = s.id", [UP], [INS],
                 **ALIAS)
    assert cmd.metrics["numTargetRowsUpdated"] == 1
    assert _rows(log) == [{"id": 2, "v": 21}, {"id": 3, "v": 31},
                          {"id": None, "v": 0}]


def test_null_never_matches_null(tmp_path, executor):
    log = _write(tmp_path / "t", pa.table({
        "id": pa.array([None], pa.int64()), "v": pa.array([0], pa.int64())}))
    src = pa.table({"id": pa.array([None], pa.int64()),
                    "v": pa.array([99], pa.int64())})
    cmd = _merge(log, src, "t.id = s.id", [UP], [INS], **ALIAS)
    assert cmd.metrics["numTargetRowsUpdated"] == 0
    assert cmd.metrics["numTargetRowsInserted"] == 1
    got = sorted(_rows(log, sort=None), key=lambda r: r["v"])
    assert got == [{"id": None, "v": 0}, {"id": None, "v": 99}]


# -- star expansion ----------------------------------------------------------


def test_star_with_reordered_source_columns(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10], "w": [5]})
    src = pa.table({"w": [50], "v": [100], "id": [1]})  # reordered
    _merge(log, src, "t.id = s.id", [UP], [INS], **ALIAS)
    assert _rows(log) == [{"id": 1, "v": 100, "w": 50}]


def test_star_missing_source_column_errors_without_evolution(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10], "w": [5]})
    src = pa.table({"id": [1], "v": [100]})  # no "w"
    with pytest.raises(DeltaAnalysisError, match="cannot resolve"):
        _merge(log, src, "t.id = s.id", [UP], [], **ALIAS)


def test_star_extra_source_column_ignored_without_evolution(tmp_path, executor):
    # star expands over TARGET columns without evolution
    # (`deltaMerge.scala:322-328`): extra source columns are never referenced
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    src = pa.table({"id": [1, 2], "v": [100, 200], "extra": [7, 8]})
    _merge(log, src, "t.id = s.id", [UP], [INS], **ALIAS)
    assert [f.name for f in log.update().metadata.schema.fields] == ["id", "v"]
    assert _rows(log) == [{"id": 1, "v": 100}, {"id": 2, "v": 200}]


def test_explicit_assignments_ignore_extra_source_columns(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    src = pa.table({"id": [1, 2], "v": [100, 200], "extra": [7, 8]})
    _merge(
        log, src, "t.id = s.id",
        [MergeClause("update", assignments={"v": "s.v"})],
        [MergeClause("insert", assignments={"id": "s.id", "v": "s.extra"})],
        **ALIAS,
    )
    assert _rows(log) == [{"id": 1, "v": 100}, {"id": 2, "v": 8}]


def test_case_insensitive_column_resolution(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "Value": [10, 20]})
    src = pa.table({"ID": [2, 3], "VALUE": [21, 31]})
    _merge(log, src, "t.id = s.ID", [UP], [INS], **ALIAS)
    assert _rows(log) == [{"id": 1, "Value": 10}, {"id": 2, "Value": 21},
                          {"id": 3, "Value": 31}]


# -- schema evolution --------------------------------------------------------


def _evolved(on=True):
    return conf.set_temporarily(**{"delta.tpu.schema.autoMerge.enabled": on})


def test_evolution_insert_all_adds_new_column(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    src = pa.table({"id": [2, 3], "v": [21, 31], "extra": ["a", "b"]})
    with _evolved():
        _merge(log, src, "t.id = s.id", [UP], [INS], **ALIAS)
    snap = log.update()
    assert [f.name for f in snap.metadata.schema.fields] == ["id", "v", "extra"]
    assert _rows(log) == [
        {"id": 1, "v": 10, "extra": None},
        {"id": 2, "v": 21, "extra": "a"},
        {"id": 3, "v": 31, "extra": "b"},
    ]


def test_evolution_update_all_adds_new_column(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    src = pa.table({"id": [2], "v": [99], "flag": [True]})
    with _evolved():
        _merge(log, src, "t.id = s.id", [UP], [], **ALIAS)
    assert _rows(log) == [
        {"id": 1, "v": 10, "flag": None},
        {"id": 2, "v": 99, "flag": True},
    ]


def test_evolution_requires_star_clause(tmp_path, executor):
    # explicit assignments never migrate the schema, even with the conf on
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    src = pa.table({"id": [1], "v": [100], "extra": [1]})
    with _evolved():
        _merge(log, src, "t.id = s.id",
               [MergeClause("update", assignments={"v": "s.v"})], [], **ALIAS)
    assert [f.name for f in log.update().metadata.schema.fields] == ["id", "v"]


def test_evolution_off_is_default_schema_unchanged(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    src = pa.table({"id": [1], "v": [100], "extra": [1]})
    _merge(log, src, "t.id = s.id", [UP], [], **ALIAS)
    assert [f.name for f in log.update().metadata.schema.fields] == ["id", "v"]
    assert _rows(log) == [{"id": 1, "v": 100}]


def test_evolution_cannot_retype_generated_column(tmp_path, executor):
    from delta_tpu.schema.generated import generated_field
    from delta_tpu.schema.types import IntegerType, LongType, StructType

    from delta_tpu.api.tables import DeltaTable

    schema = (
        StructType()
        .add("id", LongType())
        .add_field(generated_field("twice", LongType(), "id + id"))
    )
    t = DeltaTable.create(str(tmp_path / "gen"), schema)
    t.write({"id": [1]})
    src = pa.table({"id": pa.array([2], pa.int64()),
                    "twice": pa.array([4.5], pa.float64())})  # type change
    with _evolved(), pytest.raises(DeltaAnalysisError, match="generated column"):
        _merge(t.delta_log, src, "t.id = s.id", [UP], [INS], **ALIAS)


def test_evolution_preserves_target_column_order_and_case(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "Val": [10]})
    src = pa.table({"val": [99], "id": [1], "z": [0]})
    with _evolved():
        _merge(log, src, "t.id = s.id", [UP], [INS], **ALIAS)
    assert [f.name for f in log.update().metadata.schema.fields] == [
        "id", "Val", "z"
    ]


# -- self-merge & multi-match ------------------------------------------------


def test_self_merge_dedupe_pattern(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    from delta_tpu.exec.scan import scan_to_table

    src = scan_to_table(log.update())
    _merge(log, src, "t.id = s.id", [UP], [INS], **ALIAS)
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 2, "v": 20}]


def test_multi_match_update_errors(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    with pytest.raises(DeltaUnsupportedOperationError, match="multiple source rows"):
        _merge(log, {"id": [1, 1], "v": [1, 2]}, "t.id = s.id", [UP], [], **ALIAS)


def test_multi_match_single_unconditional_delete_ok(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1, 2], "v": [10, 20]})
    cmd = _merge(log, {"id": [1, 1], "v": [0, 0]}, "t.id = s.id",
                 [MergeClause("delete")], [], **ALIAS)
    assert _rows(log) == [{"id": 2, "v": 20}]
    assert cmd.metrics["numTargetRowsDeleted"] == 1


def test_multi_match_insert_only_is_duplicate_insensitive(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    cmd = _merge(log, {"id": [1, 1, 2], "v": [0, 0, 20]}, "t.id = s.id",
                 [], [INS], **ALIAS)
    assert cmd.metrics["numTargetRowsInserted"] == 1
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 2, "v": 20}]


# -- key expressions & aliases ----------------------------------------------


def test_key_expression_on_source_side(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [5, 6], "v": [10, 20]})
    # updateAll replaces EVERY target column, including the key: the matched
    # row (t.id=5) takes the source row's id=4
    _merge(log, {"id": [4], "v": [99]}, "t.id = s.id + 1",
           [UP], [], **ALIAS)
    assert _rows(log) == [{"id": 4, "v": 99}, {"id": 6, "v": 20}]


def test_unknown_qualifier_errors(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    with pytest.raises(DeltaAnalysisError, match="qualifier"):
        _merge(log, {"id": [1], "v": [2]}, "x.id = s.id", [UP], [], **ALIAS)


def test_composite_key_with_nulls(tmp_path, executor):
    log = _write(tmp_path / "t", pa.table({
        "a": pa.array([1, 1, None], pa.int64()),
        "b": pa.array([1, 2, 3], pa.int64()),
        "v": pa.array([10, 20, 30], pa.int64()),
    }))
    src = pa.table({
        "a": pa.array([1, None], pa.int64()),
        "b": pa.array([2, 3], pa.int64()),
        "v": pa.array([99, 98], pa.int64()),
    })
    cmd = _merge(log, src, "t.a = s.a AND t.b = s.b", [UP], [INS], **ALIAS)
    assert cmd.metrics["numTargetRowsUpdated"] == 1  # (1,2) only
    assert cmd.metrics["numTargetRowsInserted"] == 1  # null-a source row
    got = sorted(_rows(log, sort=None), key=lambda r: r["v"])
    assert got == [
        {"a": 1, "b": 1, "v": 10},
        {"a": None, "b": 3, "v": 30},
        {"a": None, "b": 3, "v": 98},
        {"a": 1, "b": 2, "v": 99},
    ]


def test_matched_only_merge_never_inserts(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    cmd = _merge(log, {"id": [1, 9], "v": [11, 90]}, "t.id = s.id", [UP], [],
                 **ALIAS)
    assert cmd.metrics["numTargetRowsInserted"] == 0
    assert _rows(log) == [{"id": 1, "v": 11}]


def test_insert_only_merge_never_updates(tmp_path, executor):
    log = _write(tmp_path / "t", {"id": [1], "v": [10]})
    cmd = _merge(log, {"id": [1, 9], "v": [11, 90]}, "t.id = s.id", [], [INS],
                 **ALIAS)
    assert cmd.metrics["numTargetRowsUpdated"] == 0
    assert _rows(log) == [{"id": 1, "v": 10}, {"id": 9, "v": 90}]


# -- non-equi conditions (blocked cartesian pairing, r5) --------------------


def test_non_equi_merge_small(tmp_table):
    """Range-condition MERGE (no equi conjunct): matched rows update."""
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({
        "k": np.arange(100, dtype=np.int64), "v": np.zeros(100)})).run()
    src = pa.table({"lo": pa.array([10, 50], pa.int64()),
                    "hi": pa.array([13, 52], pa.int64()),
                    "nv": pa.array([1.0, 2.0])})
    MergeIntoCommand(
        log, src, "t.k >= s.lo AND t.k < s.hi",
        [MergeClause("update", assignments={"v": "s.nv"})], [],
        source_alias="s", target_alias="t",
    ).run()
    from delta_tpu.exec.scan import scan_to_table

    d = dict(zip(*(scan_to_table(log.update()).column(c).to_pylist()
                   for c in ("k", "v"))))
    for k in (10, 11, 12):
        assert d[k] == 1.0, k
    for k in (50, 51):
        assert d[k] == 2.0, k
    assert d[13] == 0.0 and d[49] == 0.0


def test_non_equi_merge_beyond_old_pair_cap(tmp_table):
    """60M candidate pairs (old hard cap: 50M) streams through tiles with
    bounded memory; results match the per-row oracle."""
    log = DeltaLog.for_table(tmp_table)
    n = 30_000
    WriteIntoDelta(log, "append", pa.table({
        "k": np.arange(n, dtype=np.int64), "v": np.zeros(n)})).run()
    m = 2_000
    lo = np.arange(m, dtype=np.int64) * 15
    src = pa.table({"lo": lo, "hi": lo + 2,
                    "nv": np.arange(m, dtype=np.float64) + 1})
    with conf.set_temporarily(**{"delta.tpu.merge.nonEquiPairBudget": "1000000"}):
        cmd = MergeIntoCommand(
            log, src, "t.k >= s.lo AND t.k < s.hi",
            [MergeClause("update", assignments={"v": "s.nv"})], [],
            source_alias="s", target_alias="t",
        )
        cmd.run()
    from delta_tpu.exec.scan import scan_to_table

    t = scan_to_table(log.update())
    d = dict(zip(t.column("k").to_pylist(), t.column("v").to_pylist()))
    # oracle: row k matches source i iff 15i <= k < 15i + 2 (within range)
    import random

    for k in random.Random(5).sample(range(n), 500):
        i, r = divmod(k, 15)
        expect = float(i + 1) if r < 2 and i < m else 0.0
        assert d[k] == expect, (k, d[k], expect)
    assert cmd.metrics["numTargetRowsUpdated"] == sum(
        1 for k in range(n) if k % 15 < 2 and k // 15 < m)
