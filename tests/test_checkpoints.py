"""Checkpoint write/read + _last_checkpoint semantics (≈ ``CheckpointsSuite``
behaviors embedded in ``DeltaLogSuite``)."""
import pytest

from delta_tpu.log import checkpoints as ck
from delta_tpu.log.checkpoints import CheckpointInstance, CheckpointMetaData
from delta_tpu.protocol.actions import AddFile, Metadata, Protocol, RemoveFile, SetTransaction
from delta_tpu.storage.logstore import MemoryLogStore

LOG = "/tbl/_delta_log"


def state_actions():
    return [
        Protocol(1, 2),
        Metadata(id="m1", schema_string='{"type":"struct","fields":[]}'),
        SetTransaction("app", 3, 5),
        AddFile("f1", {"p": "1"}, 10, 100, False, stats='{"numRecords":2}'),
        AddFile("f2", {"p": None}, 20, 200, False),
        RemoveFile("f0", deletion_timestamp=50, data_change=False,
                   extended_file_metadata=True, partition_values={"p": "0"}, size=5),
    ]


def test_single_part_roundtrip():
    store = MemoryLogStore()
    md = ck.write_checkpoint(store, LOG, 10, state_actions())
    assert md == CheckpointMetaData(10, 6, None)
    assert store.exists(f"{LOG}/00000000000000000010.checkpoint.parquet")

    back = ck.read_checkpoint_actions(store, [f"{LOG}/00000000000000000010.checkpoint.parquet"])
    assert sorted(type(a).__name__ for a in back) == sorted(type(a).__name__ for a in state_actions())
    adds = {a.path: a for a in back if isinstance(a, AddFile)}
    assert adds["f1"].partition_values == {"p": "1"}
    assert adds["f1"].stats == '{"numRecords":2}'
    assert adds["f2"].partition_values == {"p": None}
    rem = next(a for a in back if isinstance(a, RemoveFile))
    assert rem.deletion_timestamp == 50 and rem.partition_values == {"p": "0"}
    txn = next(a for a in back if isinstance(a, SetTransaction))
    assert (txn.app_id, txn.version, txn.last_updated) == ("app", 3, 5)


def test_multipart_roundtrip():
    store = MemoryLogStore()
    md = ck.write_checkpoint(store, LOG, 4, state_actions(), parts=3)
    assert md.parts == 3
    paths = [
        f"{LOG}/00000000000000000004.checkpoint.{i+1:010d}.{3:010d}.parquet" for i in range(3)
    ]
    for p in paths:
        assert store.exists(p)
    back = ck.read_checkpoint_actions(store, paths)
    assert len(back) == 6


def test_last_checkpoint_roundtrip_and_corruption():
    store = MemoryLogStore()
    assert ck.read_last_checkpoint(store, LOG) is None
    ck.write_last_checkpoint(store, LOG, CheckpointMetaData(5, 100, None))
    got = ck.read_last_checkpoint(store, LOG)
    assert got == CheckpointMetaData(5, 100, None)
    # corrupt the pointer: reader falls back to None (re-list), not an error
    store.write_bytes(f"{LOG}/_last_checkpoint", b"{not-json", overwrite=True)
    assert ck.read_last_checkpoint(store, LOG) is None


def test_latest_complete_checkpoint():
    insts = [
        CheckpointInstance(2),
        CheckpointInstance(5, 2), CheckpointInstance(5, 2),  # both parts present
        CheckpointInstance(7, 3), CheckpointInstance(7, 3),  # 2 of 3 parts: incomplete
    ]
    assert ck.latest_complete_checkpoint(insts) == CheckpointInstance(5, 2)
    assert ck.latest_complete_checkpoint(insts, not_later_than=4) == CheckpointInstance(2)
    assert ck.latest_complete_checkpoint([], None) is None


def test_find_last_complete_checkpoint_before():
    store = MemoryLogStore()
    ck.write_checkpoint(store, LOG, 10, state_actions())
    ck.write_checkpoint(store, LOG, 20, state_actions(), parts=2)
    found = ck.find_last_complete_checkpoint_before(store, LOG, 15)
    assert found == CheckpointInstance(10, None)
    found = ck.find_last_complete_checkpoint_before(store, LOG, 25)
    assert found == CheckpointInstance(20, 2)
    assert ck.find_last_complete_checkpoint_before(store, LOG, 10) is None


def test_v2_checkpoint_struct_columns(tmp_path):
    """`delta.checkpoint.writeStatsAsStruct=true` adds the CheckpointV2
    typed columns (`Checkpoints.scala:340-389`): partitionValues_parsed and
    stats_parsed; the checkpoint stays readable by the normal path."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.log.deltalog import DeltaLog
    from delta_tpu.protocol import filenames

    path = str(tmp_path / "t")
    data = pa.table({
        "part": pa.array(["a", "a", "b"]),
        "x": pa.array([1, 2, 30], pa.int64()),
    })
    t = DeltaTable.create(
        path, data=data, partition_columns=["part"],
        configuration={"delta.checkpoint.writeStatsAsStruct": "true"},
    )
    md = t.delta_log.checkpoint()
    ckpt = f"{t.delta_log.log_path}/{filenames.checkpoint_file_single(md.version)}"
    table = pq.read_table(ckpt)
    add_type = table.schema.field("add").type
    names = [add_type.field(i).name for i in range(add_type.num_fields)]
    assert "partitionValues_parsed" in names
    assert "stats_parsed" in names
    adds = [r for r in table.column("add").to_pylist() if r is not None]
    by_part = {r["partitionValues_parsed"]["part"]: r for r in adds}
    assert by_part["b"]["stats_parsed"]["minValues"]["x"] == 30
    assert by_part["a"]["stats_parsed"]["numRecords"] == 2
    assert by_part["a"]["stats_parsed"]["nullCount"]["x"] == 0

    # normal read path unaffected
    DeltaLog.clear_cache()
    t2 = DeltaTable.for_path(path)
    assert t2.to_arrow().num_rows == 3
    assert t2.to_arrow(filters=["part = 'b'"]).column("x").to_pylist() == [30]


def _add_field_names(t, md):
    import pyarrow.parquet as pq

    from delta_tpu.protocol import filenames

    ckpt = f"{t.delta_log.log_path}/{filenames.checkpoint_file_single(md.version)}"
    add_type = pq.read_table(ckpt).schema.field("add").type
    return [add_type.field(i).name for i in range(add_type.num_fields)]


def test_default_checkpoint_has_v2_stats_struct(tmp_path):
    """The engine default (`delta.tpu.checkpoint.writeStatsAsStruct`, on)
    materializes `stats_parsed` so the cold state-cache build reads typed
    columns instead of re-parsing stats JSON."""
    import pyarrow as pa

    from delta_tpu.api.tables import DeltaTable

    path = str(tmp_path / "t")
    t = DeltaTable.create(
        path, data=pa.table({"x": pa.array([1], pa.int64())})
    )
    md = t.delta_log.checkpoint()
    assert "stats_parsed" in _add_field_names(t, md)


def test_table_property_opts_out_of_v2_columns(tmp_path):
    """An explicit `delta.checkpoint.writeStatsAsStruct=false` table
    property (and likewise the session conf, when the property is unset)
    suppresses the V2 typed columns."""
    import pyarrow as pa

    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.utils.config import conf

    path = str(tmp_path / "t")
    t = DeltaTable.create(
        path, data=pa.table({"x": pa.array([1], pa.int64())}),
        configuration={"delta.checkpoint.writeStatsAsStruct": "false"},
    )
    md = t.delta_log.checkpoint()
    names = _add_field_names(t, md)
    assert "stats_parsed" not in names and "partitionValues_parsed" not in names

    path2 = str(tmp_path / "t2")
    with conf.set_temporarily(**{"delta.tpu.checkpoint.writeStatsAsStruct": False}):
        t2 = DeltaTable.create(
            path2, data=pa.table({"x": pa.array([1], pa.int64())})
        )
        md2 = t2.delta_log.checkpoint()
    names2 = _add_field_names(t2, md2)
    assert "stats_parsed" not in names2 and "partitionValues_parsed" not in names2


def test_v2_checkpoint_typed_and_nested_stats(tmp_path):
    """Date/timestamp stats arrive as ISO strings in the stats JSON and
    struct columns nest their nullCount — the V2 writer must coerce both
    instead of crashing the checkpoint."""
    import datetime

    import pyarrow as pa
    import pyarrow.parquet as pq

    from delta_tpu.api.tables import DeltaTable
    from delta_tpu.protocol import filenames

    path = str(tmp_path / "t")
    data = pa.table({
        "d": pa.array([datetime.date(2024, 1, 2), datetime.date(2024, 3, 4)]),
        "ts": pa.array([datetime.datetime(2024, 1, 2, 3, 4, 5),
                        datetime.datetime(2024, 6, 7, 8, 9, 10)],
                       pa.timestamp("us")),
        "s": pa.array([{"a": 1, "b": None}, {"a": 2, "b": "x"}],
                      pa.struct([("a", pa.int64()), ("b", pa.string())])),
    })
    t = DeltaTable.create(
        path, data=data,
        configuration={"delta.checkpoint.writeStatsAsStruct": "true"},
    )
    md = t.delta_log.checkpoint()  # must not raise
    ckpt = f"{t.delta_log.log_path}/{filenames.checkpoint_file_single(md.version)}"
    [add] = [r for r in pq.read_table(ckpt).column("add").to_pylist() if r]
    sp = add["stats_parsed"]
    assert sp["minValues"]["d"] == datetime.date(2024, 1, 2)
    assert sp["maxValues"]["ts"].year == 2024
    if sp["nullCount"]["s"] is not None:
        assert isinstance(sp["nullCount"]["s"], dict)


# -- columnar checkpoint writer (round 4) -----------------------------------


def _read_checkpoint_rows(store, paths):
    import io

    import pyarrow.parquet as pq

    tables = [pq.read_table(io.BytesIO(store.read_bytes(p))) for p in paths]
    rows = []
    for t in tables:
        rows.extend(t.to_pylist())
    return rows


def _row_key(r):
    for k in ("add", "remove", "metaData", "protocol", "txn"):
        if r.get(k) is not None:
            inner = r[k]
            return (k, inner.get("path") or inner.get("appId") or inner.get("id") or "")
    return ("?", "")


def test_columnar_checkpoint_matches_dataclass_writer(tmp_table):
    """The columnar fast path and the dataclass row builder must produce
    the same checkpoint CONTENT (row sets equal; both reconstruct)."""
    import numpy as np
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.delete import DeleteCommand
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.log import checkpoints as ckpt_mod
    from delta_tpu.utils.config import conf

    log = DeltaLog.for_table(tmp_table)
    rng = np.random.RandomState(2)
    for i in range(4):
        WriteIntoDelta(log, "append", pa.table({
            "a": np.arange(i * 25, (i + 1) * 25, dtype=np.int64),
            "b": rng.rand(25),
        })).run()
    with conf.set_temporarily(**{"delta.tpu.deletionVectors.enabled": False}):
        DeleteCommand(log, "a < 25").run()  # whole-file remove -> tombstone
    snap = log.update()

    md_col = ckpt_mod.write_checkpoint_columnar(
        log.store, log.log_path, snap)
    assert md_col is not None
    col_rows = _read_checkpoint_rows(
        log.store,
        ckpt_mod.CheckpointInstance(md_col.version, md_col.parts).paths(log.log_path))

    # dataclass writer into a scratch log dir for comparison
    import os

    scratch = os.path.join(tmp_table, "_scratch_log")
    from delta_tpu.storage.logstore import get_log_store

    store2 = get_log_store(scratch)
    md_row = ckpt_mod.write_checkpoint(
        store2, scratch, snap.version, snap.checkpoint_actions())
    row_rows = _read_checkpoint_rows(
        store2,
        ckpt_mod.CheckpointInstance(md_row.version, md_row.parts).paths(scratch))

    assert sorted(col_rows, key=_row_key) == sorted(row_rows, key=_row_key)

    # cold reader reconstructs from the columnar checkpoint
    DeltaLog.clear_cache()
    snap2 = DeltaLog.for_table(tmp_table).update()
    assert snap2.segment.checkpoint_version == snap.version
    assert snap2.num_of_files == snap.num_of_files
    assert len(snap2.tombstones) == len(snap.tombstones)


def test_columnar_checkpoint_falls_back(tmp_table):
    """Partitioned tables and DV-carrying segments take the dataclass path."""
    import numpy as np
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.delete import DeleteCommand
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.log import checkpoints as ckpt_mod
    from delta_tpu.utils.config import conf

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({
        "a": np.arange(50, dtype=np.int64), "b": np.zeros(50)})).run()
    from delta_tpu.commands.alter import set_table_properties

    set_table_properties(log, {"delta.tpu.enableDeletionVectors": "true"})
    with conf.set_temporarily(**{"delta.tpu.deletionVectors.enabled": True}):
        DeleteCommand(log, "a = 3").run()  # DV on a file action
    snap = log.update()
    assert ckpt_mod.write_checkpoint_columnar(log.store, log.log_path, snap) is None
    # but DeltaLog.checkpoint still works via the fallback
    md = log.checkpoint(snap)
    DeltaLog.clear_cache()
    snap2 = DeltaLog.for_table(tmp_table).update()
    assert snap2.num_of_files == snap.num_of_files
    import pyarrow.compute as pc

    from delta_tpu.exec.scan import scan_to_table

    assert scan_to_table(snap2).num_rows == 49  # the DV'd row stays deleted
