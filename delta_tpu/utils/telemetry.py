"""Structured telemetry — the engine-wide observability subsystem.

Reference: ``metering/DeltaLogging.scala:50-109`` wraps every user action in
``recordDeltaOperation(opType)`` / ``recordDeltaEvent`` with hierarchical op
types (e.g. ``delta.commit.retry.conflictCheck``) and JSON payloads; the OSS
backend is a no-op stub. Here the backend is real, in three pieces:

1. **Hierarchical spans** — :func:`record_operation` nests via a contextvar
   parent stack, so ``delta.commit`` contains its ``prepare`` /
   ``conflictCheck`` / ``write`` / ``postCommit`` phases and a scan contains
   its planning/prune phases. Spans export as Chrome trace-event JSON
   (:func:`export_chrome_trace`) loadable in Perfetto / ``chrome://tracing``
   alongside the ``jax.named_scope`` annotations each span also opens, so
   device timelines line up with engine operations. Contextvars give each
   thread its own stack: concurrent writers never parent each other's spans.

2. **A metrics registry** — monotonic counters (:func:`bump_counter`),
   gauges (:func:`set_gauge`) and fixed log2-bucket latency histograms
   (:func:`observe`), with Prometheus text exposition
   (:func:`prometheus_text`) and a JSON snapshot
   (:func:`metrics_snapshot`). Gauges and histograms take labels (e.g. the
   table path); counters stay label-free name strings — they are the hot
   path and a dict bump must stay a dict bump.

3. **Events** — :func:`record_event` point-in-time payloads (the analogue of
   ``recordDeltaEvent``), e.g. the per-commit ``delta.commit.stats``.

Everything lands in one in-process ring buffer (size:
``delta.tpu.telemetry.bufferSize``, default 4096) and a standard ``logging``
logger. ``delta.tpu.telemetry.enabled=False`` suppresses events and spans
entirely (zero allocation on the hot path); counters keep working — they are
cheap and the serving-envelope numbers must survive an event blackout.

Spans are also DISTRIBUTED traces: every root span mints a 128-bit hex
``trace_id``, span ids are namespaced with a random per-process high word so
two hosts can never collide, and :func:`span_context(wire=True)` serializes
the identity as a traceparent-shaped string that
:func:`adopt_span_context` (and the ``DELTA_TPU_TRACEPARENT`` environment
variable, for spawned worker processes) accepts — a sharded job's per-item /
per-worker / per-host spans all parent under the coordinator's root. Sampled
traces (head sampling via ``delta.tpu.trace.sampleRate``; forced on error
and while SLO objectives burn) additionally stream each completed span to
registered span sinks — ``obs/trace_store`` spools them as JSONL for
cross-process stitching.
"""
from __future__ import annotations

import contextlib
import contextvars
import functools
import itertools
import json
import logging
import os
import random
import re
import sys
import threading
import time
from bisect import bisect_left
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterator, List, Optional, Sequence, Tuple

from delta_tpu.utils.config import conf

logger = logging.getLogger("delta_tpu.usage")

__all__ = [
    "record_event", "record_operation", "with_status", "recent_events",
    "clear_events", "UsageEvent", "bump_counter", "counters",
    "clear_counters", "set_gauge", "gauges", "observe", "histograms",
    "prometheus_text", "metrics_snapshot", "bench_snapshot",
    "export_chrome_trace", "current_span", "add_span_data", "reset_all",
    "HISTOGRAM_BUCKETS", "span_stack_snapshot", "add_failure_hook",
    "remove_failure_hook", "span_context", "adopt_span_context", "propagated",
    "histogram_rows", "bucket_quantile", "drop_labeled_series",
    "current_trace_id", "last_sampled_trace_id", "add_span_sink",
    "remove_span_sink", "TRACEPARENT_ENV",
]


@dataclass
class UsageEvent:
    op_type: str
    timestamp_ms: int
    duration_ms: Optional[int] = None
    tags: Dict[str, str] = field(default_factory=dict)
    data: Dict[str, Any] = field(default_factory=dict)
    error: Optional[str] = None
    # span identity (0/None on plain events recorded outside any operation)
    span_id: int = 0
    parent_id: Optional[int] = None
    depth: int = 0
    # trace-export timeline: microseconds on the perf_counter clock
    start_us: int = 0
    duration_us: Optional[int] = None
    thread_id: int = 0
    thread_name: str = ""
    # distributed-trace identity: 32-hex trace id shared across processes,
    # plus the span start on the EPOCH clock (µs) — perf_counter is
    # per-process and cannot order spans from two hosts on one timeline
    trace_id: str = ""
    wall_us: int = 0

    def to_json(self) -> str:
        return json.dumps(
            {
                "opType": self.op_type,
                "timestamp": self.timestamp_ms,
                "durationMs": self.duration_ms,
                "tags": self.tags,
                "data": self.data,
                "error": self.error,
                "spanId": self.span_id or None,
                "parentId": self.parent_id,
                "traceId": self.trace_id or None,
            },
            separators=(",", ":"),
            default=str,
        )


_BUFFER: Deque[UsageEvent] = deque(maxlen=4096)
_LOCK = threading.Lock()
_SPAN_IDS = itertools.count(1)
# span ids are globally unique across a distributed job: a random 32-bit
# per-process namespace in the high word, the local counter in the low —
# two hosts' spools can stitch into one trace without id collisions
_SPAN_NS = int.from_bytes(os.urandom(4), "big") << 32
# innermost-last tuple of active span ids for THIS thread/context
_SPAN_STACK: "contextvars.ContextVar[Tuple[int, ...]]" = contextvars.ContextVar(
    "delta_telemetry_span_stack", default=()
)
# spans currently open (still mutable via add_span_data), by span id
_ACTIVE: Dict[int, UsageEvent] = {}
# callables invoked when a span closes with an exception: fn(event, exc).
# Empty by default — the error path pays one truthiness check. Consumers
# (obs/flight_recorder) must never raise; failures are swallowed here so a
# broken hook can't mask the original error.
_FAILURE_HOOKS: List[Any] = []


# -- distributed trace identity ----------------------------------------------

#: environment variable a coordinator sets on spawned worker processes so
#: every root span in the child adopts the coordinator's trace
TRACEPARENT_ENV = "DELTA_TPU_TRACEPARENT"


class _TraceState:
    """Mutable per-trace identity: the 128-bit hex trace id, the head-sampling
    decision (mutable — an error anywhere in the trace force-samples it), and
    the remote parent span id when the trace was adopted over the wire."""

    __slots__ = ("trace_id", "sampled", "remote_parent")

    def __init__(self, trace_id: str, sampled: bool,
                 remote_parent: Optional[int] = None):
        self.trace_id = trace_id
        self.sampled = sampled
        self.remote_parent = remote_parent


# the current trace for THIS context: set by the root span (reset when it
# closes) or by adopt_span_context, so sequential roots get fresh traces
_TRACE: "contextvars.ContextVar[Optional[_TraceState]]" = contextvars.ContextVar(
    "delta_telemetry_trace", default=None
)
# process-wide remote parent parsed once from TRACEPARENT_ENV (spawned
# workers: EVERY root span in the process joins the coordinator's trace)
_PROCESS_REMOTE: Optional[_TraceState] = None
_PROCESS_REMOTE_READ = False
# completed spans of sampled traces stream here: fn(event) after the span
# closes (obs/trace_store spools them as JSONL). Lazily installed on the
# first sampled close so importing telemetry never drags in the obs layer.
_SPAN_SINKS: List[Any] = []
_SINKS_PROBED = False
_LAST_SAMPLED_TRACE: str = ""


def _parse_traceparent(carrier: str) -> _TraceState:
    """Parse a ``00-<32hex traceId>-<16hex parentSpanId>-<2hex flags>``
    wire carrier (traceparent-shaped; flags bit 0 = sampled)."""
    parts = carrier.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        raise ValueError(f"malformed trace carrier: {carrier!r}")
    int(parts[1], 16)
    parent = int(parts[2], 16)
    sampled = bool(int(parts[3], 16) & 1)
    return _TraceState(parts[1], sampled, parent or None)


def _process_remote() -> Optional[_TraceState]:
    global _PROCESS_REMOTE, _PROCESS_REMOTE_READ
    if not _PROCESS_REMOTE_READ:
        _PROCESS_REMOTE_READ = True
        raw = os.environ.get(TRACEPARENT_ENV)
        if raw:
            try:
                _PROCESS_REMOTE = _parse_traceparent(raw)
            except ValueError:
                logger.warning("ignoring malformed %s=%r", TRACEPARENT_ENV, raw)
    return _PROCESS_REMOTE


def _slo_burning() -> bool:
    """True while any SLO objective fires — forced sampling during burn
    windows so the alert always has an exemplar trace. Probed via
    sys.modules: telemetry must not import the obs layer, and a process
    that never evaluated SLOs pays one dict lookup."""
    slo = sys.modules.get("delta_tpu.obs.slo")
    if slo is None:
        return False
    try:
        return slo.firing_count() > 0
    except Exception:  # noqa: BLE001
        return False


def _new_trace_state() -> _TraceState:
    remote = _process_remote()
    if remote is not None:
        return _TraceState(remote.trace_id, remote.sampled,
                           remote.remote_parent)
    rate = _conf_snapshot()[3]
    if rate >= 1.0:
        sampled = True
    else:
        sampled = rate > 0.0 and random.random() < rate
        if not sampled and _slo_burning():
            sampled = True
    return _TraceState(os.urandom(16).hex(), sampled)


def _emit_span(ev: UsageEvent) -> None:
    """Stream a completed span of a sampled trace to the sinks (called
    OUTSIDE ``_LOCK`` — sinks take their own locks and read conf)."""
    global _SINKS_PROBED
    if not _SINKS_PROBED:
        _SINKS_PROBED = True
        try:
            from delta_tpu.obs import trace_store

            trace_store.install()
        except Exception:  # noqa: BLE001 — tracing must never break the op
            logger.debug("trace spool install failed", exc_info=True)
    for sink in list(_SPAN_SINKS):
        try:
            sink(ev)
        except Exception:  # noqa: BLE001
            logger.debug("trace span sink raised", exc_info=True)


def add_span_sink(fn) -> None:
    """Register ``fn(event)`` to receive every completed span/event of a
    sampled trace. Sinks must be fast and must not raise."""
    if fn not in _SPAN_SINKS:
        _SPAN_SINKS.append(fn)


def remove_span_sink(fn) -> None:
    try:
        _SPAN_SINKS.remove(fn)
    except ValueError:
        pass


def current_trace_id() -> Optional[str]:
    """The trace id of the current context (inside a span or an adopted
    wire context), or None."""
    t = _TRACE.get()
    return t.trace_id if t is not None else None


def last_sampled_trace_id() -> Optional[str]:
    """The most recently completed SAMPLED span's trace id — the exemplar
    an SLO alert or incident attaches when it has no ambient span."""
    return _LAST_SAMPLED_TRACE or None


# (generation, enabled, buffer_size, sample_rate) — the conf reads on the
# per-span hot path, re-resolved only when conf mutates. Benign race: a
# stale read costs one redundant resolve, never a wrong value for the
# generation it is keyed to.
_CONF_CACHE: Tuple[int, bool, int, float] = (-1, True, 4096, 1.0)


def _conf_snapshot() -> Tuple[int, bool, int, float]:
    global _CONF_CACHE
    cached = _CONF_CACHE
    gen = conf.generation()
    if cached[0] == gen:
        return cached
    enabled = conf.get_bool("delta.tpu.telemetry.enabled", True)
    try:
        size = int(conf.get("delta.tpu.telemetry.bufferSize", 4096))
    except (TypeError, ValueError):
        size = 4096
    if size <= 0:
        size = 4096
    try:
        rate = float(conf.get("delta.tpu.trace.sampleRate", 1.0))
    except (TypeError, ValueError):
        rate = 1.0
    cached = (gen, enabled, size, rate)
    _CONF_CACHE = cached
    return cached


def _enabled() -> bool:
    return _conf_snapshot()[1]


def _buffer_size() -> int:
    """Resolve the configured ring size OUTSIDE the telemetry lock — the
    conf lock must never be taken while holding ``_LOCK``."""
    return _conf_snapshot()[2]


def _buffer_locked(size: int) -> Deque[UsageEvent]:
    """The ring buffer at ``size``; callers hold ``_LOCK``."""
    global _BUFFER
    if _BUFFER.maxlen != size:
        _BUFFER = deque(_BUFFER, maxlen=size)
    return _BUFFER


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


def record_event(op_type: str, data: Optional[Dict[str, Any]] = None, **tags: str) -> None:
    if not _enabled():
        return
    th = threading.current_thread()
    tstate = _TRACE.get()
    ev = UsageEvent(op_type, int(time.time() * 1000),
                    tags={k: str(v) for k, v in tags.items()},
                    data=data or {},
                    parent_id=(_SPAN_STACK.get() or (None,))[-1],
                    start_us=_now_us(),
                    thread_id=th.ident or 0, thread_name=th.name,
                    trace_id=tstate.trace_id if tstate else "",
                    wall_us=time.time_ns() // 1000)
    size = _buffer_size()
    with _LOCK:
        _buffer_locked(size).append(ev)
    if tstate is not None and tstate.sampled:
        _emit_span(ev)
    if logger.isEnabledFor(logging.DEBUG):
        logger.debug("%s", ev.to_json())


@contextlib.contextmanager
def record_operation(op_type: str, data: Optional[Dict[str, Any]] = None, **tags: str) -> Iterator[UsageEvent]:
    """Wrap an operation in a span: duration + error capture + parent/child
    nesting + JAX profiler annotation. The yielded event is live — mutate
    ``ev.data`` (or call :func:`add_span_data` from anywhere below) to attach
    payloads before the span closes."""
    if not _enabled():
        # zero-overhead: no span bookkeeping, no buffer append, no timing
        yield UsageEvent(op_type, 0, data=dict(data or {}))
        return
    th = threading.current_thread()
    stack = _SPAN_STACK.get()
    tstate = _TRACE.get()
    ttoken = None
    if tstate is None:
        # this is a trace root: mint the 128-bit trace id (or join the
        # process-wide remote parent) and decide head sampling once
        tstate = _new_trace_state()
        ttoken = _TRACE.set(tstate)
    ev = UsageEvent(op_type, int(time.time() * 1000),
                    tags={k: str(v) for k, v in tags.items()},
                    data=dict(data or {}),
                    span_id=_SPAN_NS | next(_SPAN_IDS),
                    parent_id=stack[-1] if stack else tstate.remote_parent,
                    depth=len(stack),
                    start_us=_now_us(),
                    thread_id=th.ident or 0, thread_name=th.name,
                    trace_id=tstate.trace_id,
                    wall_us=time.time_ns() // 1000)
    with _LOCK:
        _ACTIVE[ev.span_id] = ev
    token = _SPAN_STACK.set(stack + (ev.span_id,))
    start_ns = time.perf_counter_ns()
    try:
        with _maybe_jax_trace(op_type):
            yield ev
    except BaseException as e:
        ev.error = f"{type(e).__name__}: {e}"
        # an error anywhere force-samples the whole trace: the incident the
        # flight recorder writes must link to a spooled, stitchable trace
        tstate.sampled = True
        # span still on the stack and in _ACTIVE here: hooks see the full
        # failing span chain via span_stack_snapshot()
        if _FAILURE_HOOKS:
            for hook in list(_FAILURE_HOOKS):
                try:
                    hook(ev, e)
                except Exception:  # noqa: BLE001 — never mask the original
                    logger.debug("telemetry failure hook raised", exc_info=True)
        raise
    finally:
        _SPAN_STACK.reset(token)
        if ttoken is not None:
            _TRACE.reset(ttoken)
        dur_us = (time.perf_counter_ns() - start_ns) // 1000
        ev.duration_us = int(dur_us)
        ev.duration_ms = int(dur_us // 1000)
        size = _buffer_size()
        with _LOCK:
            _ACTIVE.pop(ev.span_id, None)
            _buffer_locked(size).append(ev)
        if tstate.sampled:
            global _LAST_SAMPLED_TRACE
            _LAST_SAMPLED_TRACE = tstate.trace_id
            _emit_span(ev)
        # to_json serialises tags+data — only pay for it when debug logging
        # is actually on (this is the per-span hot path)
        if logger.isEnabledFor(logging.DEBUG):
            logger.debug("%s", ev.to_json())


def current_span() -> Optional[UsageEvent]:
    """The innermost open span in this context, or None."""
    stack = _SPAN_STACK.get()
    if not stack:
        return None
    with _LOCK:
        return _ACTIVE.get(stack[-1])


def span_stack_snapshot() -> List[Dict[str, Any]]:
    """The open span chain for THIS context, outermost first, as JSON-able
    dicts (opType/spanId/parentId/depth/tags/data/elapsedMs/error). The raw
    events stay private — they are still live and mutating."""
    stack = _SPAN_STACK.get()
    if not stack:
        return []
    now = _now_us()
    out: List[Dict[str, Any]] = []
    with _LOCK:
        # copy payload dicts under the lock — the events are live
        for sid in stack:
            ev = _ACTIVE.get(sid)
            if ev is None:
                continue
            out.append({
                "opType": ev.op_type,
                "spanId": ev.span_id,
                "parentId": ev.parent_id,
                "depth": ev.depth,
                "tags": dict(ev.tags),
                "data": dict(ev.data),
                "elapsedMs": max(0, (now - ev.start_us) // 1000),
                "error": ev.error,
            })
    return out


# -- cross-thread span propagation -------------------------------------------
#
# Contextvars isolate each thread's span stack — correct for concurrent
# writers, wrong for the engine's OWN worker threads: a Parquet decode pool,
# a checkpoint part writer, or the MERGE staging/uploader threads would each
# start an orphan span root, and the decode/compute overlap the router
# assumes becomes invisible in `export_chrome_trace`. The carrier pattern
# fixes it: capture the submitting context's open span chain at submit time
# (`span_context` / `propagated`), restore it inside the worker
# (`adopt_span_context`), and the worker's spans parent under the submitting
# operation while keeping their own thread lane in the trace.


class SpanContextCarrier(tuple):
    """In-process carrier: compares and unpacks exactly like the legacy
    span-id tuple, plus the trace state (``.trace``) so adopting threads
    keep the trace id and sampling decision."""

    trace: Optional[_TraceState] = None


def span_context(wire: bool = False) -> Any:
    """The open span chain of THIS context as an opaque carrier — capture at
    task-submit time, hand to the worker thread, restore with
    :func:`adopt_span_context`.

    With ``wire=True``, returns instead a serializable traceparent-shaped
    string (``00-<traceId>-<parentSpanId>-<flags>``) for crossing a PROCESS
    boundary — put it in a job payload or the ``DELTA_TPU_TRACEPARENT``
    environment of a spawned worker. None when no trace is active."""
    stack = _SPAN_STACK.get()
    tstate = _TRACE.get()
    if wire:
        if tstate is None:
            return None
        parent = stack[-1] if stack else (tstate.remote_parent or 0)
        return "00-%s-%016x-%s" % (tstate.trace_id, parent,
                                   "01" if tstate.sampled else "00")
    carrier = SpanContextCarrier(stack)
    carrier.trace = tstate
    return carrier


@contextlib.contextmanager
def adopt_span_context(carrier) -> Iterator[None]:
    """Run the body under ``carrier`` (a :func:`span_context` capture, or its
    ``wire=True`` string form): spans opened inside parent under the
    carrier's innermost span instead of starting an orphan root in the
    worker thread — and they join the carrier's trace."""
    if isinstance(carrier, str):
        tstate: Optional[_TraceState] = _parse_traceparent(carrier)
        stack: Tuple[int, ...] = ()
    else:
        tstate = getattr(carrier, "trace", None)
        stack = tuple(carrier)
    token = _SPAN_STACK.set(stack)
    ttoken = _TRACE.set(tstate) if tstate is not None else None
    try:
        yield
    finally:
        if ttoken is not None:
            _TRACE.reset(ttoken)
        _SPAN_STACK.reset(token)


def propagated(fn):
    """Wrap ``fn`` so it executes under the CURRENT context's span chain —
    the one-liner for thread pools::

        pool.map(telemetry.propagated(read_one), jobs)

    The capture happens NOW (at wrap time, i.e. task submit), not when the
    worker runs. Zero-overhead: with telemetry disabled or no span open,
    ``fn`` is returned unchanged."""
    if not _enabled():
        return fn
    carrier = _SPAN_STACK.get()
    if not carrier:
        return fn
    tstate = _TRACE.get()

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        token = _SPAN_STACK.set(carrier)
        ttoken = _TRACE.set(tstate) if tstate is not None else None
        try:
            return fn(*args, **kwargs)
        finally:
            if ttoken is not None:
                _TRACE.reset(ttoken)
            _SPAN_STACK.reset(token)

    return wrapper


def add_failure_hook(fn) -> None:
    """Register ``fn(event, exc)`` to run when any span exits with an
    exception (before the span closes, so the open stack is inspectable).
    Hooks must be fast and must not raise."""
    if fn not in _FAILURE_HOOKS:
        _FAILURE_HOOKS.append(fn)


def remove_failure_hook(fn) -> None:
    try:
        _FAILURE_HOOKS.remove(fn)
    except ValueError:
        pass


def add_span_data(**kv: Any) -> None:
    """Merge key/values into the innermost open span's data payload — how a
    layer deep inside an operation (e.g. DML rewrite metrics) reports into
    the span that wraps it, without threading the event object through."""
    ev = current_span()
    if ev is not None:
        ev.data.update(kv)


@contextlib.contextmanager
def with_status(message: str, **tags: str) -> Iterator[None]:
    """Human-readable job description around a long step — the analogue of
    the reference's ``DeltaProgressReporter.withStatusCode`` ("Filtering
    files for query", `PartitionFiltering.scala:34`). Logs at INFO on entry
    and records a `delta.status` usage event with the duration on exit, so
    operators can see WHAT a long-running command is doing, not just that
    it is running."""
    logger.info("%s", message)
    with record_operation("delta.status", {"message": message}, **tags):
        yield


def _maybe_jax_trace(name: str):
    try:
        import sys

        jax = sys.modules.get("jax")
        if jax is not None:
            return jax.named_scope(name.replace("delta.", "delta/"))
    except Exception:  # noqa: BLE001
        pass
    return contextlib.nullcontext()


def _prefix_match(name: str, prefix: str) -> bool:
    """Dotted-name boundary match: ``"delta.commit"`` matches itself and
    ``delta.commit.*`` but NOT ``delta.commitFoo``."""
    return not prefix or name == prefix or name.startswith(prefix + ".")


def recent_events(op_prefix: str = "") -> List[UsageEvent]:
    with _LOCK:
        return [e for e in _BUFFER if _prefix_match(e.op_type, op_prefix)]


def clear_events() -> None:
    with _LOCK:
        _BUFFER.clear()


# -- monotonic counters ------------------------------------------------------
#
# Cheap process-wide tallies for questions like "what fraction of scan
# plans actually served from the resident state cache, and why did the
# rest fall back?" — the serving envelope as a NUMBER, not a hope.
# Deliberately label-free and NOT gated on telemetry.enabled: a name lookup
# plus an int add, even during an event blackout.

_COUNTERS: Dict[str, int] = {}


def bump_counter(name: str, by: int = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + by


def counters(prefix: str = "") -> Dict[str, int]:
    with _LOCK:
        return {k: v for k, v in _COUNTERS.items() if _prefix_match(k, prefix)}


def clear_counters() -> None:
    with _LOCK:
        _COUNTERS.clear()


# -- gauges + histograms -----------------------------------------------------

LabelKey = Tuple[str, Tuple[Tuple[str, str], ...]]

#: Fixed log2 bucket upper bounds (ms when observing latencies):
#: 1, 2, 4, ..., 65536; values above the last bound land in +Inf.
HISTOGRAM_BUCKETS: Tuple[float, ...] = tuple(float(2 ** i) for i in range(17))

_GAUGES: Dict[LabelKey, float] = {}
_HISTOGRAMS: Dict[LabelKey, "_Histogram"] = {}


class _Histogram:
    __slots__ = ("counts", "sum", "count")

    def __init__(self):
        self.counts = [0] * (len(HISTOGRAM_BUCKETS) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0


def _label_key(name: str, labels: Dict[str, str]) -> LabelKey:
    return name, tuple(sorted((k, str(v)) for k, v in labels.items()))


def set_gauge(name: str, value: float, **labels: str) -> None:
    with _LOCK:
        _GAUGES[_label_key(name, labels)] = float(value)


def gauges(prefix: str = "") -> Dict[LabelKey, float]:
    with _LOCK:
        return {k: v for k, v in _GAUGES.items() if _prefix_match(k[0], prefix)}


def observe(name: str, value: float, **labels: str) -> None:
    """Record ``value`` into the fixed-log-bucket histogram ``name``."""
    value = float(value)
    key = _label_key(name, labels)
    ix = bisect_left(HISTOGRAM_BUCKETS, value)
    with _LOCK:
        h = _HISTOGRAMS.get(key)
        if h is None:
            h = _HISTOGRAMS[key] = _Histogram()
        h.counts[ix] += 1
        h.sum += value
        h.count += 1


def histograms(prefix: str = "") -> Dict[LabelKey, "_Histogram"]:
    with _LOCK:
        return {k: v for k, v in _HISTOGRAMS.items() if _prefix_match(k[0], prefix)}


def histogram_rows(prefix: str = "") -> List[Tuple[str, Tuple[Tuple[str, str], ...], List[int], float, int]]:
    """Immutable ``(name, labels, bucket_counts, sum, count)`` rows for every
    labeled histogram matching ``prefix`` — the payloads are COPIED under the
    lock, so the obs scraper (`obs/timeseries`) can diff cumulative bucket
    counts across scrapes without holding any reference to live state."""
    with _LOCK:
        return [(n, lb, list(h.counts), h.sum, h.count)
                for (n, lb), h in _HISTOGRAMS.items()
                if _prefix_match(n, prefix)]


def drop_labeled_series(**labels: str) -> int:
    """Remove every gauge/histogram series whose label set contains ALL of
    ``labels`` (e.g. ``drop_labeled_series(table=<hash>)``); returns the
    series dropped. The registry otherwise never forgets a labeled series,
    so per-table series would accumulate for the life of a long-running
    process under table churn — the fleet registry calls this when a
    table's handle dies (obs/fleet.live_tables). Counters are label-free
    and unaffected."""
    want = {(k, str(v)) for k, v in labels.items()}
    dropped = 0
    with _LOCK:
        for store in (_GAUGES, _HISTOGRAMS):
            dead = [key for key in store if want <= set(key[1])]
            for key in dead:
                del store[key]
            dropped += len(dead)
    return dropped


def clear_metrics() -> None:
    with _LOCK:
        _GAUGES.clear()
        _HISTOGRAMS.clear()


def reset_all() -> None:
    """Events + counters + gauges + histograms back to empty (tests, bench
    per-config isolation)."""
    with _LOCK:
        _BUFFER.clear()
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTOGRAMS.clear()


# -- exposition --------------------------------------------------------------

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _NAME_SANITIZE.sub("_", name)


def _prom_escape(v: str) -> str:
    # text-format label values require \\, \", \n escaping
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(labels: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{_prom_name(k)}="{_prom_escape(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _metric_descriptions() -> Dict[str, str]:
    """One-line ``# HELP`` text per cataloged metric name (lazy import —
    the obs layer sits above telemetry; a broken catalog must never break
    exposition)."""
    try:
        from delta_tpu.obs.metric_names import DESCRIPTIONS

        return DESCRIPTIONS
    except Exception:  # noqa: BLE001
        return {}


def prometheus_text() -> str:
    """Prometheus text-format exposition of every counter, gauge, and
    histogram (stable ordering — scrape-diff friendly). Cataloged names
    (``obs/metric_names.DESCRIPTIONS``) get a ``# HELP`` line so scrapers
    classify and document each series; ``# TYPE`` is emitted once per metric
    name (label sets of one gauge/histogram share their header)."""
    with _LOCK:
        ctrs = sorted(_COUNTERS.items())
        gags = sorted(_GAUGES.items())
        hists = sorted(_HISTOGRAMS.items(), key=lambda kv: kv[0])
        hist_rows = [(k, list(h.counts), h.sum, h.count) for k, h in hists]
    descs = _metric_descriptions()
    lines: List[str] = []

    def _header(name: str, pn: str, kind: str, seen: set) -> None:
        if name in seen:
            return
        seen.add(name)
        if name in descs:
            lines.append(f"# HELP {pn} {descs[name]}")
        lines.append(f"# TYPE {pn} {kind}")

    seen_ctr: set = set()
    for name, value in ctrs:
        pn = _prom_name(name) + "_total"
        _header(name, pn, "counter", seen_ctr)
        lines.append(f"{pn} {value}")
    seen_g: set = set()
    for (name, labels), value in gags:
        pn = _prom_name(name)
        _header(name, pn, "gauge", seen_g)
        lines.append(f"{pn}{_prom_labels(labels)} {_fmt(value)}")
    seen_h: set = set()
    for (name, labels), counts, total, count in hist_rows:
        pn = _prom_name(name)
        _header(name, pn, "histogram", seen_h)
        cum = 0
        for bound, c in zip(HISTOGRAM_BUCKETS, counts):
            cum += c
            le = _prom_labels(labels, f'le="{_fmt(bound)}"')
            lines.append(f"{pn}_bucket{le} {cum}")
        cum += counts[-1]
        inf_labels = _prom_labels(labels, 'le="+Inf"')
        lines.append(f"{pn}_bucket{inf_labels} {cum}")
        lines.append(f"{pn}_sum{_prom_labels(labels)} {_fmt(total)}")
        lines.append(f"{pn}_count{_prom_labels(labels)} {count}")
    return "\n".join(lines) + ("\n" if lines else "")


def _labels_suffix(labels: Tuple[Tuple[str, str], ...]) -> str:
    return "{" + ",".join(f"{k}={v}" for k, v in labels) + "}" if labels else ""


def bucket_quantile(counts: Sequence[int], count: int, q: float) -> Optional[float]:
    """Upper bucket bound where the cumulative count crosses q (approximate,
    conservative-upward — the usual bucket-quantile estimate). Public: the
    obs scraper extracts windowed quantiles from cumulative-bucket deltas
    with exactly this rule, so /slo and bench_snapshot can never disagree.
    Returns None for an empty histogram or a crossing past the last bound
    (the +Inf bucket) — callers choose their own sentinel."""
    if count <= 0:
        return None
    target = q * count
    cum = 0
    for bound, c in zip(HISTOGRAM_BUCKETS, counts):
        cum += c
        if cum >= target:
            return bound
    return None  # beyond the last bound (+Inf bucket) — keep JSON strict


_hist_quantile = bucket_quantile


def metrics_snapshot() -> Dict[str, Any]:
    """JSON-able snapshot of the whole registry."""
    with _LOCK:
        ctrs = dict(_COUNTERS)
        gags = dict(_GAUGES)
        hists = [((n, lb), list(h.counts), h.sum, h.count)
                 for (n, lb), h in _HISTOGRAMS.items()]
    out: Dict[str, Any] = {
        "counters": dict(sorted(ctrs.items())),
        "gauges": {f"{n}{_labels_suffix(lb)}": v
                   for (n, lb), v in sorted(gags.items())},
        "histograms": {},
    }
    for (n, lb), counts, total, count in sorted(hists, key=lambda r: r[0]):
        buckets = {_fmt(b): c for b, c in zip(HISTOGRAM_BUCKETS, counts) if c}
        if counts[-1]:
            buckets["+Inf"] = counts[-1]
        out["histograms"][f"{n}{_labels_suffix(lb)}"] = {
            "count": count, "sum": round(total, 3), "buckets": buckets,
        }
    return out


def bench_snapshot(top: int = 12,
                   include: Sequence[str] = ()) -> Dict[str, Any]:
    """Compact per-bench-config attachment: top counters by value plus
    histogram summaries (count/sum/approx p50/p95) — internal metrics for
    BENCH_*.json trajectories, not just wall-clock. Counters AND gauges
    matching an ``include`` prefix ride along even when they miss the top-N
    cut (skip rates and health gauges matter at every magnitude)."""
    with _LOCK:
        ctrs = sorted(_COUNTERS.items(), key=lambda kv: (-kv[1], kv[0]))[:top]
        if include:
            seen = {k for k, _ in ctrs}
            ctrs += [
                (k, v) for k, v in sorted(_COUNTERS.items())
                if k not in seen and any(_prefix_match(k, p) for p in include)
            ]
        gags = (
            {k: v for k, v in _GAUGES.items()
             if any(_prefix_match(k[0], p) for p in include)}
            if include else {}
        )
        hists = [((n, lb), list(h.counts), h.sum, h.count)
                 for (n, lb), h in _HISTOGRAMS.items()]
    out: Dict[str, Any] = {"counters": dict(ctrs), "histograms": {}}
    if gags:
        out["gauges"] = {f"{n}{_labels_suffix(lb)}": v
                        for (n, lb), v in sorted(gags.items())}
    for (n, lb), counts, total, count in sorted(hists, key=lambda r: r[0]):
        out["histograms"][f"{n}{_labels_suffix(lb)}"] = {
            "count": count,
            "sum": round(total, 3),
            "p50": _hist_quantile(counts, count, 0.50),
            "p95": _hist_quantile(counts, count, 0.95),
        }
    return out


# -- Chrome trace-event export (Perfetto / chrome://tracing) -----------------

#: default thread names (Thread-12, ThreadPoolExecutor-0_3, MainThread is
#: kept — it IS informative); engine pools override these on a recycled tid
_GENERIC_THREAD = re.compile(r"(Thread-\d+.*|ThreadPoolExecutor-\d+_\d+)")


def export_chrome_trace(path: Optional[str] = None, op_prefix: str = "",
                        limit: Optional[int] = None) -> Dict[str, Any]:
    """Export the event ring buffer as Chrome trace-event JSON.

    Spans become complete ("X") events with real durations; point events
    become instants ("i"). Spans still OPEN at export time (in ``_ACTIVE``,
    not yet in the ring buffer) are emitted too, with their duration clamped
    to "now" and ``args.incomplete = true`` — an export taken mid-operation
    must show the operation, not silently drop it. Thread-name metadata rows
    keep multi-writer traces readable. Load the result in
    https://ui.perfetto.dev or ``chrome://tracing``; with the JAX profiler
    active, span names also appear as ``delta/...`` named scopes on the
    device timeline.

    ``op_prefix`` keeps only ops on a dotted-name boundary match
    (``delta.commit`` matches ``delta.commit.*``); ``limit`` keeps only the
    NEWEST N ring events (open spans always export — they are the current
    operation)."""
    pid = os.getpid()
    now_us = _now_us()
    with _LOCK:
        events = list(_BUFFER)
        # open spans are still LIVE (add_span_data mutates ev.data with no
        # lock): copy their payloads while we hold the lock, or a concurrent
        # mutation mid-iteration blows up the export
        open_clamped = [
            (ev.op_type, ev.thread_id or 0, ev.thread_name,
             dict(ev.tags), dict(ev.data), ev.error,
             ev.span_id, ev.parent_id, ev.start_us,
             max(0, now_us - ev.start_us))
            for ev in sorted(_ACTIVE.values(), key=lambda e: e.start_us)
            if _prefix_match(ev.op_type, op_prefix)
        ]
    if op_prefix:
        events = [e for e in events if _prefix_match(e.op_type, op_prefix)]
    if limit is not None and limit >= 0:
        events = events[-limit:] if limit else []
    rows: List[Dict[str, Any]] = []
    seen_tids: Dict[int, str] = {}

    def _note_tid(tid: int, tname: str) -> None:
        # prefer an engine-named lane (delta-scan-decode_3, merge-slab-
        # upload, delta-journal-writer, ...) over a generic Thread-N: the
        # OS recycles thread ids across pool generations, and the named
        # pools are what make a multi-lane trace readable in Perfetto
        name = tname or str(tid)
        cur = seen_tids.get(tid)
        if cur is None:
            seen_tids[tid] = name
        elif _GENERIC_THREAD.fullmatch(cur) and not _GENERIC_THREAD.fullmatch(name):
            seen_tids[tid] = name

    for ev in events:
        tid = ev.thread_id or 0
        _note_tid(tid, ev.thread_name)
        args: Dict[str, Any] = {}
        if ev.tags:
            args.update(ev.tags)
        if ev.data:
            args.update(ev.data)
        if ev.error:
            args["error"] = ev.error
        if ev.span_id:
            args["spanId"] = ev.span_id
        if ev.parent_id:
            args["parentId"] = ev.parent_id
        if ev.trace_id:
            args["traceId"] = ev.trace_id
        row: Dict[str, Any] = {
            "name": ev.op_type,
            "cat": "delta",
            "pid": pid,
            "tid": tid,
            "ts": ev.start_us,
            "args": args,
        }
        if ev.duration_us is not None:
            row["ph"] = "X"
            row["dur"] = ev.duration_us
        else:
            row["ph"] = "i"
            row["s"] = "t"
        rows.append(row)
    for (op_type, tid, tname, tags, data, error,
         span_id, parent_id, start_us, dur) in open_clamped:
        _note_tid(tid, tname)
        args = dict(tags)
        args.update(data)
        if error:
            args["error"] = error
        args["spanId"] = span_id
        if parent_id:
            args["parentId"] = parent_id
        args["incomplete"] = True
        rows.append({
            "name": op_type, "cat": "delta", "pid": pid, "tid": tid,
            "ts": start_us, "ph": "X", "dur": dur, "args": args,
        })
    # metadata rows: the process lane plus one thread_name per tid, so the
    # registered pools (delta-scan-decode, delta-merge-slab-upload,
    # delta-merge-device-probe, delta-ckpt-part, ... — see
    # analysis/passes/pool_naming.REGISTERED_POOLS) render as labeled lanes
    rows.append({
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "delta-tpu"},
    })
    for tid, tname in seen_tids.items():
        rows.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": tname},
        })
    trace = {"traceEvents": rows, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f, default=str)
    return trace
