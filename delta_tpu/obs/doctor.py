"""Table-health doctor — interpret the raw state into severities + remedies.

The reference surfaces raw numbers (``DESCRIBE DETAIL``, per-file stats,
checkpoint metadata) and leaves interpretation to the operator; small-file
and layout debt is the dominant silent performance killer in file-based
tables ("Only Aggressive Elephants are Fast Elephants", PAPERS.md), so this
module computes it: :func:`doctor` walks the current snapshot and
``_delta_log`` segment and yields one :class:`HealthDimension` per axis of
debt, each with a severity (``ok``/``warn``/``critical``), the numbers that
justified it, and the remedy command (OPTIMIZE / CHECKPOINT / VACUUM / PURGE
/ REPARTITION). Every numeric metric is also published as a
``table.health.*`` gauge (labeled by table path, names validated against
``obs/metric_names.py``) so the report flows into ``/metrics`` scrapes and
``bench.py`` snapshots without a second pipeline.

Thresholds are module constants, deliberately simple and visible — the
doctor's job is to rank debt, not to model it precisely.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from delta_tpu.obs import actions as actions_mod
from delta_tpu.obs.metric_names import health_gauge
from delta_tpu.utils import telemetry

__all__ = ["HealthDimension", "TableHealthReport", "doctor", "SEVERITY_RANK"]

SEVERITY_RANK = {"ok": 0, "warn": 1, "critical": 2}

# checkpoint staleness: commits replayed on every cold snapshot build
CHECKPOINT_WARN_COMMITS = 20
CHECKPOINT_CRIT_COMMITS = 100
# log tail bytes re-read per snapshot update
CHECKPOINT_WARN_TAIL_BYTES = 16 << 20
CHECKPOINT_CRIT_TAIL_BYTES = 256 << 20
# small-file debt: files below the OPTIMIZE compaction floor
SMALL_FILE_BYTES = 256 << 20  # OptimizeCommand.DEFAULT_MIN_FILE_SIZE
SMALL_WARN_COUNT = 16
SMALL_CRIT_COUNT = 128
# deletion-vector debt
DV_PURGE_FILE_PCT = 0.30  # per-file soft-deleted fraction past which PURGE
DV_WARN_PCT = 0.05
DV_CRIT_PCT = 0.20
# stats coverage
STATS_WARN_PCT = 0.90
# partition skew (Gini over per-partition bytes)
SKEW_WARN_GINI, SKEW_WARN_PARTS = 0.50, 4
SKEW_CRIT_GINI, SKEW_CRIT_PARTS = 0.80, 8


@dataclass
class HealthDimension:
    """One axis of table debt: the numbers, the verdict, and the fix."""

    name: str
    severity: str  # ok | warn | critical
    metrics: Dict[str, Any] = field(default_factory=dict)
    remedy: Optional[str] = None  # suggested command; None when ok
    detail: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "severity": self.severity,
            "metrics": dict(self.metrics),
            "remedy": self.remedy,
            "detail": self.detail,
        }


@dataclass
class TableHealthReport:
    path: str
    version: int
    generated_at_ms: int
    severity: str
    dimensions: List[HealthDimension]
    num_files: int
    size_in_bytes: int

    def dimension(self, name: str) -> HealthDimension:
        for d in self.dimensions:
            if d.name == name:
                return d
        raise KeyError(name)

    def remedies(self) -> List[str]:
        """Distinct suggested remedies, worst dimension first."""
        out: List[str] = []
        for d in sorted(self.dimensions,
                        key=lambda d: -SEVERITY_RANK[d.severity]):
            if d.remedy and d.remedy not in out:
                out.append(d.remedy)
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "version": self.version,
            "generatedAt": self.generated_at_ms,
            "severity": self.severity,
            "remedies": self.remedies(),
            "numFiles": self.num_files,
            "sizeInBytes": self.size_in_bytes,
            "dimensions": [d.to_dict() for d in self.dimensions],
            # every remedy string above is a key of the shared maintenance
            # Action catalog — the autopilot consumes it without string
            # matching, and so can any external consumer
            "remedyCatalog": actions_mod.CATALOG_REF,
            # the doctor is point-in-time; the workload journal's advisor
            # answers the longitudinal question (what layout do the queries
            # this table ACTUALLY serves need) — see obs/advisor.py
            "advisor": "longitudinal layout advice: DeltaTable.advise() / "
                       "GET /advisor?path=<table>",
        }


def _gini(values: Sequence[float]) -> float:
    """Gini coefficient of a non-negative distribution (0 = equal,
    → 1 = one partition holds everything)."""
    n = len(values)
    total = float(sum(values))
    if n <= 1 or total <= 0:
        return 0.0
    xs = sorted(float(v) for v in values)
    weighted = sum(i * x for i, x in enumerate(xs, 1))
    return max(0.0, (2.0 * weighted) / (n * total) - (n + 1.0) / n)


def _dim_checkpoint(snapshot) -> HealthDimension:
    seg = snapshot.segment
    # no checkpoint yet: every commit since version 0 replays on cold start
    commits_since = (
        snapshot.version - seg.checkpoint_version
        if seg.checkpoint_version is not None
        else snapshot.version + 1
    )
    tail_bytes = sum(f.size for f in seg.deltas)
    sev = "ok"
    if (commits_since > CHECKPOINT_CRIT_COMMITS
            or tail_bytes > CHECKPOINT_CRIT_TAIL_BYTES):
        sev = "critical"
    elif (commits_since > CHECKPOINT_WARN_COMMITS
          or tail_bytes > CHECKPOINT_WARN_TAIL_BYTES):
        sev = "warn"
    detail = (f"{commits_since} commits replay after the last checkpoint "
              f"({tail_bytes} tail bytes)")
    if sev != "ok":
        from delta_tpu.utils.config import conf as _conf

        if not _conf.get_bool("delta.tpu.checkpoint.async", False):
            # a long tail under sustained write traffic usually means the
            # synchronous interval checkpoint can't keep up with (or is
            # being skipped by) the writers — the async builder keeps the
            # tail short without stalling commits
            detail += ("; consider delta.tpu.checkpoint.async=true "
                       "(+ .incremental) under sustained write traffic")
    return HealthDimension(
        "checkpoint", sev,
        {"commitsSince": commits_since, "tailBytes": tail_bytes,
         "tailFiles": len(seg.deltas)},
        remedy=actions_mod.remedy_name("CHECKPOINT") if sev != "ok" else None,
        detail=detail,
    )


def _dim_small_files(files) -> HealthDimension:
    small = [f for f in files if (f.size or 0) < SMALL_FILE_BYTES]
    small_bytes = sum(f.size or 0 for f in small)
    # OPTIMIZE bin-packs per partition: estimate the post-compaction file
    # count as ceil(bytes/target) per partition
    by_part: Dict[tuple, int] = {}
    for f in small:
        key = tuple(sorted((f.partition_values or {}).items()))
        by_part[key] = by_part.get(key, 0) + (f.size or 0)
    est_after = sum(max(1, math.ceil(b / SMALL_FILE_BYTES))
                    for b in by_part.values())
    reduction = max(0, len(small) - est_after)
    sev = "ok"
    if reduction >= len(small) / 2 and len(small) >= SMALL_CRIT_COUNT:
        sev = "critical"
    elif reduction >= len(small) / 2 and len(small) >= SMALL_WARN_COUNT:
        sev = "warn"
    return HealthDimension(
        "smallFiles", sev,
        {"count": len(small), "bytes": small_bytes,
         "estReduction": reduction},
        remedy=actions_mod.remedy_name("OPTIMIZE") if sev != "ok" else None,
        detail=f"{len(small)} files below the {SMALL_FILE_BYTES >> 20} MiB "
               f"compaction floor; OPTIMIZE would remove ~{reduction}",
    )


def _dim_dv(files) -> HealthDimension:
    dv_files = [f for f in files if f.deletion_vector is not None]
    deleted = sum(int((f.deletion_vector or {}).get("cardinality") or 0)
                  for f in dv_files)
    physical = 0
    past_purge = 0
    for f in files:
        n = f.num_logical_records  # stats numRecords: rows as written
        physical += n or 0
    for f in dv_files:
        n = f.num_logical_records
        card = int((f.deletion_vector or {}).get("cardinality") or 0)
        if n and card / n >= DV_PURGE_FILE_PCT:
            past_purge += 1
    pct = deleted / physical if physical else 0.0
    sev = "ok"
    if dv_files:
        if pct >= DV_CRIT_PCT:
            sev = "critical"
        elif pct >= DV_WARN_PCT or past_purge:
            sev = "warn"
    return HealthDimension(
        "dv", sev,
        {"files": len(dv_files), "deletedRows": deleted,
         "deletedPct": round(pct, 4), "filesPastPurge": past_purge},
        remedy=actions_mod.remedy_name("PURGE") if sev != "ok" else None,
        detail=f"{deleted} rows soft-deleted across {len(dv_files)} files "
               f"({pct:.1%} of the table); {past_purge} files past the "
               f"{DV_PURGE_FILE_PCT:.0%} purge threshold",
    )


def _dim_stats(files) -> HealthDimension:
    n = len(files)
    with_stats = sum(1 for f in files if f.stats is not None)
    parsed = sum(1 for f in files if f.stats_dict() is not None)
    cov = with_stats / n if n else 1.0
    parsed_pct = parsed / n if n else 1.0
    sev = "ok"
    if n and with_stats == 0:
        sev = "critical"
    elif cov < STATS_WARN_PCT or parsed_pct < STATS_WARN_PCT:
        sev = "warn"
    return HealthDimension(
        "stats", sev,
        {"coveragePct": round(cov, 4), "parsedPct": round(parsed_pct, 4)},
        remedy=actions_mod.remedy_name("OPTIMIZE") if sev != "ok" else None,
        detail=f"{with_stats}/{n} files carry stats ({parsed} parseable); "
               "files without stats are never skipped",
    )


def _dim_partition(files, partition_columns) -> HealthDimension:
    if not partition_columns:
        return HealthDimension(
            "partition", "ok", {"count": 1, "gini": 0.0},
            detail="unpartitioned table",
        )
    bytes_per: Dict[tuple, int] = {}
    for f in files:
        key = tuple(sorted((f.partition_values or {}).items()))
        bytes_per[key] = bytes_per.get(key, 0) + (f.size or 0)
    gini = _gini(list(bytes_per.values()))
    n_parts = len(bytes_per)
    sev = "ok"
    if gini >= SKEW_CRIT_GINI and n_parts >= SKEW_CRIT_PARTS:
        sev = "critical"
    elif gini >= SKEW_WARN_GINI and n_parts >= SKEW_WARN_PARTS:
        sev = "warn"
    return HealthDimension(
        "partition", sev,
        {"count": n_parts, "gini": round(gini, 4)},
        remedy=actions_mod.remedy_name("REPARTITION") if sev != "ok" else None,
        detail=f"{n_parts} partitions, byte-skew Gini {gini:.2f}",
    )


def _dim_tombstones(snapshot, live_bytes: int) -> HealthDimension:
    tombs = snapshot.tombstones
    tomb_bytes = sum(int(t.size or 0) for t in tombs)
    sev = "ok"
    if tombs and tomb_bytes > max(live_bytes, 0):
        sev = "warn"
        if live_bytes and tomb_bytes > 4 * live_bytes:
            sev = "critical"
    return HealthDimension(
        "tombstones", sev,
        {"count": len(tombs), "bytes": tomb_bytes},
        remedy=actions_mod.remedy_name("VACUUM") if sev != "ok" else None,
        detail=f"{len(tombs)} removed files ({tomb_bytes} bytes) await "
               "retention expiry",
    )


def _dim_device() -> HealthDimension:
    """Device residency pressure (8th dimension): the process-wide HBM
    ledger (`obs/hbm_ledger`) against the ``delta.tpu.device.hbmBudgetBytes``
    soft budget. Process-wide by nature — the caches are shared across
    tables — but reported per doctor call so the operator diagnosing THIS
    table sees what device memory its merges/scans compete with. Remedy
    EVICT: shrink the budgets (``delta.tpu.keyCache.maxBytes`` /
    ``delta.tpu.stateCache.maxBytes``) or disable the key cache
    (``delta.tpu.merge.keyCache.enabled=false``); `hbm_ledger.maybe_relieve`
    applies the LRU pressure immediately."""
    from delta_tpu.obs import hbm_ledger

    t = hbm_ledger.totals()
    budget = hbm_ledger.budget_bytes()
    used = t["total"]
    pressure = (used / budget) if budget else 0.0
    # per-device breakdown (sharded residency attributes slices): severity
    # follows the WORST device, not the mesh-wide mean — under an even
    # budget split, one device at 5x its fair share is the OOM candidate
    # even when the aggregate looks healthy
    per_device = hbm_ledger.device_totals()
    worst = hbm_ledger.worst_device()
    worst_pressure = 0.0
    if worst is not None and budget and per_device:
        fair = budget / max(len(per_device), 1)
        worst_pressure = worst[1] / fair if fair else 0.0
    sev = "ok"
    if budget:
        eff = max(pressure, worst_pressure)
        if eff > 1.0:
            sev = "critical"
        elif eff >= 0.8:
            sev = "warn"
    metrics = {"hbmBytes": used, "keyCacheBytes": t["keyCache"],
               "stateCacheBytes": t["stateCache"], "scratchBytes": t["scratch"],
               "budgetBytes": budget or 0, "pressure": round(pressure, 4)}
    if worst is not None:
        metrics["worstDevice"] = worst[0]
        metrics["worstDeviceBytes"] = worst[1]
        metrics["worstDevicePressure"] = round(worst_pressure, 4)
    return HealthDimension(
        "device", sev,
        metrics,
        remedy=actions_mod.remedy_name("EVICT") if sev != "ok" else None,
        detail=f"{used} device bytes resident "
               f"(keyCache {t['keyCache']}, stateCache {t['stateCache']}, "
               f"scratch {t['scratch']})"
               + (f"; worst device {worst[0]} holds {worst[1]} bytes"
                  if worst is not None else "")
               + (f" against a {budget}-byte soft budget" if budget
                  else "; no delta.tpu.device.hbmBudgetBytes budget set"),
    )


def _dim_distributed() -> HealthDimension:
    """Distributed-execution supervision health (9th dimension):
    process-wide evidence from the sharded executor's fault handling —
    retries are routine (transient IO happens), but quarantined items mean
    committed work is INCOMPLETE (an OPTIMIZE skipped a group's rewrite)
    and degradations mean a structural capability (device plan, worker
    pool, merge probe, lease coverage) silently fell back to a slower or
    more conservative path. Process-wide by nature, like the device
    dimension — the executor is shared across tables — but surfaced per
    doctor call so the operator sees WHY a job's output differs from its
    plan."""
    c = telemetry.counters("dist")
    retried = c.get("dist.items.retried", 0)
    quarantined = c.get("dist.items.quarantined", 0)
    speculated = c.get("dist.items.speculated", 0)
    wins = c.get("dist.speculation.wins", 0)
    recovered = c.get("dist.slice.recovered", 0)
    degraded = sum(v for k, v in c.items() if k.startswith("dist.degraded."))
    sev = "ok"
    if quarantined > 0 or degraded > 0:
        sev = "warn"
    return HealthDimension(
        "distributed", sev,
        {"itemsRetried": retried, "itemsQuarantined": quarantined,
         "itemsSpeculated": speculated, "speculationWins": wins,
         "slicesRecovered": recovered, "degraded": degraded},
        detail=f"{retried} item retries, {quarantined} quarantined, "
               f"{speculated} speculative re-dispatches ({wins} won), "
               f"{recovered} orphaned slices recovered, "
               f"{degraded} degradations (plan/pool/probe/lease rungs)",
    )


def _dim_protocol(snapshot) -> HealthDimension:
    p = snapshot.protocol
    features = sorted(set(p.reader_features or ()) | set(p.writer_features or ()))
    return HealthDimension(
        "protocol", "ok",
        {"minReader": p.min_reader_version, "minWriter": p.min_writer_version,
         "features": features},
        detail=f"protocol ({p.min_reader_version}, {p.min_writer_version})"
               + (f", features: {', '.join(features)}" if features else ""),
    )


def _publish(report: TableHealthReport) -> None:
    telemetry.set_gauge("table.health.severity",
                        SEVERITY_RANK[report.severity], path=report.path)
    telemetry.set_gauge("table.health.files.count", report.num_files,
                        path=report.path)
    telemetry.set_gauge("table.health.files.bytes", report.size_in_bytes,
                        path=report.path)
    for d in report.dimensions:
        for k, v in d.metrics.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue  # lists/strings stay report-only
            telemetry.set_gauge(health_gauge(d.name, k), v, path=report.path)


def doctor(table, snapshot=None, publish_gauges: bool = True) -> TableHealthReport:
    """Compute a :class:`TableHealthReport` for ``table`` (a
    :class:`~delta_tpu.api.tables.DeltaTable`, a ``DeltaLog``, or a path).

    Reads the current snapshot (or the one given) and the log segment; never
    writes. ``publish_gauges=False`` skips the ``table.health.*`` gauge
    publication (DESCRIBE DETAIL uses the numbers inline)."""
    from delta_tpu.log.deltalog import DeltaLog

    if isinstance(table, str):
        delta_log = DeltaLog.for_table(table)
    else:
        delta_log = getattr(table, "delta_log", table)
    with telemetry.record_operation("delta.utility.doctor",
                                    path=delta_log.data_path):
        snap = snapshot if snapshot is not None else delta_log.update()
        files = snap.all_files
        live_bytes = sum(f.size or 0 for f in files)
        dims = [
            _dim_checkpoint(snap),
            _dim_small_files(files),
            _dim_dv(files),
            _dim_stats(files),
            _dim_partition(files, snap.metadata.partition_columns),
            _dim_tombstones(snap, live_bytes),
            _dim_protocol(snap),
            _dim_device(),
            _dim_distributed(),
        ]
        severity = max((d.severity for d in dims), key=SEVERITY_RANK.get)
        report = TableHealthReport(
            path=delta_log.data_path,
            version=snap.version,
            generated_at_ms=delta_log.clock(),
            severity=severity,
            dimensions=dims,
            num_files=len(files),
            size_in_bytes=live_bytes,
        )
        if publish_gauges:
            _publish(report)
        telemetry.add_span_data(severity=severity,
                                remedies=report.remedies())
        return report
