"""Log replay semantics (≈ ``InMemoryLogReplay`` behavior + PROTOCOL.md
"Action Reconciliation")."""
from delta_tpu.log.replay import LogReplay
from delta_tpu.protocol.actions import (
    AddFile,
    CommitInfo,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
)


def add(path, ts=0, size=1):
    return AddFile(path, {}, size, ts, True)


def test_last_add_wins():
    r = LogReplay()
    r.append(0, [Protocol(), Metadata(id="m"), add("f1", size=1)])
    r.append(1, [add("f1", size=2)])
    assert list(r.active_files) == ["f1"]
    assert r.active_files["f1"].size == 2


def test_remove_tombstones_add():
    r = LogReplay(min_file_retention_timestamp=0)
    r.append(0, [add("f1")])
    r.append(1, [RemoveFile("f1", deletion_timestamp=100)])
    assert r.active_files == {}
    assert [t.path for t in r.get_tombstones()] == ["f1"]


def test_add_after_remove_restores():
    r = LogReplay()
    r.append(0, [add("f1")])
    r.append(1, [RemoveFile("f1", deletion_timestamp=100)])
    r.append(2, [add("f1", size=9)])
    assert r.active_files["f1"].size == 9
    assert r.get_tombstones() == []


def test_tombstone_expiry():
    r = LogReplay(min_file_retention_timestamp=150)
    r.append(0, [add("f1"), add("f2")])
    r.append(1, [RemoveFile("f1", deletion_timestamp=100)])
    r.append(2, [RemoveFile("f2", deletion_timestamp=200)])
    assert [t.path for t in r.get_tombstones()] == ["f2"]


def test_latest_metadata_protocol_win():
    r = LogReplay()
    r.append(0, [Protocol(1, 1), Metadata(id="a")])
    r.append(1, [Protocol(1, 2), Metadata(id="b")])
    assert r.current_protocol.min_writer_version == 2
    assert r.current_metadata.id == "b"


def test_set_transaction_per_app_id():
    r = LogReplay()
    r.append(0, [SetTransaction("app1", 1), SetTransaction("app2", 5)])
    r.append(1, [SetTransaction("app1", 2)])
    assert r.transactions["app1"].version == 2
    assert r.transactions["app2"].version == 5


def test_commit_info_ignored():
    r = LogReplay()
    r.append(0, [CommitInfo(operation="WRITE"), add("f1")])
    assert list(r.active_files) == ["f1"]


def test_checkpoint_actions_normalize_datachange():
    r = LogReplay()
    r.append(0, [Protocol(), Metadata(id="m"), add("f1")])
    r.append(1, [RemoveFile("f2", deletion_timestamp=100, data_change=True)])
    acts = r.checkpoint_actions()
    adds = [a for a in acts if isinstance(a, AddFile)]
    removes = [a for a in acts if isinstance(a, RemoveFile)]
    assert all(a.data_change is False for a in adds)
    assert all(rm.data_change is False for rm in removes)
    kinds = [type(a).__name__ for a in acts]
    assert kinds.count("Protocol") == 1 and kinds.count("Metadata") == 1


def test_path_canonicalization():
    r = LogReplay()
    r.append(0, [add("./f1")])
    r.append(1, [RemoveFile("f1", deletion_timestamp=1)])
    assert r.active_files == {}
