"""Version-compat shims for the narrow slice of jax API the engine uses.

Two names have moved across the jax releases the engine targets:
``enable_x64`` (top-level in newer releases, ``jax.experimental`` before)
and ``shard_map`` (top-level since 0.5, ``jax.experimental.shard_map``
before). Kernels import the wrappers below so a version bump is a
one-file fix.

The wrappers resolve jax LAZILY, at call time: several modules
(``ops/pruning``, ``ops/zorder``, ``ops/key_cache``, ``ops/join_kernel``)
deliberately keep every jax import function-local so the plain host scan
path never pays the multi-second ``import jax`` — importing this module
must not break that.
"""
from __future__ import annotations

__all__ = ["enable_x64", "shard_map"]


def enable_x64():
    """Context manager enabling 64-bit dtypes (``jax.enable_x64()``)."""
    try:  # jax >= 0.5
        from jax import enable_x64 as _enable_x64
    except ImportError:  # pragma: no cover - version-dependent import
        from jax.experimental import enable_x64 as _enable_x64
    return _enable_x64()


def shard_map(*args, **kwargs):
    """``jax.shard_map`` / ``jax.experimental.shard_map.shard_map``."""
    try:  # jax >= 0.5
        from jax import shard_map as _shard_map
    except ImportError:  # pragma: no cover - version-dependent import
        from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(*args, **kwargs)
