"""Declarative SLO objectives with multi-window burn-rate alerts.

The scraped series (`obs/timeseries`) answer "what happened"; this module
answers "is it acceptable" continuously: each :class:`SloObjective` states a
target over a series (a per-table latency quantile or a process-wide failure
ratio), and :func:`evaluate` — driven after every scrape — computes its
**burn rate** (observed / objective) over two trailing windows:

* **fast** (``delta.tpu.obs.slo.fastWindowMs``, default 5m) — is the
  problem happening *now*;
* **slow** (``delta.tpu.obs.slo.slowWindowMs``, default 1h) — is it
  *sustained* enough to matter.

An alert **fires** only when BOTH windows burn ≥ 1.0 (the classic
multi-window rule: a short blip inside budget never pages, and an already-
recovered incident doesn't either), and **clears with hysteresis** once the
fast window drops below ``clearRatio`` (default 0.8) — a series flapping
around the threshold stays firing instead of strobing.

A firing alert is attributed: per-table objectives carry the ``table=``
label (`obs/fleet.table_label`) and the resolved path. Three consumers see
it: ``GET /slo`` (live state), the flight recorder (one incident JSON per
fire, when ``incidentDir`` is set), and the autopilot planner
(`autopilot/planner.plan` boosts the offending table's actions by
``delta.tpu.obs.slo.priorityBoost`` and cites the alert in their evidence).

Default objectives (thresholds conf-overridable):

==================  ========================================================
commitLatencyP99    p99 of ``delta.commit.duration_ms`` per table ≤
                    ``commitLatencyP99Ms`` (2s)
scanPlanningP99     p99 of ``delta.scan.planning.duration_ms`` per table ≤
                    ``scanPlanningP99Ms`` (500ms)
commitConflictRate  ``commit.conflicts`` / ``commit.total`` ≤
                    ``commitConflictRate`` (5%)
retryExhaustion     ``storage.retry.exhausted`` / ``storage.retry.attempts``
                    ≤ ``retryExhaustionRate`` (2%)
journalDropRate     ``journal.entriesDropped`` / ``journal.entries`` ≤
                    ``journalDropRate`` (1%)
==================  ========================================================

Blackout-inert by construction: evaluation is only ever driven from
:func:`~delta_tpu.obs.timeseries.scrape_once`, which returns before any
work under ``delta.tpu.telemetry.enabled=false`` — and :func:`evaluate`
re-checks the gate for direct callers.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["SloObjective", "SloAlert", "SloBreach", "objectives", "evaluate",
           "active_alerts", "priority_boost", "firing_count", "status",
           "reset"]


class SloBreach(Exception):
    """The exception a firing alert records through the flight recorder —
    an SLO breach is an operational failure even when no operation raised."""


@dataclass(frozen=True)
class SloObjective:
    """One declarative objective over the scraped series."""

    name: str
    kind: str                 # "latencyQuantile" | "ratio"
    description: str
    #: latencyQuantile: histogram name + quantile
    series: str = ""
    q: float = 0.99
    #: ratio: bad-event counter / total-event counter
    bad: str = ""
    total: str = ""
    #: the objective value (latency ms / bad fraction), conf-resolved at
    #: construction — :func:`objectives` rebuilds per evaluation, so a
    #: conf change applies on the next pass
    threshold: float = 0.0
    threshold_conf: str = ""
    #: evaluated once per ``table=`` label (vs once process-wide)
    per_table: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "kind": self.kind,
            "description": self.description,
            "series": self.series or f"{self.bad} / {self.total}",
            "q": self.q if self.kind == "latencyQuantile" else None,
            "threshold": self.threshold,
            "thresholdConf": self.threshold_conf,
            "perTable": self.per_table,
        }


def _thr(value, default: float) -> float:
    try:
        return float(value)
    except (TypeError, ValueError):
        return default


def objectives() -> List[SloObjective]:
    """The engine's default objectives (thresholds read live from conf)."""
    return [
        SloObjective(
            "commitLatencyP99", "latencyQuantile",
            "p99 commit pipeline latency per table",
            series="delta.commit.duration_ms", q=0.99,
            threshold=_thr(conf.get(
                "delta.tpu.obs.slo.commitLatencyP99Ms", 2_000.0), 2_000.0),
            threshold_conf="delta.tpu.obs.slo.commitLatencyP99Ms",
            per_table=True),
        SloObjective(
            "scanPlanningP99", "latencyQuantile",
            "p99 scan-planning latency per table",
            series="delta.scan.planning.duration_ms", q=0.99,
            threshold=_thr(conf.get(
                "delta.tpu.obs.slo.scanPlanningP99Ms", 500.0), 500.0),
            threshold_conf="delta.tpu.obs.slo.scanPlanningP99Ms",
            per_table=True),
        SloObjective(
            "commitConflictRate", "ratio",
            "fraction of commits aborted on logical conflicts",
            bad="commit.conflicts", total="commit.total",
            threshold=_thr(conf.get(
                "delta.tpu.obs.slo.commitConflictRate", 0.05), 0.05),
            threshold_conf="delta.tpu.obs.slo.commitConflictRate"),
        SloObjective(
            "retryExhaustion", "ratio",
            "fraction of storage retries that gave up",
            bad="storage.retry.exhausted", total="storage.retry.attempts",
            threshold=_thr(conf.get(
                "delta.tpu.obs.slo.retryExhaustionRate", 0.02), 0.02),
            threshold_conf="delta.tpu.obs.slo.retryExhaustionRate"),
        SloObjective(
            "journalDropRate", "ratio",
            "fraction of journal entries dropped before landing",
            bad="journal.entriesDropped", total="journal.entries",
            threshold=_thr(conf.get(
                "delta.tpu.obs.slo.journalDropRate", 0.01), 0.01),
            threshold_conf="delta.tpu.obs.slo.journalDropRate"),
    ]


@dataclass
class SloAlert:
    """One firing (or recently cleared) alert instance."""

    objective: str
    table: str                      # hashed label; "" = process-wide
    path: Optional[str]             # resolved table path, when known
    fired_at_ms: int
    burn_fast: float
    burn_slow: float
    threshold: float
    observed: float                 # the fast-window observation that fired
    firing: bool = True
    cleared_at_ms: Optional[int] = None
    #: exemplar: the last sampled trace id at fire time — the stitched
    #: /traces/<id> view an operator jumps to from the alert
    trace_id: Optional[str] = None

    @property
    def key(self) -> Tuple[str, str]:
        return (self.objective, self.table)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "objective": self.objective,
            "table": self.table or None,
            "path": self.path,
            "firedAt": self.fired_at_ms,
            "clearedAt": self.cleared_at_ms,
            "firing": self.firing,
            "burnFast": round(self.burn_fast, 3),
            "burnSlow": round(self.burn_slow, 3),
            "threshold": self.threshold,
            "observed": round(self.observed, 3),
            "traceId": self.trace_id,
        }


_LOCK = threading.Lock()
_ALERTS: Dict[Tuple[str, str], SloAlert] = {}
_LAST_EVAL: List[Dict[str, Any]] = []
_LAST_EVAL_MS = 0


def _windows() -> Tuple[int, int]:
    fast = conf.get_int("delta.tpu.obs.slo.fastWindowMs", 300_000)
    slow = conf.get_int("delta.tpu.obs.slo.slowWindowMs", 3_600_000)
    return max(fast, 1), max(slow, fast, 1)


def _clear_ratio() -> float:
    try:
        r = float(conf.get("delta.tpu.obs.slo.clearRatio", 0.8))
    except (TypeError, ValueError):
        r = 0.8
    return min(max(r, 0.0), 1.0)


def _min_observations() -> int:
    return max(conf.get_int("delta.tpu.obs.slo.minObservations", 10), 1)


def _quantile_burns(obj: SloObjective, fast_ms: int, slow_ms: int,
                    now_ms: int) -> List[Dict[str, Any]]:
    from delta_tpu.obs import fleet, timeseries

    rows: List[Dict[str, Any]] = []
    threshold = obj.threshold
    for labels in timeseries.histogram_labels(obj.series):
        label_map = dict(labels)
        table = label_map.get("table", "")
        if obj.per_table and not table:
            continue  # unlabeled series can't be attributed to a table
        fast_v, fast_n = timeseries.quantile_window(
            obj.series, labels, obj.q, fast_ms, now_ms)
        slow_v, slow_n = timeseries.quantile_window(
            obj.series, labels, obj.q, slow_ms, now_ms)
        rows.append({
            "objective": obj.name, "table": table,
            "path": fleet.label_path(table) if table else None,
            "threshold": threshold,
            "fast": {"value": fast_v, "observations": fast_n},
            "slow": {"value": slow_v, "observations": slow_n},
            "burnFast": (fast_v / threshold
                         if fast_v is not None and threshold > 0 else 0.0),
            "burnSlow": (slow_v / threshold
                         if slow_v is not None and threshold > 0 else 0.0),
        })
    return rows


def _ratio_burns(obj: SloObjective, fast_ms: int, slow_ms: int,
                 now_ms: int) -> List[Dict[str, Any]]:
    from delta_tpu.obs import timeseries

    threshold = obj.threshold

    def _ratio(window_ms: int) -> Tuple[float, float]:
        bad = timeseries.counter_window(obj.bad, window_ms, now_ms)
        tot = timeseries.counter_window(obj.total, window_ms, now_ms)
        if tot["delta"] <= 0:
            return 0.0, 0.0
        ratio = bad["delta"] / tot["delta"]
        return ratio, tot["delta"]

    fast_r, fast_n = _ratio(fast_ms)
    slow_r, slow_n = _ratio(slow_ms)
    return [{
        "objective": obj.name, "table": "", "path": None,
        "threshold": threshold,
        "fast": {"value": fast_r, "observations": fast_n},
        "slow": {"value": slow_r, "observations": slow_n},
        "burnFast": fast_r / threshold if threshold > 0 else 0.0,
        "burnSlow": slow_r / threshold if threshold > 0 else 0.0,
    }]


def _record_incident(alert: SloAlert) -> None:
    """One flight-recorder incident per fire (inert without incidentDir)."""
    from delta_tpu.obs import flight_recorder

    ev = telemetry.UsageEvent(
        "delta.slo.alert", alert.fired_at_ms,
        tags={"objective": alert.objective, "table": alert.table or ""},
        data=alert.to_dict(), trace_id=alert.trace_id or "")
    try:
        flight_recorder.record_incident(ev, SloBreach(
            f"SLO {alert.objective} burning: fast {alert.burn_fast:.2f}x / "
            f"slow {alert.burn_slow:.2f}x budget "
            f"(table {alert.path or alert.table or 'process'})"))
    except Exception:  # noqa: BLE001 — alerting must never raise
        telemetry.logger.warning("slo incident write failed", exc_info=True)


def evaluate(now_ms: Optional[int] = None) -> List[Dict[str, Any]]:
    """One evaluation pass over every objective: compute fast/slow burns,
    publish ``slo.burnRate``/``slo.alerts`` metrics, and advance the alert
    state machine (fire on both-window burn ≥ 1, clear below the hysteresis
    ratio). Returns the evaluation rows. No-op (empty list) under a
    telemetry blackout."""
    global _LAST_EVAL, _LAST_EVAL_MS
    if not conf.get_bool("delta.tpu.telemetry.enabled", True):
        return []
    now = int(now_ms if now_ms is not None else time.time() * 1000)
    fast_ms, slow_ms = _windows()
    clear_ratio = _clear_ratio()
    min_obs = _min_observations()
    telemetry.bump_counter("slo.evaluations")
    rows: List[Dict[str, Any]] = []
    for obj in objectives():
        if obj.kind == "latencyQuantile":
            rows.extend(_quantile_burns(obj, fast_ms, slow_ms, now))
        else:
            rows.extend(_ratio_burns(obj, fast_ms, slow_ms, now))
    fired: List[SloAlert] = []
    with _LOCK:
        for row in rows:
            key = (row["objective"], row["table"])
            telemetry.set_gauge(
                "slo.burnRate", row["burnFast"],
                objective=row["objective"], table=row["table"] or "-",
                window="fast")
            telemetry.set_gauge(
                "slo.burnRate", row["burnSlow"],
                objective=row["objective"], table=row["table"] or "-",
                window="slow")
            alert = _ALERTS.get(key)
            if alert is not None and alert.firing:
                alert.burn_fast = row["burnFast"]
                alert.burn_slow = row["burnSlow"]
                if row["burnFast"] < clear_ratio:
                    alert.firing = False
                    alert.cleared_at_ms = now
                    telemetry.bump_counter("slo.alerts.cleared")
                row["alert"] = alert.to_dict()
            elif (row["burnFast"] >= 1.0 and row["burnSlow"] >= 1.0
                  and row["fast"]["observations"] >= min_obs
                  and row["slow"]["observations"] >= min_obs):
                # the observation floor keeps thin windows honest: a
                # young series' fast and slow windows can hold the SAME
                # handful of samples (both baseline at the first scrape),
                # so without it a few outliers would defeat the
                # multi-window "a short blip never pages" rule
                alert = SloAlert(
                    objective=row["objective"], table=row["table"],
                    path=row["path"], fired_at_ms=now,
                    burn_fast=row["burnFast"], burn_slow=row["burnSlow"],
                    threshold=row["threshold"],
                    observed=float(row["fast"]["value"] or 0.0),
                    trace_id=telemetry.last_sampled_trace_id())
                _ALERTS[key] = alert
                fired.append(alert)
                telemetry.bump_counter("slo.alerts.fired")
                row["alert"] = alert.to_dict()
        # an alert whose series vanished from the rings (table died and its
        # series aged out past scrape.maxSeries) produces no burn row — it
        # must clear, not burn as a phantom forever
        visited = {(r["objective"], r["table"]) for r in rows}
        for key, alert in _ALERTS.items():
            if alert.firing and key not in visited:
                alert.burn_fast = 0.0
                alert.firing = False
                alert.cleared_at_ms = now
                telemetry.bump_counter("slo.alerts.cleared")
        firing = sum(1 for a in _ALERTS.values() if a.firing)
        # cleared alerts are history, not state: keep a bounded tail for
        # /slo (newest first), like every other capped structure in the
        # plane — the alert map must not grow for the process lifetime
        cleared = sorted(
            (k for k, a in _ALERTS.items() if not a.firing),
            key=lambda k: _ALERTS[k].cleared_at_ms or 0, reverse=True)
        for k in cleared[64:]:
            del _ALERTS[k]
        _LAST_EVAL = rows
        _LAST_EVAL_MS = now
    telemetry.set_gauge("slo.alerts", firing)
    for alert in fired:  # incidents outside the lock: file IO
        _record_incident(alert)
    return rows


def firing_count() -> int:
    """Currently-firing alerts as one lock-guarded sum — cheap enough for
    the trace sampler's forced-sampling probe on every new root span
    (`telemetry._slo_burning`), which must not read conf or build dicts."""
    with _LOCK:
        return sum(1 for a in _ALERTS.values() if a.firing)


def active_alerts(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Currently-firing alerts, optionally only those attributed to
    ``path`` (per-table objectives resolve their hashed label through the
    fleet registry)."""
    with _LOCK:
        alerts = [a for a in _ALERTS.values() if a.firing]
    if path is not None:
        want = path.rstrip("/")
        alerts = [a for a in alerts if a.path == want]
    return [a.to_dict() for a in sorted(
        alerts, key=lambda a: (-max(a.burn_fast, a.burn_slow), a.objective))]


def priority_boost(path: str) -> Tuple[float, List[Dict[str, Any]]]:
    """(priority boost, citing alerts) for a table: the autopilot planner
    adds the boost to every action planned for a table whose per-table SLO
    is firing, so fleet scheduling puts burning tables first."""
    alerts = active_alerts(path)
    if not alerts:
        return 0.0, []
    try:
        boost = float(conf.get("delta.tpu.obs.slo.priorityBoost", 25.0))
    except (TypeError, ValueError):
        boost = 25.0
    return boost, alerts


def status() -> Dict[str, Any]:
    """The ``/slo`` payload: objectives, windows, the last evaluation's
    burn rows, and every alert (firing first)."""
    fast_ms, slow_ms = _windows()
    with _LOCK:
        rows = list(_LAST_EVAL)
        eval_ms = _LAST_EVAL_MS
        alerts = sorted(_ALERTS.values(),
                        key=lambda a: (not a.firing, -a.fired_at_ms))
    return {
        "enabled": (conf.get_bool("delta.tpu.telemetry.enabled", True)
                    and conf.get_bool("delta.tpu.obs.slo.enabled", True)),
        "windows": {"fastMs": fast_ms, "slowMs": slow_ms,
                    "clearRatio": _clear_ratio(),
                    "minObservations": _min_observations()},
        "objectives": [o.to_dict() for o in objectives()],
        "lastEvaluationAt": eval_ms or None,
        "burns": rows,
        "alerts": [a.to_dict() for a in alerts],
        "firing": sum(1 for a in alerts if a.firing),
    }


def reset() -> None:
    """Drop alert state and the last evaluation (tests / bench)."""
    global _LAST_EVAL, _LAST_EVAL_MS
    with _LOCK:
        _ALERTS.clear()
        _LAST_EVAL = []
        _LAST_EVAL_MS = 0
