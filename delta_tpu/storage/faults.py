"""Deterministic, seeded fault injection for the storage/txn stack.

The LogStore contract (atomic visibility, mutual exclusion, consistent
listing — ``storage/LogStore.scala:44-138``) is what makes every fast path
in this engine trustworthy, yet real stores fail in ways the happy path
never exercises: connections reset mid-PUT, processes die between staging
and publishing a commit, multi-part checkpoints tear, ``_last_checkpoint``
goes stale, listings lag writes. :class:`FaultInjectingLogStore` wraps any
store and injects those failures at **named fault points**, following a
**reproducible seeded plan** — the same seed over the same workload yields
the same fault sequence, so every torture-test failure is replayable.

Fault kinds (:data:`ALL_KINDS`):

* ``transient`` — raise :class:`TransientIOError`; on a non-idempotent
  commit write a seeded coin decides whether the error fires *before* or
  *after* the underlying write (a lost response — the ambiguous-commit case
  reconciled in ``txn/transaction.py``).
* ``crash_before_publish`` — stage a ``.tmp`` orphan next to the target
  (what a died ``LocalLogStore.write`` leaves behind), then raise
  :class:`SimulatedCrash` without publishing.
* ``crash_after_publish`` — perform the write, then raise
  :class:`SimulatedCrash`: the commit is durable but the writer never
  learned.
* ``torn_checkpoint`` — crash a multi-part checkpoint part write, leaving a
  partial (incomplete) checkpoint that must never block readers.
* ``stale_last_checkpoint`` — silently drop a ``_last_checkpoint`` update,
  leaving the pointer behind the log.
* ``listing_lag`` — omit the newest log file from one listing (object-store
  eventual consistency).
* ``slow`` — sleep briefly (tail-latency stand-in; exercises nothing but
  timing assumptions, deliberately).

A *crash* is simulated by raising :class:`SimulatedCrash` — a
``BaseException`` so no ``except Exception`` recovery path can swallow it,
exactly like a process death — and the workload resumes with a fresh
``DeltaLog`` (see ``delta_tpu/testing/harness.py``).

Installation: set session conf ``delta.tpu.faults.plan`` to a
:class:`FaultPlan` (tests) or a spec string like
``"seed=42,rate=0.05,kinds=transient|crash_after_publish"``;
``DeltaLog`` wraps its store via :func:`maybe_wrap` at construction. With
the conf unset, :func:`maybe_wrap` returns the store unchanged — zero
wrapper, zero overhead (asserted by ``bench.py``).
"""
from __future__ import annotations

import threading
import time
from typing import (Any, Dict, Iterable, Iterator, List, Optional, Sequence,
                    Tuple)

from delta_tpu.protocol import filenames
from delta_tpu.storage.logstore import FileStatus, LogStore
from delta_tpu.utils.retries import TransientIOError

__all__ = [
    "SimulatedCrash",
    "FaultPlan",
    "FaultInjectingLogStore",
    "ALL_KINDS",
    "fire",
    "maybe_wrap",
    "plan_from_conf",
    "reset_plan_cache",
]


class SimulatedCrash(BaseException):
    """A simulated process death at a fault point. BaseException on purpose:
    recovery code that catches ``Exception`` (post-commit checkpointing,
    cleanup) must not be able to "survive" a crash — only the workload
    driver resumes, with a fresh ``DeltaLog``."""

    def __init__(self, point: str):
        super().__init__(f"simulated crash at fault point {point!r}")
        self.point = point


#: Every fault kind the injector knows, keyed to where it can fire.
ALL_KINDS: Tuple[str, ...] = (
    "transient",
    "crash_before_publish",
    "crash_after_publish",
    "torn_checkpoint",
    "stale_last_checkpoint",
    "listing_lag",
    "slow",
)

#: kinds applicable per fault-point family. Read/list points never crash:
#: a reader dying teaches nothing new (no state mutated), while keeping
#: them crash-free keeps the seeded op sequence deterministic under the
#: engine's parallel part decodes.
_POINT_KINDS: Dict[str, Tuple[str, ...]] = {
    "read": ("transient", "slow"),
    "list": ("transient", "listing_lag", "slow"),
    "exists": ("transient",),
    "delete": ("transient",),
    "write.commit": ("transient", "crash_before_publish",
                     "crash_after_publish", "slow"),
    "write.checkpoint": ("transient", "torn_checkpoint", "slow"),
    "write.lastCheckpoint": ("transient", "stale_last_checkpoint"),
    "write.crc": ("transient",),
    "write.other": ("transient", "slow"),
    # engine-level points (fired via :func:`fire`, not through a store op):
    # the group-commit leader's write loop draws once per batch member
    # BEFORE that member's log-entry create — a crash here dies between
    # batch members, leaving a prefix of the batch durable; the async
    # checkpoint writer draws once per build request, pre-build (genuinely
    # TORN builds come from the write.checkpoint store point firing inside
    # the build's part writes — fire() has no partial-write to tear).
    "txn.groupLoop": ("transient", "crash_before_publish", "slow"),
    "checkpoint.asyncBuild": ("transient", "crash_before_publish", "slow"),
    # distributed-execution supervision points (parallel/executor,
    # parallel/leases): item attempts may die transiently, crash the
    # "process" (SimulatedCrash pierces the supervisor — only the workload
    # driver recovers), or stall (the straggler the speculation path
    # rescues); worker spawns and lease writes fail like any other IO;
    # heartbeat loss must cost at most a spurious speculation.
    "dist.itemExec": ("transient", "crash_before_publish", "slow"),
    "dist.workerSpawn": ("transient",),
    "dist.heartbeat": ("transient",),
    "dist.leaseWrite": ("transient", "crash_before_publish", "slow"),
}


class FaultPlan:
    """A reproducible seeded fault schedule.

    Each ``(fault point, target file name)`` pair owns an independent
    ``random.Random(f"{seed}:{point}|{name}")`` stream and its own draw
    index, so the decision for the i-th operation on a given file is a
    PURE FUNCTION of (seed, point, name, i). That makes the fault sequence
    immune to thread interleaving: the engine's pooled IO (parallel
    checkpoint part writes/decodes) may race, but racing threads touch
    different files — and same-file retries replay the same stream — so
    the same seed over the same workload reproduces the identical faults.
    (Plain per-point streams are NOT enough: two threads racing for the
    next stream value would swap which call gets the fault, and the
    workload's reaction to it diverges run over run.)

    ``script`` overrides the seeded draw for targeted tests: an ordered
    list of ``(point_prefix, kind)`` or ``(point_prefix, kind, sub)``
    tuples consumed one at a time — the next store op whose point matches
    the head injects that fault.
    """

    def __init__(
        self,
        seed: int = 0,
        rate: float = 0.0,
        kinds: Sequence[str] = ALL_KINDS,
        max_faults: Optional[int] = None,
        slow_ms: float = 2.0,
        script: Optional[Sequence[Tuple[str, str]]] = None,
    ):
        import random

        unknown = set(kinds) - set(ALL_KINDS)
        if unknown:
            raise ValueError(f"Unknown fault kinds: {sorted(unknown)}")
        self.seed = seed
        self.rate = rate
        self.kinds = tuple(kinds)
        self.max_faults = max_faults
        self.slow_ms = slow_ms
        self.script: List[Tuple[str, str]] = list(script or [])
        self._lock = threading.Lock()
        self._rngs: Dict[str, "random.Random"] = {}
        self._random = random
        #: chronological fault log [(stream key, kind, per-stream index)]
        self.injected: List[Tuple[str, str, int]] = []
        #: per-(point|name) kind sequences — the determinism witness:
        #: identical across runs of the same seeded workload even when
        #: global interleaving of parallel IO differs
        self.per_point: Dict[str, List[str]] = {}

    # -- draw -------------------------------------------------------------

    def _rng(self, key: str):
        rng = self._rngs.get(key)
        if rng is None:
            rng = self._random.Random(f"{self.seed}:{key}")
            self._rngs[key] = rng
        return rng

    def total_injected(self) -> int:
        return len(self.injected)

    def kinds_seen(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for _, kind, _ in self.injected:
            out[kind] = out.get(kind, 0) + 1
        return out

    def draw(self, point: str, name: str = "") -> Optional[Tuple[str, float]]:
        """One decision for one store op at ``point`` targeting file
        ``name``. Returns ``(kind, sub)`` to inject (``sub`` in [0,1): a
        secondary seeded coin, e.g. before/after for ambiguous write
        errors) or None."""
        key = f"{point}|{name}"
        with self._lock:
            if self.script:
                entry = self.script[0]
                prefix, kind = entry[0], entry[1]
                if point.startswith(prefix):
                    self.script.pop(0)
                    return self._record(key, kind,
                                        entry[2] if len(entry) > 2 else 0.0)
                return None
            if self.max_faults is not None and len(self.injected) >= self.max_faults:
                return None
            rng = self._rng(key)
            if rng.random() >= self.rate:
                return None
            applicable = [k for k in _POINT_KINDS[point] if k in self.kinds]
            if not applicable:
                return None
            kind = applicable[rng.randrange(len(applicable))]
            return self._record(key, kind, rng.random())

    def _record(self, key: str, kind: str, sub: float) -> Tuple[str, float]:
        seq = self.per_point.setdefault(key, [])
        self.injected.append((key, kind, len(seq)))
        seq.append(kind)
        from delta_tpu.utils import telemetry

        telemetry.bump_counter("faults.injected")
        return kind, sub


# -- conf plumbing ----------------------------------------------------------

_SPEC_CACHE: Dict[str, FaultPlan] = {}
_SPEC_LOCK = threading.Lock()


def reset_plan_cache() -> None:
    """Forget parsed string-spec plans. A spec string's plan is cached so
    its RNG streams survive crash-resume DeltaLog re-creations — which also
    means a LATER install of the same spec text in this process would
    resume the half-consumed streams. Call this between independent runs
    that reuse a spec string and expect a fresh seeded sequence."""
    with _SPEC_LOCK:
        _SPEC_CACHE.clear()


def plan_from_conf() -> Optional[FaultPlan]:
    """The session's fault plan, or None. A string spec is parsed once and
    cached by its literal text, so plan state (RNG streams, fault log)
    persists across the DeltaLog re-creations a crash-resume loop does —
    see :func:`reset_plan_cache` before reusing a spec for a fresh run."""
    from delta_tpu.utils.config import conf

    v = conf.get("delta.tpu.faults.plan")
    if not v:
        return None
    if isinstance(v, FaultPlan):
        return v
    spec = str(v)
    with _SPEC_LOCK:
        plan = _SPEC_CACHE.get(spec)
        if plan is None:
            plan = _parse_spec(spec)
            _SPEC_CACHE[spec] = plan
        return plan


def _parse_spec(spec: str) -> FaultPlan:
    """``"seed=42,rate=0.05,kinds=transient|slow,maxFaults=100,slowMs=2"``"""
    kw: Dict[str, object] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        val = val.strip()
        if key == "seed":
            kw["seed"] = int(val)
        elif key == "rate":
            kw["rate"] = float(val)
        elif key == "kinds":
            kw["kinds"] = tuple(k for k in val.split("|") if k)
        elif key == "maxFaults":
            kw["max_faults"] = int(val)
        elif key == "slowMs":
            kw["slow_ms"] = float(val)
        else:
            raise ValueError(f"Unknown fault-plan key {key!r} in {spec!r}")
    return FaultPlan(**kw)  # type: ignore[arg-type]


_UNPINNED = object()  # sentinel: fire() resolves the plan from conf


def fire(point: str, name: str = "",
         plan: Any = _UNPINNED) -> None:
    """Engine-level fault point — for code paths that are not a single
    store operation (the group-commit leader loop, the async checkpoint
    builder). Consults the session's active plan directly and raises the
    drawn fault; a no-op when no plan is installed (zero overhead: one
    conf read). Crash kinds raise :class:`SimulatedCrash`; ``transient``
    raises :class:`TransientIOError`; ``slow`` sleeps.

    Long-lived machinery whose threads can outlive the operation that
    spawned them (the sharded executor's worker pool) passes ``plan``
    explicitly — resolved once at job start — so a task that runs late
    draws from ITS job's plan instead of whatever the session conf holds
    by then. ``plan=None`` is an explicit no-op."""
    if plan is _UNPINNED:
        plan = plan_from_conf()
    if plan is None:
        return
    d = plan.draw(point, name)
    if d is None:
        return
    kind, _ = d
    if kind == "slow":
        time.sleep(plan.slow_ms / 1000.0)
        return
    if kind == "transient":
        raise TransientIOError(f"injected transient at {point}")
    raise SimulatedCrash(point)


def maybe_wrap(store: LogStore) -> LogStore:
    """Wrap ``store`` in a FaultInjectingLogStore when a plan is configured;
    otherwise return ``store`` itself (no wrapper, zero overhead)."""
    plan = plan_from_conf()
    if plan is None:
        return store
    return FaultInjectingLogStore(store, plan)


# -- the injecting store ----------------------------------------------------

def _classify_write(path: str) -> str:
    name = path.rsplit("/", 1)[-1]
    if name == filenames.LAST_CHECKPOINT:
        return "write.lastCheckpoint"
    if filenames.is_delta_file(name):
        return "write.commit"
    if filenames.is_checkpoint_file(name):
        return "write.checkpoint"
    if filenames.is_checksum_file(name):
        return "write.crc"
    return "write.other"


class FaultInjectingLogStore(LogStore):
    """Injects ``plan``'s faults around ``base``'s operations."""

    def __init__(self, base: LogStore, plan: FaultPlan):
        self.base = base
        self.plan = plan

    # -- reads ------------------------------------------------------------

    @staticmethod
    def _name(path: str) -> str:
        return path.rsplit("/", 1)[-1]

    def _simple_fault(self, point: str, path: str) -> None:
        d = self.plan.draw(point, self._name(path))
        if d is None:
            return
        kind, _ = d
        if kind == "slow":
            time.sleep(self.plan.slow_ms / 1000.0)
            return
        raise TransientIOError(f"injected {kind} at {point}")

    def read(self, path: str) -> List[str]:
        self._simple_fault("read", path)
        return self.base.read(path)

    def read_iter(self, path: str) -> Iterator[str]:
        self._simple_fault("read", path)
        return self.base.read_iter(path)

    def read_bytes(self, path: str) -> bytes:
        self._simple_fault("read", path)
        return self.base.read_bytes(path)

    def exists(self, path: str) -> bool:
        self._simple_fault("exists", path)
        return self.base.exists(path)

    def delete(self, path: str) -> bool:
        self._simple_fault("delete", path)
        return self.base.delete(path)

    def list_from(self, path: str) -> Iterator[FileStatus]:
        d = self.plan.draw("list", self._name(path))
        entries = list(self.base.list_from(path))
        if d is not None:
            kind, _ = d
            if kind == "transient":
                raise TransientIOError("injected transient at list")
            if kind == "slow":
                time.sleep(self.plan.slow_ms / 1000.0)
            elif kind == "listing_lag" and entries:
                # the newest log file isn't visible yet (eventual listing):
                # drop the lexicographically-last delta/checkpoint entry —
                # readers see a consistent, slightly older prefix
                for i in range(len(entries) - 1, -1, -1):
                    n = entries[i].name
                    if filenames.is_delta_file(n) or filenames.is_checkpoint_file(n):
                        entries.pop(i)
                        break
        return iter(entries)

    # -- writes -----------------------------------------------------------

    def write(self, path: str, lines: Iterable[str], overwrite: bool = False) -> None:
        data = ("".join(line + "\n" for line in lines)).encode("utf-8")
        self.write_bytes(path, data, overwrite=overwrite)

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        point = _classify_write(path)
        d = self.plan.draw(point, self._name(path))
        if d is None:
            return self.base.write_bytes(path, data, overwrite=overwrite)
        kind, sub = d
        if kind == "slow":
            time.sleep(self.plan.slow_ms / 1000.0)
            return self.base.write_bytes(path, data, overwrite=overwrite)
        if kind == "stale_last_checkpoint":
            return None  # pointer update silently lost; log moves ahead of it
        if kind == "transient":
            if not overwrite and point == "write.commit" and sub < 0.5:
                # lost response: the PUT landed, the writer never heard back.
                # THE ambiguous commit — reconciled via commitInfo.txnId.
                self.base.write_bytes(path, data, overwrite=overwrite)
            raise TransientIOError(f"injected transient at {point}")
        if kind == "crash_before_publish":
            # what a died LocalLogStore.write leaves: staged temp, no publish
            parent, _, name = path.rpartition("/")
            # delta-lint: ignore[crash-tmpfile] -- the orphan IS the fault being
            # injected: it simulates what a died LocalLogStore.write leaves
            orphan = f"{parent}/.{name}.deadbeef{len(self.plan.injected):08x}.tmp"
            try:
                self.base.write_bytes(orphan, data, overwrite=True)
            except Exception:  # noqa: BLE001 — orphan staging is best-effort
                pass
            raise SimulatedCrash(point)
        if kind == "torn_checkpoint":
            # the writer dies before THIS part lands; sibling parts (all
            # attempted — checkpoints.py `_run_all_parts`) may land, so the
            # surviving set is a partial multi-part checkpoint that misses
            # this part, and _last_checkpoint never advances
            raise SimulatedCrash(point)
        if kind == "crash_after_publish":
            self.base.write_bytes(path, data, overwrite=overwrite)
            raise SimulatedCrash(point)
        raise AssertionError(f"unhandled fault kind {kind!r}")

    # -- passthrough ------------------------------------------------------

    def is_partial_write_visible(self, path: str) -> bool:
        return self.base.is_partial_write_visible(path)

    def resolve_path(self, path: str) -> str:
        return self.base.resolve_path(path)

    def mkdirs(self, path: str) -> None:
        self.base.mkdirs(path)

    def __getattr__(self, name):
        return getattr(self.base, name)

    def __repr__(self) -> str:
        return f"FaultInjectingLogStore({self.base!r}, faults={len(self.plan.injected)})"
