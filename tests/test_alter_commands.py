"""End-to-end ALTER TABLE behavioral matrix (≈ ``DeltaAlterTableTests``,
1,571 LoC): each DDL against a live table with data, checked through
subsequent reads/writes — not just through schema transforms.
"""
import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.alter import (
    add_columns,
    add_constraint,
    change_column,
    drop_constraint,
    set_table_properties,
    unset_table_properties,
)
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.schema.types import IntegerType, LongType, StringType, StructField
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    DeltaUnsupportedOperationError,
    InvariantViolationError,
)


def make(tmp_table, **kw):
    return DeltaTable.create(
        tmp_table,
        data=pa.table({"id": pa.array([1, 2], pa.int64()),
                       "v": pa.array(["a", "b"])}),
        **kw,
    )


def append(t, data):
    WriteIntoDelta(t.delta_log, "append", data).run()


# -- SET / UNSET TBLPROPERTIES ------------------------------------------------


def test_set_properties_roundtrip_and_history(tmp_table):
    t = make(tmp_table)
    set_table_properties(t.delta_log, {"custom.owner": "team-x",
                                       "delta.checkpointInterval": "25"})
    cfg = t.delta_log.update().metadata.configuration
    assert cfg["custom.owner"] == "team-x"
    assert cfg["delta.checkpointInterval"] == "25"
    assert t.history()[0]["operation"] == "SET TBLPROPERTIES"


def test_set_property_validation(tmp_table):
    from delta_tpu.utils.errors import DeltaIllegalArgumentError

    t = make(tmp_table)
    with pytest.raises(DeltaIllegalArgumentError, match="checkpointInterval"):
        set_table_properties(t.delta_log, {"delta.checkpointInterval": "-3"})
    with pytest.raises(DeltaIllegalArgumentError, match="interval"):
        set_table_properties(
            t.delta_log, {"delta.logRetentionDuration": "not an interval"}
        )


def test_unset_property(tmp_table):
    t = make(tmp_table, configuration={"custom.tag": "x"})
    unset_table_properties(t.delta_log, ["custom.tag"])
    assert "custom.tag" not in t.delta_log.update().metadata.configuration


def test_unset_missing_property_errors_unless_if_exists(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        unset_table_properties(t.delta_log, ["nope.nope"])
    unset_table_properties(t.delta_log, ["nope.nope"], if_exists=True)


def test_append_only_property_enforced_after_set(tmp_table):
    t = make(tmp_table)
    t.delete("id = 1")  # allowed before
    set_table_properties(t.delta_log, {"delta.appendOnly": "true"})
    with pytest.raises(DeltaUnsupportedOperationError):
        t.delete("id = 2")
    append(t, pa.table({"id": pa.array([3], pa.int64()),
                        "v": pa.array(["c"])}))  # appends still fine
    assert sorted(t.to_arrow().column("id").to_pylist()) == [2, 3]


def test_protocol_pin_via_properties(tmp_table):
    t = make(tmp_table)
    set_table_properties(t.delta_log, {"delta.minWriterVersion": "4"})
    assert t.delta_log.update().protocol.min_writer_version >= 4


# -- ADD COLUMNS --------------------------------------------------------------


def test_add_column_reads_null_from_old_files(tmp_table):
    t = make(tmp_table)
    add_columns(t.delta_log, [StructField("extra", LongType())])
    got = t.to_arrow()
    assert got.column("extra").to_pylist() == [None, None]
    append(t, pa.table({"id": pa.array([3], pa.int64()),
                        "v": pa.array(["c"]),
                        "extra": pa.array([7], pa.int64())}))
    vals = dict(zip(t.to_arrow().column("id").to_pylist(),
                    t.to_arrow().column("extra").to_pylist()))
    assert vals == {1: None, 2: None, 3: 7}


def test_add_column_first_position(tmp_table):
    t = make(tmp_table)
    add_columns(t.delta_log, [StructField("z", LongType())],
                positions={"z": "first"})
    assert t.schema().field_names[0] == "z"
    assert t.to_arrow().column_names[0] == "z"


def test_add_column_after_sibling(tmp_table):
    t = make(tmp_table)
    add_columns(t.delta_log, [StructField("mid", LongType())],
                positions={"mid": ("after", "id")})
    assert t.schema().field_names == ["id", "mid", "v"]


def test_add_non_nullable_column_rejected(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        add_columns(t.delta_log, [StructField("req", LongType(), nullable=False)])


def test_add_existing_column_rejected(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        add_columns(t.delta_log, [StructField("ID", LongType())])  # case-insensitive clash


# -- CHANGE COLUMN ------------------------------------------------------------


def test_change_column_widen_then_read_and_write(tmp_table):
    data = pa.table({"n": pa.array([1, 2], pa.int32())})
    t = DeltaTable.create(tmp_table, data=data)
    change_column(t.delta_log, "n", new_type=LongType())
    # old int32 file reads as long
    assert t.to_arrow().column("n").type == pa.int64()
    append(t, pa.table({"n": pa.array([2**40], pa.int64())}))
    assert sorted(t.to_arrow().column("n").to_pylist()) == [1, 2, 2**40]


def test_change_column_narrow_rejected(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        change_column(t.delta_log, "id", new_type=IntegerType())


def test_change_column_comment_preserves_data(tmp_table):
    t = make(tmp_table)
    change_column(t.delta_log, "v", comment="the value")
    f = next(f for f in t.delta_log.update().metadata.schema.fields if f.name == "v")
    assert (f.metadata or {}).get("comment") == "the value"
    assert t.to_arrow().num_rows == 2


def test_change_column_relax_nullability(tmp_table):
    from delta_tpu.schema.types import StructType

    s = StructType().add("id", LongType(), nullable=False).add("v", StringType())
    t = DeltaTable.create(tmp_table, schema=s)
    change_column(t.delta_log, "id", nullable=True)
    append(t, pa.table({"id": pa.array([None], pa.int64()),
                        "v": pa.array(["x"])}))
    assert t.to_arrow().column("id").to_pylist() == [None]


def test_change_column_tighten_nullability_rejected(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        change_column(t.delta_log, "id", nullable=False)


def test_change_missing_column_rejected(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        change_column(t.delta_log, "ghost", new_type=LongType())


# -- CONSTRAINTS --------------------------------------------------------------


def test_add_constraint_validates_existing_rows(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError, match="violate"):
        add_constraint(t.delta_log, "pos", "id > 1")  # row id=1 violates
    add_constraint(t.delta_log, "pos", "id > 0")  # all rows pass


def test_constraint_enforced_on_future_writes(tmp_table):
    t = make(tmp_table)
    add_constraint(t.delta_log, "pos", "id > 0")
    with pytest.raises(InvariantViolationError):
        append(t, pa.table({"id": pa.array([-5], pa.int64()),
                            "v": pa.array(["bad"])}))
    # constraint bumps writer protocol to >= 3
    assert t.delta_log.update().protocol.min_writer_version >= 3


def test_duplicate_constraint_name_rejected(tmp_table):
    t = make(tmp_table)
    add_constraint(t.delta_log, "c1", "id > 0")
    with pytest.raises(DeltaAnalysisError):
        add_constraint(t.delta_log, "C1", "id > -1")  # case-insensitive


def test_drop_constraint_lifts_enforcement(tmp_table):
    t = make(tmp_table)
    add_constraint(t.delta_log, "pos", "id > 0")
    drop_constraint(t.delta_log, "pos", if_exists=False)
    append(t, pa.table({"id": pa.array([-5], pa.int64()),
                        "v": pa.array(["now ok"])}))
    assert -5 in t.to_arrow().column("id").to_pylist()


def test_drop_missing_constraint(tmp_table):
    t = make(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        drop_constraint(t.delta_log, "ghost", if_exists=False)
    drop_constraint(t.delta_log, "ghost", if_exists=True)  # no-op


# -- interplay ----------------------------------------------------------------


def test_alter_then_time_travel_sees_old_schema(tmp_table):
    t = make(tmp_table)
    v = t.version
    add_columns(t.delta_log, [StructField("extra", LongType())])
    set_table_properties(t.delta_log, {"custom.x": "1"})
    old = t.to_arrow(version=v)
    assert "extra" not in old.column_names


def test_alter_conflicts_with_concurrent_writer(tmp_table):
    """Metadata change must conflict-check against concurrent commits
    (MetadataChangedException semantics are tested in test_txn; here the
    command-level path must simply succeed in sequence)."""
    t = make(tmp_table)
    add_columns(t.delta_log, [StructField("e1", LongType())])
    add_columns(t.delta_log, [StructField("e2", LongType())])
    assert t.schema().field_names == ["id", "v", "e1", "e2"]
