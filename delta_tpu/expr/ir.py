"""Expression IR — the engine's predicate/projection language.

The reference leans on Spark Catalyst for predicates, update expressions,
generated columns and constraints (SURVEY §7 "Hard parts"). This is our
replacement: a small, SQL-semantics (3-valued logic, casts) expression tree
with three evaluators:

* :meth:`Expression.eval` — row-at-a-time over a ``dict`` (host, used for
  partition-value pruning, conflict checking, constraint messages);
* ``delta_tpu.expr.vectorized`` — pyarrow/numpy columnar evaluation (host
  scan filtering, DML projection);
* ``delta_tpu.expr.jaxeval`` — compile to ``jnp`` ops over device-resident
  columns (stats pruning and DML kernels on TPU).

NULL is represented as Python ``None`` / masked lanes; comparisons with NULL
yield NULL; AND/OR use Kleene logic — matching Spark SQL.
"""
from __future__ import annotations

import math
import re
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from delta_tpu.schema.types import (
    BooleanType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    LongType,
    StringType,
    TimestampType,
)
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = [
    "Expression",
    "Column",
    "Literal",
    "Alias",
    "And",
    "Or",
    "Not",
    "Eq",
    "NullSafeEq",
    "Ne",
    "Lt",
    "Le",
    "Gt",
    "Ge",
    "In",
    "IsNull",
    "IsNotNull",
    "Add",
    "Sub",
    "Mul",
    "Div",
    "Mod",
    "Neg",
    "Cast",
    "Like",
    "StartsWith",
    "Coalesce",
    "CaseWhen",
    "Func",
    "TRUE",
    "FALSE",
    "and_all",
    "split_conjuncts",
    "references",
]


class Expression:
    children: Tuple["Expression", ...] = ()

    def eval(self, row: Dict[str, Any]) -> Any:
        raise NotImplementedError

    # -- tree utilities --------------------------------------------------

    def walk(self) -> Iterator["Expression"]:
        yield self
        for c in self.children:
            yield from c.walk()

    def transform(self, fn: Callable[["Expression"], Optional["Expression"]]) -> "Expression":
        replaced = fn(self)
        if replaced is not None:
            return replaced
        new_children = tuple(c.transform(fn) for c in self.children)
        if new_children == self.children:
            return self
        clone = object.__new__(type(self))
        clone.__dict__.update(self.__dict__)
        clone.children = new_children
        return clone

    def sql(self) -> str:
        raise NotImplementedError

    def __repr__(self) -> str:
        return self.sql()

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self.sql() == other.sql()

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.sql()))


def references(expr: Expression) -> List[str]:
    """Column names referenced (lower-cased for case-insensitive resolution)."""
    out = []
    for e in expr.walk():
        if isinstance(e, Column):
            out.append(e.name)
    return out


def split_conjuncts(expr: Expression) -> List[Expression]:
    if isinstance(expr, And):
        return split_conjuncts(expr.left) + split_conjuncts(expr.right)
    return [expr]


def and_all(exprs: Sequence[Expression]) -> Expression:
    if not exprs:
        return TRUE
    out = exprs[0]
    for e in exprs[1:]:
        out = And(out, e)
    return out


class Column(Expression):
    def __init__(self, name: str):
        self.name = name
        self.children = ()

    def eval(self, row: Dict[str, Any]) -> Any:
        if self.name in row:
            return row[self.name]
        # case-insensitive fallback (Delta is case-insensitive by default)
        lname = self.name.lower()
        for k, v in row.items():
            if k.lower() == lname:
                return v
        raise errors.column_not_found_in_row(self.name, row)

    def sql(self) -> str:
        if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", self.name):
            return self.name
        escaped = self.name.replace("`", "``")
        return f"`{escaped}`"


class Literal(Expression):
    def __init__(self, value: Any, data_type: Optional[DataType] = None):
        self.value = value
        self.data_type = data_type or _infer_type(value)
        self.children = ()

    def eval(self, row: Dict[str, Any]) -> Any:
        return self.value

    def sql(self) -> str:
        if self.value is None:
            return "NULL"
        if isinstance(self.value, bool):
            return "TRUE" if self.value else "FALSE"
        if isinstance(self.value, str):
            escaped = self.value.replace("'", "''")
            return f"'{escaped}'"
        return str(self.value)


TRUE = Literal(True, BooleanType())
FALSE = Literal(False, BooleanType())


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        self.children = (child,)
        self.name = name

    @property
    def child(self) -> Expression:
        return self.children[0]

    def eval(self, row):
        return self.child.eval(row)

    def sql(self) -> str:
        return f"{self.child.sql()} AS {self.name}"


def _infer_type(v: Any) -> DataType:
    if v is None:
        return StringType()
    if isinstance(v, bool):
        return BooleanType()
    if isinstance(v, int):
        return LongType()
    if isinstance(v, float):
        return DoubleType()
    if isinstance(v, str):
        return StringType()
    return StringType()


class _Binary(Expression):
    op = ""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]

    def sql(self) -> str:
        return f"({self.left.sql()} {self.op} {self.right.sql()})"


class And(_Binary):
    op = "AND"

    def eval(self, row):
        l = self.left.eval(row)
        if l is False:
            return False
        r = self.right.eval(row)
        if r is False:
            return False
        if l is None or r is None:
            return None
        return True


class Or(_Binary):
    op = "OR"

    def eval(self, row):
        l = self.left.eval(row)
        if l is True:
            return True
        r = self.right.eval(row)
        if r is True:
            return True
        if l is None or r is None:
            return None
        return False


class Not(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        v = self.child.eval(row)
        if v is None:
            return None
        return not v

    def sql(self) -> str:
        return f"(NOT {self.child.sql()})"


def _parse_temporal_str(s: str, like: Any):
    import datetime as _dt

    from delta_tpu.utils.timeparse import iso_to_date, iso_to_naive_utc

    if isinstance(like, _dt.datetime):
        out = iso_to_naive_utc(s)
        if like.tzinfo is not None:
            out = out.replace(tzinfo=_dt.timezone.utc)  # compare as aware
        return out
    return iso_to_date(s)


def _coerce_pair(l: Any, r: Any) -> Tuple[Any, Any]:
    """Numeric cross-type comparisons; strings compare as strings — except
    against dates/timestamps, where the string side parses as ISO-8601
    (Spark's implicit cast of temporal literals)."""
    import datetime as _dt

    if isinstance(l, bool) or isinstance(r, bool):
        return l, r
    if isinstance(l, (int, float)) and isinstance(r, (int, float)):
        return l, r
    if isinstance(l, str) and isinstance(r, (_dt.datetime, _dt.date)):
        try:
            return _parse_temporal_str(l, r), r
        except ValueError:
            return l, r
    if isinstance(r, str) and isinstance(l, (_dt.datetime, _dt.date)):
        try:
            return l, _parse_temporal_str(r, l)
        except ValueError:
            return l, r
    return l, r


class _Comparison(_Binary):
    py = staticmethod(lambda l, r: None)

    def eval(self, row):
        l = self.left.eval(row)
        r = self.right.eval(row)
        if l is None or r is None:
            return None
        l, r = _coerce_pair(l, r)
        try:
            return self.py(l, r)
        except TypeError:
            raise errors.cannot_compare_types(
                type(l).__name__, type(r).__name__, self.sql())


class Eq(_Comparison):
    op = "="
    py = staticmethod(lambda l, r: l == r)


class NullSafeEq(_Binary):
    op = "<=>"

    def eval(self, row):
        l = self.left.eval(row)
        r = self.right.eval(row)
        return l == r  # None <=> None is True


class Ne(_Comparison):
    op = "!="
    py = staticmethod(lambda l, r: l != r)


class Lt(_Comparison):
    op = "<"
    py = staticmethod(lambda l, r: l < r)


class Le(_Comparison):
    op = "<="
    py = staticmethod(lambda l, r: l <= r)


class Gt(_Comparison):
    op = ">"
    py = staticmethod(lambda l, r: l > r)


class Ge(_Comparison):
    op = ">="
    py = staticmethod(lambda l, r: l >= r)


class In(Expression):
    def __init__(self, value: Expression, options: Sequence[Expression]):
        self.children = (value, *options)

    @property
    def value(self):
        return self.children[0]

    @property
    def options(self):
        return self.children[1:]

    def eval(self, row):
        v = self.value.eval(row)
        if v is None:
            return None
        saw_null = False
        for o in self.options:
            ov = o.eval(row)
            if ov is None:
                saw_null = True
            elif ov == v:
                return True
        return None if saw_null else False

    def sql(self) -> str:
        opts = ", ".join(o.sql() for o in self.options)
        return f"({self.value.sql()} IN ({opts}))"


class IsNull(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        return self.child.eval(row) is None

    def sql(self) -> str:
        return f"({self.child.sql()} IS NULL)"


class IsNotNull(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        return self.child.eval(row) is not None

    def sql(self) -> str:
        return f"({self.child.sql()} IS NOT NULL)"


class _Arith(_Binary):
    py = staticmethod(lambda l, r: None)

    def eval(self, row):
        l = self.left.eval(row)
        r = self.right.eval(row)
        if l is None or r is None:
            return None
        try:
            return self.py(l, r)
        except TypeError:
            raise errors.cannot_apply_operator(
                self.op, type(l).__name__, type(r).__name__, self.sql())


class Add(_Arith):
    op = "+"
    py = staticmethod(lambda l, r: l + r)


class Sub(_Arith):
    op = "-"
    py = staticmethod(lambda l, r: l - r)


class Mul(_Arith):
    op = "*"
    py = staticmethod(lambda l, r: l * r)


class Div(_Arith):
    op = "/"

    @staticmethod
    def py(l, r):
        if r == 0:
            return None  # Spark: div by zero yields NULL (ansi off)
        return l / r


class Mod(_Arith):
    op = "%"

    @staticmethod
    def py(l, r):
        if r == 0:
            return None
        return math.fmod(l, r) if isinstance(l, float) or isinstance(r, float) else l % r


class Neg(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        v = self.child.eval(row)
        return None if v is None else -v

    def sql(self) -> str:
        return f"(- {self.child.sql()})"


class Cast(Expression):
    def __init__(self, child: Expression, data_type: DataType):
        self.children = (child,)
        self.data_type = data_type

    @property
    def child(self):
        return self.children[0]

    def eval(self, row):
        return cast_value(self.child.eval(row), self.data_type)

    def sql(self) -> str:
        return f"CAST({self.child.sql()} AS {self.data_type.simple_string().upper()})"


def cast_value(v: Any, dt: DataType) -> Any:
    """Spark-style permissive cast; invalid casts yield NULL (ansi off)."""
    if v is None:
        return None
    try:
        name = dt.name if not isinstance(dt, DecimalType) else "decimal"
        if isinstance(dt, BooleanType):
            if isinstance(v, str):
                s = v.strip().lower()
                if s in ("true", "t", "yes", "y", "1"):
                    return True
                if s in ("false", "f", "no", "n", "0"):
                    return False
                return None
            return bool(v)
        if name in ("byte", "short", "integer", "long"):
            if isinstance(v, bool):
                return int(v)
            if isinstance(v, str):
                v = v.strip()
                return int(float(v)) if "." in v or "e" in v.lower() else int(v)
            return int(v)
        if name in ("float", "double", "decimal"):
            return float(v)
        if isinstance(dt, StringType):
            if isinstance(v, bool):
                return "true" if v else "false"
            return str(v)
        if isinstance(dt, DateType):
            if isinstance(v, int):
                return v
            import datetime as _dt

            return (_dt.date.fromisoformat(str(v)[:10]) - _dt.date(1970, 1, 1)).days
        if isinstance(dt, TimestampType):
            if isinstance(v, int):
                return v
            import datetime as _dt

            s = str(v).replace(" ", "T")
            return int(_dt.datetime.fromisoformat(s).replace(tzinfo=_dt.timezone.utc).timestamp() * 1_000_000)
    except (ValueError, TypeError):
        return None
    return v


class Like(_Binary):
    """SQL LIKE with % and _ wildcards."""

    op = "LIKE"
    _rx_cache: Optional[Tuple[str, Any]] = None

    def eval(self, row):
        v = self.left.eval(row)
        p = self.right.eval(row)
        if v is None or p is None:
            return None
        if not isinstance(v, str) or not isinstance(p, str):
            raise errors.like_requires_strings(type(v).__name__, self.sql())
        cached = self._rx_cache
        if cached is None or cached[0] != p:
            rx = re.compile(
                "".join(".*" if ch == "%" else "." if ch == "_" else re.escape(ch) for ch in p),
                re.DOTALL,
            )
            self._rx_cache = cached = (p, rx)
        return cached[1].fullmatch(v) is not None


class StartsWith(_Binary):
    op = "STARTSWITH"

    def eval(self, row):
        v = self.left.eval(row)
        p = self.right.eval(row)
        if v is None or p is None:
            return None
        return str(v).startswith(str(p))

    def sql(self) -> str:
        return f"startswith({self.left.sql()}, {self.right.sql()})"


class Coalesce(Expression):
    def __init__(self, *options: Expression):
        self.children = tuple(options)

    def eval(self, row):
        for o in self.children:
            v = o.eval(row)
            if v is not None:
                return v
        return None

    def sql(self) -> str:
        return f"coalesce({', '.join(o.sql() for o in self.children)})"


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 [WHEN ...] ELSE d END. Children layout:
    (c1, v1, c2, v2, ..., default)."""

    def __init__(self, branches: Sequence[Tuple[Expression, Expression]],
                 default: Optional[Expression] = None):
        flat: List[Expression] = []
        for c, v in branches:
            flat.extend((c, v))
        flat.append(default if default is not None else Literal(None))
        self.children = tuple(flat)
        self.n_branches = len(branches)

    def eval(self, row):
        for i in range(self.n_branches):
            if self.children[2 * i].eval(row) is True:
                return self.children[2 * i + 1].eval(row)
        return self.children[-1].eval(row)

    def sql(self) -> str:
        parts = ["CASE"]
        for i in range(self.n_branches):
            parts.append(f"WHEN {self.children[2*i].sql()} THEN {self.children[2*i+1].sql()}")
        parts.append(f"ELSE {self.children[-1].sql()} END")
        return " ".join(parts)


class Func(Expression):
    """Named scalar function (whitelisted set, used by generated columns)."""

    FUNCS: Dict[str, Callable[..., Any]] = {
        "abs": lambda x: None if x is None else abs(x),
        "length": lambda x: None if x is None else len(x),
        "lower": lambda x: None if x is None else str(x).lower(),
        "upper": lambda x: None if x is None else str(x).upper(),
        "trim": lambda x: None if x is None else str(x).strip(),
        "concat": lambda *xs: None if any(x is None for x in xs) else "".join(str(x) for x in xs),
        "substring": lambda s, pos, ln=None: None if s is None else (
            s[max(pos - 1, 0):] if ln is None else s[max(pos - 1, 0):max(pos - 1, 0) + ln]
        ),
        "year": lambda d: None if d is None else _epoch_day_field(d, "year"),
        "month": lambda d: None if d is None else _epoch_day_field(d, "month"),
        "day": lambda d: None if d is None else _epoch_day_field(d, "day"),
        "hour": lambda t: None if t is None else ((t // 3_600_000_000) % 24),
        "floor": lambda x: None if x is None else math.floor(x),
        "ceil": lambda x: None if x is None else math.ceil(x),
        "round": lambda x, n=0: None if x is None else round(x, n),
    }

    def __init__(self, name: str, args: Sequence[Expression]):
        self.name = name.lower()
        if self.name not in self.FUNCS:
            raise errors.unsupported_function(name)
        self.children = tuple(args)

    def eval(self, row):
        return self.FUNCS[self.name](*(a.eval(row) for a in self.children))

    def sql(self) -> str:
        return f"{self.name}({', '.join(a.sql() for a in self.children)})"


def _epoch_day_field(days: Any, field: str) -> Optional[int]:
    import datetime as _dt

    if isinstance(days, _dt.date):
        d = days
    else:
        d = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(days))
    return getattr(d, field)
