"""Z-order (Morton) interleaving on device.

The reference carries Z-order cluster tags in the file format
(`actions/actions.scala:270-291`) but ships no OPTIMIZE command; the baseline
harness measures Z-ORDER + point-query skipping, so we implement it: each
clustering column is rank-normalized to 16 bits, ranks are bit-interleaved
into one Morton key on device (16 static rounds of shifts/masks — pure VPU
work, fused by XLA), and rows sort by that key. Sorting by Morton keys makes
per-file min/max boxes compact in every clustered dimension, which is what
the skipping predicate (`ops/pruning.py`) exploits.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np
from delta_tpu.utils.jaxcompat import enable_x64

__all__ = ["morton_order", "rank_u16"]

_BITS = 16


def rank_u16(values: np.ndarray) -> np.ndarray:
    """Dense-rank a column and scale into [0, 2^16): order-preserving,
    type-agnostic (works for strings via argsort on host)."""
    order = np.argsort(values, kind="stable")
    ranks = np.empty(len(values), np.int64)
    ranks[order] = np.arange(len(values))
    n = max(len(values) - 1, 1)
    return ((ranks * ((1 << _BITS) - 1)) // n).astype(np.uint32)


def morton_order(columns: Sequence[np.ndarray]) -> np.ndarray:
    """Row permutation sorting by the interleaved (Morton) key of the given
    rank columns. Uses the device for the bit-interleave when JAX is usable;
    identical numpy fallback otherwise."""
    k = len(columns)
    if k == 0:
        raise ValueError("morton_order needs at least one column")
    ranks = [rank_u16(c) for c in columns]
    try:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def interleave(rs):
            key = jnp.zeros(rs[0].shape, jnp.uint64)
            for b in range(_BITS):
                for c in range(k):
                    bit = (rs[c] >> b) & 1
                    key = key | (bit.astype(jnp.uint64) << (b * k + c))
            return key

        with enable_x64():
            key = np.asarray(interleave([jnp.asarray(r) for r in ranks]))
    except Exception:
        key = np.zeros(len(ranks[0]), np.uint64)
        for b in range(_BITS):
            for c in range(k):
                key |= ((ranks[c].astype(np.uint64) >> b) & 1) << (b * k + c)
    return np.argsort(key, kind="stable")
