"""SQL front end for the Delta utility statements.

Scope matches the reference grammar (`antlr4/.../DeltaSqlBase.g4:74-81`):
VACUUM, DESCRIBE HISTORY | DETAIL, GENERATE, CONVERT TO DELTA — plus
DELETE FROM / UPDATE, which the reference delegates to Spark SQL but a
standalone engine must parse itself. Table references are
``delta.`/path``` or a bare quoted path, like the reference's path-based
identifiers (`DeltaTableIdentifier.scala`).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.schema.types import StructField, StructType
from delta_tpu.utils.errors import DeltaAnalysisError

__all__ = ["execute_sql"]

_WS = r"\s+"


def _table_path(token: str) -> str:
    token = token.strip()
    m = re.fullmatch(r"(?:delta\s*\.\s*)?`([^`]+)`", token, re.IGNORECASE)
    if m:
        return m.group(1)
    m = re.fullmatch(r"(?:parquet\s*\.\s*)?`([^`]+)`", token, re.IGNORECASE)
    if m:
        return m.group(1)
    m = re.fullmatch(r"'([^']+)'|\"([^\"]+)\"", token)
    if m:
        return m.group(1) or m.group(2)
    return token


def _parse_type(s: str):
    from delta_tpu.schema.types import (
        BooleanType, DateType, DoubleType, FloatType, IntegerType, LongType,
        StringType, TimestampType,
    )

    t = s.strip().lower()
    return {
        "int": IntegerType(), "integer": IntegerType(), "bigint": LongType(),
        "long": LongType(), "string": StringType(), "double": DoubleType(),
        "float": FloatType(), "boolean": BooleanType(), "date": DateType(),
        "timestamp": TimestampType(),
    }.get(t) or _fail(f"Unsupported type in PARTITIONED BY: {s!r}")


def _fail(msg: str):
    raise DeltaAnalysisError(msg)


def execute_sql(sql: str) -> Any:
    """Parse and run one Delta statement; returns the command's result."""
    stmt = sql.strip().rstrip(";").strip()

    m = re.fullmatch(
        r"VACUUM\s+(?P<tbl>\S+|delta\s*\.\s*`[^`]+`)"
        r"(?:\s+RETAIN\s+(?P<hours>[\d.]+)\s+HOURS?)?"
        r"(?:\s+(?P<dry>DRY\s+RUN))?",
        stmt, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.vacuum import VacuumCommand

        log = DeltaLog.for_table(_table_path(m.group("tbl")))
        hours = float(m.group("hours")) if m.group("hours") else None
        return VacuumCommand(log, hours, dry_run=bool(m.group("dry"))).run()

    m = re.fullmatch(
        r"DESCRIBE\s+HISTORY\s+(?P<tbl>\S+|delta\s*\.\s*`[^`]+`)"
        r"(?:\s+LIMIT\s+(?P<limit>\d+))?",
        stmt, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.describe import describe_history

        log = DeltaLog.for_table(_table_path(m.group("tbl")))
        limit = int(m.group("limit")) if m.group("limit") else None
        return describe_history(log, limit)

    m = re.fullmatch(
        r"DESCRIBE\s+DETAIL\s+(?P<tbl>\S+|delta\s*\.\s*`[^`]+`)",
        stmt, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.describe import describe_detail

        return describe_detail(DeltaLog.for_table(_table_path(m.group("tbl"))))

    m = re.fullmatch(
        r"GENERATE\s+(?P<mode>\w+)\s+FOR\s+TABLE\s+(?P<tbl>\S+|delta\s*\.\s*`[^`]+`)",
        stmt, re.IGNORECASE,
    )
    if m:
        mode = m.group("mode").lower()
        if mode != "symlink_format_manifest":
            _fail(f"Unsupported GENERATE mode: {mode}")
        from delta_tpu.hooks.symlink_manifest import generate_full_manifest

        return generate_full_manifest(DeltaLog.for_table(_table_path(m.group("tbl"))))

    m = re.fullmatch(
        r"CONVERT\s+TO\s+DELTA\s+(?P<tbl>parquet\s*\.\s*`[^`]+`|\S+)"
        r"(?:\s+PARTITIONED\s+BY\s*\((?P<parts>[^)]*)\))?",
        stmt, re.IGNORECASE,
    )
    if m:
        from delta_tpu.commands.convert import ConvertToDeltaCommand

        part_schema = None
        if m.group("parts"):
            fields = []
            for spec in m.group("parts").split(","):
                bits = spec.strip().split()
                if len(bits) != 2:
                    _fail(f"Bad PARTITIONED BY column spec: {spec.strip()!r}")
                fields.append(StructField(bits[0], _parse_type(bits[1])))
            part_schema = StructType(fields)
        log = DeltaLog.for_table(_table_path(m.group("tbl")))
        return ConvertToDeltaCommand(log, partition_schema=part_schema).run()

    m = re.fullmatch(
        r"DELETE\s+FROM\s+(?P<tbl>\S+|delta\s*\.\s*`[^`]+`)"
        r"(?:\s+WHERE\s+(?P<cond>.+))?",
        stmt, re.IGNORECASE | re.DOTALL,
    )
    if m:
        from delta_tpu.commands.delete import DeleteCommand

        log = DeltaLog.for_table(_table_path(m.group("tbl")))
        cmd = DeleteCommand(log, m.group("cond"))
        cmd.run()
        return cmd.metrics

    m = re.fullmatch(
        r"UPDATE\s+(?P<tbl>\S+|delta\s*\.\s*`[^`]+`)"
        r"\s+SET\s+(?P<sets>.+?)(?:\s+WHERE\s+(?P<cond>.+))?",
        stmt, re.IGNORECASE | re.DOTALL,
    )
    if m:
        from delta_tpu.commands.update import UpdateCommand

        sets: Dict[str, str] = {}
        for part in _split_top_level(m.group("sets")):
            col, _, expr = part.partition("=")
            if not expr:
                _fail(f"Bad SET clause: {part!r}")
            sets[col.strip().strip("`")] = expr.strip()
        log = DeltaLog.for_table(_table_path(m.group("tbl")))
        cmd = UpdateCommand(log, sets, m.group("cond"))
        cmd.run()
        return cmd.metrics

    _fail(f"Unsupported SQL statement: {stmt[:80]!r}")


def _split_top_level(s: str) -> List[str]:
    """Split on commas not inside parens/quotes."""
    out, depth, start, in_str = [], 0, 0, None
    for i, ch in enumerate(s):
        if in_str:
            if ch == in_str:
                in_str = None
            continue
        if ch in "'\"":
            in_str = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    out.append(s[start:])
    return [p for p in (x.strip() for x in out) if p]
