"""Delta transaction-log actions model + JSON codec.

Byte-compatible with the Delta protocol's action schema (normative spec:
``/root/reference/PROTOCOL.md`` "Actions" section; reference implementation
``core/src/main/scala/org/apache/spark/sql/delta/actions/actions.scala``).
Each commit file is newline-delimited JSON; each line is a single-action
envelope ``{"add": {...}}`` / ``{"remove": {...}}`` / etc.

This module is pure Python with zero JAX/arrow dependencies — it is the
host-side log kernel's vocabulary. Checkpoint (Parquet) serialization of the
same actions lives in ``delta_tpu.log.checkpoints``.
"""
from __future__ import annotations

import json
import uuid
import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

from delta_tpu.schema.types import StructType, schema_from_json

__all__ = [
    "Action",
    "Protocol",
    "SetTransaction",
    "FileAction",
    "AddFile",
    "RemoveFile",
    "AddCDCFile",
    "Format",
    "Metadata",
    "JobInfo",
    "NotebookInfo",
    "CommitInfo",
    "action_from_json",
    "actions_from_lines",
]

# Default protocol versions for new tables.
# Mirrors actions.scala:52-55 (readerVersion=1, writerVersion=4 in the reference).
READER_VERSION = 1
WRITER_VERSION = 4

# Highest protocol versions this implementation can read/write. (3, 7) is the
# table-features range: versions 3/7 carry explicit readerFeatures/
# writerFeatures lists and a table is admitted only when every listed feature
# is supported here (see SUPPORTED_*_FEATURES). Version 2 (column mapping)
# and 5/6 are NOT supported and stay refused.
SUPPORTED_READER_VERSION = 3
SUPPORTED_WRITER_VERSION = 7

# This engine's DV flavor uses its own bitmap encoding
# (protocol/deletion_vectors.py), so it advertises a distinct feature name:
# real-Delta DV tables (feature "deletionVectors", RoaringBitmap payloads)
# are refused cleanly here, and vice versa.
DV_FEATURE_NAME = "tpu.deletionVectors"
SUPPORTED_READER_FEATURES = frozenset({DV_FEATURE_NAME})
SUPPORTED_WRITER_FEATURES = frozenset({DV_FEATURE_NAME})


def _drop_none(d: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in d.items() if v is not None}


def _json(obj: Any) -> str:
    # Compact separators to match the reference's Jackson output (no spaces).
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False)


class Action:
    """Base class. Subclasses implement ``wrap_key`` and ``to_dict``."""

    wrap_key: str = ""

    def to_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    def wrap(self) -> Dict[str, Any]:
        return {self.wrap_key: self.to_dict()}

    def json(self) -> str:
        return _json(self.wrap())


@dataclass(frozen=True)
class Protocol(Action):
    """Protocol version gate (PROTOCOL.md "Protocol Evolution";
    actions.scala:84-193). Reader 3 / writer 7 are the table-features
    versions: they carry explicit feature-name lists, per the modern Delta
    table-features spec — reader 3 REQUIRES readerFeatures, writer 7
    REQUIRES writerFeatures."""

    min_reader_version: int = READER_VERSION
    min_writer_version: int = WRITER_VERSION
    reader_features: Optional[Tuple[str, ...]] = None
    writer_features: Optional[Tuple[str, ...]] = None

    wrap_key = "protocol"

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "minReaderVersion": self.min_reader_version,
            "minWriterVersion": self.min_writer_version,
        }
        if self.min_reader_version >= 3:
            d["readerFeatures"] = sorted(self.reader_features or ())
        if self.min_writer_version >= 7:
            d["writerFeatures"] = sorted(self.writer_features or ())
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Protocol":
        rf = d.get("readerFeatures")
        wf = d.get("writerFeatures")
        return Protocol(
            int(d["minReaderVersion"]),
            int(d["minWriterVersion"]),
            tuple(rf) if rf is not None else None,
            tuple(wf) if wf is not None else None,
        )


@dataclass(frozen=True)
class SetTransaction(Action):
    """Streaming-sink idempotency marker (PROTOCOL.md "Transaction Identifiers";
    actions.scala:199-216)."""

    app_id: str
    version: int
    last_updated: Optional[int] = None

    wrap_key = "txn"

    def to_dict(self) -> Dict[str, Any]:
        return _drop_none(
            {"appId": self.app_id, "version": self.version, "lastUpdated": self.last_updated}
        )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SetTransaction":
        return SetTransaction(d["appId"], int(d["version"]), d.get("lastUpdated"))


class FileAction(Action):
    path: str
    data_change: bool


@dataclass(frozen=True)
class AddFile(FileAction):
    """A data file that is logically part of the table
    (PROTOCOL.md "Add File and Remove File"; actions.scala:220-295)."""

    path: str
    partition_values: Dict[str, Optional[str]] = field(default_factory=dict)
    size: int = 0
    modification_time: int = 0
    data_change: bool = True
    stats: Optional[str] = None  # raw JSON string, parsed lazily
    tags: Optional[Dict[str, str]] = None
    # deletion-vector descriptor dict (protocol/deletion_vectors.py); rows
    # listed there are logically deleted from this file
    deletion_vector: Optional[Dict[str, Any]] = None

    wrap_key = "add"

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "path": self.path,
            "partitionValues": self.partition_values,
            "size": self.size,
            "modificationTime": self.modification_time,
            "dataChange": self.data_change,
        }
        if self.stats is not None:
            d["stats"] = self.stats
        if self.tags is not None:
            d["tags"] = self.tags
        if self.deletion_vector is not None:
            d["deletionVector"] = self.deletion_vector
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AddFile":
        return AddFile(
            path=d["path"],
            partition_values=dict(d.get("partitionValues") or {}),
            size=int(d.get("size") or 0),
            modification_time=int(d.get("modificationTime") or 0),
            data_change=bool(d.get("dataChange", True)),
            stats=d.get("stats"),
            tags=d.get("tags"),
            deletion_vector=d.get("deletionVector"),
        )

    def remove(self, deletion_timestamp: Optional[int] = None, data_change: bool = True) -> "RemoveFile":
        """Tombstone for this file (actions.scala:245-252). Carries the
        add's deletion vector so vacuum keeps/expires the DV sidecar with
        the data file."""
        ts = deletion_timestamp if deletion_timestamp is not None else int(time.time() * 1000)
        return RemoveFile(
            path=self.path,
            deletion_timestamp=ts,
            data_change=data_change,
            extended_file_metadata=True,
            partition_values=self.partition_values,
            size=self.size,
            tags=self.tags,
            deletion_vector=self.deletion_vector,
        )

    def with_data_change(self, data_change: bool) -> "AddFile":
        return replace(self, data_change=data_change)

    def stats_dict(self) -> Optional[Dict[str, Any]]:
        if self.stats is None:
            return None
        try:
            return json.loads(self.stats)
        except (ValueError, TypeError):
            return None

    @property
    def num_logical_records(self) -> Optional[int]:
        s = self.stats_dict()
        n = s.get("numRecords") if isinstance(s, dict) else None
        # foreign writers may emit "numRecords": null — treat as absent
        return int(n) if isinstance(n, (int, float)) else None


@dataclass(frozen=True)
class RemoveFile(FileAction):
    """Tombstone (PROTOCOL.md "Add File and Remove File";
    actions.scala:307-324)."""

    path: str
    deletion_timestamp: Optional[int] = None
    data_change: bool = True
    extended_file_metadata: Optional[bool] = None
    partition_values: Optional[Dict[str, Optional[str]]] = None
    size: Optional[int] = None
    tags: Optional[Dict[str, str]] = None
    deletion_vector: Optional[Dict[str, Any]] = None

    wrap_key = "remove"

    def to_dict(self) -> Dict[str, Any]:
        return _drop_none(
            {
                "path": self.path,
                "deletionTimestamp": self.deletion_timestamp,
                "dataChange": self.data_change,
                "extendedFileMetadata": self.extended_file_metadata,
                "partitionValues": self.partition_values,
                "size": self.size,
                "tags": self.tags,
                "deletionVector": self.deletion_vector,
            }
        )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "RemoveFile":
        return RemoveFile(
            path=d["path"],
            deletion_timestamp=d.get("deletionTimestamp"),
            data_change=bool(d.get("dataChange", True)),
            extended_file_metadata=d.get("extendedFileMetadata"),
            partition_values=d.get("partitionValues"),
            size=d.get("size"),
            tags=d.get("tags"),
            deletion_vector=d.get("deletionVector"),
        )

    @property
    def delete_timestamp(self) -> int:
        return self.deletion_timestamp or 0


@dataclass(frozen=True)
class AddCDCFile(FileAction):
    """Change-data file (PROTOCOL.md "Add CDC File"; actions.scala:328-341).
    Write side is protocol-gated the same way the reference gates it."""

    path: str
    partition_values: Dict[str, Optional[str]] = field(default_factory=dict)
    size: int = 0
    tags: Optional[Dict[str, str]] = None

    wrap_key = "cdc"
    data_change = False

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "path": self.path,
            "partitionValues": self.partition_values,
            "size": self.size,
            "dataChange": False,
        }
        if self.tags is not None:
            d["tags"] = self.tags
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AddCDCFile":
        return AddCDCFile(
            path=d["path"],
            partition_values=dict(d.get("partitionValues") or {}),
            size=int(d.get("size") or 0),
            tags=d.get("tags"),
        )


@dataclass(frozen=True)
class Format:
    provider: str = "parquet"
    options: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"provider": self.provider, "options": self.options}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Format":
        return Format(d.get("provider", "parquet"), dict(d.get("options") or {}))


@dataclass(frozen=True)
class Metadata(Action):
    """Table metadata (PROTOCOL.md "Change Metadata"; actions.scala:348-393)."""

    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    name: Optional[str] = None
    description: Optional[str] = None
    format: Format = field(default_factory=Format)
    schema_string: Optional[str] = None
    partition_columns: List[str] = field(default_factory=list)
    configuration: Dict[str, str] = field(default_factory=dict)
    created_time: Optional[int] = None

    wrap_key = "metaData"

    def to_dict(self) -> Dict[str, Any]:
        return _drop_none(
            {
                "id": self.id,
                "name": self.name,
                "description": self.description,
                "format": self.format.to_dict(),
                "schemaString": self.schema_string,
                "partitionColumns": list(self.partition_columns),
                "configuration": self.configuration,
                "createdTime": self.created_time,
            }
        )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Metadata":
        return Metadata(
            id=d.get("id") or str(uuid.uuid4()),
            name=d.get("name"),
            description=d.get("description"),
            format=Format.from_dict(d.get("format") or {}),
            schema_string=d.get("schemaString"),
            partition_columns=list(d.get("partitionColumns") or []),
            configuration=dict(d.get("configuration") or {}),
            created_time=d.get("createdTime"),
        )

    @property
    def schema(self) -> StructType:
        """Lazy schema parse (actions.scala:368-372)."""
        if self.schema_string is None:
            return StructType([])
        return schema_from_json(self.schema_string)

    @property
    def data_schema(self) -> StructType:
        part = set(self.partition_columns)
        return StructType([f for f in self.schema.fields if f.name not in part])

    @property
    def partition_schema(self) -> StructType:
        by_name = {f.name: f for f in self.schema.fields}
        return StructType([by_name[c] for c in self.partition_columns if c in by_name])


@dataclass(frozen=True)
class JobInfo:
    job_id: Optional[str] = None
    job_name: Optional[str] = None
    run_id: Optional[str] = None
    job_owner_id: Optional[str] = None
    trigger_type: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return _drop_none(
            {
                "jobId": self.job_id,
                "jobName": self.job_name,
                "runId": self.run_id,
                "jobOwnerId": self.job_owner_id,
                "triggerType": self.trigger_type,
            }
        )


@dataclass(frozen=True)
class NotebookInfo:
    notebook_id: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return _drop_none({"notebookId": self.notebook_id})


@dataclass(frozen=True)
class CommitInfo(Action):
    """Provenance record, first action of every commit
    (actions.scala:414-511). Not part of table state reconstruction."""

    version: Optional[int] = None
    timestamp: Optional[int] = None
    user_id: Optional[str] = None
    user_name: Optional[str] = None
    operation: str = ""
    operation_parameters: Dict[str, Any] = field(default_factory=dict)
    job: Optional[JobInfo] = None
    notebook: Optional[NotebookInfo] = None
    cluster_id: Optional[str] = None
    read_version: Optional[int] = None
    isolation_level: Optional[str] = None
    is_blind_append: Optional[bool] = None
    operation_metrics: Optional[Dict[str, str]] = None
    user_metadata: Optional[str] = None
    engine_info: Optional[str] = None
    # per-commit ownership token (actions.scala:489 `txnId`): lets a writer
    # whose create returned an indeterminate error re-read version N and
    # decide won/lost (txn/transaction.py ambiguous-commit reconciliation)
    txn_id: Optional[str] = None

    wrap_key = "commitInfo"

    def to_dict(self) -> Dict[str, Any]:
        return _drop_none(
            {
                "version": self.version,
                "timestamp": self.timestamp,
                "userId": self.user_id,
                "userName": self.user_name,
                "operation": self.operation,
                # operationParameters values are JSON-encoded strings, matching
                # DeltaOperations.scala jsonEncodedValues.
                "operationParameters": self.operation_parameters,
                "job": self.job.to_dict() if self.job else None,
                "notebook": self.notebook.to_dict() if self.notebook else None,
                "clusterId": self.cluster_id,
                "readVersion": self.read_version,
                "isolationLevel": self.isolation_level,
                "isBlindAppend": self.is_blind_append,
                "operationMetrics": self.operation_metrics,
                "userMetadata": self.user_metadata,
                "engineInfo": self.engine_info,
                "txnId": self.txn_id,
            }
        )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "CommitInfo":
        job = d.get("job")
        notebook = d.get("notebook")
        return CommitInfo(
            version=d.get("version"),
            timestamp=d.get("timestamp"),
            user_id=d.get("userId"),
            user_name=d.get("userName"),
            operation=d.get("operation") or "",
            operation_parameters=dict(d.get("operationParameters") or {}),
            job=JobInfo(
                job.get("jobId"), job.get("jobName"), job.get("runId"),
                job.get("jobOwnerId"), job.get("triggerType"),
            ) if job else None,
            notebook=NotebookInfo(notebook.get("notebookId")) if notebook else None,
            cluster_id=d.get("clusterId"),
            read_version=d.get("readVersion"),
            isolation_level=d.get("isolationLevel"),
            is_blind_append=d.get("isBlindAppend"),
            operation_metrics=d.get("operationMetrics"),
            user_metadata=d.get("userMetadata"),
            engine_info=d.get("engineInfo"),
            txn_id=d.get("txnId"),
        )

    def with_version_timestamp(self, version: int, timestamp: Optional[int] = None) -> "CommitInfo":
        return replace(self, version=version,
                       timestamp=timestamp if timestamp is not None else self.timestamp)


_DECODERS = {
    "add": AddFile.from_dict,
    "remove": RemoveFile.from_dict,
    "metaData": Metadata.from_dict,
    "protocol": Protocol.from_dict,
    "txn": SetTransaction.from_dict,
    "cdc": AddCDCFile.from_dict,
    "commitInfo": CommitInfo.from_dict,
}


def action_from_json(line: str) -> Optional[Action]:
    """Decode one log line into an Action (actions.scala:57-59).
    Unknown single-action keys are ignored (forward compatibility)."""
    if not line or not line.strip():
        return None
    obj = json.loads(line)
    for key, decoder in _DECODERS.items():
        if key in obj and obj[key] is not None:
            return decoder(obj[key])
    return None


def actions_from_lines(lines) -> List[Action]:
    out = []
    for line in lines:
        a = action_from_json(line)
        if a is not None:
            out.append(a)
    return out
