"""Shared DML machinery: candidate selection and file rewrites.

The reference's `commands/DeltaCommand.scala:48-219` equivalent — resolve the
files a predicate may touch (partition pruning + stats skipping), read them,
and rewrite survivors — but columnar: per-file row masks come from one
vectorized predicate evaluation instead of `input_file_name()` joins.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import pyarrow as pa

from delta_tpu.exec.scan import read_files_as_table
from delta_tpu.expr import ir
from delta_tpu.expr.vectorized import boolean_mask
from delta_tpu.ops import pruning
from delta_tpu.protocol.actions import AddFile

__all__ = [
    "TouchedFile",
    "candidate_files",
    "read_candidates",
    "Timer",
    "POSITION_COL",
    "dv_enabled",
    "dv_mark_deleted",
    "dv_mark_from_mask",
]

# physical-row-position column attached to scans when deletion vectors are on
POSITION_COL = "__pos__"


class Timer:
    """Phase timer for operation metrics (scanTimeMs / rewriteTimeMs)."""

    def __init__(self):
        self.t0 = time.perf_counter()

    def lap_ms(self) -> int:
        now = time.perf_counter()
        ms = int((now - self.t0) * 1000)
        self.t0 = now
        return ms

    def lap_ms_f(self) -> float:
        """Float-precision lap for phases that feed the router calibrator:
        int truncation turns a sub-millisecond phase into a zero-duration
        sample the calibrator must reject — starving calibration exactly
        on the hardware (fast, warm caches) where samples are plentiful."""
        now = time.perf_counter()
        ms = (now - self.t0) * 1000.0
        self.t0 = now
        return ms

    def peek_ms(self) -> int:
        return int((time.perf_counter() - self.t0) * 1000)


@dataclass
class TouchedFile:
    add: AddFile
    table: pa.Table  # full rows of the file (with partition columns)
    mask: pa.ChunkedArray  # True = row matches the predicate


def candidate_files(txn, predicate: Optional[ir.Expression]) -> List[AddFile]:
    """Files the predicate may touch; registers the read set on the txn.

    Conjuncts are split so a mixed predicate (``part='a' AND data>5``)
    records the partition leg as the transaction's read predicate — keeping
    the OCC read set partition-scoped instead of whole-table — while stats
    skipping still applies the data leg."""
    if predicate is None:
        return txn.filter_files()
    conjuncts = ir.split_conjuncts(predicate)
    matched = txn.filter_files(conjuncts)
    scan = pruning.files_for_scan(txn.snapshot, [predicate])
    kept_paths = {f.path for f in scan.files}
    return [f for f in matched if f.path in kept_paths]


def read_candidates(
    data_path: str,
    files: Sequence[AddFile],
    metadata,
    predicate: Optional[ir.Expression],
    with_positions: bool = False,
    prune_row_groups: bool = False,
) -> List[TouchedFile]:
    """Read each candidate (parallel decode) and compute its match mask.

    ``prune_row_groups=True`` pushes the predicate into the decode so row
    groups that definitely contain no matches never leave disk
    (`exec/rowgroups`). Only safe when the caller never rewrites untouched
    rows — i.e. deletion-vector DML, which consumes ONLY mask-True rows
    (their physical positions stay correct under skipping). The rewrite
    path must read files whole: rows in pruned groups are exactly the
    non-matching rows it must copy forward."""
    out: List[TouchedFile] = []
    tables = read_files_as_table(
        data_path, files, metadata, per_file=True,
        position_column=POSITION_COL if with_positions else None,
        predicate=predicate if prune_row_groups else None,
    )
    for add, t in zip(files, tables):
        if predicate is None:
            mask = pa.chunked_array([pa.array([True] * t.num_rows)])
        else:
            mask = boolean_mask(predicate, t)
        out.append(TouchedFile(add=add, table=t, mask=mask))
    return out


def dv_enabled(metadata) -> bool:
    from delta_tpu.utils.config import DeltaConfigs, conf

    if not bool(conf.get("delta.tpu.deletionVectors.enabled", True)):
        return False  # session kill switch (forces the rewrite path)
    return bool(DeltaConfigs.ENABLE_DELETION_VECTORS.from_metadata(metadata))


def dv_mark_from_mask(data_path: str, add: AddFile, table: pa.Table, mask):
    """DV-mark the rows of ``table`` (a :class:`TouchedFile` read with
    positions) selected by ``mask``; see :func:`dv_mark_deleted`."""
    import pyarrow.compute as pc

    positions = pc.filter(table.column(POSITION_COL), mask).to_numpy(
        zero_copy_only=False
    )
    return dv_mark_deleted(data_path, add, positions)


def dv_mark_deleted(data_path: str, add: AddFile, matched_positions):
    """Mark physical row positions deleted via a deletion vector.

    Returns ``(remove, new_add)``: a tombstone for the old file entry and a
    re-add of the same path carrying the union of the old DV and
    ``matched_positions``. ``new_add`` is None when every live row is gone —
    the file is then simply removed. Replay handles the re-add by path
    last-wins (`actions/InMemoryLogReplay.scala:43-65` semantics unchanged).
    """
    import numpy as np
    from dataclasses import replace as _replace

    from delta_tpu.protocol import deletion_vectors as dv_mod

    matched_positions = np.asarray(matched_positions, dtype=np.uint32)
    old_rows = None
    if add.deletion_vector is not None:
        old_rows = dv_mod.read_deletion_vector(
            dv_mod.DeletionVectorDescriptor.from_dict(add.deletion_vector),
            data_path,
        )
        all_rows = np.union1d(old_rows, matched_positions)
    else:
        all_rows = np.unique(matched_positions)
    live = add.num_logical_records
    if live is not None and len(all_rows) >= live:
        return add.remove(), None
    desc = dv_mod.write_deletion_vector(all_rows, data_path)
    return add.remove(), _replace(add, deletion_vector=desc.to_dict(), data_change=True)
