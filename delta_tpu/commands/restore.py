"""RESTORE TABLE — roll the table state back to an earlier version.

A beyond-reference command (the 0.9 reference has no RESTORE; modern Delta
ships ``RESTORE TABLE t TO VERSION AS OF v``). The restore is itself a new
commit — history is preserved and the restore can be time-traveled past or
restored again:

* files live at the target version but not now  → re-``AddFile``
* files live now but not at the target version → ``RemoveFile``
* metadata (schema/partitioning/properties) of the target version is
  re-committed when it differs.

Restoring past VACUUM is detected up front: every file to re-add must still
exist on disk, else the restore fails (like modern Delta's missing-file
check) rather than committing a corrupt state.
"""
from __future__ import annotations

import os
import urllib.parse
from dataclasses import replace
from typing import Dict, Optional, Union

from delta_tpu.commands import operations as ops
from delta_tpu.commands.dml_common import Timer
from delta_tpu.protocol.actions import Action, Metadata
from delta_tpu.utils import errors

__all__ = ["RestoreCommand"]


def Restore(version: Optional[int], timestamp: Optional[str]) -> ops.Operation:
    params = {}
    if version is not None:
        params["version"] = version
    if timestamp is not None:
        params["timestamp"] = timestamp
    return ops.Operation(
        "RESTORE", params,
        ["numRestoredFiles", "numRemovedFiles", "restoredFilesSize"],
    )


class RestoreCommand:
    def __init__(self, delta_log, version: Optional[int] = None,
                 timestamp: Optional[Union[str, int]] = None):
        if (version is None) == (timestamp is None):
            raise errors.DeltaAnalysisError(
                "RESTORE requires exactly one of version or timestamp"
            )
        self.delta_log = delta_log
        self.version = version
        self.timestamp = timestamp
        self.metrics: Dict[str, int] = {}

    def _target_version(self) -> int:
        if self.version is not None:
            self.delta_log.history.check_version_exists(int(self.version))
            return int(self.version)
        from delta_tpu.utils.timeparse import timestamp_option_to_ms

        return self.delta_log.history.get_active_commit_at_time(
            timestamp_option_to_ms(self.timestamp), can_return_last_commit=True
        ).version

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.utility.restore",
                              path=self.delta_log.data_path):
            return self._run_impl()

    def _run_impl(self) -> int:
        target_version = self._target_version()
        target = self.delta_log.get_snapshot_at(target_version)

        def body(txn) -> int:
            timer = Timer()
            current = txn.snapshot
            txn.read_whole_table()
            cur_files = {f.path: f for f in current.all_files}
            tgt_files = {f.path: f for f in target.all_files}

            actions: list[Action] = []
            restored = removed = restored_size = 0
            for path, f in tgt_files.items():
                cur = cur_files.get(path)
                # identical entry (same path AND same deletion vector) is
                # already in place; anything else is re-added as of target
                if cur is not None and cur.deletion_vector == f.deletion_vector:
                    continue
                abs_path = os.path.join(
                    self.delta_log.data_path,
                    urllib.parse.unquote(path).replace("/", os.sep),
                )
                if not os.path.exists(abs_path):
                    raise errors.DeltaIllegalStateError(
                        f"Cannot restore to version {target_version}: data "
                        f"file {path} no longer exists (removed by VACUUM?)"
                    )
                # a sidecar deletion vector ('u' storage) is as load-bearing
                # as the data file: scans of the restored state read it
                from delta_tpu.protocol.deletion_vectors import dv_sidecar_path

                dv_abs = dv_sidecar_path(
                    f.deletion_vector or {}, self.delta_log.data_path
                )
                if dv_abs is not None and not os.path.exists(dv_abs):
                    raise errors.DeltaIllegalStateError(
                        f"Cannot restore to version {target_version}: "
                        f"deletion-vector file "
                        f"{(f.deletion_vector or {}).get('pathOrInlineDv')} "
                        f"for data file {path} no longer exists "
                        f"(removed by VACUUM?)"
                    )
                actions.append(replace(f, data_change=True))
                restored += 1
                restored_size += f.size or 0
            for path, f in cur_files.items():
                if path not in tgt_files:
                    actions.append(f.remove())
                    removed += 1

            tgt_meta: Metadata = target.metadata
            if tgt_meta.to_dict() != current.metadata.to_dict():
                txn.update_metadata(tgt_meta)

            self.metrics.update(
                numRestoredFiles=restored,
                numRemovedFiles=removed,
                restoredFilesSize=restored_size,
                executionTimeMs=timer.lap_ms(),
            )
            txn.report_metrics(**self.metrics)
            version = txn.commit(actions, Restore(self.version, (
                str(self.timestamp) if self.timestamp is not None else None
            )))
            if actions:
                # file-set rewind (re-adds may shrink deletion vectors):
                # bump the resident key-cache and scan column-cache epochs
                from delta_tpu.ops.column_cache import ColumnCache
                from delta_tpu.ops.key_cache import KeyCache

                KeyCache.instance().bump_epoch(self.delta_log.log_path)
                ColumnCache.instance().bump_epoch(self.delta_log.log_path)
            return version

        return self.delta_log.with_new_transaction(body)
