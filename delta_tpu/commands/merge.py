"""MERGE INTO — columnar three-phase upsert.

The reference (`commands/MergeIntoCommand.scala:201-771`) runs MERGE as:
(1) findTouchedFiles — inner join source×target to locate files with matches
    plus multi-match detection (`:310-389`);
(2) writeAllChanges — re-read only touched files, outer join, then a
    row-at-a-time clause interpreter (`JoinedRowProcessor :681-753`);
(3) commit removes ++ adds.

This engine keeps the phase structure but replaces the row interpreter with
columnar blocks: matched pairs / unmatched target rows / unmatched source
rows are materialized separately, and every clause becomes a vectorized mask
+ projection over its block. The join itself has two executors:

- **device** — 1-2 integer equi-keys, no residual conjuncts (the TPC-DS
  upsert shape), three variants by residency (PR 6 fused pipeline):
  *resident* (the table's key lane is HBM-resident in `ops/key_cache` —
  ships only source keys), *device-cold* (per-file key decode streams onto
  a pre-sized slab while the remaining files decode, then registers the
  slab so the next merge cache-hits), and *device-upload* (multichip mesh:
  target sharded, source all-gathered, per-shard sort-merge —
  `ops/join_kernel.py`). The probe kernel computes match masks AND the
  matched pairing on device; the host maps O(matched) pairs onto the
  decode. Toggle: ``delta.tpu.merge.devicePath.enabled``; routing is
  link-priced per residency case (`parallel/link.py`), and every decision
  emits a ``delta.merge.router`` event + ``merge.device.*`` counters.
- **host fallback** (Arrow hash join — the C++ kernel) for string /
  multi-key / non-equi conditions.

Multi-clause ordering, clause conditions, multi-match errors, the insert-only
fast path (`:397-450`) and `MergeStats` (`:79-174`) follow the reference.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.commands import operations as ops
from delta_tpu.commands import dml_common as dv_common
from delta_tpu.commands.dml_common import POSITION_COL, Timer, candidate_files
from delta_tpu.exec import cdf as cdf_exec
from delta_tpu.exec import write as write_exec
from delta_tpu.exec.scan import read_files_as_table
from delta_tpu.expr import ir
from delta_tpu.expr.parser import parse_expression, parse_predicate
from delta_tpu.expr.vectorized import boolean_mask, evaluate
from delta_tpu.protocol.actions import Action, AddFile
from delta_tpu.utils.config import conf
from delta_tpu.utils.errors import DeltaAnalysisError, DeltaUnsupportedOperationError
from delta_tpu.utils import errors as errors_mod

__all__ = ["MergeIntoCommand", "MergeClause"]

def _coerce_join_keys(t_vals, s_vals):
    """Lossless join-key coercion: never run a narrowing or precision-losing
    cast (wrapped/rounded keys fabricate matches).

    int vs int → wider int; float vs float → float64; int vs float → keep
    int64 and map the float side through an integrality check (non-integral
    or out-of-range floats become NULL, and NULL keys never join)."""
    a, b = t_vals.type, s_vals.type
    if a == b:
        return t_vals, s_vals
    if pa.types.is_integer(a) and pa.types.is_integer(b):
        common = a if a.bit_width >= b.bit_width else b
        return pc.cast(t_vals, common), pc.cast(s_vals, common)
    if pa.types.is_floating(a) and pa.types.is_floating(b):
        return pc.cast(t_vals, pa.float64()), pc.cast(s_vals, pa.float64())

    def float_to_int64(vals):
        f = pc.cast(vals, pa.float64())
        # any integral float64 in [-2^63, 2^63) casts to int64 exactly (it
        # IS a representable integer); non-integral / out-of-range can't
        # equal any int64 key, so they become NULL (null keys never join)
        integral = pc.and_(
            pc.equal(pc.floor(f), f),
            pc.and_(pc.greater_equal(f, pa.scalar(-(2.0**63))),
                    pc.less(f, pa.scalar(2.0**63))),
        )
        return pc.cast(
            pc.if_else(pc.fill_null(integral, False), f, pa.scalar(None, pa.float64())),
            pa.int64(),
        )

    if pa.types.is_integer(a) and pa.types.is_floating(b):
        return pc.cast(t_vals, pa.int64()), float_to_int64(s_vals)
    if pa.types.is_floating(a) and pa.types.is_integer(b):
        return float_to_int64(t_vals), pc.cast(s_vals, pa.int64())
    if pa.types.is_string(a) or pa.types.is_string(b):
        return pc.cast(t_vals, pa.string()), pc.cast(s_vals, pa.string())
    return t_vals, s_vals


_SRC = "__s__"  # prefix for source columns in the combined pair table
_TID = "__t_row__"
_SID = "__s_row__"
_FID = "__t_file__"


def _rows_from_stats(candidates) -> Optional[int]:
    """Total numRecords over the candidate files, None when any file lacks
    stats (routing then falls back to the post-decode estimate)."""
    total = 0
    for add in candidates:
        n = add.num_logical_records
        if n is None:
            return None
        total += int(n)
    return total


@dataclass
class MergeClause:
    """One WHEN clause (`catalyst/plans/logical/deltaMerge.scala:161-221`)."""

    kind: str  # "update" | "delete" | "insert"
    condition: Optional[ir.Expression] = None
    # None = updateAll/insertAll (star); else target column -> expression
    assignments: Optional[Dict[str, ir.Expression]] = None

    @property
    def is_star(self) -> bool:
        return self.assignments is None and self.kind in ("update", "insert")


def _parse_opt(e: Optional[Union[str, ir.Expression]], pred=True):
    if e is None or isinstance(e, ir.Expression):
        return e
    return parse_predicate(e) if pred else parse_expression(e)


class MergeIntoCommand:
    def __init__(
        self,
        delta_log,
        source: Any,
        condition: Union[str, ir.Expression],
        matched_clauses: Sequence[MergeClause] = (),
        not_matched_clauses: Sequence[MergeClause] = (),
        source_alias: Optional[str] = None,
        target_alias: Optional[str] = None,
    ):
        from delta_tpu.commands.write import coerce_to_table

        self.delta_log = delta_log
        self.source = coerce_to_table(source)
        self.condition = _parse_opt(condition)

        def _norm(c: MergeClause) -> MergeClause:
            return MergeClause(
                kind=c.kind,
                condition=_parse_opt(c.condition),
                assignments=None if c.assignments is None else {
                    col: (parse_expression(e) if isinstance(e, str) else e)
                    for col, e in c.assignments.items()
                },
            )

        self.matched_clauses = [_norm(c) for c in matched_clauses]
        self.not_matched_clauses = [_norm(c) for c in not_matched_clauses]
        self.source_alias = source_alias
        self.target_alias = target_alias
        self.metrics: Dict[str, int] = {}
        # wall-clock per phase (decode/key/join/apply/write ms) — the bench
        # breakdown the optimization loop steers by
        self.phase_ms: Dict[str, float] = {}
        # set by _join when the device kernel ran: JoinResult with exact
        # per-target match counts and per-source matched flags
        self._device_join = None
        self._validate_clauses()

    def _validate_clauses(self) -> None:
        for c in self.matched_clauses:
            if c.kind not in ("update", "delete"):
                raise errors_mod.invalid_merge_clause(c.kind, matched=True)
        for c in self.not_matched_clauses:
            if c.kind != "insert":
                raise errors_mod.invalid_merge_clause(c.kind, matched=False)
        for c in self.matched_clauses:
            if c.kind == "delete" and c.assignments:
                raise DeltaAnalysisError(
                    "DELETE clauses cannot carry SET assignments"
                )
        # only the last clause of each group may lack a condition
        for group in (self.matched_clauses, self.not_matched_clauses):
            for c in group[:-1]:
                if c.condition is None:
                    raise DeltaAnalysisError(
                        "When there are more than one MATCHED/NOT MATCHED clauses, "
                        "only the last can omit its condition"
                    )
        # duplicate assignment targets within one clause (case-insensitive)
        for group in (self.matched_clauses, self.not_matched_clauses):
            for c in group:
                if not c.assignments:
                    continue
                seen = set()
                for col in c.assignments:
                    low = col.split(".")[-1].lower()
                    if low in seen:
                        raise errors_mod.merge_conflicting_set_columns(col)
                    seen.add(low)

    def _analyze_clauses(self, target_cols, source_cols) -> None:
        """Post-schema-resolution clause validation: every clause condition
        and assignment must resolve, insert conditions see only the source,
        and assignment targets must be real target columns."""
        t_low = {c.lower() for c in target_cols}
        for clause in self.matched_clauses:
            if clause.condition is not None:
                self._resolve(clause.condition, target_cols, source_cols)
            if clause.assignments:
                for col, e in clause.assignments.items():
                    name = col.split(".")[-1]
                    if name.lower() not in t_low:
                        raise errors_mod.merge_unresolvable_column(
                            col, target_cols, [])
                    self._resolve(e, target_cols, source_cols)
        for clause in self.not_matched_clauses:
            if clause.condition is not None:
                # NOT MATCHED: there is no target row to reference
                self._resolve(clause.condition, [], source_cols)
            if clause.assignments:
                for col, e in clause.assignments.items():
                    name = col.split(".")[-1]
                    if name.lower() not in t_low:
                        raise errors_mod.merge_unresolvable_column(
                            col, target_cols, [])
                    self._resolve(e, [], source_cols)

    def _migrate_schema(self, txn):
        """MERGE schema evolution (`deltaMerge.scala:224-424`,
        `PreprocessTableMerge.scala:65-71`): when
        ``delta.tpu.schema.autoMerge.enabled`` is on and the merge has a
        star clause (updateAll/insertAll), the target schema widens to
        ``mergeSchemas(target, source)`` — new source columns append, and
        existing columns keep the target's name case/position with types
        implicitly widened. Returns the (possibly evolved) txn metadata."""
        from dataclasses import replace

        from delta_tpu.schema import schema_utils
        from delta_tpu.schema.arrow_interop import schema_from_arrow

        metadata = txn.metadata
        auto = bool(conf.get("delta.tpu.schema.autoMerge.enabled", False))
        has_star = any(
            c.is_star for c in list(self.matched_clauses) + list(self.not_matched_clauses)
        )
        if not (auto and has_star):
            return metadata
        from delta_tpu.schema import generated as generated_mod

        src_schema = schema_from_arrow(self.source.schema)
        merged = schema_utils.merge_schemas(
            metadata.schema, src_schema, allow_implicit_conversions=True,
            fixed_type_columns=generated_mod.fixed_type_columns(metadata.schema),
        )
        if merged.to_json() != metadata.schema.to_json():
            txn.update_metadata(replace(metadata, schema_string=merged.to_json()))
            metadata = txn.metadata
        return metadata

    # -- name resolution --------------------------------------------------

    def _resolve(self, e: ir.Expression, target_cols: Sequence[str],
                 source_cols: Sequence[str]) -> ir.Expression:
        """Rewrite alias-qualified/unqualified refs onto the combined pair
        table: target columns keep their names, source columns get _SRC."""
        t_low = {c.lower(): c for c in target_cols}
        s_low = {c.lower(): c for c in source_cols}
        t_alias = (self.target_alias or "").lower()
        s_alias = (self.source_alias or "").lower()

        def rewrite(node: ir.Expression) -> Optional[ir.Expression]:
            if not isinstance(node, ir.Column):
                return None
            name = node.name
            low = name.lower()
            if "." in low and low not in t_low and low not in s_low:
                qual, _, col = low.partition(".")
                if qual == s_alias and col in s_low:
                    return ir.Column(_SRC + s_low[col])
                if qual == t_alias and col in t_low:
                    return ir.Column(t_low[col])
                # an unknown qualifier must NOT fall back to bare resolution:
                # 't.id = s.id' without aliases would resolve both sides to
                # the target and turn the condition into a tautology
                raise errors_mod.merge_unresolvable_qualifier(
                    name, qual, self.target_alias, self.source_alias)
            if low in t_low:
                return ir.Column(t_low[low])
            if low in s_low:
                return ir.Column(_SRC + s_low[low])
            raise errors_mod.merge_unresolvable_column(name, target_cols, source_cols)

        return e.transform(rewrite)

    def _split_equi_keys(
        self, cond: ir.Expression
    ) -> Tuple[List[Tuple[ir.Expression, ir.Expression]], List[ir.Expression]]:
        """Split the (resolved) join condition into target=source equi pairs
        + residual conjuncts."""
        pairs: List[Tuple[ir.Expression, ir.Expression]] = []
        residual: List[ir.Expression] = []
        for c in ir.split_conjuncts(cond):
            if isinstance(c, ir.Eq):
                sides = [c.left, c.right]
                refs = [set(ir.references(s)) for s in sides]
                t_side = s_side = None
                for side, r in zip(sides, refs):
                    if r and all(x.startswith(_SRC) for x in r):
                        s_side = side
                    elif r and not any(x.startswith(_SRC) for x in r):
                        t_side = side
                if t_side is not None and s_side is not None:
                    pairs.append((t_side, s_side))
                    continue
            residual.append(c)
        return pairs, residual

    # -- main -------------------------------------------------------------

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.dml.merge", path=self.delta_log.data_path):
            return self.delta_log.with_new_transaction(self._body)

    def _body(self, txn) -> int:
        # self-calibrating cost model: install any persisted constant
        # overrides BEFORE routing, so a fresh process routes with what the
        # last one learned (no-op unless router.calibration.enabled)
        from delta_tpu.obs import calibration

        calibration.apply_state(self.delta_log.log_path)
        # reset per-execution state: a re-run that takes the host or empty
        # path must not consume a previous run's device-join flags
        self._device_join = None
        self._resident_candidate = None
        # (target rows, source rows) the join actually saw — the router
        # audit's workload sizes (obs/router_audit); slab rows when a
        # device probe ran (the probe's real n is the slab, not the
        # possibly-pruned decode)
        self._audit_units = None
        self._audit_eligible = False
        self._audit_slab_rows = None
        # 'resident' (HBM cache hit) | 'device-cold' (fused slab build) |
        # 'device-upload' (mesh all-gather kernel) | 'host'
        self._join_path = "host"
        self._router: Dict[str, Any] = {}
        self._cdf_blocks = []
        self._use_cdf = cdf_exec.cdf_enabled(txn.metadata)
        self.phase_ms.clear()
        timer = Timer()
        metadata = self._migrate_schema(txn)
        target_cols = [f.name for f in metadata.schema.fields]
        source_cols = list(self.source.column_names)
        # static star-coverage analysis (the reference resolves stars at
        # analysis time, `deltaMerge.scala:322-328` — the error must not
        # depend on whether any row fires the clause)
        for clause in self.matched_clauses:
            if clause.is_star:
                self._check_star_coverage(target_cols, source_cols, "UPDATE", metadata)
                break
        for clause in self.not_matched_clauses:
            if clause.is_star:
                self._check_star_coverage(target_cols, source_cols, "INSERT", metadata)
                break
        # read-side char padding on the merge condition and clause
        # conditions (literals vs char(n) target columns). Only refs that
        # resolve to the TARGET pad: a source column sharing a name with a
        # target char column (s.status = 'x') must keep its literal as-is.
        from delta_tpu.schema.char_varchar import pad_char_literals

        tq = frozenset({self.target_alias.lower()} if self.target_alias
                       else ())
        self.condition = pad_char_literals(self.condition, metadata, tq)
        self.matched_clauses = [
            MergeClause(c.kind, pad_char_literals(c.condition, metadata, tq)
                        if c.condition is not None else None, c.assignments)
            for c in self.matched_clauses
        ]
        # static clause analysis (the reference rejects these shapes at
        # analysis time regardless of which rows fire,
        # `deltaMerge.scala:161-221` resolution errors)
        self._analyze_clauses(target_cols, source_cols)
        cond = self._resolve(self.condition, target_cols, source_cols)
        equi, residual = self._split_equi_keys(cond)

        # source with prefixed names + row ids
        src = self.source.rename_columns([_SRC + c for c in source_cols])
        src = src.append_column(_SID, pa.array(range(src.num_rows), pa.int64()))

        # phase 1: candidates by target-only conjuncts, then the join
        target_only = [
            c for c in ir.split_conjuncts(cond)
            if not any(r.startswith(_SRC) for r in ir.references(c))
        ]
        candidates = candidate_files(txn, ir.and_all(target_only) if target_only else None)
        # distributed findTouchedFiles probe: restrict the candidates to
        # files whose equi keys intersect the source BEFORE the join
        # decodes full rows (conf-gated; result-identical — see the method)
        if equi:
            candidates = self._probe_touched_files(candidates, src, equi, metadata)
        insert_only = not self.matched_clauses
        matched_pairs, tgt_tables = self._join(
            txn, candidates, src, equi, residual, metadata,
            prune_pred=ir.and_all(target_only) if target_only else None,
        )
        self._emit_router()
        scan_ms = timer.lap_ms()

        if not insert_only:
            # insert-only merges can't modify target rows, so duplicate
            # matches are harmless (reference fast path, `:397-450`)
            self._check_multi_match(matched_pairs)

        removes: List[Action] = []
        dv_adds: List[Action] = []
        out_blocks: List[pa.Table] = []
        n_copied = n_updated = n_deleted = 0
        use_dv = not insert_only and dv_common.dv_enabled(metadata)

        if not insert_only:
            # matched block → per-clause masks
            upd, n_updated, n_deleted, n_pair_copied, claimed_tbl, fired_fids = (
                self._apply_matched(
                    matched_pairs, target_cols, metadata, dv_mode=use_dv
                )
            )
            n_copied += n_pair_copied
            if upd is not None:
                out_blocks.append(upd)
            import numpy as np

            if use_dv:
                # claimed rows are marked deleted via per-file deletion
                # vectors; everything else stays live in place — the file
                # rewrite (and its copy block below) disappears entirely
                if claimed_tbl is not None and claimed_tbl.num_rows:
                    fids = claimed_tbl.column(_FID).to_numpy(zero_copy_only=False)
                    poss = claimed_tbl.column(POSITION_COL).to_numpy(zero_copy_only=False)
                    for fid in np.unique(fids):
                        rm, re_add = dv_common.dv_mark_deleted(
                            self.delta_log.data_path,
                            candidates[int(fid)],
                            poss[fids == fid],
                        )
                        removes.append(rm)
                        if re_add is not None:
                            dv_adds.append(re_add)
            else:
                for fid in sorted(fired_fids):
                    removes.append(candidates[fid].remove())
                # unmatched target rows inside touched files → copy. _TID is
                # the global row index over the candidate concat, so one
                # boolean scatter replaces a per-file hash-set probe
                total_rows = sum(t.num_rows for t in tgt_tables.values())
                claimed = np.zeros(total_rows, bool)
                claimed[matched_pairs.column(_TID).to_numpy(zero_copy_only=False)] = True
                row_start = 0
                starts = {}
                for fid in sorted(tgt_tables):
                    starts[fid] = row_start
                    row_start += tgt_tables[fid].num_rows
                for fid in sorted(fired_fids):
                    t = tgt_tables[fid]
                    keep = ~claimed[starts[fid]: starts[fid] + t.num_rows]
                    if not keep.all():
                        copied = t.filter(pa.array(keep)).select(target_cols)
                    else:
                        copied = t.select(target_cols)
                    n_copied += copied.num_rows
                    if copied.num_rows:
                        out_blocks.append(copied)

        # not-matched source rows → insert clauses
        inserts, n_inserted = self._apply_not_matched(
            matched_pairs, src, target_cols, source_cols, metadata
        )
        if inserts is not None and inserts.num_rows:
            out_blocks.append(inserts)
            if self._use_cdf:
                self._cdf_blocks.append(("insert", inserts))

        self.phase_ms["apply_ms"] = timer.peek_ms()
        adds: List[Action] = list(dv_adds)
        cdc_actions: List[Action] = []
        if self._cdf_blocks:
            cdc_actions = list(cdf_exec.write_change_data(
                self.delta_log.data_path, self._cdf_blocks, metadata
            ))
        if out_blocks:
            out = pa.concat_tables(out_blocks, promote_options="permissive")
            if out.column_names != target_cols:
                out = out.select(target_cols)
            if out.num_rows:
                adds += list(
                    write_exec.write_files(
                        self.delta_log.data_path, out, metadata, data_change=True
                    )
                )
        rewrite_ms = timer.lap_ms()
        self.phase_ms["write_ms"] = rewrite_ms - self.phase_ms["apply_ms"]

        self.metrics.update(
            numSourceRows=self.source.num_rows,
            numTargetRowsCopied=n_copied,
            numTargetRowsUpdated=n_updated,
            numTargetRowsDeleted=n_deleted,
            numTargetRowsInserted=n_inserted,
            numTargetFilesRemoved=len(removes),
            numTargetFilesAdded=len(adds),
            scanTimeMs=scan_ms,
            rewriteTimeMs=rewrite_ms,
        )
        txn.report_metrics(**self.metrics)
        def _clause_info(c: MergeClause) -> Dict[str, Any]:
            info: Dict[str, Any] = {"actionType": c.kind}
            if c.condition is not None:
                info["predicate"] = c.condition.sql()
            return info

        op = ops.Merge(
            predicate=self.condition.sql(),
            updates=[_clause_info(c) for c in self.matched_clauses if c.kind == "update"],
            deletes=[_clause_info(c) for c in self.matched_clauses if c.kind == "delete"],
            inserts=[_clause_info(c) for c in self.not_matched_clauses],
        )
        version = txn.commit(removes + adds + cdc_actions, op)
        self._maybe_build_resident_keys()
        return version

    # -- distributed touched-files probe ----------------------------------

    def _probe_touched_files(self, candidates, src, equi, metadata):
        """findTouchedFiles-style pre-probe on the sharded executor
        (reference `MergeIntoCommand.scala` findTouchedFiles — phase 1 of
        the two-phase merge): read ONLY the equi-key columns of each
        candidate file as byte-weighted work items and keep the files whose
        keys intersect the source keys.

        Soundness: per-key-column ``is_in`` is a conservative superset of
        exact tuple membership, so a touched file is never dropped;
        untouched files contribute no matched pairs and are never
        rewritten, and this MERGE has no NOT-MATCHED-BY-SOURCE clauses, so
        restricting the candidate set is result-identical by construction.
        Null target keys never equal a source key, so dropping all-miss
        files stays exact under SQL join semantics.
        """
        from delta_tpu.utils.config import conf

        if not conf.get_bool("delta.tpu.distributed.merge.probe.enabled", True):
            return candidates
        min_files = conf.get_int("delta.tpu.distributed.merge.probe.minFiles", 8)
        if len(candidates) < max(min_files, 2):
            return candidates
        import pyarrow.compute as pc

        cols = sorted({r.lower() for t_e, _ in equi for r in ir.references(t_e)})
        svals = [(t_e, evaluate(s_e, src)) for t_e, s_e in equi]

        def _touched(f) -> bool:
            tbl = read_files_as_table(
                self.delta_log.data_path, [f], metadata, columns=cols)
            if tbl.num_rows == 0:
                return False
            for t_e, sv in svals:
                tv, sv2 = _coerce_join_keys(evaluate(t_e, tbl), sv)
                if isinstance(tv, pa.ChunkedArray):
                    tv = tv.combine_chunks()
                if isinstance(sv2, pa.ChunkedArray):
                    sv2 = sv2.combine_chunks()
                if not pc.any(pc.is_in(tv, value_set=sv2)).as_py():
                    return False
            return True

        from delta_tpu.parallel.executor import run_sharded
        from delta_tpu.utils import telemetry

        probe_t = Timer()
        telemetry.bump_counter("dist.merge.filesProbed", len(candidates))
        with telemetry.record_operation(
            "delta.dist.mergeProbe", {"candidates": len(candidates)}
        ) as probe_ev:
            try:
                report = run_sharded(
                    candidates, _touched,
                    sizes=[f.size or 0 for f in candidates],
                    label="merge-probe", on_failure="quarantine")
            except Exception:  # noqa: BLE001 — probe machinery failure:
                # the probe is an OPTIMIZATION — fall back to the full
                # conservative candidate set rather than failing the MERGE
                telemetry.bump_counter("dist.degraded.probe")
                probe_ev.data["degraded"] = True
                probe_ev.data["touched"] = len(candidates)
                return candidates
            # a quarantined probe item is a file whose keys we could not
            # read — soundness demands it stays IN the candidate set (the
            # probe may only drop files proven all-miss, hit is False)
            touched = [f for f, hit in zip(candidates, report.results)
                       if hit is not False]
            if report.quarantined:
                telemetry.bump_counter("dist.degraded.probe")
            probe_ev.data["touched"] = len(touched)
        self.phase_ms["probe_ms"] = probe_t.lap_ms_f()
        return touched

    # -- join -------------------------------------------------------------

    def _join(self, txn, candidates: List[AddFile], src: pa.Table, equi, residual,
              metadata, prune_pred: Optional[ir.Expression] = None,
              ) -> Tuple[pa.Table, Dict[int, pa.Table]]:
        """Inner-join source×candidate-target. Returns (pair table with
        target cols bare + source cols prefixed + ids, per-file target
        tables with row ids).

        Device path: the join-key columns decode first (a cheap projected
        Parquet read), the membership kernel launches asynchronously, and
        the full-column decode of the candidates runs on the host *while the
        device probes* — the kernel's wall-clock hides under the decode.

        ``prune_pred`` (the target-only conjuncts of the merge condition)
        enables row-group skipping inside candidate files: a pruned group
        can hold no join matches (the conjuncts are implied by the full
        condition). Applied only when unmatched target rows are never
        written back — DV mode (positions stay physical) or insert-only
        merges (target rows feed the join and nothing else)."""
        import numpy as np

        target_cols = [f.name for f in metadata.schema.fields]
        insert_only = not self.matched_clauses
        key_need = {r.lower() for t_e, _ in equi for r in ir.references(t_e)}
        # insert-only merges never rewrite target rows: read only the columns
        # the join condition touches (the reference's left-anti fast path
        # reads the full target; we push the projection into the Parquet scan)
        read_cols: Optional[List[str]] = None
        if insert_only:
            need = key_need | {
                r.lower()
                for c in residual
                for r in ir.references(c)
                if not r.startswith(_SRC)
            }
            cols = [c for c in target_cols if c.lower() in need]
            read_cols = cols or None
        else:
            read_cols = self._referenced_target_columns(
                metadata, target_cols, [c for c in src.column_names
                                        if c.startswith(_SRC)],
                key_need, residual,
            )

        mode = str(conf.get("delta.tpu.merge.devicePath.mode", "auto"))
        base_eligible = (
            bool(conf.get("delta.tpu.merge.devicePath.enabled", True))
            and mode != "off"
            and 1 <= len(equi) <= 2
            and not residual
            and candidates
            and src.num_rows > 0
        )
        device_eligible = base_eligible
        # audit: whether a device route even existed for this condition
        # shape — a structurally host-only merge is audited without a
        # device alternative (no hindsight miss against a route that
        # could not have run)
        self._audit_eligible = base_eligible
        if device_eligible and mode == "auto":
            # pre-decode routing check from AddFile stats row counts: on a
            # slow link even the *optimistic* plan (int32 keys) loses to the
            # host hash join — skip the early key decode entirely then.
            # This is the COLD price (slab upload + sort + probe); the
            # cache-hit case was already evaluated above with its own,
            # upload-free economics.
            n_est = _rows_from_stats(candidates)
            if n_est is not None:
                import jax

                from delta_tpu.parallel import link

                rows = n_est + src.num_rows
                if not (len(jax.devices()) > 1 and conf.get_bool(
                        "delta.tpu.merge.devicePath.preferMesh", False)):
                    device_s = link.cold_merge_device_s(
                        n_est, src.num_rows, link.profile())
                else:
                    device_s = link.estimate_device_s(
                        up_bytes=rows * 4,
                        down_bytes=rows // 8,
                        kernel_rows=rows,
                        shards=len(jax.devices()),
                    ).device_s
                host_est_s = rows * link.constant("HOST_JOIN_S_PER_ROW")
                self._router.setdefault("deviceEstS", round(device_s, 3))
                self._router.setdefault("hostEstS", round(host_est_s, 3))
                if device_s > host_est_s:
                    device_eligible = False
                    from delta_tpu.utils.telemetry import bump_counter

                    bump_counter("merge.device.declined")
                    self._router.update(reason="cold-estimate")

        # DV-mode matched clauses mark physical rows deleted — every scan
        # that can end up as the phase-2 tables must carry positions
        pos_col = (
            POSITION_COL
            if (not insert_only and dv_common.dv_enabled(metadata))
            else None
        )
        # row-group skipping is only safe when unmatched target rows never
        # need writing back: DV mode (matched rows mark by physical
        # position) or insert-only (target rows exist only to probe)
        if pos_col is None and not insert_only:
            prune_pred = None
        decode_t = Timer()
        pending = None
        resident = None
        via = None
        key_pieces: Optional[List[pa.Table]] = None
        key_pieces_have_pos = False
        if base_eligible:
            # resident-operand path first: the target key lane already lives
            # in HBM (ops/key_cache), so the probe ships only source keys —
            # different economics from the cold upload path, hence evaluated
            # before (and independent of) the upload-cost gate above
            resident = self._launch_resident_probe(
                txn, candidates, src, equi, target_cols, key_need,
                pos_col, insert_only,
            )
            if resident is not None:
                via = "resident"
        if resident is None and device_eligible:
            import jax

            prefer_mesh = (
                len(jax.devices()) > 1
                and conf.get_bool("delta.tpu.merge.devicePath.preferMesh",
                                  False)
            )
            if not prefer_mesh:
                # fused cold pipeline: per-file key decode streams into a
                # pre-sized HBM slab (upload overlaps decode), then the
                # block-bucketed probe joins + pairs on device — and the
                # slab registers in the KeyCache so the NEXT merge against
                # this table skips the upload entirely
                resident, key_pieces = self._launch_slab_pipeline(
                    txn, candidates, src, equi, target_cols, key_need,
                    pos_col, insert_only, metadata,
                )
                if resident is not None:
                    via = "device-cold"
                key_pieces_have_pos = key_pieces is not None
            if resident is None and key_pieces is None:
                # multichip mesh (all-gather sort-merge kernel, opt-in via
                # devicePath.preferMesh), or the slab pipeline bailed before
                # decoding: decode the key projection and launch the upload
                # join
                key_cols = [c for c in target_cols if c.lower() in key_need]
                key_pieces = read_files_as_table(
                    self.delta_log.data_path, candidates, metadata,
                    columns=key_cols or None, per_file=True,
                    position_column=pos_col, predicate=prune_pred,
                    # the key read and the full read below must stay
                    # row-aligned (the device probe's indices map onto the
                    # full decode) — stats-pruning is deterministic across
                    # both, but late materialization's verdict depends on
                    # the decoded columns
                    late_materialize=False,
                )
            if resident is None:
                key_tab = pa.concat_tables(key_pieces,
                                           promote_options="permissive")
                if key_tab.num_rows:
                    pending = self._launch_device_join(key_tab, src, equi)
                    if pending is not None:
                        via = "device-upload"
                    else:
                        self._router.setdefault("reason", "upload-declined")
        self.phase_ms["key_decode_ms"] = decode_t.lap_ms_f()

        # full-column decode (overlaps the in-flight device probe); when the
        # key projection already covers every needed column, reuse it (the
        # slab pipeline's pieces carry an extra position column — harmless,
        # every write-side consumer projects to target_cols)
        if key_pieces is not None and read_cols is not None and set(
            c.lower() for c in read_cols
        ) <= key_need and (not key_pieces_have_pos or pos_col is not None
                           or insert_only):
            raw_pieces = key_pieces
        else:
            raw_pieces = read_files_as_table(
                self.delta_log.data_path, candidates, metadata,
                columns=read_cols, per_file=True, position_column=pos_col,
                predicate=prune_pred, late_materialize=False,
            )
        tgt_tables: Dict[int, pa.Table] = {}
        pieces: List[pa.Table] = []
        row_base = 0
        for fid, t in enumerate(raw_pieces):
            t = t.append_column(
                _TID,
                pa.array(np.arange(row_base, row_base + t.num_rows, dtype=np.int64)),
            )
            t = t.append_column(
                _FID, pa.array(np.full(t.num_rows, fid, dtype=np.int64))
            )
            row_base += t.num_rows
            tgt_tables[fid] = t
            pieces.append(t)
        self.phase_ms["decode_ms"] = decode_t.lap_ms_f()
        if not pieces:
            empty = pa.schema(
                [pa.field(_TID, pa.int64()), pa.field(_FID, pa.int64())]
            ).empty_table()
            target = empty
        else:
            target = pa.concat_tables(pieces, promote_options="permissive")
        self._audit_units = (target.num_rows, src.num_rows)

        def empty_pairs() -> pa.Table:
            # empty pair table with the full combined (target + source) schema
            combined = target.slice(0, 0)
            for name in src.column_names:
                combined = combined.append_column(
                    name, pa.nulls(0, src.column(name).type)
                )
            return combined

        if target.num_rows == 0 or src.num_rows == 0:
            return empty_pairs(), tgt_tables

        join_t = Timer()
        if resident is not None and pending is None:
            pending = self._finalize_resident(
                resident, candidates, tgt_tables, target, src, equi,
                pos_col, insert_only,
            )
        if pending is not None:
            res = pending.result()
            if res is None:
                self._router.setdefault("reason", "device-finalize-fallback")
            else:
                self._device_join = res
                self._join_path = via
                # insert-only never consumes the pair rows (the not-matched
                # block comes from s_matched): skip materializing them
                if insert_only:
                    joined = empty_pairs()
                else:
                    matched = np.flatnonzero(res.t_matched)
                    joined = target.take(pa.array(matched, pa.int64()))
                    s_taken = src.take(
                        pa.array(res.t_first_s[matched], pa.int64())
                    )
                    for name in s_taken.column_names:
                        joined = joined.append_column(name, s_taken.column(name))
                self.phase_ms["join_ms"] = join_t.lap_ms_f()
                return joined, tgt_tables

        if equi:
            # Join INDEX tables (keys + row positions), then take the full
            # rows: Arrow's hash join refuses nested (struct/list/map)
            # non-key payload columns, and carrying 2 int columns through
            # the join beats carrying every column anyway.
            key_cols = []
            for t_e, s_e in equi:
                t_vals = evaluate(t_e, target)
                s_vals = evaluate(s_e, src)
                key_cols.append(_coerce_join_keys(t_vals, s_vals))
            t_idx_cols = {"__trow__": pa.array(np.arange(target.num_rows), pa.int64())}
            s_idx_cols = {"__srow__": pa.array(np.arange(src.num_rows), pa.int64())}
            tkeys, skeys = [], []
            for i, (t_vals, s_vals) in enumerate(key_cols):
                k = f"__k{i}__"
                t_idx_cols[k] = t_vals
                s_idx_cols[k] = s_vals
                tkeys.append(k)
                skeys.append(k)
            pairs_idx = pa.table(t_idx_cols).join(
                pa.table(s_idx_cols), keys=tkeys, right_keys=skeys,
                join_type="inner", use_threads=False,
            )
            t_take = pairs_idx.column("__trow__")
            s_take = pairs_idx.column("__srow__")
            joined = target.take(t_take)
            s_taken = src.take(s_take)
            for name in s_taken.column_names:
                joined = joined.append_column(name, s_taken.column(name))
            # take() emits one chunk per input chunk: defragment once here
            # or every downstream mask/projection/encode pays per-chunk costs
            joined = joined.combine_chunks()
        else:
            # general condition: BLOCKED cartesian pairing — tile the
            # target x source grid and stream each tile through the clause
            # condition immediately, so peak memory is one tile of pairs
            # (`delta.tpu.merge.nonEquiPairBudget`) regardless of input
            # sizes. The reference handles arbitrary conditions via a real
            # join (`MergeIntoCommand.scala:335-341`); this is the bounded
            # equivalent for a columnar engine without a theta-join kernel.
            budget = int(conf.get("delta.tpu.merge.nonEquiPairBudget",
                                  8_000_000))
            m = src.num_rows
            tile = max(budget // max(m, 1), 1)
            cond = ir.and_all(residual) if residual else None
            pieces = []
            s_base = np.tile(np.arange(m, dtype=np.int64), tile)
            for t0 in range(0, target.num_rows, tile):
                rows = min(tile, target.num_rows - t0)
                t_idx = np.repeat(np.arange(t0, t0 + rows, dtype=np.int64), m)
                piece = target.take(pa.array(t_idx, pa.int64()))
                s_taken = src.take(pa.array(s_base[: rows * m], pa.int64()))
                for name in s_taken.column_names:
                    piece = piece.append_column(name, s_taken.column(name))
                if cond is not None:
                    piece = piece.filter(boolean_mask(cond, piece))
                if piece.num_rows:
                    pieces.append(piece.combine_chunks())
            joined = (pa.concat_tables(pieces).combine_chunks()
                      if pieces else empty_pairs())
            self.phase_ms["join_ms"] = join_t.lap_ms_f()
            return joined, tgt_tables
        if residual:
            joined = joined.filter(boolean_mask(ir.and_all(residual), joined))
        self.phase_ms["join_ms"] = join_t.lap_ms_f()
        return joined, tgt_tables

    def _referenced_target_columns(
        self, metadata, target_cols, src_prefixed, key_need, residual,
    ) -> Optional[List[str]]:
        """Project the candidate scan to the target columns phase 2 can
        touch — or None when every column is needed.

        Valid only when nothing re-materializes whole target rows: deletion
        vectors on (no copy block — unclaimed/unmatched rows stay in their
        files), CDC off (no preimages), no generated columns (recompute
        reads arbitrary base columns), and every update clause a star
        (explicit assignments keep unassigned target columns, i.e. all of
        them). For a star upsert this collapses the scan to the join keys —
        the dominant cost of the DV merge path."""
        from delta_tpu.schema.generated import generated_column_names

        if not dv_common.dv_enabled(metadata) or self._use_cdf:
            return None
        if generated_column_names(metadata.schema):
            return None
        source_bare = [c[len(_SRC):] for c in src_prefixed]
        src_lower = {c.lower() for c in source_bare}
        need = set(key_need)
        for c in residual:
            need |= {r.lower() for r in ir.references(c)
                     if not r.startswith(_SRC)}
        try:
            for clause in self.matched_clauses + self.not_matched_clauses:
                if clause.condition is not None:
                    resolved = self._resolve(
                        clause.condition, target_cols, source_bare
                    )
                    need |= {r.lower() for r in ir.references(resolved)
                             if not r.startswith(_SRC)}
                if clause.kind == "update":
                    if not clause.is_star:
                        return None
                    # star update: target-only columns copy from the target
                    need |= {c.lower() for c in target_cols
                             if c.lower() not in src_lower}
        except DeltaAnalysisError:
            return None  # let the normal path raise the real resolution error
        cols = [c for c in target_cols if c.lower() in need]
        if len(cols) == len(target_cols):
            return None
        return cols or None

    # -- resident-key device path (ops/key_cache) -------------------------

    @staticmethod
    def _key_signature(t_exprs) -> str:
        return repr([repr(e) for e in t_exprs])

    def _launch_resident_probe(self, txn, candidates, src, equi, target_cols,
                               key_need, pos_col, insert_only):
        """Probe the HBM-resident target key lane (if one is current for this
        table + key signature): ships only the source keys. Returns
        (entry, PendingProbe, s_keys, s_ok) or None — and when the lane
        doesn't exist yet, records the signature so a background build can
        start after this merge commits (the CDC steady-state warmup)."""
        import numpy as np

        from delta_tpu.expr.vectorized import evaluate
        from delta_tpu.ops import key_cache as kc_mod
        from delta_tpu.parallel import link

        if not kc_mod.key_cache_enabled():
            return None
        # bit mapping back to the DV-filtered decode needs physical
        # positions; without them only DV-free candidates are alignable
        # (insert-only merges never consume per-target bits)
        if (pos_col is None and not insert_only
                and any(f.deletion_vector is not None for f in candidates)):
            return None
        t_exprs = [t for t, _ in equi]
        s_exprs = [s for _, s in equi]
        sig = self._key_signature(t_exprs)
        key_cols = [c for c in target_cols if c.lower() in key_need]
        entry = kc_mod.KeyCache.instance().get(
            txn.snapshot, sig, key_cols, t_exprs, build_if_missing=False
        )
        if entry is None:
            self._resident_candidate = (sig, key_cols, t_exprs)
            return None
        packed = kc_mod._pack_lanes(src, s_exprs, evaluate)
        if packed is None:
            return None
        s_keys, s_ok = packed
        self._router["cacheHit"] = True
        if str(conf.get("delta.tpu.merge.devicePath.mode", "auto")) == "auto":
            m = len(s_keys)
            n = entry.num_rows
            p = link.profile()
            # the fused-path probe model (shared with the bench's
            # auto_routes_device report: link.resident_probe_device_s)
            device_s = link.resident_probe_device_s(n, m, p)
            if not entry.is_resident:
                # the device copy was evicted / regrown: the probe would
                # synchronously re-ship the whole slab first — charge it
                device_s += p.upload_s(entry.capacity * 9)
            host_s = ((n + m) * link.constant("HOST_JOIN_S_PER_ROW")
                      + n * link.constant("HOST_KEY_DECODE_S_PER_ROW"))
            self._router["deviceEstS"] = round(device_s, 3)
            self._router["hostEstS"] = round(host_s, 3)
            if device_s > host_s:
                from delta_tpu.utils.telemetry import bump_counter

                bump_counter("merge.device.declined")
                self._router.update(reason="resident-estimate")
                return None
        probe = entry.probe_async(
            s_keys, s_ok, expected_version=txn.snapshot.version,
            insert_only=insert_only,
        )
        if probe is None:
            return None
        self._audit_slab_rows = entry.num_rows
        return entry, probe, s_keys, s_ok

    def _launch_slab_pipeline(self, txn, candidates, src, equi, target_cols,
                              key_need, pos_col, insert_only, metadata):
        """The cold fused device MERGE pipeline: decode the key projection
        per file, streaming each decoded file's packed lane onto a
        pre-sized HBM slab from an uploader thread (transfer overlaps the
        remaining Parquet decode), then launch the block-bucketed probe —
        and register the slab in the KeyCache so repeated MERGEs against a
        hot table skip the upload entirely.

        Returns ``(resident_tuple_or_None, key_pieces_or_None)`` —
        ``resident_tuple`` feeds `_finalize_resident`; ``key_pieces`` (the
        per-file decoded key tables, position column attached) is returned
        even on build failure so the caller can reuse the decode."""
        import queue as queue_mod
        import threading as threading_mod

        from delta_tpu.expr.vectorized import evaluate
        from delta_tpu.ops import key_cache as kc_mod

        # DV alignment guard (mirrors the resident-hit path)
        if (pos_col is None and not insert_only
                and any(f.deletion_vector is not None for f in candidates)):
            return None, None
        t_exprs = [t for t, _ in equi]
        s_exprs = [s for _, s in equi]
        packed = kc_mod._pack_lanes(src, s_exprs, evaluate)
        if packed is None:
            return None, None
        s_keys, s_ok = packed
        snapshot = txn.snapshot
        sig = self._key_signature(t_exprs)
        key_cols = [c for c in target_cols if c.lower() in key_need]
        cache = kc_mod.KeyCache.instance()
        try:
            builder = kc_mod.SlabBuilder(
                snapshot.delta_log.log_path, snapshot.metadata.id,
                snapshot.version, sig, key_cols, t_exprs,
                self.delta_log.data_path, candidates,
                epoch=cache.epoch(snapshot.delta_log.log_path),
            )
        except Exception:
            return None, None
        if builder.failed is not None:
            return None, None

        q: "queue_mod.Queue" = queue_mod.Queue()

        def on_ready(i, add, tab):
            q.put((add, tab))

        # carry the MERGE span chain into the uploader thread so each slab
        # upload shows as a `delta.merge.slabUpload` span on its own trace
        # lane under `delta.dml.merge` — the decode/upload overlap the
        # router assumes, finally visible in export_chrome_trace
        from delta_tpu.utils import telemetry

        upload_ctx = telemetry.span_context()

        def uploader():
            # device dispatches are async: this thread mostly queues
            # transfers, which the transfer engine overlaps with the
            # decode pool still running on the other files
            with telemetry.adopt_span_context(upload_ctx):
                while True:
                    item = q.get()
                    if item is None:
                        return
                    add, tab = item
                    try:
                        with telemetry.record_operation(
                                "delta.merge.slabUpload",
                                {"file": add.path, "rows": tab.num_rows}):
                            pos = tab.column(POSITION_COL).to_numpy(
                                zero_copy_only=False)
                            builder.add_file(add, tab, pos)
                    except Exception:
                        builder.failed = builder.failed or "slab append failed"

        th = threading_mod.Thread(target=uploader, daemon=True,
                                  name="delta-merge-slab-upload")
        th.start()
        try:
            # full physical rows per file: no row-group pruning, positions
            # attached so DV-filtered decodes scatter into slab layout
            key_pieces = read_files_as_table(
                self.delta_log.data_path, candidates, metadata,
                columns=key_cols or None, per_file=True,
                position_column=POSITION_COL, predicate=None,
                late_materialize=False, file_ready=on_ready,
            )
        finally:
            q.put(None)
            th.join()
        entry = builder.finish(len(candidates))
        if entry is None:
            self._router.setdefault("reason", "slab-build-failed")
            return None, key_pieces
        # under device eligibility the candidate set is the whole table (a
        # residual-free condition prunes nothing), so the slab is complete
        # and future merges can cache-hit it
        registered = cache.register(entry)
        if registered:
            self._resident_candidate = None  # no background build needed
        probe = entry.probe_async(
            s_keys, s_ok, expected_version=snapshot.version,
            insert_only=insert_only,
        )
        if probe is None:
            self._router.setdefault("reason", "no-sentinel-room")
            return None, key_pieces
        self._audit_slab_rows = entry.num_rows
        return (entry, probe, s_keys, s_ok), key_pieces

    def _finalize_resident(self, resident, candidates, tgt_tables, target,
                           src, equi, pos_col, insert_only):
        """Map the device-computed pairs (physical slab row → first-match
        source row) onto the DV-filtered decode: the host does only the
        O(matched) position mapping — no key re-derivation, no host-side
        pairing sort. Returns a PendingJoin whose result is a JoinResult
        (or None → the caller falls back to the host hash join)."""
        import numpy as np

        from delta_tpu.ops import join_kernel

        entry, probe, s_keys, s_ok = resident

        def finalize():
            # any failure in here — the probe itself, or the pair mapping
            # disagreeing with the slab — must surface as None (documented
            # host-join fallback), never an exception that crashes the MERGE
            try:
                res_p = probe.result()
                n_target = target.num_rows
                t_first_s = np.full(n_target, -1, np.int64)
                if insert_only:
                    # only s_matched / any_multi are consumed downstream
                    return join_kernel.JoinResult(
                        t_first_s, res_p.s_matched, res_p.any_multi
                    )
                row_base = 0
                for fid in sorted(tgt_tables):
                    t = tgt_tables[fid]
                    add = candidates[fid]
                    if pos_col is not None:
                        positions = t.column(pos_col).to_numpy(
                            zero_copy_only=False)
                    else:
                        positions = None
                    got = res_p.pairs_for_file(add.path, positions,
                                               t.num_rows)
                    if got is None:
                        return None  # slab/decode disagree: host fallback
                    local_idx, s_rows = got
                    t_first_s[row_base + local_idx] = s_rows
                    row_base += t.num_rows
                return join_kernel.JoinResult(t_first_s, res_p.s_matched,
                                              res_p.any_multi)
            except Exception:
                return None

        return join_kernel.PendingJoin(finalize)

    def _emit_router(self) -> None:
        """One `delta.merge.router` event per MERGE — the production-table
        observable behind the bench's `auto_used_device` field — plus the
        `merge.device.*` counters the /metrics endpoint and flight recorder
        surface, and the router AUDIT record pricing the decision against
        the measured phase durations (obs/router_audit)."""
        from delta_tpu.utils.telemetry import bump_counter, record_event

        decision = self._join_path
        if self._device_join is not None:
            bump_counter("merge.device.engaged")
            if decision == "resident":
                bump_counter("merge.device.cacheHit")
        data = dict(self._router, decision=decision)
        if "cacheHit" in data:
            # a cache lookup may have hit and then been abandoned (pricing
            # decline, no sentinel room): the emitted flag reports whether
            # the ENGAGED join actually used the cache
            data["cacheHit"] = decision == "resident"
        record_event(
            "delta.merge.router", data,
            path=self.delta_log.data_path,
        )
        audit = self._emit_audit(decision)
        # workload journal: the routed decision + audit verdict persist so
        # the advisor can trend the key-cache hit trajectory across
        # processes (buffered; inert under blackout / journal disabled)
        from delta_tpu.obs import journal as journal_mod

        journal_mod.record_dml(
            self.delta_log.log_path, "merge", decision=decision,
            router={k: v for k, v in data.items() if k != "decision"},
            audit=({"miss": audit.miss, "actualMs": round(audit.actual_ms, 3),
                    "predictedMs": dict(audit.predicted_ms)}
                   if audit is not None else None),
        )

    def _emit_audit(self, decision: str):
        """Record the routed join in the audit ledger: predicted phase
        costs (through ``link.constant``, so calibration feeds back into
        what is being judged) vs the measured ``key_decode + join`` wall
        time — plus the attributable throughput samples the EWMA calibrator
        refits from. Empty joins (no candidates / empty source) have no
        measured join phase and are not audited. Returns the recorded
        audit (or None) so the journal's dml entry can carry the verdict."""
        if "join_ms" not in self.phase_ms or self._audit_units is None:
            return None
        if not conf.get_bool("delta.tpu.telemetry.enabled", True):
            return None  # blackout: no audit, and no link probe to price one
        from delta_tpu.obs import router_audit
        from delta_tpu.parallel import link

        n, m = self._audit_units
        # the device probe's real workload is the SLAB, not the (possibly
        # row-group-pruned / DV-filtered) decode — audit and calibrate the
        # prediction the router actually made
        n_dev = (self._audit_slab_rows
                 if self._audit_slab_rows is not None else n)
        actual_s = (self.phase_ms.get("key_decode_ms", 0.0)
                    + self.phase_ms["join_ms"]) / 1000.0
        key_decode_s = self.phase_ms.get("key_decode_ms", 0.0) / 1000.0
        join_s = self.phase_ms["join_ms"] / 1000.0
        # host prediction needs only the throughput constants; the device
        # prediction (and its link.profile() probe) is computed ONLY when a
        # device route structurally existed — a devicePath-off deployment
        # never pays the probe just to price a route it cannot take
        predicted_map = {
            "host": ((n + m) * link.constant("HOST_JOIN_S_PER_ROW")
                     + n * link.constant("HOST_KEY_DECODE_S_PER_ROW")),
        }
        # key the device prediction under the route actually taken (or the
        # generic "device" when the host won), so a miss reads as "the
        # rejected ROUTE's prediction beat what ran"
        device_key = "device" if decision == "host" else decision
        if self._audit_eligible:
            # the router may have recorded the estimate it ACTUALLY compared
            # (resident-hit economics, cold price, or the mesh estimator) —
            # a hindsight miss must judge that prediction, not a recomputed
            # one from a different cost model (e.g. a warm-cache decline
            # re-priced as a cold slab build could never read as a miss)
            recorded = self._router.get("deviceEstS")
            if recorded is not None:
                predicted_map[device_key] = float(recorded)
            else:
                try:
                    p = link.profile()
                    predicted_map[device_key] = (
                        link.resident_probe_device_s(n_dev, m, p)
                        if decision == "resident"
                        else link.cold_merge_device_s(n_dev, m, p))
                except Exception:  # noqa: BLE001 — pricing must not fail DML
                    pass
        # throughput samples for the calibrator — only cleanly attributable
        # phases: the host join/decode rates, and the resident probe's
        # EFFECTIVE per-row rate (fixed dispatch floor subtracted; link
        # terms folded in, which self-corrects the same prediction above)
        samples = []
        if decision == "host":
            if join_s > 0 and (n + m) > 0:
                samples.append(("HOST_JOIN_S_PER_ROW", n + m, join_s))
            if key_decode_s > 0 and n > 0:
                samples.append(("HOST_KEY_DECODE_S_PER_ROW", n, key_decode_s))
        elif decision == "resident" and (n_dev + m) > 0:
            eff = join_s + key_decode_s - link.RESIDENT_PROBE_FIXED_S
            if eff > 0:
                samples.append(("RESIDENT_PROBE_S_PER_ROW", n_dev + m, eff))
        return router_audit.record_audit(
            "merge.join", self.delta_log.data_path, decision,
            predicted_map,
            actual_s,
            units={"targetRows": n, "sourceRows": m, "slabRows": n_dev},
            samples=samples, log_path=self.delta_log.log_path,
            phases={k: round(v, 1) for k, v in self.phase_ms.items()},
        )

    def _maybe_build_resident_keys(self) -> None:
        """Post-commit: start the background build of the resident key lane
        recorded by `_launch_resident_probe`, so the NEXT merge into this
        table probes from HBM. Never blocks the committing merge."""
        from delta_tpu.ops.key_cache import key_cache_enabled

        cand = getattr(self, "_resident_candidate", None)
        if cand is None:
            return
        self._resident_candidate = None
        if not key_cache_enabled():
            return
        if str(conf.get("delta.tpu.merge.devicePath.mode", "auto")) == "off":
            return
        sig, key_cols, t_exprs = cand
        log = self.delta_log

        def build():
            try:
                from delta_tpu.ops.key_cache import KeyCache

                snap = log.update()
                min_rows = int(conf.get(
                    "delta.tpu.merge.residentKeys.minRows", 1 << 20))
                est = sum(f.num_logical_records or 0 for f in snap.all_files)
                if est < min_rows:
                    return
                e = KeyCache.instance().get(
                    snap, sig, key_cols, t_exprs, build_if_missing=True)
                if e is not None:
                    e.ensure_resident()
            except Exception:
                pass  # best-effort warmup; the next merge just stays cold

        import threading

        threading.Thread(target=build, daemon=True,
                         name="delta-merge-keys-build").start()

    def _launch_device_join(self, key_tab: pa.Table, src: pa.Table, equi):
        """Evaluate + coerce the join keys and launch the device membership
        probe asynchronously (`ops/join_kernel.py`). Composite integer keys
        pack into one int64 lane (hi<<32 | lo) when both components fit in
        int32. Returns a PendingJoin, or None when the keys aren't device-
        representable (caller falls back to the host hash join) or — in
        ``devicePath.mode=auto`` — when the link cost model says shipping
        the keys costs more than the host hash join (`parallel/link.py`)."""
        import numpy as np

        import jax

        from delta_tpu.ops import join_kernel
        from delta_tpu.parallel.mesh import state_mesh

        def to_np(vals):
            arr = vals.combine_chunks() if isinstance(vals, pa.ChunkedArray) else vals
            valid = ~np.asarray(pc.is_null(arr))
            keys = np.asarray(arr.fill_null(0).cast(pa.int64()))
            return keys, valid

        lanes = []
        for t_e, s_e in equi:
            try:
                t_vals = evaluate(t_e, key_tab)
                s_vals = evaluate(s_e, src)
            except Exception:
                return None
            t_vals, s_vals = _coerce_join_keys(t_vals, s_vals)
            if not (
                pa.types.is_integer(t_vals.type) and pa.types.is_integer(s_vals.type)
            ):
                return None
            lanes.append((to_np(t_vals), to_np(s_vals)))

        if len(lanes) == 1:
            (t_keys, t_ok), (s_keys, s_ok) = lanes[0]
        else:
            i32 = np.iinfo(np.int32)
            for (tk, t_ok_i), (sk, s_ok_i) in lanes:
                if (
                    np.min(tk, where=t_ok_i, initial=0) < i32.min
                    or np.max(tk, where=t_ok_i, initial=0) > i32.max
                    or np.min(sk, where=s_ok_i, initial=0) < i32.min
                    or np.max(sk, where=s_ok_i, initial=0) > i32.max
                ):
                    return None  # component exceeds 32 bits: host join
            (t0, t_ok0), (s0, s_ok0) = lanes[0]
            (t1, t_ok1), (s1, s_ok1) = lanes[1]
            t_keys = (t0 << 32) | (t1 & 0xFFFFFFFF)
            s_keys = (s0 << 32) | (s1 & 0xFFFFFFFF)
            t_ok = t_ok0 & t_ok1
            s_ok = s_ok0 & s_ok1

        budget_s = None
        if str(conf.get("delta.tpu.merge.devicePath.mode", "auto")) == "auto":
            from delta_tpu.parallel import link

            budget_s = (len(t_keys) + len(s_keys)) \
                * link.constant("HOST_JOIN_S_PER_ROW")
        mesh = state_mesh() if len(jax.devices()) > 1 else None
        return join_kernel.inner_join_async(
            t_keys, t_ok, s_keys, s_ok, mesh=mesh, budget_s=budget_s
        )

    def _check_star_coverage(
        self, target_cols: Sequence[str], src_cols: Sequence[str], typ: str,
        metadata,
    ) -> None:
        """Star clauses resolve every target column against the source unless
        schema evolution is on (then the star expands over source columns)."""
        if bool(conf.get("delta.tpu.schema.autoMerge.enabled", False)):
            return
        src_low = {s.lower() for s in src_cols}
        # generated columns are computed, not resolved from the source
        from delta_tpu.schema import generated as generated_mod

        gen = generated_mod.generated_column_names(metadata.schema)
        missing = [
            c for c in target_cols
            if c.lower() not in src_low and c.lower() not in gen
        ]
        if missing:
            raise errors_mod.merge_clause_unresolvable(missing[0], typ, src_cols)

    def _check_multi_match(self, pairs: pa.Table) -> None:
        """Error when a target row matches multiple source rows, unless the
        merge is a single unconditional DELETE (`:351-365`)."""
        single_delete = (
            len(self.matched_clauses) == 1
            and self.matched_clauses[0].kind == "delete"
            and self.matched_clauses[0].condition is None
        )
        if self._device_join is not None:
            if not single_delete and self._device_join.any_multi:
                raise DeltaUnsupportedOperationError(
                    "Cannot perform Merge as multiple source rows matched and "
                    "attempted to modify the same target row in the Delta table "
                    "in possibly conflicting ways."
                )
            return
        if pairs.num_rows == 0:
            return
        if single_delete:
            return
        counts = pairs.group_by(_TID).aggregate([(_TID, "count")])
        if pc.max(counts.column(f"{_TID}_count")).as_py() > 1:
            raise DeltaUnsupportedOperationError(
                "Cannot perform Merge as multiple source rows matched and attempted "
                "to modify the same target row in the Delta table in possibly "
                "conflicting ways."
            )

    # -- clause application ------------------------------------------------

    def _apply_matched(self, pairs: pa.Table, target_cols: List[str], metadata,
                       dv_mode: bool = False):
        """Matched block: rows claimed by update clauses are projected, by
        delete clauses dropped, unclaimed pairs copy the target row.

        ``dv_mode``: unclaimed pairs stay in their files (no copy block);
        the 5th return value is a (file id, physical position) table of the
        claimed rows for deletion-vector marking."""
        if pairs.num_rows == 0 or not self.matched_clauses:
            return None, 0, 0, 0, None, set()
        n = pairs.num_rows
        unclaimed = pa.chunked_array([pa.array([True] * n)])
        out_parts: List[pa.Table] = []
        n_updated = n_deleted = 0
        for clause in self.matched_clauses:
            if clause.condition is None:
                fire = unclaimed
            else:
                cond = self._resolve_in_pairs(clause.condition, pairs)
                fire = pc.and_(unclaimed, boolean_mask(cond, pairs))
            count = pc.sum(fire).as_py() or 0
            if count:
                block = pairs.filter(fire)
                if clause.kind == "update":
                    projected = self._project_update(
                        block, clause, target_cols, metadata
                    )
                    out_parts.append(projected)
                    if self._use_cdf:
                        self._cdf_blocks.append(
                            ("update_preimage", block.select(target_cols))
                        )
                        self._cdf_blocks.append(("update_postimage", projected))
                    n_updated += count
                else:
                    if self._use_cdf:
                        # distinct target rows (a legal multi-match would
                        # otherwise emit duplicate delete rows in the feed)
                        import numpy as np

                        tids = block.column(_TID).to_numpy(zero_copy_only=False)
                        _, first = np.unique(tids, return_index=True)
                        self._cdf_blocks.append((
                            "delete",
                            block.take(pa.array(np.sort(first))).select(target_cols),
                        ))
                    # count distinct target ROWS, not pairs: a single
                    # unconditional DELETE may legally multi-match, and the
                    # reference's numTargetRowsDeleted is rows deleted
                    n_deleted += pc.count_distinct(block.column(_TID)).as_py()
            unclaimed = pc.and_(unclaimed, pc.invert(fire))
        claimed_pairs = pairs.filter(pc.invert(unclaimed))
        # files with at least one FIRED row: only these are rewritten. A file
        # whose matches all fall through every clause condition stays in place
        # untouched — rewriting it would commit a remove+add with
        # dataChange=true and make CDF reconstruct delete+insert change rows
        # for rows that never logically changed.
        fired_fids: set = (
            set(pc.unique(claimed_pairs.column(_FID)).to_pylist())
            if claimed_pairs.num_rows else set()
        )
        claimed_tbl = None
        if dv_mode:
            # claimed rows get marked deleted in-place; unclaimed matched
            # pairs stay live in their files — nothing is copied
            claimed_tbl = claimed_pairs.select([_FID, POSITION_COL])
            n_rest = 0
        else:
            # unclaimed matched pairs: copy target row unchanged — but only
            # out of files actually being rewritten (fired_fids)
            rest = pairs.filter(unclaimed)
            if rest.num_rows:
                if fired_fids:
                    keep = pc.is_in(
                        rest.column(_FID),
                        value_set=pa.array(sorted(fired_fids), pa.int64()),
                    )
                    rest = rest.filter(keep)
                else:
                    rest = rest.slice(0, 0)
            if rest.num_rows:
                out_parts.append(rest.select(target_cols))
            n_rest = rest.num_rows
        out = (
            pa.concat_tables(out_parts, promote_options="permissive")
            if out_parts
            else None
        )
        return out, n_updated, n_deleted, n_rest, claimed_tbl, fired_fids

    def _resolve_in_pairs(self, e: ir.Expression, pairs: pa.Table) -> ir.Expression:
        src_cols = [c[len(_SRC):] for c in pairs.column_names if c.startswith(_SRC)]
        tgt_cols = [
            c for c in pairs.column_names
            if not c.startswith("__") and not c.startswith(_SRC)
        ]
        return self._resolve(e, tgt_cols, src_cols)

    def _project_update(self, block: pa.Table, clause: MergeClause,
                        target_cols: List[str], metadata) -> pa.Table:
        src_cols = [c[len(_SRC):] for c in block.column_names if c.startswith(_SRC)]
        if clause.is_star:
            # updateAll: SET t.c = s.c (star coverage validated statically
            # in _body; with evolution target-only columns are no-ops)
            assignments = {
                c: ir.Column(_SRC + next(s for s in src_cols if s.lower() == c.lower()))
                for c in target_cols
                if any(s.lower() == c.lower() for s in src_cols)
            }
        else:
            assignments = {}
            for col, e in clause.assignments.items():
                name = col.split(".")[-1]  # strip target alias qualifier
                assignments[name] = self._resolve_in_pairs(e, block)
        from delta_tpu.expr.vectorized import arrow_type_for

        declared = {f.name: arrow_type_for(f.data_type)
                    for f in metadata.schema.fields}
        cols = []
        for c in target_cols:
            e = None
            for k, v in assignments.items():
                if k.lower() == c.lower():
                    e = v
                    break
            if e is None:
                cols.append(block.column(c))
            else:
                new = evaluate(e, block)
                # cast to the SCHEMA's declared type — with projection
                # pushdown the assigned target column isn't decoded at all
                cols.append(pc.cast(new, declared[c], safe=False))
        out = pa.table(cols, names=target_cols)
        # recompute generated columns whose referenced base columns were
        # assigned (stale copies fail write-time checks); uses the txn's
        # metadata, the same schema the rest of the merge writes against
        from delta_tpu.schema import generated as generated_mod

        return generated_mod.recompute_stale(out, metadata.schema, list(assignments))

    def _apply_not_matched(self, pairs: pa.Table, src: pa.Table,
                           target_cols: List[str], source_cols: List[str], metadata):
        if not self.not_matched_clauses:
            return None, 0
        if self._device_join is not None:
            # device kernel computed per-source matched flags via the reverse
            # probe + psum (exact: the device path requires no residual)
            unmatched = src.filter(pa.array(~self._device_join.s_matched))
        elif pairs.num_rows:
            matched_sids = pc.unique(pairs.column(_SID))
            unmatched = src.filter(
                pc.invert(pc.is_in(src.column(_SID), value_set=matched_sids))
            )
        else:
            unmatched = src
        if unmatched.num_rows == 0:
            return None, 0
        n = unmatched.num_rows
        unclaimed = pa.chunked_array([pa.array([True] * n)])
        parts: List[pa.Table] = []
        n_inserted = 0
        from delta_tpu.expr.vectorized import arrow_type_for

        for clause in self.not_matched_clauses:
            if clause.condition is None:
                fire = unclaimed
            else:
                cond = self._resolve(clause.condition, [], source_cols)
                fire = pc.and_(unclaimed, boolean_mask(cond, unmatched))
            count = pc.sum(fire).as_py() or 0
            if count:
                block = unmatched.filter(fire)
                if clause.is_star:
                    assignments = {
                        c: ir.Column(_SRC + next(
                            s for s in source_cols if s.lower() == c.lower()
                        ))
                        for c in target_cols
                        if any(s.lower() == c.lower() for s in source_cols)
                    }
                else:
                    assignments = {
                        col.split(".")[-1]: self._resolve(e, [], source_cols)
                        for col, e in clause.assignments.items()
                    }
                from delta_tpu.schema import generated as generated_mod

                gen_cols = generated_mod.generated_column_names(metadata.schema)
                cols, names = [], []
                for f in metadata.schema.fields:
                    e = None
                    for k, v in assignments.items():
                        if k.lower() == f.name.lower():
                            e = v
                            break
                    at = arrow_type_for(f.data_type)
                    if e is None:
                        # unassigned generated columns are computed from the
                        # built row, not nulled (GeneratedColumn.scala:267)
                        if f.name.lower() in gen_cols:
                            continue
                        cols.append(pa.nulls(block.num_rows, at))
                    else:
                        cols.append(pc.cast(evaluate(e, block), at, safe=False))
                    names.append(f.name)
                part = pa.table(cols, names=names)
                part = generated_mod.compute_on_write(part, metadata.schema)
                parts.append(part.select(target_cols))
                n_inserted += count
            unclaimed = pc.and_(unclaimed, pc.invert(fire))
        out = pa.concat_tables(parts, promote_options="permissive") if parts else None
        return out, n_inserted
