"""Device-resident hot-column scan cache + the jitted residual-filter path.

PR 12 made the scan *planner* device-servable; every surviving row group
still decoded on host Arrow and evaluated the residual predicate through
Arrow compute. This module keeps the decode product itself accelerator-side
for the predicate columns: per-(table, file, column) SoA lanes —
dictionary-encoded strings as int32 codes, temporal columns as epoch
days/µs, numerics widened to lane dtypes — live in HBM across queries, and
the residual filter mask is computed in ONE jitted pass per file
(`expr/jaxeval.compile_residual` + `compile_expr`). Only survivor rows are
then fetched / late-materialized on host (`exec/scan.read_files_as_table`'s
``device_masks``), with result identity guaranteed by construction: the
mask is the exact Kleene TRUE set of the residual, and ``scan_to_table``
re-applies the same residual over the survivors.

Cache discipline mirrors `ops/key_cache.KeyCache`: a process-wide singleton
keyed by (log path, file path, column), per-table rewrite epochs
(:meth:`ColumnCache.bump_epoch` — OPTIMIZE/UPDATE/DELETE-rewrite/RESTORE
drop the table's lanes outright; a decode racing a rewrite is served but
never cached), LRU eviction under
``min(delta.tpu.columnCache.maxBytes, hbm_ledger.column_cache_allowance())``
(the process-wide soft HBM budget, `obs/hbm_ledger` component
``columnCache``), and per-table ``columnCache.residentBytes`` residency
gauges. Parquet files are immutable, so a resident lane never goes stale
for the file it decoded — the epoch machinery frees rewritten tables'
memory promptly and guarantees a post-rewrite scan can only see lanes that
re-decode from the new files.

The device-vs-host choice routes through `parallel/link` pricing
(``HOST_RESIDUAL_S_PER_CELL`` / ``DEVICE_RESIDUAL_S_PER_CELL``, both
calibratable) and every decision is audited via `obs/router_audit` under
``op="scan.residual"`` — the same observability contract as the MERGE
router. ``delta.tpu.read.deviceResidual.mode``: ``auto`` prices each scan,
``force`` always engages (bench legs), ``off`` disables.
"""
from __future__ import annotations

import functools
import os
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from delta_tpu.expr import ir, jaxeval
from delta_tpu.expr.jaxeval import NotDeviceCompilable
from delta_tpu.obs import hbm_ledger
from delta_tpu.ops.state_cache import _next_pow2  # shared pad-size bucketing
from delta_tpu.utils.config import conf
from delta_tpu.utils.jaxcompat import enable_x64

__all__ = ["ResidentColumn", "ColumnCache", "device_residual_masks",
           "column_cache_enabled"]


def column_cache_enabled() -> bool:
    return str(conf.get("delta.tpu.read.deviceResidual.mode", "auto")
               ).lower() != "off"


def _abs_data_path(data_path: str, file_path: str) -> str:
    if "://" in file_path or os.path.isabs(file_path):
        return urllib.parse.unquote(file_path)
    return os.path.join(data_path,
                        urllib.parse.unquote(file_path).replace("/", os.sep))


def _lane_from_arrow(arr) -> Optional[Tuple[np.ndarray, np.ndarray,
                                            Optional[Dict[str, int]]]]:
    """Decode one Arrow column to its device lane encoding:
    ``(values, valid, dict)`` — strings become int32 dictionary codes with
    the value→code map returned for literal binding, date32 becomes epoch
    days (int32), timestamps epoch µs (int64), numerics widen to
    int64/float64. Returns None for types with no lane form."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    valid = pc.is_valid(arr).to_numpy(zero_copy_only=False).astype(bool)
    t = arr.type
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        enc = arr.dictionary_encode()
        codes = enc.indices.fill_null(-1).to_numpy(
            zero_copy_only=False).astype(np.int32, copy=False)
        mapping = {v: i for i, v in enumerate(enc.dictionary.to_pylist())}
        return codes, valid, mapping
    if pa.types.is_date(t):
        vals = arr.cast(pa.date32()).cast(pa.int32()).fill_null(0).to_numpy(
            zero_copy_only=False).astype(np.int32, copy=False)
    elif pa.types.is_timestamp(t):
        vals = arr.cast(pa.timestamp("us")).cast(pa.int64()).fill_null(
            0).to_numpy(zero_copy_only=False).astype(np.int64, copy=False)
    elif pa.types.is_boolean(t):
        vals = arr.fill_null(False).to_numpy(
            zero_copy_only=False).astype(bool)
    elif pa.types.is_integer(t):
        vals = arr.cast(pa.int64()).fill_null(0).to_numpy(
            zero_copy_only=False).astype(np.int64, copy=False)
    elif pa.types.is_floating(t):
        vals = arr.cast(pa.float64()).fill_null(0.0).to_numpy(
            zero_copy_only=False).astype(np.float64, copy=False)
    else:
        return None
    return vals, valid, None


class ResidentColumn:
    """One decoded (file, column) lane resident in HBM: values + validity
    padded to the shared pow2 buckets (`state_cache._next_pow2`) so files of
    similar size hit the same jit shape-cache entry; pad rows carry
    ``valid=False`` and slice away after the mask download. String lanes
    keep their host-side value→code dictionary for per-scan literal
    binding."""

    __slots__ = ("log_path", "file_path", "column", "values", "valid", "n",
                 "dict_codes", "nbytes", "epoch", "last_used", "_account",
                 "_lock", "__weakref__")

    def __init__(self, log_path: str, file_path: str, column: str,
                 values: np.ndarray, valid: np.ndarray,
                 dict_codes: Optional[Dict[str, int]], epoch: int):
        self.log_path = log_path
        self.file_path = file_path
        self.column = column
        self.n = int(len(values))
        cap = _next_pow2(max(self.n, 1), floor=64)
        pv = np.zeros(cap, dtype=values.dtype)
        pv[: self.n] = values
        pm = np.zeros(cap, dtype=bool)
        pm[: self.n] = valid
        self.nbytes = int(pv.nbytes + pm.nbytes)
        self.dict_codes = dict_codes
        self.epoch = epoch
        self.last_used = 0
        self._lock = threading.Lock()
        self._account = hbm_ledger.Account("columnCache")
        import jax

        with enable_x64():
            self.values = jax.device_put(pv)
            self.valid = jax.device_put(pm)
        self._account.on(self, self.nbytes)

    @property
    def is_resident(self) -> bool:
        return self.values is not None

    def device_column(self) -> jaxeval.DeviceColumn:
        return jaxeval.DeviceColumn(self.values, self.valid)

    def drop_device(self) -> None:
        with self._lock:
            self.values = None
            self.valid = None
            self._account.off()


class ColumnCache:
    """Process-wide registry of resident scan-column lanes, keyed by
    (log path, file path, column). Locking and epoch discipline mirror
    `ops/key_cache.KeyCache`; entries are immutable after construction
    (Parquet files never change), so there are no build locks or version
    advances — only residency and the per-table rewrite epoch."""

    _instance: Optional["ColumnCache"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._entries: Dict[Tuple[str, str, str], ResidentColumn] = {}
        self._lock = threading.RLock()
        self._tick = 0
        # per-table rewrite generation (bump_epoch): lanes decoded under an
        # older epoch are never cached, and a bump drops the table's lanes
        self._epochs: Dict[str, int] = {}
        self._last_resident: set = set()
        self._published_bytes: Dict[str, int] = {}

    @classmethod
    def instance(cls) -> "ColumnCache":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = ColumnCache()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._instance_lock:
            cls._instance = None

    def epoch(self, log_path: str) -> int:
        with self._lock:
            return self._epochs.get(log_path, 0)

    def bump_epoch(self, log_path: str) -> None:
        """File-rewrite invalidation (OPTIMIZE / UPDATE / DELETE-rewrite /
        RESTORE): drop the table's resident lanes outright — the rewritten
        files' lanes are garbage, and the epoch guard keeps any decode that
        raced the rewrite from being cached under the new generation."""
        from delta_tpu.utils.telemetry import bump_counter

        with self._lock:
            self._epochs[log_path] = self._epochs.get(log_path, 0) + 1
            stale = [k for k in self._entries if k[0] == log_path]
            for k in stale:
                self._entries.pop(k).drop_device()
        if stale:
            bump_counter("columnCache.invalidations", len(stale))
            self._publish_residency()

    def invalidate(self, log_path: str) -> None:
        with self._lock:
            for k in [k for k in self._entries if k[0] == log_path]:
                self._entries.pop(k).drop_device()
        self._publish_residency()

    def get(self, log_path: str, file_path: str,
            column: str) -> Optional[ResidentColumn]:
        with self._lock:
            self._tick += 1
            key = (log_path, file_path, column)
            e = self._entries.get(key)
            if e is not None and e.epoch != self._epochs.get(log_path, 0):
                # belt-and-braces: bump_epoch pops the table's entries, but
                # a registration racing the bump could have slipped in
                self._entries.pop(key, None)
                e.drop_device()
                return None
            if e is not None and e.is_resident:
                e.last_used = self._tick
                return e
            if e is not None:
                self._entries.pop(key, None)  # evicted husk
            return None

    def register(self, entry: ResidentColumn) -> bool:
        """Adopt a freshly decoded lane. Refused when the table's epoch
        moved during the decode (a rewrite raced it) — the caller's mask
        stays exact for its snapshot (file contents are immutable), so it
        serves the lane without caching it."""
        with self._lock:
            if entry.epoch != self._epochs.get(entry.log_path, 0):
                return False
            self._tick += 1
            entry.last_used = self._tick
            self._entries[(entry.log_path, entry.file_path,
                           entry.column)] = entry
        self._evict(keep=(entry.log_path, entry.file_path, entry.column))
        return True

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values()
                       if e.is_resident)

    def _publish_residency(self) -> None:
        """Per-table ``columnCache.residentBytes`` gauges (label: hashed
        table path), same contract as the key cache: mutation paths only,
        unchanged values skip the telemetry lock, a full drop publishes an
        explicit 0."""
        from delta_tpu.obs.fleet import table_label
        from delta_tpu.utils.telemetry import set_gauge

        with self._lock:
            by_table: Dict[str, int] = {t: 0 for t in self._last_resident}
            for (log_path, _f, _c), e in self._entries.items():
                if e.is_resident:
                    table = log_path[: -len("/_delta_log")] \
                        if log_path.endswith("/_delta_log") else log_path
                    by_table[table] = by_table.get(table, 0) + e.nbytes
            self._last_resident = {t for t, b in by_table.items() if b}
            changed = {t: b for t, b in by_table.items()
                       if self._published_bytes.get(t) != b}
            self._published_bytes.update(changed)
            for table, total in changed.items():
                set_gauge("columnCache.residentBytes", total,
                          table=table_label(table))

    def _evict(self, keep=None) -> None:
        from delta_tpu.utils.telemetry import bump_counter

        budget = int(conf.get("delta.tpu.columnCache.maxBytes", 1 << 30))
        allowance = hbm_ledger.column_cache_allowance()
        if allowance is not None:
            budget = min(budget, allowance)
        max_entries = int(conf.get("delta.tpu.columnCache.maxEntries", 4096))
        dropped = 0
        with self._lock:
            resident = [(k, e) for k, e in self._entries.items()
                        if e.is_resident]
            total = sum(e.nbytes for _, e in resident)
            for k, e in sorted(resident, key=lambda kv: kv[1].last_used):
                if total <= budget and len(self._entries) <= max_entries:
                    break
                if k == keep:
                    continue
                self._entries.pop(k, None)
                e.drop_device()
                total -= e.nbytes
                dropped += 1
        if dropped:
            bump_counter("columnCache.evictions", dropped)
        self._publish_residency()


# -- the jitted residual mask kernel -----------------------------------------


@functools.lru_cache(maxsize=128)
def _mask_kernel(expr: ir.Expression):
    """jit-compiled Kleene-TRUE mask for a lowered residual — keyed on the
    (hashable) rewritten expression; pow2-padded lanes keep the XLA shape
    cache warm across similarly sized files."""
    import jax

    fn = jaxeval.compile_expr(expr)

    def kernel(env):
        out = fn(env)
        return out.values.astype(bool) & out.valid

    return jax.jit(kernel)


def _scalar_column(value: Any) -> jaxeval.DeviceColumn:
    """A per-file scalar binding (partition value / string-literal code) as
    a broadcastable device scalar."""
    import datetime as _dt

    import jax.numpy as jnp

    if value is None:
        return jaxeval.DeviceColumn(jnp.zeros((), jnp.float32),
                                    jnp.zeros((), bool))
    if isinstance(value, bool):
        arr = np.asarray(value)
    elif isinstance(value, _dt.datetime):
        if value.tzinfo is None:
            value = value.replace(tzinfo=_dt.timezone.utc)
        arr = np.asarray(int(value.timestamp() * 1_000_000), np.int64)
    elif isinstance(value, _dt.date):
        arr = np.asarray((value - _dt.date(1970, 1, 1)).days, np.int32)
    elif isinstance(value, int):
        arr = np.asarray(value, np.int64)
    elif isinstance(value, float):
        arr = np.asarray(value, np.float64)
    else:
        raise NotDeviceCompilable(f"partition value {value!r} has no lane form")
    return jaxeval.DeviceColumn(jnp.asarray(arr), jnp.ones((), bool))


def _ensure_lanes(cache: "ColumnCache", log_path: str, data_path: str, add,
                  need: List[str], epoch: int,
                  counters: Dict[str, int]) -> Optional[Dict[str, ResidentColumn]]:
    """Resident lanes for one file's predicate columns, decoding misses
    cold (predicate columns ONLY — the projection still decodes lazily for
    survivors on host). Returns None when a column's Arrow type has no lane
    form. Lanes for columns the file predates bind all-invalid (NULL)."""
    out: Dict[str, ResidentColumn] = {}
    missing = []
    for c in need:
        e = cache.get(log_path, add.path, c)
        if e is not None:
            out[c] = e
            counters["hits"] += 1
        else:
            missing.append(c)
            counters["misses"] += 1
    if not missing:
        return out
    import pyarrow.parquet as pq

    pf = pq.ParquetFile(_abs_data_path(data_path, add.path), memory_map=True)
    present = {n.lower(): n for n in pf.schema_arrow.names}
    stored = [present[c] for c in missing if c in present]
    tbl = pf.read(columns=stored) if stored else None
    n_rows = pf.metadata.num_rows
    counters["coldBytes"] += sum(
        pf.metadata.row_group(i).total_byte_size
        for i in range(pf.metadata.num_row_groups)) if stored else 0
    for c in missing:
        if c in present:
            lane = _lane_from_arrow(tbl.column(present[c]))
            if lane is None:
                return None
            vals, valid, codes = lane
        else:
            # schema evolution: the file predates the column → all-NULL
            vals = np.zeros(n_rows, np.float64)
            valid = np.zeros(n_rows, bool)
            codes = None
        entry = ResidentColumn(log_path, add.path, c, vals, valid, codes,
                               epoch)
        cache.register(entry)  # epoch race → served uncached, still exact
        out[c] = entry
    return out


def device_residual_masks(snapshot, files, predicate) -> Optional[Dict[str, np.ndarray]]:
    """Per-file physical-row survivor masks for ``predicate``, computed on
    device from resident lanes — or None when the predicate doesn't lower,
    the router prices the host faster, or anything on the device path
    fails (the caller's Arrow path is always correct on its own).

    The returned mask is the exact Kleene-TRUE row set of the residual for
    each file of THIS snapshot; deletion vectors are NOT applied here (the
    decode composes them downstream via physical positions)."""
    mode = str(conf.get("delta.tpu.read.deviceResidual.mode", "auto")).lower()
    if mode == "off" or predicate is None or not files:
        return None
    from delta_tpu.utils.telemetry import bump_counter

    metadata = snapshot.metadata
    log_path = snapshot.delta_log.log_path
    data_path = snapshot.delta_log.data_path
    try:
        from delta_tpu.expr.synthesis import schema_types

        types = schema_types(metadata)
        plan = jaxeval.compile_residual(predicate, types,
                                        metadata.partition_columns)
    except NotDeviceCompilable:
        bump_counter("scan.device.fallback")
        return None
    if not plan.refs:
        return None  # partition-only residual: file pruning already exact
    from delta_tpu.obs import router_audit, scan_report
    from delta_tpu.parallel import link

    est_rows = sum(max((f.size or 0) // 64, 1024) for f in files)
    ncols = max(len(plan.refs), 1)
    cache = ColumnCache.instance()
    resident_rows = sum(
        e.n for f in files for c in plan.refs
        if (e := cache.get(log_path, f.path, c)) is not None) // ncols
    cold_rows = max(est_rows - resident_rows, 0)
    p = link.profile()
    predicted = {
        "device": link.device_residual_mask_s(cold_rows, resident_rows,
                                              ncols, p),
        "host": link.host_residual_filter_s(est_rows, ncols),
    }
    decision = "device" if (mode == "force"
                            or predicted["device"] < predicted["host"]) \
        else "host"
    if decision == "host":
        bump_counter("scan.device.declined")
        router_audit.record_audit(
            "scan.residual", data_path, "host", predicted,
            predicted["host"], units={"rows": est_rows, "cols": ncols},
            log_path=log_path, calibration_flush=False,
            files=len(files), mode=mode)
        return None
    counters = {"hits": 0, "misses": 0, "coldBytes": 0}
    part_schema = metadata.partition_schema
    masks: Dict[str, np.ndarray] = {}
    t0 = time.perf_counter()
    try:
        with enable_x64():
            kernel = _mask_kernel(plan.expr)
            for add in files:
                lanes = _ensure_lanes(cache, log_path, data_path, add,
                                      sorted(plan.refs), cache.epoch(log_path),
                                      counters)
                if lanes is None:
                    bump_counter("scan.device.fallback")
                    return None
                n = max((e.n for e in lanes.values()), default=0)
                env = {c: e.device_column() for c, e in lanes.items()}
                for ph, col, value in plan.str_binds:
                    codes = lanes[col].dict_codes or {}
                    env[ph] = _scalar_column(
                        int(codes.get(value, jaxeval.STR_CODE_ABSENT)))
                if plan.part_refs:
                    from delta_tpu.expr.partition import typed_partition_row

                    typed = typed_partition_row(add, part_schema)
                    lowered = {k.lower(): v for k, v in typed.items()}
                    for c in plan.part_refs:
                        env[c] = _scalar_column(lowered.get(c))
                masks[add.path] = np.asarray(kernel(env))[:n]
    except NotDeviceCompilable:
        bump_counter("scan.device.fallback")
        return None
    except Exception:
        # the device path must never fail a scan the Arrow path can serve
        bump_counter("scan.device.fallback")
        return None
    actual_s = time.perf_counter() - t0
    bump_counter("scan.device.engaged")
    if counters["hits"]:
        bump_counter("columnCache.hits", counters["hits"])
    if counters["misses"]:
        bump_counter("columnCache.misses", counters["misses"])
    total_rows = sum(len(m) for m in masks.values())
    samples = []
    if total_rows and counters["misses"] == 0:
        # warm pass: the whole wall time is the kernel+download — a clean
        # sample for the device per-cell constant
        samples.append(("DEVICE_RESIDUAL_S_PER_CELL", total_rows * ncols,
                        actual_s))
    router_audit.record_audit(
        "scan.residual", data_path, "device", predicted, actual_s,
        units={"rows": total_rows, "cols": ncols},
        samples=samples, log_path=log_path, calibration_flush=False,
        files=len(files), cacheHits=counters["hits"],
        cacheMisses=counters["misses"], mode=mode)
    rep = scan_report.current_report()
    if rep is not None:
        rep.device_residual = "device"
    return masks
