"""Sharded execution plane (`parallel/distributed`, `parallel/executor`,
sharded scan planning in `ops/state_cache`): byte-weighted LPT vs the strided
partitioner on a zipf-100k file population, the work-stealing executor's
ordering/abort/steal semantics, shard_map plan identity on the virtual
8-device mesh, per-device HBM attribution + the doctor's worst-device flag,
and parallel OPTIMIZE / probe-restricted MERGE result identity."""
import time

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.optimize import OptimizeCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.expr.parser import parse_expression
from delta_tpu.obs import hbm_ledger
from delta_tpu.ops import pruning
from delta_tpu.ops.state_cache import DeviceStateCache, ResidentState, extract_ranges
from delta_tpu.parallel.distributed import bytes_skew, host_shard_indices, lpt_assign
from delta_tpu.parallel.executor import run_sharded
from delta_tpu.storage.faults import SimulatedCrash
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_ledger():
    hbm_ledger.reset()
    DeviceStateCache.reset()
    yield
    DeviceStateCache.reset()
    hbm_ledger.reset()


# -- LPT partitioner --------------------------------------------------------


def test_lpt_assign_tiles_and_is_deterministic():
    sizes = [5, 3, 3, 2, 2, 1, 1, 1]
    a = lpt_assign(sizes, 3)
    # tiling without overlap, every bucket sorted
    flat = sorted(j for b in a for j in b)
    assert flat == list(range(len(sizes)))
    assert all(b == sorted(b) for b in a)
    # pure function of (sizes, count): recompute == first run (the RPC-free
    # contract — every host derives the identical assignment)
    assert a == lpt_assign(sizes, 3)
    # count=1 degenerates to everything on host 0
    assert lpt_assign(sizes, 1) == [list(range(len(sizes)))]


def test_lpt_beats_strided_on_zipf_100k():
    """Regression for the strided partitioner's hot-shard failure: on a
    zipf-like 100k file population the strided slices concentrate the head
    of the distribution on one host (max/mean bytes well above 1), while the
    size-weighted LPT assignment stays within a percent of perfectly even."""
    n = 100_000
    sizes = [1_000_000 // (i + 1) + 1 for i in range(n)]  # zipf s=1 head
    hosts = 8
    strided = [list(range(h, n, hosts)) for h in range(hosts)]
    lpt = lpt_assign(sizes, hosts)
    s_skew = bytes_skew(sizes, strided)
    l_skew = bytes_skew(sizes, lpt)
    assert s_skew > 1.3, s_skew  # strided inherits the hot shard
    assert l_skew < 1.01, l_skew  # LPT is near-perfectly balanced
    assert l_skew < s_skew
    # the sized host_shard_indices slices agree with lpt_assign exactly
    for h in range(hosts):
        assert host_shard_indices(n, h, hosts, sizes=sizes) == lpt[h]


def test_host_shard_indices_strided_default_unchanged():
    # sizes=None keeps the legacy strided contract (vacuum/scan composition
    # in test_multihost relies on the exact indices)
    assert host_shard_indices(10, 1, 3) == [1, 4, 7]
    with pytest.raises(ValueError):
        host_shard_indices(10, 0, 2, sizes=[1, 2, 3])  # length mismatch


# -- work-stealing executor -------------------------------------------------


def test_run_sharded_preserves_order_and_steals():
    items = list(range(10))
    # LPT over 2 workers: the hot item owns worker 0's whole deque, the 9
    # small ones queue on worker 1 — worker 0 drains first and must steal
    sizes = [10_000] + [1] * 9

    def fn(x):
        time.sleep(0.08 if x == 0 else 0.03)
        return x * 2

    before = telemetry.counters("dist")
    rep = run_sharded(items, fn, sizes=sizes, workers=2, label="t")
    after = telemetry.counters("dist")
    assert rep.results == [x * 2 for x in items]  # index-ordered
    assert rep.workers == 2
    assert rep.steals >= 1
    assert rep.per_worker[0].stolen >= 1
    assert sum(s.items for s in rep.per_worker.values()) == len(items)
    assert sum(s.bytes for s in rep.per_worker.values()) == sum(sizes)
    assert after.get("dist.jobs", 0) == before.get("dist.jobs", 0) + 1
    assert after.get("dist.items", 0) == before.get("dist.items", 0) + 10
    assert after.get("dist.steals", 0) >= before.get("dist.steals", 0) + 1
    rows = rep.timings()
    assert [r["worker"] for r in rows] == [0, 1]
    assert all(r["busy_s"] > 0 for r in rows)


def test_run_sharded_stealing_conf_gate():
    with conf.set_temporarily(**{"delta.tpu.distributed.workStealing.enabled": False}):
        rep = run_sharded(list(range(8)), lambda x: x, sizes=[100] + [1] * 7,
                          workers=2, label="t")
    assert rep.results == list(range(8))
    assert rep.steals == 0


def test_run_sharded_inline_single_worker():
    rep = run_sharded([3, 1, 2], lambda x: x + 1, workers=1, label="t")
    assert rep.results == [4, 2, 3]
    assert rep.workers == 1 and rep.steals == 0 and rep.skew == 1.0


def test_run_sharded_crash_aborts_and_reraises():
    """A SimulatedCrash on one worker mid-job pierces the pool: the first
    failure aborts the remaining queue and re-raises on the caller — no
    partial result is ever returned to commit from."""
    ran = []

    def fn(x):
        if x == 0:
            raise SimulatedCrash("dist.item")
        time.sleep(0.01)
        ran.append(x)
        return x

    with pytest.raises(SimulatedCrash):
        run_sharded(list(range(32)), fn, sizes=[1000] + [1] * 31,
                    workers=4, label="t")
    assert len(ran) < 32  # the abort actually cut the queue short


# -- sharded scan planning (shard_map on the virtual 8-device mesh) ---------


def _entry(n=5000, seed=7):
    rng = np.random.RandomState(seed)
    lo = np.sort(rng.rand(2, n) * 100.0, axis=0)
    hi = lo + rng.rand(2, n) * 10.0
    return ResidentState(
        "mem://t", "mid", 0, ["a", "b"], [f"p{i}" for i in range(n)],
        {"min": lo, "max": hi, "size": np.ones(n, np.int64)},
    )


def _ranges(entry, exprs):
    out = []
    for e in exprs:
        pred = pruning.skipping_predicate(parse_expression(e), frozenset())
        r = extract_ranges(pred, entry.columns)
        assert r is not None, e
        out.append(r)
    return out


def test_sharded_plan_identity_on_8_devices():
    """The shard_map plan kernel (lanes split along the file axis over the
    8-device mesh) returns EXACTLY the host planner's rows: the coarse
    per-shard block cull all-gathers, and the fine pass runs on the same
    float64 mirrors in both routes."""
    entry = _entry()
    rs = _ranges(entry, ["a >= 10 AND a <= 30", "b <= 20", "a = 50",
                         "a >= 99 AND b <= 1", "b >= 1000"])
    host = entry.plan_ranges(rs, k=10_000, use_device=False)
    before = telemetry.counters("dist")
    with conf.set_temporarily(**{
        "delta.tpu.distributed.plan.mode": "force",
        "delta.tpu.stateCache.devicePlan.mode": "force",
    }):
        dev = entry.plan_ranges(rs, k=10_000, use_device=True)
    assert entry.resident_shards == 8  # 8192-capacity lanes over 8 devices
    for hp, dp in zip(host, dev):
        assert list(dp.rows) == list(hp.rows)
        assert dp.count == hp.count
        if dp.via != "verdict":
            assert dp.via == "device-sharded"
    after = telemetry.counters("dist")
    assert after.get("dist.plan.sharded", 0) > before.get("dist.plan.sharded", 0)


def test_sharded_residency_accounts_per_device():
    entry = _entry()
    with conf.set_temporarily(**{"delta.tpu.distributed.plan.mode": "force"}):
        entry.ensure_resident(entry._feasible_shards())
    per = hbm_ledger.device_totals()
    assert sorted(per) == list(range(8))
    assert len(set(per.values())) == 1  # even split of the lane bytes
    assert sum(per.values()) <= entry.device_bytes
    # the labeled gauge rides next to the unlabeled aggregate
    g = telemetry.gauges("device.hbm.stateCacheBytes")
    labeled = {k[1] for k in g if k[1]}
    assert (("device", "0"),) in labeled
    assert ((), ) not in labeled and ("device.hbm.stateCacheBytes", ()) in g
    worst = hbm_ledger.worst_device()
    assert worst is not None and worst[0] == 0  # even split ties -> lowest
    entry.drop_device()
    assert hbm_ledger.device_totals() == {} or \
        all(v == 0 for v in hbm_ledger.device_totals().values())


def test_small_capacity_is_not_shardable():
    # 6 paths -> capacity 8: cannot split into whole 1024-file BLOCKs
    entry = _entry(n=6)
    assert entry._feasible_shards() == 1
    with conf.set_temporarily(**{"delta.tpu.distributed.plan.enabled": False}):
        big = _entry()
        assert big._feasible_shards() == 1


# -- doctor: worst-device dimension -----------------------------------------


def test_doctor_flags_worst_device():
    from delta_tpu.obs.doctor import _dim_device

    hbm_ledger.adjust("stateCache", 800, device=0)
    hbm_ledger.adjust("stateCache", 100, device=1)
    with conf.set_temporarily(**{"delta.tpu.device.hbmBudgetBytes": 1000}):
        dim = _dim_device()
    # aggregate pressure 0.9 would only warn; device 0 at 1.6x its fair
    # share (500) is the real OOM candidate and drives severity
    assert dim.metrics["worstDevice"] == 0
    assert dim.metrics["worstDeviceBytes"] == 800
    assert dim.metrics["worstDevicePressure"] == pytest.approx(1.6)
    assert dim.severity == "critical"
    assert "worst device 0" in dim.detail


# -- parallel OPTIMIZE ------------------------------------------------------


def _rows(log, sort="id"):
    from delta_tpu.exec.scan import scan_to_table

    t = scan_to_table(log.update())
    return t.sort_by(sort).to_pylist()


def _mk_partitioned(path, parts=4, files_per=3, rows=16):
    log = DeltaLog.for_table(str(path))
    for p in range(parts):
        for f in range(files_per):
            base = (p * files_per + f) * rows
            WriteIntoDelta(log, "append", pa.table({
                "id": np.arange(base, base + rows, dtype=np.int64),
                "part": np.full(rows, f"p{p}"),
                "v": np.arange(base, base + rows, dtype=np.float64),
            }), partition_columns=["part"]).run()
    return log


def test_parallel_optimize_identity(tmp_path):
    seq_log = _mk_partitioned(tmp_path / "seq")
    par_log = _mk_partitioned(tmp_path / "par")
    before = _rows(seq_log)
    c1 = OptimizeCommand(seq_log, min_file_size=1 << 30)
    c1.run()
    c4 = OptimizeCommand(par_log, min_file_size=1 << 30, workers=4)
    c4.run()
    # same rows, same file topology, same metrics — worker count is invisible
    assert _rows(seq_log) == before
    assert _rows(par_log) == before
    assert c1.metrics["numRemovedFiles"] == c4.metrics["numRemovedFiles"] == 12
    assert c1.metrics["numAddedFiles"] == c4.metrics["numAddedFiles"] == 4
    assert c4.shard_report is not None
    assert c4.shard_report.workers == 4
    assert [r for r in c4.shard_report.results if r is None] == []
    DeltaLog.clear_cache()
    assert DeltaLog.for_table(str(tmp_path / "par")).update().num_of_files == 4


def test_optimize_workers_conf_default(tmp_path):
    log = _mk_partitioned(tmp_path / "t", parts=2, files_per=2)
    with conf.set_temporarily(**{"delta.tpu.distributed.optimize.workers": 2}):
        cmd = OptimizeCommand(log, min_file_size=1 << 30)
        cmd.run()
    assert cmd.shard_report is not None and cmd.shard_report.workers == 2
    assert telemetry.counters("dist").get("dist.optimize.groups", 0) >= 2


# -- MERGE distributed touched-files probe ----------------------------------


def _mk_many_files(path, n_files=10, rows=8):
    log = DeltaLog.for_table(str(path))
    for i in range(n_files):
        base = i * rows
        WriteIntoDelta(log, "append", pa.table({
            "id": np.arange(base, base + rows, dtype=np.int64),
            "v": np.arange(base, base + rows, dtype=np.float64),
        })).run()
    return log


def test_merge_probe_identity_and_restriction(tmp_path):
    """Probe on vs off: identical MERGE results; the probe restricts the
    candidate set to files whose keys intersect the source (counted via
    dist.merge.filesProbed) and can never drop a touched file."""
    src = {"id": [3, 75], "v": [-1.0, -2.0]}  # touches files 0 and 9 only
    cond = "t.id = s.id"
    up = MergeClause("update", assignments=None)
    ins = MergeClause("insert", assignments=None)

    off_log = _mk_many_files(tmp_path / "off")
    with conf.set_temporarily(**{"delta.tpu.distributed.merge.probe.enabled": False}):
        m_off = MergeIntoCommand(off_log, pa.table(src), cond, [up], [ins],
                                 source_alias="s", target_alias="t")
        m_off.run()

    on_log = _mk_many_files(tmp_path / "on")
    before = telemetry.counters("dist").get("dist.merge.filesProbed", 0)
    m_on = MergeIntoCommand(on_log, pa.table(src), cond, [up], [ins],
                            source_alias="s", target_alias="t")
    m_on.run()
    after = telemetry.counters("dist").get("dist.merge.filesProbed", 0)
    assert after == before + 10  # every candidate was probed
    assert "probe_ms" in m_on.phase_ms
    assert _rows(on_log) == _rows(off_log)
    assert m_on.metrics["numTargetRowsUpdated"] == 2
    assert m_on.metrics["numTargetRowsUpdated"] == m_off.metrics["numTargetRowsUpdated"]
    assert m_on.metrics["numTargetRowsInserted"] == 0
    # the probe kept only the 2 touched files: the rewrite removed exactly 2
    assert m_on.metrics["numTargetFilesRemoved"] <= 2


def test_merge_probe_skips_below_min_files(tmp_path):
    log = _mk_many_files(tmp_path / "t", n_files=3)
    before = telemetry.counters("dist").get("dist.merge.filesProbed", 0)
    cmd = MergeIntoCommand(
        log, pa.table({"id": [1], "v": [0.0]}), "t.id = s.id",
        [MergeClause("update", assignments=None)], [],
        source_alias="s", target_alias="t")
    cmd.run()
    assert telemetry.counters("dist").get("dist.merge.filesProbed", 0) == before
