"""VACUUM — garbage-collect files no snapshot references.

Mirrors `commands/VacuumCommand.scala:49-347`: build the valid-file set from
the current state (live files + un-expired tombstones, relativized), list the
table directory recursively in parallel, and delete unreferenced files whose
modification time is older than the retention horizon. Retention below the
tombstone retention (default 168h) is refused unless the safety check is
disabled (`:54-77`) — deleting younger files breaks readers of older
snapshots and concurrent writers. Hidden files/dirs (`_`/`.`-prefixed) are
skipped except partition directories (`=` in the name) and CDC dirs.
"""
from __future__ import annotations

import os
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import List, Optional, Set

from delta_tpu.utils.config import DeltaConfigs, conf
from delta_tpu.utils import errors

__all__ = ["VacuumCommand", "VacuumResult"]

MS_PER_HOUR = 3600 * 1000


@dataclass
class VacuumResult:
    path: str
    files_deleted: int
    dirs_deleted: int
    dry_run: bool
    retention_ms: int
    deleted_paths: List[str] = field(default_factory=list)


def _is_hidden(name: str) -> bool:
    return (name.startswith("_") or name.startswith(".")) and "=" not in name and not (
        name.startswith("_change_data") or name.startswith("_cdc")
    )


class VacuumCommand:
    def __init__(
        self,
        delta_log,
        retention_hours: Optional[float] = None,
        dry_run: bool = False,
        retention_check_enabled: bool = True,
        parallelism: int = 8,
    ):
        self.delta_log = delta_log
        self.retention_hours = retention_hours
        self.dry_run = dry_run
        self.retention_check_enabled = retention_check_enabled
        self.parallelism = parallelism

    def run(self) -> VacuumResult:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.utility.vacuum", dryRun=self.dry_run,
                              path=self.delta_log.data_path):
            return self._run_impl()

    def _run_impl(self) -> VacuumResult:
        log = self.delta_log
        snapshot = log.update()
        metadata = snapshot.metadata
        tombstone_retention_ms = DeltaConfigs.TOMBSTONE_RETENTION.from_metadata(metadata)
        if self.retention_hours is None:
            retention_ms = tombstone_retention_ms
        else:
            retention_ms = int(self.retention_hours * MS_PER_HOUR)
        check_enabled = self.retention_check_enabled and bool(
            conf.get("delta.tpu.retentionDurationCheck.enabled", True)
        )
        if check_enabled and retention_ms < tombstone_retention_ms:
            raise errors.retention_period_too_short(
                self.retention_hours, tombstone_retention_ms / MS_PER_HOUR
            )
        cutoff = log.clock() - retention_ms

        # valid set: live files + tombstones younger than THIS vacuum's
        # horizon (snapshot.tombstones caches against an older clock reading)
        valid: Set[str] = set()

        def _dv_sidecar(action) -> Optional[str]:
            dv = getattr(action, "deletion_vector", None)
            if dv and dv.get("storageType") == "u":
                return dv.get("pathOrInlineDv")
            return None

        for f in snapshot.all_files:
            valid.add(urllib.parse.unquote(f.path))
            side = _dv_sidecar(f)
            if side:
                valid.add(side)
        for r in snapshot.tombstones_newer_than(cutoff):
            valid.add(urllib.parse.unquote(r.path))
            side = _dv_sidecar(r)
            if side:
                valid.add(side)

        data_path = log.data_path
        from delta_tpu.utils.telemetry import with_status

        all_files: List[str] = []
        all_dirs: List[str] = []

        def walk(rel_dir: str) -> None:
            abs_dir = os.path.join(data_path, rel_dir) if rel_dir else data_path
            try:
                entries = sorted(os.scandir(abs_dir), key=lambda e: e.name)
            except FileNotFoundError:
                return
            subdirs = []
            for e in entries:
                rel = f"{rel_dir}/{e.name}" if rel_dir else e.name
                if e.is_dir(follow_symlinks=False):
                    if not _is_hidden(e.name):
                        subdirs.append(rel)
                        all_dirs.append(rel)
                else:
                    if not _is_hidden(e.name):
                        all_files.append(rel)
            for s in subdirs:
                walk(s)

        # parallel top-level fan-out (the reference lists with a Spark job)
        with with_status("Listing files for VACUUM", table=data_path):
            top = []
            try:
                for e in sorted(os.scandir(data_path), key=lambda x: x.name):
                    if e.is_dir(follow_symlinks=False):
                        if not _is_hidden(e.name):
                            top.append(e.name)
                            all_dirs.append(e.name)
                    elif not _is_hidden(e.name):
                        all_files.append(e.name)
            except FileNotFoundError:
                pass
            if top:
                with ThreadPoolExecutor(
                        max_workers=self.parallelism,
                        thread_name_prefix="delta-vacuum-list") as pool:
                    list(pool.map(walk, top))

        to_delete: List[str] = []
        bytes_reclaimed = 0
        for rel in all_files:
            if rel in valid:
                continue
            abs_p = os.path.join(data_path, rel)
            try:
                st = os.stat(abs_p)
            except FileNotFoundError:
                continue
            if int(st.st_mtime * 1000) < cutoff:
                to_delete.append(rel)
                bytes_reclaimed += st.st_size

        if self.dry_run:
            return VacuumResult(
                path=data_path,
                files_deleted=len(to_delete),
                dirs_deleted=0,
                dry_run=True,
                retention_ms=retention_ms,
                deleted_paths=sorted(to_delete),
            )

        def rm(rel: str) -> None:
            try:
                os.remove(os.path.join(data_path, rel))
            except FileNotFoundError:
                pass

        # multi-host fan-out (§2.8 distributed GC): each process deletes
        # its strided slice of the candidates; single-host = identity
        from delta_tpu.parallel.distributed import host_partition

        my_deletes = host_partition(sorted(to_delete))
        if my_deletes:
            with ThreadPoolExecutor(
                    max_workers=self.parallelism,
                    thread_name_prefix="delta-vacuum-delete") as pool:
                list(pool.map(rm, my_deletes))

        # drop now-empty partition dirs (deepest first)
        dirs_deleted = 0
        for rel in sorted(all_dirs, key=lambda d: -d.count("/")):
            abs_d = os.path.join(data_path, rel)
            try:
                if not os.listdir(abs_d):
                    os.rmdir(abs_d)
                    dirs_deleted += 1
            except OSError:
                pass

        # feed the table-health doctor: vacuum recency + work done
        from delta_tpu.utils import telemetry

        telemetry.set_gauge("table.maintenance.lastVacuumTimestamp",
                            log.clock(), path=data_path)
        if to_delete:
            telemetry.bump_counter("maintenance.vacuum.filesDeleted",
                                   len(to_delete))
            telemetry.bump_counter("maintenance.vacuum.bytesReclaimed",
                                   bytes_reclaimed)

        return VacuumResult(
            path=data_path,
            files_deleted=len(to_delete),
            dirs_deleted=dirs_deleted,
            dry_run=False,
            retention_ms=retention_ms,
            deleted_paths=sorted(to_delete),
        )
