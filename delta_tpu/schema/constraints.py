"""Constraints & invariants — row-level write enforcement, vectorized.

The reference wraps the write plan in `DeltaInvariantCheckerExec`
(`constraints/DeltaInvariantCheckerExec.scala:42-99`) which codegens a per-row
check; violations raise `InvariantViolationException`. Here the checks are
columnar: each constraint compiles to one vectorized predicate over the whole
Arrow batch (Arrow C++ kernels; `expr.vectorized`), so enforcement costs one
scan per constraint instead of per-row interpretation.

Sources of constraints (`constraints/Constraints.scala:39-84`,
`constraints/Invariants.scala`):
* NOT NULL from non-nullable schema fields;
* CHECK constraints from table properties ``delta.constraints.<name>``;
* legacy invariants from schema field metadata key ``delta.invariants``.
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List

import pyarrow as pa
import pyarrow.compute as pc

from delta_tpu.expr import ir
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.protocol.actions import Metadata
from delta_tpu.schema.types import StructType
from delta_tpu.utils import errors
from delta_tpu.utils.errors import InvariantViolationError

__all__ = ["Constraint", "NotNull", "Check", "from_metadata", "enforce"]

CONSTRAINT_PROP_PREFIX = "delta.constraints."
INVARIANTS_META_KEY = "delta.invariants"


@dataclass(frozen=True)
class Constraint:
    name: str


@dataclass(frozen=True)
class NotNull(Constraint):
    column: str


@dataclass(frozen=True)
class Check(Constraint):
    expr: ir.Expression


def from_metadata(metadata: Metadata) -> List[Constraint]:
    """Collect every constraint the table carries (Constraints.scala:56-81)."""
    out: List[Constraint] = []
    schema: StructType = metadata.schema
    for f in schema.fields:
        if not f.nullable:
            out.append(NotNull(name=f"NOT NULL {f.name}", column=f.name))
        inv = (f.metadata or {}).get(INVARIANTS_META_KEY)
        if inv:
            rule = json.loads(inv) if isinstance(inv, str) else inv
            expr_sql = rule.get("expression", {}).get("expression")
            if expr_sql:
                out.append(Check(name=f"INVARIANT {expr_sql}", expr=parse_predicate(expr_sql)))
    for k, v in sorted((metadata.configuration or {}).items()):
        if k.lower().startswith(CONSTRAINT_PROP_PREFIX):
            out.append(Check(name=k[len(CONSTRAINT_PROP_PREFIX):], expr=parse_predicate(v)))
    return out


def enforce(constraints: List[Constraint], table: pa.Table) -> None:
    """Check every constraint against a write batch; raise on first violation
    with a sample row, mirroring `InvariantViolationException` messages."""
    if table.num_rows == 0:
        return
    from delta_tpu.expr.vectorized import evaluate

    for c in constraints:
        if isinstance(c, NotNull):
            col = None
            for name in table.column_names:
                if name.lower() == c.column.lower():
                    col = table.column(name)
                    break
            if col is None:
                raise InvariantViolationError(
                    f"Column {c.column} declared NOT NULL is missing from the data"
                )
            nulls = col.null_count
            if nulls:
                raise errors.not_null_invariant_violated(c.column, nulls)
        elif isinstance(c, Check):
            verdict = evaluate(c.expr, table)
            # violation = rows where the check is FALSE or NULL
            ok = pc.fill_null(pc.cast(verdict, pa.bool_()), False)
            bad = pc.sum(pc.invert(ok)).as_py() or 0
            if bad:
                idx = pc.index(ok, False).as_py()
                sample = {k: table.column(k)[idx].as_py() for k in table.column_names}
                raise errors.check_constraint_violated(c.name, c.expr.sql(), sample)
