"""SQL front end for the Delta statements — token-based recursive descent.

Scope is a superset of the reference grammar
(`antlr4/io/delta/sql/parser/DeltaSqlBase.g4:74-81`): VACUUM,
DESCRIBE HISTORY | DETAIL, GENERATE, CONVERT TO DELTA — plus the DML and
DDL the reference delegates to Spark SQL but a standalone engine must parse
itself: DELETE, UPDATE, MERGE INTO, CREATE [OR REPLACE] TABLE (columns,
generated columns, PARTITIONED BY, TBLPROPERTIES) and ALTER TABLE
(properties, columns incl. FIRST/AFTER, constraints).

The statement structure parses from the token stream (`sql/lexer.py` — a
real tokenizer, so keywords inside string literals, comments, and newlines
cannot mis-parse); embedded *expressions* (WHERE / ON / SET bodies / CHECK)
are sliced out of the source verbatim via token offsets and handed to the
expression parser (`expr/parser.py`), mirroring how the reference's
delegating parser hands expression text to Spark.

Table references are ``delta.`/path``` / ``parquet.`/path``` or a bare
quoted path, like the reference's path-based identifiers
(`DeltaTableIdentifier.scala`).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.schema.types import StructField, StructType
from delta_tpu.sql.lexer import Token, tokenize
from delta_tpu.utils.errors import DeltaAnalysisError, DeltaParseError
from delta_tpu.utils import errors

__all__ = ["execute_sql", "parse_statement"]


_TYPES = {
    "int": "IntegerType", "integer": "IntegerType", "bigint": "LongType",
    "long": "LongType", "smallint": "ShortType", "short": "ShortType",
    "tinyint": "ByteType", "byte": "ByteType", "string": "StringType",
    "varchar": "StringType", "double": "DoubleType", "float": "FloatType",
    "real": "FloatType", "boolean": "BooleanType", "bool": "BooleanType",
    "date": "DateType", "timestamp": "TimestampType", "binary": "BinaryType",
}


def _make_type(name: str, args: List[str]):
    import delta_tpu.schema.types as T

    low = name.lower()
    if low == "decimal":
        try:
            p = int(args[0]) if args else 10
            s = int(args[1]) if len(args) > 1 else 0
        except ValueError:
            raise errors.sql_invalid_decimal(args)
        return T.DecimalType(p, s)
    if low in ("char", "varchar") and args:
        try:
            n = int(args[0])
        except ValueError:
            raise errors.sql_unsupported_type(f"{name}({args[0]})")
        return T.CharType(n) if low == "char" else T.VarcharType(n)
    cls = _TYPES.get(low)
    if cls is None:
        raise errors.sql_unsupported_type(name)
    return getattr(T, cls)()


class _Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks: List[Token] = tokenize(sql)
        self.i = 0

    # -- token plumbing ----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "END":
            self.i += 1
        return t

    def at_end(self) -> bool:
        t = self.peek()
        return t.kind == "END" or (t.kind == "PUNCT" and t.value == ";")

    def accept_word(self, *words: str) -> Optional[Token]:
        if self.peek().is_word(*words):
            return self.next()
        return None

    def expect_word(self, *words: str) -> Token:
        t = self.next()
        if not t.is_word(*words):
            raise errors.sql_expected(' or '.join(words), t.start, t.value)
        return t

    def accept_punct(self, p: str) -> bool:
        t = self.peek()
        if t.kind == "PUNCT" and t.value == p:
            self.next()
            return True
        return False

    def expect_punct(self, p: str) -> None:
        t = self.next()
        if not (t.kind == "PUNCT" and t.value == p):
            raise errors.sql_expected(repr(p), t.start, t.value)

    def expect_end(self) -> None:
        if not self.at_end():
            t = self.peek()
            raise errors.sql_trailing_input(t.start, t.value)

    # -- shared pieces -----------------------------------------------------

    def table_path(self) -> Tuple[str, str]:
        """[delta|parquet] . `path` | `path` | 'path' | bare path | name.

        Returns ("path", p) for explicit paths and ("name", n) for bare
        identifiers (resolved through the catalog at run time)."""
        t = self.next()
        if t.kind == "WORD" and t.value.lower() in ("delta", "parquet") and (
            self.peek().kind == "PUNCT" and self.peek().value == "."
        ):
            self.next()  # '.'
            ident = self.next()
            if ident.kind not in ("QUOTED_IDENT", "WORD", "STRING"):
                raise errors.sql_expected_table_identifier(t.value, ident.start)
            # delta.`/p` is a path; delta.name is a catalog name
            if ident.kind == "WORD":
                return ("name", ident.value)
            return ("path", ident.value)
        if t.kind in ("QUOTED_IDENT", "STRING"):
            return ("path", t.value)
        path_start = (t.kind == "WORD") or (
            t.kind == "PUNCT" and t.value in "./"
        )
        if not path_start:
            raise errors.sql_expected('table reference', t.start)
        # greedy run of ADJACENT tokens (no whitespace) forming a bare path
        # (/tmp/x, ./rel/x) or a dotted catalog name
        text = t.value
        end = t.end
        while True:
            nxt = self.peek()
            if nxt.kind == "END" or nxt.start != end:
                break
            if nxt.kind in ("WORD", "NUMBER") or (
                nxt.kind == "PUNCT" and nxt.value in "./-"
            ):
                text += nxt.value
                end = nxt.end
                self.next()
            else:
                break
        return ("path", text) if "/" in text else ("name", text)

    def ident(self) -> str:
        t = self.next()
        if t.kind in ("WORD", "QUOTED_IDENT"):
            return t.value
        raise errors.sql_expected('identifier', t.start)

    def slice_expr(
        self, stop_words: Tuple[str, ...] = (), stop_comma: bool = False
    ) -> Optional[str]:
        """Source text from here to the next boundary: a depth-0 stop
        keyword, an unbalanced ')', a depth-0 comma (when ``stop_comma``),
        ';' or end of input. CASE...END bodies are opaque — their WHEN/THEN
        keywords never terminate the slice. Returns None when empty."""
        depth = 0
        case_depth = 0
        start_tok = self.peek()
        last_end = start_tok.start
        while True:
            t = self.peek()
            if t.kind == "END" or (
                t.kind == "PUNCT" and t.value == ";" and depth == 0
            ):
                break
            if t.kind == "PUNCT" and t.value == "(":
                depth += 1
            elif t.kind == "PUNCT" and t.value == ")":
                if depth == 0:
                    break
                depth -= 1
            elif t.kind == "WORD" and t.value.upper() == "CASE":
                case_depth += 1
            elif t.kind == "WORD" and t.value.upper() == "END" and case_depth > 0:
                case_depth -= 1
            elif depth == 0 and case_depth == 0:
                if stop_comma and t.kind == "PUNCT" and t.value == ",":
                    break
                if t.kind == "WORD" and t.value.upper() in stop_words:
                    break
            self.next()
            last_end = t.end
        text = self.sql[start_tok.start:last_end].strip()
        return text or None

    def number(self, as_int: bool = False):
        t = self.next()
        if t.kind != "NUMBER":
            raise errors.sql_expected('a number', t.start)
        try:
            return int(t.value) if as_int else float(t.value)
        except ValueError:
            raise errors.sql_invalid_number(t.value, 'integer' if as_int else 'number', t.start)

    def string_or_number(self) -> str:
        t = self.next()
        if t.kind in ("STRING", "NUMBER", "WORD"):
            return t.value
        raise errors.sql_expected('literal', t.start)

    def properties(self) -> Dict[str, str]:
        """( 'k' = 'v' [, ...] )"""
        self.expect_punct("(")
        out: Dict[str, str] = {}
        while True:
            key = self.string_or_number()
            # dotted bare keys: delta.appendOnly
            while self.accept_punct("."):
                key += "." + self.string_or_number()
            self.expect_punct("=")
            out[key] = self.string_or_number()
            if self.accept_punct(")"):
                return out
            self.expect_punct(",")

    def column_type(self):
        name = self.ident()
        args: List[str] = []
        if self.accept_punct("("):
            while not self.accept_punct(")"):
                t = self.next()
                if t.kind == "NUMBER":
                    args.append(t.value)
                elif not (t.kind == "PUNCT" and t.value == ","):
                    raise errors.sql_bad_type_argument(t.start, t.value)
        return _make_type(name, args)

    def column_def(self) -> StructField:
        """name TYPE [GENERATED ALWAYS AS (expr)] [NOT NULL] [COMMENT 's'].
        Dotted names (``s.x``) address nested structs (ALTER ADD COLUMNS)."""
        name = self.ident()
        while self.accept_punct("."):
            name += "." + self.ident()
        dtype = self.column_type()
        nullable = True
        metadata: Dict[str, Any] = {}
        while True:
            if self.accept_word("NOT"):
                self.expect_word("NULL")
                nullable = False
            elif self.accept_word("COMMENT"):
                t = self.next()
                if t.kind != "STRING":
                    raise errors.sql_expected('comment string', t.start)
                metadata["comment"] = t.value
            elif self.accept_word("GENERATED"):
                self.expect_word("ALWAYS")
                self.expect_word("AS")
                self.expect_punct("(")
                expr = self.slice_expr()
                if expr is None:
                    raise DeltaParseError("Empty generation expression")
                self.expect_punct(")")
                from delta_tpu.schema.generated import GENERATION_EXPRESSION_KEY

                metadata[GENERATION_EXPRESSION_KEY] = expr
            else:
                break
        return StructField(name, dtype, nullable, metadata)

    def column_name_list(self) -> List[str]:
        self.expect_punct("(")
        out = [self.ident()]
        while self.accept_punct(","):
            out.append(self.ident())
        self.expect_punct(")")
        return out


def _log_for(ref: Tuple[str, str]) -> DeltaLog:
    kind, value = ref
    if kind == "name":
        from delta_tpu.catalog.catalog import resolve_identifier

        return DeltaLog.for_table(resolve_identifier(value))
    return DeltaLog.for_table(value)


def parse_statement(sql: str):
    """Parse one statement into a zero-argument runner (late-bound command
    construction so parse errors surface before any table IO)."""
    p = _Parser(sql)
    t = p.peek()
    if t.kind != "WORD":
        raise errors.sql_expected_statement(t.value)
    head = t.value.upper()
    if head == "SELECT":
        return _select(p)
    if head == "INSERT":
        return _insert(p)
    if head == "VACUUM":
        return _vacuum(p)
    if head == "DESCRIBE" or head == "DESC":
        return _describe(p)
    if head == "GENERATE":
        return _generate(p)
    if head == "CONVERT":
        return _convert(p)
    if head == "DELETE":
        return _delete(p)
    if head == "UPDATE":
        return _update(p)
    if head == "MERGE":
        return _merge(p)
    if head == "CREATE":
        return _create(p)
    if head == "ALTER":
        return _alter(p)
    if head == "RESTORE":
        return _restore(p)
    raise errors.unsupported_sql_statement(sql)


def execute_sql(sql: str) -> Any:
    """Parse and run one Delta statement; returns the command's result."""
    return parse_statement(sql)()


# -- statement parsers -------------------------------------------------------


def _parse_aggregate(text: str):
    """(func, inner_sql|'*') when ``text`` is a top-level aggregate call
    (COUNT/SUM/AVG/MIN/MAX), else None."""
    import re as _re

    m = _re.match(r"(?is)^\s*(count|sum|avg|min|max)\s*\((.*)\)\s*$", text)
    if not m:
        return None
    inner = m.group(2).strip()
    # the closing paren must match the opening one (reject `min(a) + max(b)`)
    depth = 0
    for ch in m.group(2):
        depth += ch == "("
        depth -= ch == ")"
        if depth < 0:
            return None
    return m.group(1).lower(), inner


def _select(p: _Parser):
    """SELECT <*|expr|aggregate [AS alias], ...> FROM <table>
    [VERSION AS OF n | TIMESTAMP AS OF ts] [WHERE pred]
    [GROUP BY col, ...] [ORDER BY col [ASC|DESC], ...] [LIMIT n] — the read
    surface reference users get from Spark SQL (`DeltaTableV2` + relation),
    routed through the engine's scan planner (`exec/scan.scan_to_table`).
    Aggregates: COUNT(*)/COUNT/SUM/AVG/MIN/MAX, optionally grouped. Returns
    an Arrow table."""
    import re as _re

    p.expect_word("SELECT")
    star = False
    items: List[Tuple[str, Optional[str]]] = []  # (expr sql, alias)
    if p.accept_punct("*"):
        star = True
    else:
        while True:
            text = p.slice_expr(stop_words=("FROM",), stop_comma=True)
            if text is None:
                raise errors.sql_expected("projection expression",
                                          p.peek().start)
            m = _re.search(r"(?is)\s+as\s+([A-Za-z_][A-Za-z_0-9]*|`[^`]+`)\s*$",
                           text)
            alias = None
            if m:
                alias = m.group(1).strip("`")
                text = text[: m.start()]
            items.append((text.strip(), alias))
            if not p.accept_punct(","):
                break
    p.expect_word("FROM")
    path = p.table_path()
    version = timestamp = None
    if p.accept_word("VERSION"):
        p.expect_word("AS")
        p.expect_word("OF")
        version = int(p.number(as_int=True))
    elif p.accept_word("TIMESTAMP"):
        p.expect_word("AS")
        p.expect_word("OF")
        t = p.next()
        if t.kind not in ("STRING", "NUMBER"):
            raise errors.sql_expected("timestamp literal", t.start)
        timestamp = t.value
    cond = None
    if p.accept_word("WHERE"):
        cond = p.slice_expr(stop_words=("GROUP", "ORDER", "LIMIT"))
        if cond is None:
            raise DeltaParseError("Empty WHERE clause")
    group_by: List[str] = []
    if p.accept_word("GROUP"):
        p.expect_word("BY")
        while True:
            group_by.append(p.ident())
            if not p.accept_punct(","):
                break
    order: List[Tuple[str, str]] = []
    if p.accept_word("ORDER"):
        p.expect_word("BY")
        while True:
            col = p.ident()
            direction = "ascending"
            if p.accept_word("DESC"):
                direction = "descending"
            else:
                p.accept_word("ASC")
            order.append((col, direction))
            if not p.accept_punct(","):
                break
    limit = None
    if p.accept_word("LIMIT"):
        limit = int(p.number(as_int=True))
    p.expect_end()

    def run():
        from delta_tpu.exec.scan import scan_to_table
        from delta_tpu.expr import ir as _ir
        from delta_tpu.expr.parser import parse_expression
        from delta_tpu.expr.vectorized import evaluate

        log = _log_for(path)
        sel_version, sel_timestamp = version, timestamp
        if not log.table_exists and path[0] == "path":
            # `delta.\`/t@v3\`` embedded time travel (reads only)
            from delta_tpu.log.deltalog import extract_path_time_travel

            spec = extract_path_time_travel(path[1])
            if spec is not None:
                base_log = DeltaLog.for_table(spec[0])
                if base_log.table_exists:
                    log = base_log
                    if sel_version is None and sel_timestamp is None:
                        sel_version, sel_timestamp = spec[1], spec[2]
        snap = log.snapshot_for(sel_version, sel_timestamp)
        schema_cols = [f.name for f in snap.metadata.schema.fields]
        lower = {c.lower(): c for c in schema_cols}
        parsed_items = None
        read_cols = None
        has_agg = False
        if not star:
            # projection pushdown: decode only the referenced columns
            parsed_items = []
            needed = set()
            for text, alias in items:
                key = text.strip("`").lower()
                agg = _parse_aggregate(text)
                if agg is not None:
                    func, inner = agg
                    if inner == "*":
                        if func != "count":
                            raise errors.sql_star_only_in_count(func)
                        inner_e = None
                    else:
                        inner_e = parse_expression(inner)
                        for r in _ir.references(inner_e):
                            if r.lower() in lower:
                                needed.add(lower[r.lower()])
                    parsed_items.append(
                        ("agg", (func, inner_e), alias or text))
                    has_agg = True
                elif key in lower:
                    parsed_items.append(("col", lower[key], alias))
                    needed.add(lower[key])
                else:
                    e = parse_expression(text)
                    parsed_items.append(("expr", e, alias or text))
                    for r in _ir.references(e):
                        if r.lower() in lower:
                            needed.add(lower[r.lower()])
            for g in group_by:
                if g.strip("`").lower() in lower:
                    needed.add(lower[g.strip("`").lower()])
            for col, _dir in order:
                if col.strip("`").lower() in lower:
                    needed.add(lower[col.strip("`").lower()])
            if needed:
                read_cols = [c for c in schema_cols if c in needed]
            elif has_agg and schema_cols:
                # aggregate-only projection (e.g. COUNT(*)): one narrow
                # column is enough to carry the row count
                read_cols = [schema_cols[0]]
            else:
                read_cols = None
        if (has_agg or group_by) and star:
            raise DeltaParseError("SELECT * cannot be combined with GROUP BY")
        table = scan_to_table(snap, filters=[cond] if cond else (),
                              columns=read_cols)
        pre_sort = False
        hidden: List[str] = []
        if has_agg or group_by:
            order_keys = [c.strip("`").lower() for c, _d in order]
            out, hidden = _run_aggregate(table, parsed_items, group_by,
                                         order_keys, evaluate)
        else:
            # ORDER BY resolves against source columns first (SQL allows
            # sorting by non-projected columns), then aliases
            src_lower = {c.lower(): c for c in table.column_names}
            pre_sort = bool(order) and all(
                c.strip("`").lower() in src_lower for c, _d in order)
            if pre_sort:
                table = table.sort_by([
                    (src_lower[c.strip("`").lower()], d) for c, d in order])
            if parsed_items is not None:
                import pyarrow as pa

                arrays, names = [], []
                for kind, payload, alias in parsed_items:
                    if kind == "col":
                        arrays.append(table.column(payload))
                        names.append(alias or payload)
                    else:
                        arrays.append(evaluate(payload, table))
                        names.append(alias)
                # from_arrays keeps duplicate output names (SELECT id, id)
                out = pa.Table.from_arrays(
                    [a.combine_chunks() if isinstance(a, pa.ChunkedArray) else a
                     for a in arrays], names=names)
            else:
                out = table
        if order and not pre_sort:
            out_lower = {c.lower(): c for c in out.column_names}
            keys = []
            for col, direction in order:
                real = out_lower.get(col.strip("`").lower())
                if real is None:
                    raise errors.column_not_found_in_table(col, out.column_names)
                keys.append((real, direction))
            out = out.sort_by(keys)
        if hidden:
            # group keys carried only for ORDER BY drop out of the result
            out = out.drop_columns(hidden)
        if limit is not None:
            out = out.slice(0, limit)
        return out

    return run


def _run_aggregate(table, parsed_items, group_by, order_keys, evaluate):
    """Execute the aggregate leg of a SELECT: non-aggregate items must be
    GROUP BY keys; aggregates compute over Arrow's hash aggregation (or
    whole-table kernels when ungrouped). Returns (table, hidden) where
    ``hidden`` are group keys appended ONLY so ORDER BY can resolve them —
    the caller drops them after sorting."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc

    tbl_lower = {c.lower(): c for c in table.column_names}
    group_keys = []
    for g in group_by:
        real = tbl_lower.get(g.strip("`").lower())
        if real is None:
            raise errors.column_not_found_in_table(g, table.column_names)
        group_keys.append(real)
    group_set = {g.lower() for g in group_keys}

    work_cols: dict = {g: table.column(g) for g in group_keys}
    aggs = []   # (workname, arrow_func, outname) in projection order
    layout = []  # ("key", real, outname) | ("agg", workname, outname)
    fn_map = {"count": "count", "sum": "sum", "avg": "mean",
              "min": "min", "max": "max"}
    for i, (kind, payload, alias) in enumerate(parsed_items):
        if kind == "col":
            if payload.lower() not in group_set:
                raise errors.sql_column_needs_group_by(payload)
            layout.append(("key", payload, alias or payload))
        elif kind == "expr":
            raise DeltaParseError(
                "Non-aggregate expressions in an aggregate SELECT must be "
                "GROUP BY columns"
            )
        else:
            func, inner_e = payload
            work = f"__agg{i}"
            if inner_e is None:  # COUNT(*): count a non-null constant
                work_cols[work] = pa.chunked_array(
                    [pa.array(np.ones(table.num_rows, np.int8))])
            else:
                work_cols[work] = evaluate(inner_e, table)
            aggs.append((work, fn_map[func], alias))
            layout.append(("agg", work, alias))

    work = pa.table(work_cols)
    if group_keys:
        res = work.group_by(group_keys).aggregate(
            [(w, f) for w, f, _ in aggs])
        agg_out = {w: f"{w}_{f}" for w, f, _ in aggs}
    else:
        cols = {}
        for w, f, _ in aggs:
            col = work.column(w)
            if f == "count":
                cols[f"{w}_{f}"] = pa.array([len(col) - col.null_count])
            else:
                kern = {"sum": pc.sum, "mean": pc.mean,
                        "min": pc.min, "max": pc.max}[f]
                # the kernel scalar carries the aggregate's natural type even
                # when its value is null (empty table) — keep it, or an
                # all-null untyped column breaks INSERT...SELECT casts
                s = kern(col)
                cols[f"{w}_{f}"] = pa.array([s.as_py()], type=s.type)
        res = pa.table(cols)
        agg_out = {w: f"{w}_{f}" for w, f, _ in aggs}

    # ORDER BY may reference a group key the projection dropped: carry it
    # through under its real name and let the caller drop it after sorting
    hidden = []
    projected = {outname.lower() for _k, _n, outname in layout}
    for g in group_keys:
        if g.lower() not in projected and g.lower() in order_keys:
            layout.append(("key", g, g))
            hidden.append(g)
    arrays, names = [], []
    for kind, name, outname in layout:
        src = name if kind == "key" else agg_out[name]
        col = res.column(src)
        arrays.append(col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col)
        names.append(outname)
    return pa.Table.from_arrays(arrays, names=names), hidden


def _insert(p: _Parser):
    """INSERT INTO|OVERWRITE <table> [(col, ...)] VALUES (...), ... |
    SELECT ... — the write companion of the SELECT surface (Spark handles
    this for the reference; here it routes through WriteIntoDelta)."""
    p.expect_word("INSERT")
    mode = "append"
    if p.accept_word("OVERWRITE"):
        mode = "overwrite"
        p.accept_word("INTO", "TABLE")
    else:
        p.expect_word("INTO")
    path = p.table_path()
    cols: Optional[List[str]] = None
    if p.accept_punct("("):
        cols = []
        while True:
            cols.append(p.ident())
            if p.accept_punct(")"):
                break
            p.expect_punct(",")
    if p.peek().is_word("SELECT"):
        select_run = _select(p)

        def run():
            from delta_tpu.commands.write import WriteIntoDelta

            log = _log_for(path)
            data = select_run()
            if cols is not None:
                if len(cols) != data.num_columns:
                    raise errors.sql_insert_arity_mismatch(
                        len(cols), data.num_columns)
                data = data.rename_columns(cols)
            else:
                # INSERT ... SELECT binds positionally: the projection must
                # cover the whole target schema (silent null-fill of missing
                # columns is a data bug, not a convenience)
                target = [f.name for f in log.update().metadata.schema.fields]
                if len(target) != data.num_columns:
                    raise errors.sql_insert_arity_mismatch(
                        len(target), data.num_columns)
                data = data.rename_columns(target)
            return WriteIntoDelta(log, mode, data).run()

        return run
    p.expect_word("VALUES")
    rows: List[List[str]] = []
    while True:
        p.expect_punct("(")
        vals: List[str] = []
        while True:
            v = p.slice_expr(stop_comma=True)
            if v is None:
                raise DeltaParseError("Empty VALUES expression")
            vals.append(v)
            if p.accept_punct(")"):
                break
            p.expect_punct(",")
        rows.append(vals)
        if not p.accept_punct(","):
            break
    p.expect_end()
    widths = {len(r) for r in rows}
    if len(widths) != 1:
        raise errors.sql_insert_arity_mismatch(min(widths), max(widths))
    if cols is not None and len(cols) != next(iter(widths)):
        raise errors.sql_insert_arity_mismatch(len(cols), next(iter(widths)))

    def run():
        import pyarrow as pa

        from delta_tpu.commands.write import WriteIntoDelta
        from delta_tpu.expr.parser import parse_expression
        from delta_tpu.expr.vectorized import arrow_type_for

        log = _log_for(path)
        schema = log.update().metadata.schema
        names = cols if cols is not None else [f.name for f in schema.fields]
        # parse time already checked the explicit-column-list arity; this
        # guards the schema-width binding when no column list was given
        if cols is None and len(names) != next(iter(widths)):
            raise errors.sql_insert_arity_mismatch(len(names), next(iter(widths)))
        types = {f.name.lower(): arrow_type_for(f.data_type) for f in schema.fields}
        arrays = {}
        for j, name in enumerate(names):
            vals = [parse_expression(r[j]).eval({}) for r in rows]
            at = types.get(name.lower())
            arrays[name] = pa.array(vals, type=at)
        data = pa.table(arrays)
        return WriteIntoDelta(log, mode, data).run()

    return run


def _vacuum(p: _Parser):
    p.expect_word("VACUUM")
    path = p.table_path()
    hours = None
    dry = False
    if p.accept_word("RETAIN"):
        hours = p.number()
        p.expect_word("HOURS", "HOUR")
    if p.accept_word("DRY"):
        p.expect_word("RUN")
        dry = True
    p.expect_end()

    def run():
        from delta_tpu.commands.vacuum import VacuumCommand

        return VacuumCommand(_log_for(path), hours, dry_run=dry).run()

    return run


def _restore(p: _Parser):
    """``RESTORE TABLE t TO VERSION AS OF n`` /
    ``RESTORE TABLE t TO TIMESTAMP AS OF 'ts'`` (beyond the reference
    grammar; modern Delta's restore statement)."""
    p.expect_word("RESTORE")
    p.accept_word("TABLE")
    path = p.table_path()
    p.expect_word("TO")
    which = p.expect_word("VERSION", "TIMESTAMP").value.upper()
    p.expect_word("AS")
    p.expect_word("OF")
    if which == "VERSION":
        version, timestamp = p.number(as_int=True), None
    else:
        version, timestamp = None, p.string_or_number()
    p.expect_end()

    def run():
        from delta_tpu.commands.restore import RestoreCommand

        cmd = RestoreCommand(_log_for(path), version=version, timestamp=timestamp)
        cmd.run()
        return cmd.metrics

    return run


def _describe(p: _Parser):
    p.expect_word("DESCRIBE", "DESC")
    which = p.expect_word("HISTORY", "DETAIL").value.upper()
    path = p.table_path()
    limit = None
    if which == "HISTORY" and p.accept_word("LIMIT"):
        limit = p.number(as_int=True)
    p.expect_end()

    def run():
        from delta_tpu.commands.describe import describe_detail, describe_history

        log = _log_for(path)
        if which == "HISTORY":
            return describe_history(log, limit)
        return describe_detail(log)

    return run


def _generate(p: _Parser):
    p.expect_word("GENERATE")
    t = p.next()
    mode = t.value if t.kind in ("WORD", "STRING") else None
    if mode is None or mode.lower() != "symlink_format_manifest":
        raise errors.unsupported_generate_mode(mode)
    p.expect_word("FOR")
    p.expect_word("TABLE")
    path = p.table_path()
    p.expect_end()

    def run():
        from delta_tpu.hooks.symlink_manifest import generate_full_manifest

        return generate_full_manifest(_log_for(path))

    return run


def _convert(p: _Parser):
    p.expect_word("CONVERT")
    p.expect_word("TO")
    p.expect_word("DELTA")
    path = p.table_path()
    part_schema = None
    if p.accept_word("PARTITIONED"):
        p.expect_word("BY")
        p.expect_punct("(")
        fields = [p.column_def()]
        while p.accept_punct(","):
            fields.append(p.column_def())
        p.expect_punct(")")
        part_schema = StructType(fields)
    p.expect_end()

    def run():
        from delta_tpu.commands.convert import ConvertToDeltaCommand

        return ConvertToDeltaCommand(
            _log_for(path), partition_schema=part_schema
        ).run()

    return run


def _delete(p: _Parser):
    p.expect_word("DELETE")
    p.expect_word("FROM")
    path = p.table_path()
    cond = None
    if p.accept_word("WHERE"):
        cond = p.slice_expr()
        if cond is None:
            raise DeltaParseError("Empty WHERE clause")
    p.expect_end()

    def run():
        from delta_tpu.commands.delete import DeleteCommand

        cmd = DeleteCommand(_log_for(path), cond)
        cmd.run()
        return cmd.metrics

    return run


def _set_assignments(p: _Parser, stop_words: Tuple[str, ...]) -> Dict[str, str]:
    """col = expr [, col = expr ...] with verbatim expression slices."""
    sets: Dict[str, str] = {}
    while True:
        col = p.ident()
        while p.accept_punct("."):
            col += "." + p.ident()
        p.expect_punct("=")
        expr = p.slice_expr(stop_words, stop_comma=True)
        if expr is None:
            raise errors.sql_empty_set_expression(col)
        sets[col] = expr
        if not p.accept_punct(","):
            return sets


def _update(p: _Parser):
    p.expect_word("UPDATE")
    path = p.table_path()
    p.expect_word("SET")
    sets = _set_assignments(p, ("WHERE",))
    cond = None
    if p.accept_word("WHERE"):
        cond = p.slice_expr()
        if cond is None:
            raise DeltaParseError("Empty WHERE clause")
    p.expect_end()

    def run():
        from delta_tpu.commands.update import UpdateCommand

        cmd = UpdateCommand(_log_for(path), sets, cond)
        cmd.run()
        return cmd.metrics

    return run


def _merge(p: _Parser):
    from delta_tpu.commands.merge import MergeClause

    p.expect_word("MERGE")
    p.expect_word("INTO")
    target_path = p.table_path()
    target_alias = None
    if p.accept_word("AS"):
        target_alias = p.ident()
    elif p.peek().kind == "WORD" and not p.peek().is_word("USING"):
        target_alias = p.ident()
    p.expect_word("USING")
    source_path = p.table_path()
    source_alias = None
    if p.accept_word("AS"):
        source_alias = p.ident()
    elif p.peek().kind == "WORD" and not p.peek().is_word("ON"):
        source_alias = p.ident()
    p.expect_word("ON")
    cond = p.slice_expr(("WHEN",))
    if cond is None:
        raise DeltaParseError("Empty MERGE condition")

    matched: List[MergeClause] = []
    not_matched: List[MergeClause] = []
    while p.accept_word("WHEN"):
        negated = False
        if p.accept_word("NOT"):
            negated = True
        p.expect_word("MATCHED")
        clause_cond = None
        if p.accept_word("AND"):
            clause_cond = p.slice_expr(("THEN",))
            if clause_cond is None:
                raise DeltaParseError("Empty clause condition")
        p.expect_word("THEN")
        if negated:
            p.expect_word("INSERT")
            if p.accept_punct("*"):
                not_matched.append(
                    MergeClause("insert", condition=clause_cond, assignments=None)
                )
            else:
                cols = p.column_name_list()
                p.expect_word("VALUES")
                p.expect_punct("(")
                vals: List[str] = []
                while True:
                    v = p.slice_expr(stop_comma=True)
                    if v is None:
                        raise DeltaParseError("Empty VALUES expression")
                    vals.append(v)
                    if p.accept_punct(")"):
                        break
                    p.expect_punct(",")
                if len(cols) != len(vals):
                    raise errors.sql_insert_arity_mismatch(len(cols), len(vals))
                not_matched.append(
                    MergeClause(
                        "insert", condition=clause_cond,
                        assignments=dict(zip(cols, vals)),
                    )
                )
        elif p.accept_word("DELETE"):
            matched.append(MergeClause("delete", condition=clause_cond))
        else:
            p.expect_word("UPDATE")
            p.expect_word("SET")
            if p.accept_punct("*"):
                matched.append(
                    MergeClause("update", condition=clause_cond, assignments=None)
                )
            else:
                sets = _set_assignments(p, ("WHEN",))
                matched.append(
                    MergeClause("update", condition=clause_cond, assignments=sets)
                )
    p.expect_end()

    def run():
        from delta_tpu.commands.merge import MergeIntoCommand
        from delta_tpu.exec.scan import scan_to_table

        source = scan_to_table(_log_for(source_path).update())
        cmd = MergeIntoCommand(
            _log_for(target_path), source, cond,
            matched, not_matched,
            source_alias=source_alias, target_alias=target_alias,
        )
        cmd.run()
        return cmd.metrics

    return run


def _create(p: _Parser):
    p.expect_word("CREATE")
    replace = False
    if p.accept_word("OR"):
        p.expect_word("REPLACE")
        replace = True
    p.expect_word("TABLE")
    if_not_exists = False
    if p.accept_word("IF"):
        p.expect_word("NOT")
        p.expect_word("EXISTS")
        if_not_exists = True
    path = p.table_path()
    if p.peek().is_word("SHALLOW"):
        # CREATE TABLE <dst> SHALLOW CLONE <src> [VERSION|TIMESTAMP AS OF]
        p.expect_word("SHALLOW")
        p.expect_word("CLONE")
        src = p.table_path()
        version = timestamp = None
        if p.accept_word("VERSION"):
            p.expect_word("AS")
            p.expect_word("OF")
            version = int(p.number(as_int=True))
        elif p.accept_word("TIMESTAMP"):
            p.expect_word("AS")
            p.expect_word("OF")
            t = p.next()
            if t.kind not in ("STRING", "NUMBER"):
                raise errors.sql_expected("timestamp literal", t.start)
            timestamp = t.value
        p.expect_end()

        def run_clone():
            from delta_tpu.commands.clone import CloneCommand

            kind, value = path
            if kind != "path":
                raise errors.create_table_needs_location(value)
            cmd = CloneCommand(
                _log_for(src), value, version=version, timestamp=timestamp,
            )
            cmd.run()
            return cmd.metrics

        return run_clone
    fields: List[StructField] = []
    if p.accept_punct("("):
        fields.append(p.column_def())
        while p.accept_punct(","):
            fields.append(p.column_def())
        p.expect_punct(")")
    if p.accept_word("USING"):
        fmt = p.ident()
        if fmt.lower() != "delta":
            raise errors.unsupported_table_format(fmt)
    part_cols: List[str] = []
    props: Dict[str, str] = {}
    comment = None
    location = None
    while not p.at_end():
        if p.accept_word("PARTITIONED"):
            p.expect_word("BY")
            part_cols = p.column_name_list()
        elif p.accept_word("TBLPROPERTIES"):
            props = p.properties()
        elif p.accept_word("COMMENT"):
            t = p.next()
            if t.kind != "STRING":
                raise errors.sql_expected('comment string', t.start)
            comment = t.value
        elif p.accept_word("LOCATION"):
            t = p.next()
            if t.kind != "STRING":
                raise errors.sql_expected('location string', t.start)
            location = t.value
        else:
            t = p.peek()
            raise errors.sql_unexpected_input(t.start, t.value)
    p.expect_end()
    if replace and if_not_exists:
        raise DeltaParseError("CREATE OR REPLACE cannot have IF NOT EXISTS")

    def run():
        from delta_tpu.commands.create import CreateDeltaTableCommand

        kind, value = path
        register_name = None
        if kind == "name":
            from delta_tpu.catalog.catalog import default_catalog

            cat = default_catalog()
            if location is not None:
                target = location
                register_name = value
            elif cat.table_exists(value):
                target = cat.table_path(value)
            else:
                raise errors.create_table_needs_location(value)
        else:
            target = location or value
        mode = "create_or_replace" if replace else (
            "create_if_not_exists" if if_not_exists else "create"
        )
        result = CreateDeltaTableCommand(
            DeltaLog.for_table(target),
            schema=StructType(fields) if fields else None,
            mode=mode,
            partition_columns=part_cols,
            configuration=props or None,
            name=register_name,
            description=comment,
        ).run()
        if register_name is not None:
            from delta_tpu.catalog.catalog import default_catalog

            cat = default_catalog()
            if not cat.table_exists(register_name):
                cat.register(register_name, target)
        return result

    return run


def _alter(p: _Parser):
    from delta_tpu.commands import alter as alter_mod

    p.expect_word("ALTER")
    p.expect_word("TABLE")
    path = p.table_path()

    if p.accept_word("SET"):
        p.expect_word("TBLPROPERTIES")
        props = p.properties()
        p.expect_end()
        return lambda: alter_mod.set_table_properties(
            _log_for(path), props
        )
    if p.accept_word("UNSET"):
        p.expect_word("TBLPROPERTIES")
        if_exists = False
        if p.accept_word("IF"):
            p.expect_word("EXISTS")
            if_exists = True
        p.expect_punct("(")
        keys = [p.string_or_number()]
        while p.accept_punct(","):
            keys.append(p.string_or_number())
        p.expect_punct(")")
        p.expect_end()
        return lambda: alter_mod.unset_table_properties(
            _log_for(path), keys, if_exists=if_exists
        )
    if p.accept_word("ADD"):
        if p.accept_word("COLUMNS", "COLUMN"):
            p.expect_punct("(")
            specs: List[Tuple[StructField, Any]] = []
            while True:
                f = p.column_def()
                pos = None
                if p.accept_word("FIRST"):
                    pos = "first"
                elif p.accept_word("AFTER"):
                    pos = ("after", p.ident())
                specs.append((f, pos))
                if p.accept_punct(")"):
                    break
                p.expect_punct(",")
            p.expect_end()

            def run_add():
                positions = {f.name: pos for f, pos in specs if pos is not None}
                return alter_mod.add_columns(
                    _log_for(path), [f for f, _ in specs],
                    positions=positions or None,
                )

            return run_add
        p.expect_word("CONSTRAINT")
        name = p.ident()
        p.expect_word("CHECK")
        p.expect_punct("(")
        expr = p.slice_expr()
        if expr is None:
            raise DeltaParseError("Empty CHECK expression")
        p.expect_punct(")")
        p.expect_end()
        return lambda: alter_mod.add_constraint(_log_for(path), name, expr)
    if p.accept_word("DROP"):
        p.expect_word("CONSTRAINT")
        if_exists = False
        if p.accept_word("IF"):
            p.expect_word("EXISTS")
            if_exists = True
        name = p.ident()
        p.expect_end()
        return lambda: alter_mod.drop_constraint(
            _log_for(path), name, if_exists=if_exists
        )
    if p.accept_word("ALTER", "CHANGE"):
        p.accept_word("COLUMN")
        name = p.ident()
        while p.accept_punct("."):
            name += "." + p.ident()
        new_type = None
        comment = None
        position = None
        nullable = None
        while not p.at_end():
            if p.accept_word("TYPE"):
                new_type = p.column_type()
            elif p.accept_word("COMMENT"):
                t = p.next()
                if t.kind != "STRING":
                    raise errors.sql_expected('comment string', t.start)
                comment = t.value
            elif p.accept_word("FIRST"):
                position = "first"
            elif p.accept_word("AFTER"):
                position = ("after", p.ident())
            elif p.accept_word("DROP"):
                p.expect_word("NOT")
                p.expect_word("NULL")
                nullable = True
            elif p.accept_word("SET"):
                p.expect_word("NOT")
                p.expect_word("NULL")
                nullable = False
            else:
                t = p.peek()
                raise errors.sql_unexpected_input(t.start, t.value)
        p.expect_end()
        return lambda: alter_mod.change_column(
            _log_for(path), name, new_type=new_type,
            nullable=nullable, comment=comment, position=position,
        )
    t = p.peek()
    raise errors.sql_unsupported_alter_action(t.start)
