"""Combined-corruption recovery matrix (satellite of the fault-injection PR).

Single-corruption fallbacks are pinned in test_hardening.py; this matrix
corrupts ``_last_checkpoint`` AND the checkpoint it points at TOGETHER, and
verifies recovery on BOTH checkpoint read paths:

* the columnar path — ``Snapshot._columnar`` segment decode with
  checkpoint exclusion + re-listing (`log/snapshot.py`), and
* the dataclass path — ``read_checkpoint_actions`` + ``LogReplay`` over
  the recovered segment (`log/checkpoints.py` / `log/replay.py`).
"""
import glob
import json
import os

import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.log import checkpoints as ckpt_mod
from delta_tpu.log import snapshot_management as sm
from delta_tpu.log.replay import LogReplay
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import actions_from_lines
from delta_tpu.utils.config import conf

N_COMMITS = 23  # checkpoints at v10 and v20, log tail to v22


def _build(tmp_path, part_size=None):
    path = str(tmp_path / "t")
    ctx = (conf.set_temporarily(delta__tpu__checkpointPartSize=part_size)
           if part_size else None)
    if ctx:
        ctx.__enter__()
    try:
        log = DeltaLog.for_table(path)
        for i in range(N_COMMITS):
            WriteIntoDelta(log, "append", pa.table({"a": [i]})).run()
    finally:
        if ctx:
            ctx.__exit__(None, None, None)
    return path


def _log_dir(path):
    return os.path.join(path, "_delta_log")


def _truncate(p, n=10):
    with open(p, "r+b") as f:
        f.truncate(n)


def _corrupt_last_checkpoint(path, mode):
    lc = os.path.join(_log_dir(path), "_last_checkpoint")
    if mode == "garbage":
        with open(lc, "w") as f:
            f.write("{ NOT JSON !!!")
    elif mode == "truncated":
        _truncate(lc, os.path.getsize(lc) // 2)
    elif mode == "stale_v10":
        with open(lc, "w") as f:
            f.write(json.dumps({"version": 10, "size": 12}))
    elif mode == "phantom_v15":  # points at a checkpoint that never existed
        with open(lc, "w") as f:
            f.write(json.dumps({"version": 15, "size": 16}))
    else:
        raise AssertionError(mode)


def _corrupt_ckpt20(path, mode):
    cks = sorted(glob.glob(os.path.join(_log_dir(path), "*20.checkpoint*")))
    assert cks, "expected a checkpoint at v20"
    if mode == "truncated":
        _truncate(cks[-1])
    elif mode == "missing":
        for p in cks:
            os.remove(p)
    elif mode == "one_part_missing":
        assert len(cks) > 1, "need a multi-part checkpoint"
        os.remove(cks[1])
    else:
        raise AssertionError(mode)


def _reload(path):
    DeltaLog.clear_cache()
    return DeltaLog.for_table(path)


def _assert_recovered_columnar(path):
    """Columnar read path: full snapshot correct despite the corruption."""
    log = _reload(path)
    snap = log.update()
    assert snap.version == N_COMMITS - 1
    assert len(snap.all_files) == N_COMMITS
    assert snap.metadata.schema_string is not None
    # time travel through the damaged region also recovers
    tt = log.get_snapshot_at(15)
    assert tt.version == 15 and len(tt.all_files) == 16
    return log, snap


def _assert_recovered_dataclass(log, snap):
    """Dataclass read path over the SAME recovered segment: checkpoint parts
    decode to Action objects, replayed with the JSON tail to the same state."""
    replay = LogReplay(min_file_retention_timestamp=0)
    seg = snap.segment
    start = 0
    if seg.checkpoint_files:
        actions = ckpt_mod.read_checkpoint_actions(log.store, [f.path for f in seg.checkpoint_files])
        replay.append(seg.checkpoint_version, actions)
        start = seg.checkpoint_version + 1
        replay.current_version = seg.checkpoint_version
    for fs in seg.deltas:
        v = filenames.delta_version(fs.name)
        assert v >= start
        replay.append(v, actions_from_lines(log.store.read_iter(fs.path)))
    assert replay.current_version == N_COMMITS - 1
    assert len(replay.active_files) == N_COMMITS
    assert replay.current_metadata is not None
    assert replay.current_protocol is not None


@pytest.mark.parametrize("lc_mode", ["garbage", "truncated", "phantom_v15"])
@pytest.mark.parametrize("ckpt_mode", ["truncated", "missing"])
def test_combined_lc_and_ckpt20_corruption(tmp_path, lc_mode, ckpt_mode):
    """The pointer lies AND the checkpoint it (should) point at is damaged:
    recovery must land on the v10 checkpoint + deltas 11..22, on both read
    paths."""
    path = _build(tmp_path)
    _corrupt_ckpt20(path, ckpt_mode)
    _corrupt_last_checkpoint(path, lc_mode)
    log, snap = _assert_recovered_columnar(path)
    if ckpt_mode == "truncated":
        # corrupt parquet is memoized so update() doesn't re-pay recovery
        assert 20 in log.corrupt_checkpoints
    assert snap.segment.checkpoint_version == 10
    _assert_recovered_dataclass(log, snap)


def test_stale_pointer_with_truncated_target(tmp_path):
    """_last_checkpoint points at v10 (stale) while the NEWER v20 checkpoint
    is corrupt: listing from v10 must not trust the broken v20."""
    path = _build(tmp_path)
    _corrupt_ckpt20(path, "truncated")
    _corrupt_last_checkpoint(path, "stale_v10")
    log, snap = _assert_recovered_columnar(path)
    assert snap.segment.checkpoint_version == 10
    _assert_recovered_dataclass(log, snap)


@pytest.mark.parametrize("lc_mode", ["garbage", "phantom_v15"])
def test_combined_corruption_multipart_one_part_missing(tmp_path, lc_mode):
    """Multi-part checkpoint at v20 missing one part (torn) + corrupt
    pointer: the incomplete checkpoint must be skipped at selection, not
    decoded and failed."""
    path = _build(tmp_path, part_size=5)
    _corrupt_ckpt20(path, "one_part_missing")
    _corrupt_last_checkpoint(path, lc_mode)
    log, snap = _assert_recovered_columnar(path)
    assert snap.segment.checkpoint_version == 10
    _assert_recovered_dataclass(log, snap)


def test_both_checkpoints_corrupt_full_json_replay(tmp_path):
    """Every checkpoint unusable + pointer garbage: recovery is a full JSON
    replay from version 0 — the last line of defense."""
    path = _build(tmp_path)
    for p in glob.glob(os.path.join(_log_dir(path), "*.checkpoint*")):
        _truncate(p)
    _corrupt_last_checkpoint(path, "garbage")
    log, snap = _assert_recovered_columnar(path)
    assert snap.segment.checkpoint_version is None  # pure delta replay
    _assert_recovered_dataclass(log, snap)


def test_recovered_segment_via_exclusion_listing(tmp_path):
    """The segment recomputation itself (get_log_segment_for_version with
    excluded_checkpoints) picks the older checkpoint when the newer is
    known-corrupt — the unit under the snapshot-level recovery."""
    path = _build(tmp_path)
    seg = sm.get_log_segment_for_version(
        DeltaLog.for_table(path).store, f"{path}/_delta_log",
        excluded_checkpoints=frozenset({20}),
    )
    assert seg.version == N_COMMITS - 1
    assert seg.checkpoint_version == 10
    assert [filenames.delta_version(f.name) for f in seg.deltas] == list(range(11, 23))
