"""Usage-logging telemetry (SURVEY §5; ``metering/DeltaLogging.scala:50-109``):
hierarchical opTypes, the real ring-buffer backend, duration/error capture,
and the engine wiring (commits emit ``delta.commit`` events).
"""
import json

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.utils import telemetry


@pytest.fixture(autouse=True)
def _fresh_buffer():
    telemetry.clear_events()
    yield
    telemetry.clear_events()


def test_record_event_and_query_by_prefix():
    telemetry.record_event("delta.test.alpha", {"n": 1}, path="/t")
    telemetry.record_event("delta.test.beta", {"n": 2})
    telemetry.record_event("other.op")
    got = telemetry.recent_events("delta.test")
    assert [e.op_type for e in got] == ["delta.test.alpha", "delta.test.beta"]
    assert got[0].tags == {"path": "/t"}
    assert got[0].data == {"n": 1}


def test_record_operation_captures_duration():
    with telemetry.record_operation("delta.test.op") as ev:
        pass
    [got] = telemetry.recent_events("delta.test.op")
    assert got is ev
    assert got.duration_ms is not None and got.duration_ms >= 0
    assert got.error is None


def test_record_operation_captures_error_and_reraises():
    with pytest.raises(ValueError):
        with telemetry.record_operation("delta.test.boom"):
            raise ValueError("kapow")
    [got] = telemetry.recent_events("delta.test.boom")
    assert got.error and "kapow" in got.error


def test_event_json_round_trips():
    telemetry.record_event("delta.test.json", {"k": [1, 2]}, table="x")
    [ev] = telemetry.recent_events("delta.test.json")
    d = json.loads(ev.to_json())
    assert d["opType"] == "delta.test.json"
    assert d["data"] == {"k": [1, 2]}


def test_commits_emit_usage_events(tmp_table):
    t = DeltaTable.create(
        tmp_table, data=pa.table({"id": pa.array([1], pa.int64())})
    )
    t.delete("id = 1")
    commits = telemetry.recent_events("delta.commit")
    assert len(commits) >= 2  # create + delete
    assert all(e.duration_ms is not None for e in commits)
    assert all(e.tags.get("path") == tmp_table for e in commits)


def test_ring_buffer_bounded():
    for i in range(5000):
        telemetry.record_event("delta.test.flood")
    # deque(maxlen=4096): exactly full — also catches silent non-recording
    assert len(telemetry.recent_events()) == 4096


def test_with_status_records_event_and_duration(tmp_table):
    import numpy as np
    import pyarrow as pa

    from delta_tpu import DeltaLog
    from delta_tpu.commands.write import WriteIntoDelta
    from delta_tpu.exec.scan import scan_files
    from delta_tpu.utils import telemetry

    telemetry.clear_events()
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({"a": np.arange(5)})).run()
    scan_files(log.update(), ["a > 1"])
    evs = [e for e in telemetry.recent_events("delta.status")
           if e.data.get("message") == "Filtering files for query"]
    assert evs and evs[-1].duration_ms is not None

    telemetry.clear_events()
    from delta_tpu.commands.vacuum import VacuumCommand

    VacuumCommand(log, retention_hours=1000, dry_run=True).run()
    evs = telemetry.recent_events("delta.status")
    assert any("VACUUM" in e.data.get("message", "") for e in evs)
