"""Test harness.

Multi-device testing mirrors the reference's ``local[*]`` trick
(SURVEY §4 "Multi-node without a cluster"): a virtual 8-device CPU mesh runs
the same `shard_map`/`pjit` code paths as a real TPU slice, with task-level
parallelism real. Must set flags before the first jax import.
"""
import os

# Force CPU: the harness boots an `axon` TPU plugin from sitecustomize (one
# real chip via a tunnel, ~30s per compile) that ignores the JAX_PLATFORMS
# env var — only the jax_platforms *config* reliably overrides it.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import Action, Metadata, Protocol
from delta_tpu.schema.types import IntegerType, StringType, StructType


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: benchmark-scale tests excluded from the tier-1 run "
        "(-m 'not slow')",
    )


@pytest.fixture(autouse=True)
def _clear_deltalog_cache():
    DeltaLog.clear_cache()
    yield
    DeltaLog.clear_cache()


@pytest.fixture
def tmp_table(tmp_path):
    return str(tmp_path / "table")


TEST_SCHEMA = StructType().add("id", IntegerType()).add("value", StringType())


def commit_manually(log: DeltaLog, version: int, actions, overwrite: bool = False):
    """Write a commit file directly, bypassing the transaction layer —
    the analogue of the reference's ``DeltaTestUtils.commitManually``."""
    path = f"{log.log_path}/{filenames.delta_file(version)}"
    log.store.write(path, [a.json() for a in actions], overwrite=overwrite)


def init_metadata(partition_columns=None, configuration=None, schema=None) -> Metadata:
    return Metadata(
        schema_string=(schema or TEST_SCHEMA).to_json(),
        partition_columns=list(partition_columns or []),
        configuration=dict(configuration or {}),
    )
