"""Seeded crash-consistency torture (delta_tpu/testing/harness.py).

Tier-1 carries a fixed-seed ~30-second subset; the full acceptance run —
>= 200 injected faults across >= 6 fault kinds, all four invariants held,
same-seed reproducibility — is marked ``slow``.
"""
import pyarrow as pa
import pytest

from delta_tpu.storage.faults import ALL_KINDS, FaultPlan
from delta_tpu.testing import TortureHarness, run_torture
from delta_tpu.utils import telemetry


@pytest.fixture(autouse=True)
def _fresh_metrics():
    telemetry.reset_all()
    yield
    telemetry.reset_all()


TIER1_SEED = 20260803


def test_torture_tier1_fixed_seed_subset(tmp_path):
    """Fixed-seed 30-second-class subset: every fault point armed, the four
    invariants checked every 10 steps and at the end."""
    report = run_torture(str(tmp_path / "t"), seed=TIER1_SEED, steps=60,
                         rate=0.08)
    assert report.steps == 60
    assert report.faults_injected >= 10
    assert len(report.fault_kinds) >= 3
    assert report.invariant_checks >= 6
    # the ledger saw real traffic, not a no-op run
    assert report.op_counts.get("append", 0) >= 10
    # bounded failure time: nothing hung on retries
    assert report.max_step_s < 60.0
    # injected faults surfaced in the metrics registry
    assert telemetry.counters("faults")["faults.injected"] == report.faults_injected


def test_torture_same_seed_reproduces_fault_sequence(tmp_path):
    """Determinism witness: two fresh runs with one seed yield identical
    per-fault-point kind sequences."""
    r1 = run_torture(str(tmp_path / "a"), seed=7, steps=25, rate=0.10)
    telemetry.reset_all()
    r2 = run_torture(str(tmp_path / "b"), seed=7, steps=25, rate=0.10)
    assert r1.per_point == r2.per_point
    assert r1.fault_kinds == r2.fault_kinds
    telemetry.reset_all()
    r3 = run_torture(str(tmp_path / "c"), seed=8, steps=25, rate=0.10)
    assert r3.per_point != r1.per_point


def test_torture_crash_only_diet_recovers_every_time(tmp_path):
    """Crash-kind-only plan at a high rate: recovery and ledger
    reconciliation carry the run, not retries."""
    report = run_torture(
        str(tmp_path / "t"), seed=11, steps=30, rate=0.25,
        kinds=("crash_before_publish", "crash_after_publish",
               "torn_checkpoint", "stale_last_checkpoint"),
    )
    assert report.crashes >= 3
    assert report.recoveries >= report.crashes


@pytest.mark.slow
def test_torture_acceptance_200_faults_6_kinds(tmp_path):
    """The acceptance bar: a long seeded run injects >= 200 faults across
    >= 6 kinds with every invariant held after every recovery, and the
    same seed reproduces the identical fault sequence."""
    seed = 424242
    h1 = TortureHarness(str(tmp_path / "a"), seed=seed, rate=0.12)
    r1 = h1.run(steps=400, check_every=10)
    assert r1.faults_injected >= 200, r1.fault_kinds
    assert len(r1.fault_kinds) >= 6, r1.fault_kinds
    assert r1.crashes >= 10
    assert r1.max_step_s < 60.0
    telemetry.reset_all()
    h2 = TortureHarness(str(tmp_path / "b"), seed=seed, rate=0.12)
    r2 = h2.run(steps=400, check_every=10)
    assert r1.per_point == r2.per_point, "same seed must reproduce the faults"


def test_harness_ledger_matches_manual_bookkeeping(tmp_path):
    """No faults at all: the harness ledger agrees with a plain read —
    guards the harness itself against bookkeeping bugs."""
    path = str(tmp_path / "t")
    h = TortureHarness(path, seed=3, plan=FaultPlan(seed=3, rate=0.0))
    h.run(steps=30)
    from delta_tpu.api.tables import DeltaTable

    got = sorted(DeltaTable.for_path(path).to_arrow(columns=["id"])
                 .column("id").to_pylist())
    assert got == sorted(h._expected_ids())
    assert h.report.crashes == 0 and h.report.faults_injected == 0


# -- high-traffic commit path (ISSUE 9): group commit + async checkpoints ----


def test_torture_grouped_async_fixed_seed_subset(tmp_path):
    """The PR 5 tier-1 workload, same seed, with the group-commit
    coordinator AND async incremental checkpointing on: every invariant
    (no committed row lost/duplicated, snapshot constructible, txnId
    reconciliation) holds under the same fault pressure, and the new
    engine-level fault points draw."""
    report = run_torture(str(tmp_path / "t"), seed=TIER1_SEED, steps=60,
                         rate=0.08, group_commit=True, async_checkpoint=True)
    assert report.steps == 60
    assert report.faults_injected >= 10
    assert len(report.fault_kinds) >= 3
    assert report.invariant_checks >= 6
    assert report.op_counts.get("append", 0) >= 10
    assert report.max_step_s < 60.0
    # the coordinator's write loop is a real fault point in this mode:
    # every grouped member draws at txn.groupLoop before its create
    assert any(k.startswith("txn.groupLoop|") for k in report.per_point)


def test_torture_grouped_crash_diet_recovers(tmp_path):
    """Crash-kind-only plan (same seed as the ungrouped diet) with grouping
    + async checkpointing: crash mid-batch / between batch members / torn
    incremental checkpoint all recover through the standard path."""
    report = run_torture(
        str(tmp_path / "t"), seed=11, steps=30, rate=0.25,
        kinds=("crash_before_publish", "crash_after_publish",
               "torn_checkpoint", "stale_last_checkpoint"),
        group_commit=True, async_checkpoint=True,
    )
    assert report.crashes >= 3
    assert report.recoveries >= report.crashes


# -- fault-tolerant distributed execution (ISSUE 20) -------------------------


def test_torture_distributed_fixed_seed_subset(tmp_path):
    """Tier-1 subset with the supervised sharded executor in the loop:
    OPTIMIZE runs on 4 workers with on_failure="quarantine" (half the time
    posing as coordinator of a 2-host job, covering the lease path), and the
    dist.* fault points draw alongside the storage points. Every ledger
    invariant holds — a quarantined group changes no rows."""
    report = run_torture(str(tmp_path / "t"), seed=TIER1_SEED, steps=60,
                         rate=0.10, distributed=True)
    assert report.steps == 60
    assert report.faults_injected >= 10
    assert report.invariant_checks >= 6
    assert report.op_counts.get("optimize", 0) >= 1
    assert report.max_step_s < 60.0
    # the supervised executor is a real fault surface in this mode
    assert any(k.startswith("dist.") for k in report.per_point), \
        sorted(report.per_point)


@pytest.mark.slow
def test_torture_distributed_acceptance(tmp_path):
    """ISSUE 20 acceptance: a fixed-seed >= 200-step distributed run with
    kills across all four dist fault points (scripted prefix guarantees
    coverage; seeded rate pressure carries the rest) loses no committed
    row, never double-commits a recovered slice (both enforced by the
    ledger + snapshot invariants after every recovery), and completes
    every job fully or with an explicit quarantine report."""
    script = [
        ("dist.workerSpawn", "transient"),
        ("dist.heartbeat", "transient"),
        ("dist.itemExec", "transient"),
        ("dist.itemExec", "crash_before_publish"),
        ("dist.leaseWrite", "crash_before_publish"),
    ]
    plan = FaultPlan(seed=424242, rate=0.12, script=script)
    h = TortureHarness(str(tmp_path / "t"), seed=424242, plan=plan,
                       distributed=True)
    r = h.run(steps=240, check_every=10)
    assert r.steps == 240
    assert not plan.script, "scripted dist faults must all have fired"
    for prefix in ("dist.workerSpawn", "dist.heartbeat",
                   "dist.itemExec", "dist.leaseWrite"):
        assert any(k.startswith(prefix) for k in r.per_point), \
            (prefix, sorted(r.per_point))
    assert r.crashes >= 2            # itemExec + leaseWrite kills pierced
    assert r.recoveries >= r.crashes
    # transient item faults surfaced as retries or explicit quarantines —
    # never as silently dropped work (the ledger check would catch that)
    assert r.items_retried + r.quarantined_groups >= 1
    assert r.max_step_s < 60.0


@pytest.mark.slow
def test_torture_grouped_acceptance(tmp_path):
    """Long grouped+async run at the PR 5 acceptance seed: sustained fault
    pressure across every kind with the coordinator and the incremental
    builder in the loop."""
    h = TortureHarness(str(tmp_path / "t"), seed=424242, rate=0.12,
                       group_commit=True, async_checkpoint=True)
    r = h.run(steps=400, check_every=10)
    assert r.faults_injected >= 150, r.fault_kinds
    assert len(r.fault_kinds) >= 6, r.fault_kinds
    assert r.crashes >= 10
    assert r.max_step_s < 60.0
