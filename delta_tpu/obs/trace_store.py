"""Distributed-trace spool and collector.

The span side of the distributed trace plane: ``utils/telemetry`` streams
every completed span (and point event) of a SAMPLED trace to the sink this
module installs, which appends one JSON line per span to a per-process
spool file under ``delta.tpu.trace.dir``. Each process in a sharded job —
the coordinator and every spawned worker — writes its own spool; nothing
coordinates at write time, so the hot path stays an append + flush.

The collector side stitches the spools back into ONE trace: spans share the
coordinator's 128-bit ``trace_id`` (threaded across process boundaries via
the traceparent-shaped wire carrier), span ids are namespaced per process,
and every span carries its start on the EPOCH clock — so
:func:`stitch_trace` can lay both hosts' spans on a single Perfetto-loadable
Chrome-trace timeline, and :func:`analyze_trace` can walk the stitched DAG
to name the critical path, the straggler shard (per-worker makespan vs the
LPT-predicted byte share), the slowest item, and how much the work-stealing
deques rescued.

Inert by default and under blackout: with ``delta.tpu.trace.dir`` unset the
sink returns before touching the filesystem, and with telemetry disabled or
the trace unsampled the sink is never called at all. The spool is bounded:
past ``delta.tpu.trace.maxBytes`` per process, spans drop (counted in
``trace.spansDropped``) instead of filling the disk.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["install", "uninstall", "read_spools", "recent_traces",
           "stitch_trace", "analyze_trace", "reset"]

_LOCK = threading.Lock()
# the open spool: directory it was opened under, file handle, bytes written
_STATE: Dict[str, Any] = {"dir": None, "fh": None, "bytes": 0, "nonce": 0}
_installed = False


# (conf generation, resolved dir) — the sink runs per sampled span, so the
# "is a spool even configured?" probe is cached until conf mutates
_DIR_CACHE = (-1, None)


def _spool_dir() -> Optional[str]:
    global _DIR_CACHE
    cached = _DIR_CACHE
    gen = conf.generation()
    if cached[0] == gen:
        return cached[1]
    d = conf.get("delta.tpu.trace.dir")
    resolved = str(d) if d else None
    _DIR_CACHE = (gen, resolved)
    return resolved


def _max_bytes() -> int:
    try:
        mb = int(conf.get("delta.tpu.trace.maxBytes", 32 * 1024 * 1024))
    except (TypeError, ValueError):
        mb = 32 * 1024 * 1024
    return mb if mb > 0 else 32 * 1024 * 1024


def _ensure_spool(directory: str):
    """The open spool handle for ``directory`` (callers hold ``_LOCK``).
    Reopens when the configured directory changes (tests, re-pointed conf)."""
    if _STATE["dir"] != directory or _STATE["fh"] is None:
        if _STATE["fh"] is not None:
            try:
                _STATE["fh"].close()
            except OSError:
                pass
        os.makedirs(directory, exist_ok=True)
        _STATE["nonce"] += 1
        path = os.path.join(
            directory, f"spool-{os.getpid()}-{_STATE['nonce']}.jsonl")
        _STATE["fh"] = open(path, "a", encoding="utf-8")  # delta-lint: ignore[lock-blocking] -- once per (re)configured spool, not per span; serialising the open IS the point
        _STATE["dir"] = directory
        _STATE["bytes"] = 0
    return _STATE["fh"]


def _sink(ev: "telemetry.UsageEvent") -> None:
    """Span sink: one JSONL line per completed span of a sampled trace.
    Conf probes happen before taking ``_LOCK`` (the conf lock must never
    nest inside a telemetry-adjacent lock)."""
    directory = _spool_dir()
    if directory is None or not ev.trace_id:
        return
    max_bytes = _max_bytes()
    line = json.dumps({
        "traceId": ev.trace_id,
        "spanId": ev.span_id or None,
        "parentId": ev.parent_id,
        "op": ev.op_type,
        "tsUs": ev.wall_us,
        "durUs": ev.duration_us,
        "pid": os.getpid(),
        "tid": ev.thread_id,
        "thread": ev.thread_name,
        "tags": ev.tags,
        "data": ev.data,
        "error": ev.error,
    }, separators=(",", ":"), default=str) + "\n"
    payload = line.encode("utf-8")
    dropped = False
    try:
        with _LOCK:
            fh = _ensure_spool(directory)
            if _STATE["bytes"] + len(payload) > max_bytes:
                dropped = True
            else:
                fh.write(line)
                fh.flush()
                _STATE["bytes"] += len(payload)
    except OSError:
        dropped = True
    if dropped:
        telemetry.bump_counter("trace.spansDropped")
    else:
        telemetry.bump_counter("trace.spansSpooled")


def install() -> None:
    """Register the spool sink with telemetry (idempotent)."""
    global _installed
    if not _installed:
        telemetry.add_span_sink(_sink)
        _installed = True


def uninstall() -> None:
    global _installed
    telemetry.remove_span_sink(_sink)
    _installed = False


def reset() -> None:
    """Close the open spool (tests / bench per-config isolation); the next
    sampled span reopens a fresh spool file."""
    with _LOCK:
        if _STATE["fh"] is not None:
            try:
                _STATE["fh"].close()
            except OSError:
                pass
        _STATE.update(dir=None, fh=None, bytes=0)


# -- collector ---------------------------------------------------------------


def read_spools(directory: str,
                trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every span row across all spool files in ``directory`` (optionally
    only one trace), in spool order. Corrupt lines — a process killed
    mid-append — are skipped, not fatal: the collector reads what landed."""
    rows: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return rows
    for name in names:
        if not (name.startswith("spool-") and name.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(directory, name), encoding="utf-8") as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if trace_id is None or row.get("traceId") == trace_id:
                        rows.append(row)
        except OSError:
            continue
    return rows


def _roots(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    # instants carry spanId None — keep None out of the id set or a root
    # whose parentId is None would never be recognised as a root
    ids = {s.get("spanId") for s in spans if s.get("spanId")}
    return [s for s in spans
            if s.get("spanId") and s.get("parentId") not in ids]


def recent_traces(directory: str, limit: int = 20) -> List[Dict[str, Any]]:
    """Index of the most recent traces in the spool directory: one row per
    trace id with its root op, start, duration, span/process/error counts —
    the ``/traces`` payload, newest first."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for row in read_spools(directory):
        by_trace.setdefault(row.get("traceId") or "?", []).append(row)
    out: List[Dict[str, Any]] = []
    for tid, spans in by_trace.items():
        starts = [int(s.get("tsUs") or 0) for s in spans]
        ends = [int(s.get("tsUs") or 0) + int(s.get("durUs") or 0)
                for s in spans]
        roots = _roots(spans)
        root = min(roots, key=lambda s: int(s.get("tsUs") or 0)) if roots \
            else None
        out.append({
            "traceId": tid,
            "rootOp": root.get("op") if root else None,
            "startUs": min(starts) if starts else 0,
            "durationMs": ((max(ends) - min(starts)) // 1000
                           if starts else 0),
            "spans": len(spans),
            "processes": len({s.get("pid") for s in spans}),
            "errors": sum(1 for s in spans if s.get("error")),
        })
    out.sort(key=lambda r: -r["startUs"])
    return out[:max(int(limit), 0)] if limit is not None else out


def stitch_trace(directory: str, trace_id: str) -> Optional[Dict[str, Any]]:
    """Stitch every process's spooled spans of ``trace_id`` into one
    Chrome-trace JSON (Perfetto-loadable): spans lie on the shared epoch
    timeline, each process renders as its own labeled lane, and every
    complete-span row carries ``traceId``/``spanId``/``parentId`` args so
    the hierarchy survives. None when the trace has no spooled spans."""
    spans = read_spools(directory, trace_id)
    if not spans:
        return None
    rows: List[Dict[str, Any]] = []
    threads: Dict[Any, str] = {}
    for s in spans:
        pid, tid = s.get("pid") or 0, s.get("tid") or 0
        threads.setdefault((pid, tid), s.get("thread") or str(tid))
        args: Dict[str, Any] = dict(s.get("tags") or {})
        args.update(s.get("data") or {})
        if s.get("error"):
            args["error"] = s["error"]
        args["traceId"] = trace_id
        args["spanId"] = s.get("spanId")
        if s.get("parentId"):
            args["parentId"] = s["parentId"]
        row: Dict[str, Any] = {
            "name": s.get("op"), "cat": "delta", "pid": pid, "tid": tid,
            "ts": int(s.get("tsUs") or 0), "args": args,
        }
        if s.get("durUs") is not None:
            row["ph"] = "X"
            row["dur"] = int(s["durUs"])
        else:
            row["ph"] = "i"
            row["s"] = "t"
        rows.append(row)
    for pid in sorted({p for p, _ in threads}):
        rows.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"delta-tpu-{pid}"}})
    for (pid, tid), name in threads.items():
        rows.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": name}})
    return {"traceEvents": rows, "displayTimeUnit": "ms",
            "otherData": {"traceId": trace_id}}


def _critical_path(spans: List[Dict[str, Any]],
                   root: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Walk from the root into the child whose END is latest at each level —
    the chain that determined the trace's makespan."""
    children: Dict[Any, List[Dict[str, Any]]] = {}
    for s in spans:
        if s.get("parentId") and s.get("durUs") is not None:
            children.setdefault(s["parentId"], []).append(s)
    path: List[Dict[str, Any]] = []
    node: Optional[Dict[str, Any]] = root
    while node is not None:
        kids = children.get(node.get("spanId"), [])
        kid = max(kids, key=lambda s: int(s.get("tsUs") or 0)
                  + int(s.get("durUs") or 0)) if kids else None
        # self time: the node's duration not covered by its own slowest child
        self_us = int(node.get("durUs") or 0) - (
            int(kid.get("durUs") or 0) if kid is not None else 0)
        path.append({
            "op": node.get("op"), "spanId": node.get("spanId"),
            "pid": node.get("pid"), "durUs": int(node.get("durUs") or 0),
            "selfUs": max(self_us, 0),
        })
        node = kid
    return path


def _job_analysis(job: Dict[str, Any],
                  spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-shard makespan vs the LPT-predicted byte share for one
    ``delta.dist.job`` span, plus slowest-item and steal-rescue rows."""
    data = job.get("data") or {}
    lpt_bytes = [int(b) for b in (data.get("lptBytes") or [])]
    total_bytes = sum(lpt_bytes)
    workers = [s for s in spans
               if s.get("op") == "delta.dist.worker"
               and s.get("parentId") == job.get("spanId")]
    # items parent under their worker span, or (inline path) under the job
    wids = {w.get("spanId") for w in workers}
    items = [s for s in spans
             if s.get("op") == "delta.dist.item"
             and (s.get("parentId") in wids
                  or s.get("parentId") == job.get("spanId"))]
    busy_total = sum(int(w.get("durUs") or 0) for w in workers)
    shards: List[Dict[str, Any]] = []
    for w in workers:
        ix = int((w.get("tags") or {}).get("worker", -1))
        share = (lpt_bytes[ix] / total_bytes
                 if 0 <= ix < len(lpt_bytes) and total_bytes else 0.0)
        predicted = int(busy_total * share)
        busy = int(w.get("durUs") or 0)
        w_items = [s for s in items if s.get("parentId") == w.get("spanId")]
        shards.append({
            "worker": ix, "pid": w.get("pid"), "busyUs": busy,
            "predictedUs": predicted, "deltaUs": busy - predicted,
            "bytes": lpt_bytes[ix] if 0 <= ix < len(lpt_bytes) else None,
            "items": len(w_items),
            "stolen": sum(1 for s in w_items
                          if (s.get("data") or {}).get("stolen")),
        })
    shards.sort(key=lambda s: -s["busyUs"])
    slowest = max(items, key=lambda s: int(s.get("durUs") or 0), default=None)
    stolen = [s for s in items if (s.get("data") or {}).get("stolen")]
    return {
        "label": (job.get("tags") or {}).get("job"),
        "spanId": job.get("spanId"),
        "pid": job.get("pid"),
        "durUs": int(job.get("durUs") or 0),
        "workers": len(workers),
        "items": len(items),
        "skew": data.get("skew"),
        "lptBytes": lpt_bytes or None,
        "shards": shards,
        "straggler": shards[0] if shards else None,
        "slowestItem": ({
            "index": (slowest.get("data") or {}).get("index"),
            "bytes": (slowest.get("data") or {}).get("bytes"),
            "durUs": int(slowest.get("durUs") or 0),
            "stolen": bool((slowest.get("data") or {}).get("stolen")),
            "pid": slowest.get("pid"),
        } if slowest is not None else None),
        "stealRescue": {
            "items": len(stolen),
            "bytes": sum(int((s.get("data") or {}).get("bytes") or 0)
                         for s in stolen),
            "busyUs": sum(int(s.get("durUs") or 0) for s in stolen),
        },
        # supervision attribution: retries/speculation racing outcomes per
        # item span — a speculative attempt that is NOT discarded beat the
        # original (the win the dist.speculation.wins counter records,
        # here attributed to its item and worker)
        "supervision": {
            "retriedAttempts": sum(
                max(int((s.get("data") or {}).get("attempt") or 1) - 1, 0)
                for s in items),
            "speculative": sum(1 for s in items
                               if (s.get("data") or {}).get("speculative")),
            "speculationWins": sum(
                1 for s in items
                if (s.get("data") or {}).get("speculative")
                and not (s.get("data") or {}).get("discarded")),
            "discarded": sum(1 for s in items
                             if (s.get("data") or {}).get("discarded")),
            "quarantined": data.get("quarantined") or 0,
        },
    }


def analyze_trace(directory: str,
                  trace_id: str) -> Optional[Dict[str, Any]]:
    """Walk the stitched span DAG of ``trace_id``: the critical path from
    the root, and — for every ``delta.dist.job`` span — each shard's
    makespan against its LPT-predicted byte share (naming the straggler),
    the slowest item, and what the work-stealing deques rescued. The answer
    to "which shard was the straggler and why" as a JSON document."""
    spans = read_spools(directory, trace_id)
    if not spans:
        return None
    closed = [s for s in spans if s.get("durUs") is not None]
    roots = _roots(closed)
    root = max(roots, key=lambda s: int(s.get("durUs") or 0)) if roots \
        else None
    starts = [int(s.get("tsUs") or 0) for s in spans]
    ends = [int(s.get("tsUs") or 0) + int(s.get("durUs") or 0)
            for s in spans]
    jobs = sorted(
        (_job_analysis(j, closed) for j in closed
         if j.get("op") == "delta.dist.job"),
        key=lambda j: -j["durUs"])
    shards = [s for j in jobs for s in j["shards"]]
    # fault-tolerance spans: orphaned-slice recoveries stitched into the
    # job trace (the coordinator re-executing a dead host's slice) — the
    # "why does this trace have an extra commit" answer
    recoveries = [{
        "spanId": s.get("spanId"), "pid": s.get("pid"),
        "durUs": int(s.get("durUs") or 0),
        "proc": (s.get("data") or {}).get("proc"),
        "outcome": (s.get("data") or {}).get("outcome"),
        "groups": (s.get("data") or {}).get("groups"),
    } for s in closed if s.get("op") == "delta.dist.sliceRecovery"]
    return {
        "traceId": trace_id,
        "rootOp": root.get("op") if root else None,
        "spans": len(spans),
        "processes": sorted({s.get("pid") for s in spans}),
        "errors": [{"op": s.get("op"), "spanId": s.get("spanId"),
                    "pid": s.get("pid"), "error": s.get("error")}
                   for s in spans if s.get("error")],
        "durationUs": max(ends) - min(starts) if starts else 0,
        "criticalPath": _critical_path(closed, root) if root else [],
        "jobs": jobs,
        "recoveries": recoveries,
        "straggler": max(shards, key=lambda s: s["busyUs"]) if shards
        else None,
    }
