"""Bench regression gate — diff two BENCH_*.json rounds mechanically.

``bench.py`` prints one JSON line per round: the headline metric plus an
``all`` map of per-config results (``{"metric", "value", "unit",
"vs_baseline", ...}``). This module compares the current round against a
prior one with percentage thresholds and reports every regression, so a
perf claim in a PR is a checkable assertion instead of prose:

    python tools/bench_diff.py BENCH_r06.json BENCH_r07.json --threshold 25
    python bench.py --compare BENCH_r06.json        # gate a live run

Direction is unit-aware: latency-like units (``s``, ``ms``) regress when
the value GROWS; throughput-like units (``GB/s``, ``commits/s``, ...)
regress when it SHRINKS. Skipped/errored configs (``value < 0`` or unit
``skipped``/``error``) are excluded on either side — a config that timed
out is a budget problem, not a perf regression — and configs present in
only one round are ignored (the set evolves across PRs). Exit status: 0
clean, 3 when any regression crossed the threshold.
"""
from __future__ import annotations

import argparse
import json
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

__all__ = ["Regression", "compare", "compare_files", "main"]

#: Units where a SMALLER value is better. "findings" is the static-analysis
#: gate (tools/analyze.py counts riding the bench artifact); "skew" is a
#: max/mean balance ratio (1.0 = perfectly even — the sharded-scan config's
#: LPT assignment gate), so growth is a load-balance regression; "pct" is
#: an overhead percentage (the tracing-overhead config), so growth means
#: the instrumentation got more expensive.
LOWER_IS_BETTER = frozenset({"s", "ms", "us", "ns", "findings", "skew",
                             "pct"})

DEFAULT_THRESHOLD_PCT = 20.0


@dataclass
class Regression:
    """One config whose headline metric moved past the threshold the wrong
    way (positive ``delta_pct`` = that much worse)."""

    config: str
    metric: str
    unit: str
    prior: float
    current: float
    delta_pct: float

    def describe(self) -> str:
        return (f"config {self.config} ({self.metric}): "
                f"{self.prior:g} -> {self.current:g} {self.unit} "
                f"({self.delta_pct:+.1f}% worse)")


def _configs(round_json: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """The per-config map from either a full bench line ({"all": {...}}) or
    a bare config map."""
    allc = round_json.get("all")
    if isinstance(allc, dict):
        return allc
    # driver-captured artifacts (BENCH_rN.json) wrap the bench line under
    # "parsed" — unwrap so --compare works against them directly
    parsed = round_json.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("all"), dict):
        return parsed["all"]
    # a bare single-config record (bench.py <only> mode) or a config map
    if "value" in round_json and "metric" in round_json:
        return {"_only": round_json}
    return {k: v for k, v in round_json.items() if isinstance(v, dict)}


def _comparable(entry: Any) -> Optional[Dict[str, Any]]:
    if not isinstance(entry, dict):
        return None
    value = entry.get("value")
    unit = str(entry.get("unit", ""))
    if not isinstance(value, (int, float)) or value < 0:
        return None  # -1 = skipped/error sentinel
    if unit in ("skipped", "error"):
        return None
    return entry


def _worse_pct(unit: str, cur_v: float, old_v: float) -> Optional[float]:
    """Direction-aware regression percentage (positive = worse). Latency
    units regress when the value grows; throughput/ratio units when it
    shrinks. None when the prior value can't anchor a percentage."""
    if old_v == 0:
        if unit == "findings" and cur_v > 0:
            # a count that was clean CAN anchor: each new finding reads as
            # +100% so any sane threshold trips (0 -> N must never pass)
            return 100.0 * cur_v
        return None
    if unit in LOWER_IS_BETTER:
        return (cur_v - old_v) / old_v * 100.0
    return (old_v - cur_v) / old_v * 100.0


def compare(current: Dict[str, Any], prior: Dict[str, Any],
            threshold_pct: float = DEFAULT_THRESHOLD_PCT) -> List[Regression]:
    """Regressions of ``current`` vs ``prior`` past ``threshold_pct``.
    Only configs present and comparable in BOTH rounds participate; a unit
    change between rounds makes the config incomparable (ignored).

    Besides the headline metric, a config may carry a ``gate`` map of named
    sub-metrics (``{"p99_ms": {"value": ..., "unit": "ms"}, ...}`` — e.g.
    the contention config's per-leg p99 latency): each sub-metric present
    and comparable in both rounds is gated with the same direction-aware
    threshold, reported as ``<config>.gate.<name>``."""
    cur_map, prior_map = _configs(current), _configs(prior)
    out: List[Regression] = []

    def _gate_one(key: str, metric: str, cur: Dict[str, Any],
                  old: Dict[str, Any]) -> None:
        if str(cur.get("unit")) != str(old.get("unit")):
            return
        unit = str(cur.get("unit", ""))
        cur_v, old_v = float(cur["value"]), float(old["value"])
        worse = _worse_pct(unit, cur_v, old_v)
        if worse is not None and worse > threshold_pct:
            out.append(Regression(
                config=key, metric=metric, unit=unit,
                prior=old_v, current=cur_v, delta_pct=worse,
            ))

    for key in sorted(cur_map.keys() & prior_map.keys()):
        cur = _comparable(cur_map[key])
        old = _comparable(prior_map[key])
        if cur is None or old is None:
            continue
        _gate_one(key, str(cur.get("metric", "")), cur, old)
        gate_cur, gate_old = cur.get("gate"), old.get("gate")
        if isinstance(gate_cur, dict) and isinstance(gate_old, dict):
            for gk in sorted(gate_cur.keys() & gate_old.keys()):
                gc, go = _comparable(gate_cur[gk]), _comparable(gate_old[gk])
                if gc is None or go is None:
                    continue
                _gate_one(f"{key}.gate.{gk}", gk, gc, go)
    return out


def compare_files(current_path: str, prior_path: str,
                  threshold_pct: float = DEFAULT_THRESHOLD_PCT) -> List[Regression]:
    with open(current_path, encoding="utf-8") as f:
        current = json.load(f)
    with open(prior_path, encoding="utf-8") as f:
        prior = json.load(f)
    return compare(current, prior, threshold_pct)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("prior", help="prior round JSON (e.g. BENCH_r06.json)")
    ap.add_argument("current", help="current round JSON")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD_PCT,
                    help="regression threshold in percent (default 20)")
    args = ap.parse_args(argv)
    regressions = compare_files(args.current, args.prior, args.threshold)
    if not regressions:
        print(f"OK: no config regressed past {args.threshold:g}%")
        return 0
    for r in regressions:
        print(f"REGRESSION: {r.describe()}")
    return 3


if __name__ == "__main__":
    sys.exit(main())
