"""Write-time schema enforcement matrix (≈ ``SchemaEnforcementSuite``, 897
LoC in the reference): what a batch may look like relative to the table
schema on append/overwrite, and exactly how it fails when it may not.
"""
import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    InvariantViolationError,
    SchemaMismatchError,
)


def base_table(tmp_table, **create_kwargs):
    data = pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "value": pa.array(["a", "b"]),
    })
    return DeltaTable.create(tmp_table, data=data, **create_kwargs)


def append(t, data, **kw):
    WriteIntoDelta(t.delta_log, "append", data, **kw).run()


# -- column presence ----------------------------------------------------------


def test_missing_column_null_filled(tmp_table):
    t = base_table(tmp_table)
    append(t, pa.table({"id": pa.array([3], pa.int64())}))
    got = t.to_arrow(filters=["id = 3"])
    assert got.column("value").to_pylist() == [None]


def test_extra_column_rejected_with_name_in_error(tmp_table):
    t = base_table(tmp_table)
    with pytest.raises(SchemaMismatchError, match="surprise"):
        append(t, pa.table({
            "id": pa.array([3], pa.int64()),
            "surprise": pa.array([1.0]),
        }))


def test_extra_column_added_with_merge_schema(tmp_table):
    t = base_table(tmp_table)
    append(t, pa.table({
        "id": pa.array([3], pa.int64()),
        "surprise": pa.array([1.5]),
    }), merge_schema=True)
    got = t.to_arrow()
    assert "surprise" in got.column_names
    # old rows read null for the new column; schema order: new col appended
    vals = dict(zip(got.column("id").to_pylist(), got.column("surprise").to_pylist()))
    assert vals[1] is None and vals[3] == 1.5
    assert t.schema().field_names[-1] == "surprise"


def test_reordered_columns_normalized(tmp_table):
    t = base_table(tmp_table)
    append(t, pa.table({
        "value": pa.array(["z"]),
        "id": pa.array([9], pa.int64()),
    }))
    got = t.to_arrow(filters=["id = 9"])
    assert got.column_names == ["id", "value"]
    assert got.column("value").to_pylist() == ["z"]


def test_empty_batch_still_schema_checked(tmp_table):
    t = base_table(tmp_table)
    with pytest.raises(SchemaMismatchError):
        append(t, pa.table({"nope": pa.array([], pa.int64())}))


# -- case handling ------------------------------------------------------------


def test_case_insensitive_column_match(tmp_table):
    t = base_table(tmp_table)
    append(t, pa.table({
        "ID": pa.array([5], pa.int64()),
        "VALUE": pa.array(["c"]),
    }))
    got = t.to_arrow(filters=["id = 5"])
    # stored under the TABLE's canonical casing
    assert got.column_names == ["id", "value"]
    assert got.column("value").to_pylist() == ["c"]


def test_case_differing_duplicates_rejected(tmp_table):
    t = base_table(tmp_table)
    with pytest.raises((SchemaMismatchError, DeltaAnalysisError)):
        append(t, pa.table([
            pa.array([1], pa.int64()),
            pa.array([2], pa.int64()),
            pa.array(["x"]),
        ], names=["id", "ID", "value"]))


# -- type compatibility -------------------------------------------------------


def test_narrower_int_upcast_on_write(tmp_table):
    t = base_table(tmp_table)
    append(t, pa.table({
        "id": pa.array([7], pa.int32()),
        "value": pa.array(["w"]),
    }))
    got = t.to_arrow(filters=["id = 7"])
    assert got.column("id").type == pa.int64()


def test_incompatible_type_rejected(tmp_table):
    t = base_table(tmp_table)
    with pytest.raises(SchemaMismatchError, match="id"):
        append(t, pa.table({
            "id": pa.array(["not-a-number"]),
            "value": pa.array(["x"]),
        }))


def test_float_to_long_lossy_rejected(tmp_table):
    t = base_table(tmp_table)
    with pytest.raises(SchemaMismatchError):
        append(t, pa.table({
            "id": pa.array([1.5]),
            "value": pa.array(["x"]),
        }))


def test_merge_schema_cannot_widen_existing_column(tmp_table):
    """mergeSchema adds NEW columns; changing an existing column's type is
    ALTER territory (`SchemaUtils.mergeSchemas` fails on int vs long)."""
    data = pa.table({"id": pa.array([1], pa.int32())})
    t = DeltaTable.create(tmp_table, data=data)
    with pytest.raises(SchemaMismatchError, match="merge"):
        append(t, pa.table({"id": pa.array([2**40], pa.int64())}),
               merge_schema=True)


def test_alter_widen_then_append_long(tmp_table):
    from delta_tpu.commands.alter import change_column
    from delta_tpu.schema.types import LongType

    data = pa.table({"id": pa.array([1], pa.int32())})
    t = DeltaTable.create(tmp_table, data=data)
    change_column(t.delta_log, "id", new_type=LongType())
    append(t, pa.table({"id": pa.array([2**40], pa.int64())}))
    assert t.to_arrow().column("id").type == pa.int64()
    assert sorted(t.to_arrow().column("id").to_pylist()) == [1, 2**40]


def test_merge_schema_conflicting_types_rejected(tmp_table):
    t = base_table(tmp_table)
    with pytest.raises((SchemaMismatchError, DeltaAnalysisError)):
        append(t, pa.table({
            "id": pa.array([1], pa.int64()),
            "value": pa.array([3.14]),  # string column fed doubles
        }), merge_schema=True)


# -- overwrite semantics ------------------------------------------------------


def test_overwrite_keeps_schema_checks(tmp_table):
    t = base_table(tmp_table)
    with pytest.raises(SchemaMismatchError):
        WriteIntoDelta(
            t.delta_log, "overwrite",
            pa.table({"other": pa.array([1], pa.int64())}),
        ).run()


def test_overwrite_schema_replaces_schema(tmp_table):
    t = base_table(tmp_table)
    WriteIntoDelta(
        t.delta_log, "overwrite",
        pa.table({"other": pa.array([1], pa.int64())}),
        overwrite_schema=True,
    ).run()
    assert t.schema().field_names == ["other"]
    assert t.to_arrow().num_rows == 1


def test_overwrite_schema_requires_overwrite_mode(tmp_table):
    t = base_table(tmp_table)
    with pytest.raises((DeltaAnalysisError, Exception)):
        append(t, pa.table({"other": pa.array([1], pa.int64())}),
               overwrite_schema=True)


# -- nested structs -----------------------------------------------------------


def nested_table(tmp_table):
    data = pa.table({
        "id": pa.array([1], pa.int64()),
        "s": pa.array([{"x": 1, "y": "a"}],
                      pa.struct([("x", pa.int64()), ("y", pa.string())])),
    })
    return DeltaTable.create(tmp_table, data=data)


def test_nested_missing_inner_field_null_filled(tmp_table):
    t = nested_table(tmp_table)
    append(t, pa.table({
        "id": pa.array([2], pa.int64()),
        "s": pa.array([{"x": 5}], pa.struct([("x", pa.int64())])),
    }))
    got = t.to_arrow(filters=["id = 2"])
    assert got.column("s").to_pylist() == [{"x": 5, "y": None}]


def test_nested_extra_inner_field_rejected_without_merge(tmp_table):
    t = nested_table(tmp_table)
    with pytest.raises((SchemaMismatchError, DeltaAnalysisError)):
        append(t, pa.table({
            "id": pa.array([2], pa.int64()),
            "s": pa.array(
                [{"x": 5, "y": "b", "z": 1.0}],
                pa.struct([("x", pa.int64()), ("y", pa.string()),
                           ("z", pa.float64())]),
            ),
        }))


def test_nested_extra_inner_field_added_with_merge(tmp_table):
    t = nested_table(tmp_table)
    append(t, pa.table({
        "id": pa.array([2], pa.int64()),
        "s": pa.array(
            [{"x": 5, "y": "b", "z": 1.0}],
            pa.struct([("x", pa.int64()), ("y", pa.string()),
                       ("z", pa.float64())]),
        ),
    }), merge_schema=True)
    got = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert got[0]["s"] == {"x": 1, "y": "a", "z": None}
    assert got[1]["s"] == {"x": 5, "y": "b", "z": 1.0}


# -- constraints interplay ----------------------------------------------------


def test_not_null_constraint_on_missing_column(tmp_table):
    from delta_tpu.schema.types import LongType, StringType, StructType

    schema = (StructType()
              .add("id", LongType(), nullable=False)
              .add("value", StringType()))
    t = DeltaTable.create(tmp_table, schema=schema)
    with pytest.raises(InvariantViolationError):
        append(t, pa.table({"value": pa.array(["x"])}))


def test_not_null_constraint_with_nulls_in_batch(tmp_table):
    from delta_tpu.schema.types import LongType, StringType, StructType

    schema = (StructType()
              .add("id", LongType(), nullable=False)
              .add("value", StringType()))
    t = DeltaTable.create(tmp_table, schema=schema)
    with pytest.raises(InvariantViolationError, match="id"):
        append(t, pa.table({
            "id": pa.array([1, None], pa.int64()),
            "value": pa.array(["x", "y"]),
        }))


def test_partition_column_cannot_be_dropped_by_batch(tmp_table):
    data = pa.table({
        "id": pa.array([1], pa.int64()),
        "part": pa.array(["p1"]),
    })
    t = DeltaTable.create(tmp_table, data=data, partition_columns=["part"])
    append(t, pa.table({"id": pa.array([2], pa.int64())}))
    got = t.to_arrow(filters=["id = 2"])
    assert got.column("part").to_pylist() == [None]  # null partition


def test_nested_case_duplicates_rejected(tmp_table):
    """Duplicate field names inside a struct are just as ambiguous as at
    top level — must raise, not silently drop one."""
    t = nested_table(tmp_table)
    dup_struct = pa.struct([("x", pa.int64()), ("X", pa.int64()),
                            ("y", pa.string())])
    with pytest.raises((SchemaMismatchError, DeltaAnalysisError)):
        append(t, pa.table({
            "id": pa.array([2], pa.int64()),
            "s": pa.array([{"x": 10, "X": 20, "y": "b"}], dup_struct),
        }))


def test_duplicates_with_generated_columns_clean_error(tmp_table):
    """The duplicate check must fire BEFORE generated-column computation
    (whose lookups KeyError on duplicate names)."""
    from delta_tpu.schema.types import LongType, StructType

    schema = StructType().add("id", LongType()).add(
        "twice", LongType(),
        metadata={"delta.generationExpression": "id * 2"},
    )
    t = DeltaTable.create(tmp_table, schema=schema)
    with pytest.raises((SchemaMismatchError, DeltaAnalysisError)):
        append(t, pa.table([
            pa.array([1], pa.int64()), pa.array([2], pa.int64()),
        ], names=["id", "ID"]))
