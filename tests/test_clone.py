"""SHALLOW CLONE semantics (beyond-reference; modern Delta's clone):
zero-copy table creation by absolute-path reference, divergence after
writes, time-traveled clones, DV carrying, and isolation of the source.
"""
import os

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.utils.errors import DeltaAnalysisError


def make(tmp_path, name="src", **kw):
    return DeltaTable.create(
        str(tmp_path / name),
        data=pa.table({"id": pa.array([1, 2, 3], pa.int64()),
                       "v": pa.array(["a", "b", "c"])}),
        **kw,
    )


def test_clone_reads_source_data_without_copying(tmp_path):
    src = make(tmp_path)
    clone = src.clone(str(tmp_path / "c"))
    assert sorted(clone.to_arrow().column("id").to_pylist()) == [1, 2, 3]
    # no parquet copied into the clone dir
    data_files = [f for f in os.listdir(str(tmp_path / "c"))
                  if f.endswith(".parquet")]
    assert data_files == []
    assert clone.history()[0]["operation"] == "CLONE"


def test_clone_gets_fresh_table_id(tmp_path):
    src = make(tmp_path)
    clone = src.clone(str(tmp_path / "c"))
    assert clone.delta_log.update().metadata.id != src.delta_log.update().metadata.id


def test_writes_to_clone_do_not_touch_source(tmp_path):
    src = make(tmp_path)
    clone = src.clone(str(tmp_path / "c"))
    WriteIntoDelta(clone.delta_log, "append", pa.table({
        "id": pa.array([99], pa.int64()), "v": pa.array(["z"]),
    })).run()
    clone.delete("id = 1")
    assert sorted(clone.to_arrow().column("id").to_pylist()) == [2, 3, 99]
    assert sorted(src.to_arrow().column("id").to_pylist()) == [1, 2, 3]
    # the clone's new file lives under the clone's directory
    new_files = [f for f in os.listdir(str(tmp_path / "c"))
                 if f.endswith(".parquet")]
    assert len(new_files) >= 1


def test_writes_to_source_do_not_affect_clone(tmp_path):
    src = make(tmp_path)
    clone = src.clone(str(tmp_path / "c"))
    WriteIntoDelta(src.delta_log, "append", pa.table({
        "id": pa.array([50], pa.int64()), "v": pa.array(["s"]),
    })).run()
    assert sorted(clone.to_arrow().column("id").to_pylist()) == [1, 2, 3]


def test_clone_at_version(tmp_path):
    src = make(tmp_path)
    WriteIntoDelta(src.delta_log, "append", pa.table({
        "id": pa.array([4], pa.int64()), "v": pa.array(["d"]),
    })).run()
    clone = src.clone(str(tmp_path / "c"), version=0)
    assert sorted(clone.to_arrow().column("id").to_pylist()) == [1, 2, 3]


def test_clone_into_existing_table_rejected(tmp_path):
    src = make(tmp_path)
    make(tmp_path, name="other")
    with pytest.raises(DeltaAnalysisError):
        src.clone(str(tmp_path / "other"))


def test_clone_version_and_timestamp_rejected(tmp_path):
    src = make(tmp_path)
    with pytest.raises(DeltaAnalysisError):
        src.clone(str(tmp_path / "c"), version=0, timestamp="2024-01-01")


def test_clone_carries_dv_state(tmp_path):
    src = make(tmp_path, configuration={"delta.tpu.enableDeletionVectors": "true"})
    src.delete("id = 2")
    clone = src.clone(str(tmp_path / "c"))
    assert sorted(clone.to_arrow().column("id").to_pylist()) == [1, 3]
    p = clone.delta_log.update().protocol
    assert (p.min_reader_version, p.min_writer_version) == (3, 7)


def test_clone_carries_schema_and_properties(tmp_path):
    src = DeltaTable.create(
        str(tmp_path / "src"),
        data=pa.table({"part": ["x", "y"], "n": pa.array([1, 2], pa.int64())}),
        partition_columns=["part"],
        configuration={"delta.appendOnly": "false", "custom.tag": "hello"},
    )
    clone = src.clone(str(tmp_path / "c"))
    meta = clone.delta_log.update().metadata
    assert meta.partition_columns == ["part"]
    assert meta.configuration.get("custom.tag") == "hello"
    assert clone.to_arrow(filters=["part = 'x'"]).num_rows == 1


def test_clone_vacuum_does_not_touch_source_files(tmp_path):
    import time as _time

    from delta_tpu.log.deltalog import DeltaLog

    src = make(tmp_path)
    clone_path = str(tmp_path / "c")
    now = [int(_time.time() * 1000)]
    src.clone(clone_path)
    DeltaLog.clear_cache()
    log = DeltaLog.for_table(clone_path, clock=lambda: now[0])
    clone = DeltaTable.for_path(clone_path)
    now[0] += 14 * 24 * 3_600_000
    r = clone.vacuum()
    assert r.files_deleted == 0
    assert sorted(src.to_arrow().column("id").to_pylist()) == [1, 2, 3]
    assert sorted(clone.to_arrow().column("id").to_pylist()) == [1, 2, 3]


def test_clone_carries_source_protocol_beyond_config(tmp_path):
    """Config under-derives protocol when DV files outlive an unset DV
    property — the clone must inherit the SOURCE protocol, not re-derive."""
    from delta_tpu.commands.alter import unset_table_properties

    src = make(tmp_path, configuration={"delta.tpu.enableDeletionVectors": "true"})
    src.delete("id = 2")  # AddFile now carries a DV
    unset_table_properties(src.delta_log, ["delta.tpu.enableDeletionVectors"])
    sp = src.delta_log.update().protocol
    assert (sp.min_reader_version, sp.min_writer_version) == (3, 7)
    clone = src.clone(str(tmp_path / "c"))
    cp = clone.delta_log.update().protocol
    assert (cp.min_reader_version, cp.min_writer_version) == (3, 7)
    assert "tpu.deletionVectors" in (cp.reader_features or ())
    assert sorted(clone.to_arrow().column("id").to_pylist()) == [1, 3]


def test_clone_into_existing_rejected_by_outer_check(tmp_path):
    from delta_tpu.commands.clone import CloneCommand

    src = make(tmp_path)
    make(tmp_path, name="raced")
    with pytest.raises(DeltaAnalysisError):
        CloneCommand(src.delta_log, str(tmp_path / "raced")).run()


def test_clone_race_window_rejected_in_txn(tmp_path, monkeypatch):
    """A table created at the target BETWEEN the pre-check and the commit
    must fail the clone, never merge two tables: make the pre-check see an
    empty table once, with the real table appearing when the transaction
    pins its snapshot."""
    from types import SimpleNamespace

    from delta_tpu.commands.clone import CloneCommand
    from delta_tpu.log.deltalog import DeltaLog

    src = make(tmp_path)
    target = str(tmp_path / "raced")
    make(tmp_path, name="raced")  # the racing creator already committed
    target_log = DeltaLog.for_table(target)
    real_update = target_log.update
    lied = []

    def update_lying_once(stale_ok=False):
        if not lied:
            lied.append(1)
            return SimpleNamespace(version=-1)  # pre-check sees "no table"
        return real_update(stale_ok=stale_ok)

    monkeypatch.setattr(target_log, "update", update_lying_once)
    with pytest.raises(DeltaAnalysisError, match="already exists"):
        CloneCommand(src.delta_log, target).run()
    # and nothing was appended to the raced table
    assert DeltaLog.for_table(target).update().version == 0
