"""Time travel + history manager semantics.

Ports the high-value slices of the reference's ``DeltaTimeTravelSuite``
(726 LoC) and ``DeltaHistoryManagerSuite`` (163 LoC): version reads,
timestamp→version resolution with monotonized commit timestamps, the
out-of-range error contract, reproducibility after log cleanup, and the
API-level time-travel options. Commit timestamps are file mtimes (as in the
reference, which sets mtimes directly via ``ManualClock`` tests).
"""
import os

import pyarrow as pa
import pytest

from tests.conftest import commit_manually, init_metadata

from delta_tpu.api.tables import DeltaTable
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import AddFile, Protocol
from delta_tpu.utils.errors import (
    DeltaAnalysisError,
    TemporallyUnstableInputError,
    TimestampEarlierThanCommitRetentionError,
    VersionNotFoundError,
)

HOUR_MS = 3_600_000


def add(path, size=1):
    return AddFile(path, {}, size, 0, True)


def set_commit_time(log, version, ts_ms):
    """Pin a commit file's mtime (the reference's ManualClock trick)."""
    p = f"{log.log_path}/{filenames.delta_file(version)}"
    os.utime(p, (ts_ms / 1000, ts_ms / 1000))


def bootstrap(tmp_table, n_commits=5, base_ts=10 * HOUR_MS):
    """n commits, one AddFile each, timestamps one hour apart."""
    log = DeltaLog.for_table(tmp_table)
    commit_manually(log, 0, [Protocol(1, 2), init_metadata(), add("f-0")])
    for v in range(1, n_commits):
        commit_manually(log, v, [add(f"f-{v}")])
    for v in range(n_commits):
        set_commit_time(log, v, base_ts + v * HOUR_MS)
    return log


# -- version time travel -----------------------------------------------------


def test_snapshot_at_each_version(tmp_table):
    log = bootstrap(tmp_table)
    for v in range(5):
        snap = log.get_snapshot_at(v)
        assert snap.version == v
        assert len(snap.all_files) == v + 1


def test_version_negative_rejected(tmp_table):
    log = bootstrap(tmp_table)
    with pytest.raises((VersionNotFoundError, DeltaAnalysisError)):
        log.get_snapshot_at(-3)


def test_version_beyond_latest_rejected(tmp_table):
    log = bootstrap(tmp_table)
    with pytest.raises((VersionNotFoundError, DeltaAnalysisError)):
        log.get_snapshot_at(99)


def test_version_travel_is_stable_under_new_commits(tmp_table):
    log = bootstrap(tmp_table)
    old = log.get_snapshot_at(2)
    commit_manually(log, 5, [add("f-5")])
    log.update()
    assert len(old.all_files) == 3  # pinned snapshot unaffected
    assert len(log.get_snapshot_at(2).all_files) == 3


# -- timestamp → version resolution ------------------------------------------


def test_timestamp_exactly_on_commit(tmp_table):
    log = bootstrap(tmp_table)
    c = log.history.get_active_commit_at_time(10 * HOUR_MS + 2 * HOUR_MS)
    assert c.version == 2


def test_timestamp_between_commits_resolves_to_earlier(tmp_table):
    log = bootstrap(tmp_table)
    c = log.history.get_active_commit_at_time(10 * HOUR_MS + 2 * HOUR_MS + 1)
    assert c.version == 2
    c = log.history.get_active_commit_at_time(10 * HOUR_MS + 3 * HOUR_MS - 1)
    assert c.version == 2


def test_timestamp_before_earliest_raises(tmp_table):
    log = bootstrap(tmp_table)
    with pytest.raises(TimestampEarlierThanCommitRetentionError):
        log.history.get_active_commit_at_time(HOUR_MS)


def test_timestamp_before_earliest_can_return_earliest(tmp_table):
    log = bootstrap(tmp_table)
    c = log.history.get_active_commit_at_time(
        HOUR_MS, can_return_earliest_commit=True
    )
    assert c.version == 0


def test_timestamp_after_latest_raises_unstable(tmp_table):
    log = bootstrap(tmp_table)
    with pytest.raises(TemporallyUnstableInputError):
        log.history.get_active_commit_at_time(10 * HOUR_MS + 100 * HOUR_MS)


def test_timestamp_after_latest_can_return_last(tmp_table):
    log = bootstrap(tmp_table)
    c = log.history.get_active_commit_at_time(
        10 * HOUR_MS + 100 * HOUR_MS, can_return_last_commit=True
    )
    assert c.version == 4


# -- timestamp monotonization ------------------------------------------------


def test_regressing_mtimes_are_monotonized(tmp_table):
    """File mtimes can regress (clock skew, copies); resolution must treat
    the sequence as monotone: a later version never maps to an earlier
    adjusted timestamp (``DeltaHistoryManager`` monotonization)."""
    log = bootstrap(tmp_table)
    # regress version 3's mtime to BEFORE version 2's
    set_commit_time(log, 3, 10 * HOUR_MS + HOUR_MS // 2)
    commits = log.history.get_commits(0, 4)
    ts = [c.timestamp for c in commits]
    assert ts == sorted(ts), "timestamps must be non-decreasing after adjustment"
    assert [c.version for c in commits] == [0, 1, 2, 3, 4]
    # v3's adjusted timestamp nudges just past v2's
    assert commits[3].timestamp > commits[2].timestamp


def test_resolution_with_regressed_mtime(tmp_table):
    log = bootstrap(tmp_table)
    set_commit_time(log, 3, 10 * HOUR_MS)  # same as v0
    # a timestamp just after v2's commit still resolves to v2 (not v3,
    # whose raw mtime regressed below it)
    c = log.history.get_active_commit_at_time(10 * HOUR_MS + 2 * HOUR_MS + 60_000)
    assert c.version in (2, 3)
    commits = log.history.get_commits(0, 4)
    assert [c.version for c in commits] == sorted(c.version for c in commits)


# -- reproducibility after cleanup -------------------------------------------


def checkpointed_log_with_cleaned_head(tmp_table):
    """10 commits, checkpoint at 6, versions 0-3 deleted from the log.

    Commit mtimes sit within LOG_RETENTION of now — the checkpoint's
    automatic metadata cleanup must NOT delete them; the head deletion
    below is the manual 'someone cleaned the log' scenario.
    """
    import time as _time

    now = int(_time.time() * 1000)
    log = bootstrap(tmp_table, n_commits=10, base_ts=now - 10 * HOUR_MS)
    log.update()
    log.checkpoint(log.get_snapshot_at(6))
    assert os.path.exists(f"{log.log_path}/{filenames.delta_file(0)}"), (
        "retention cleanup must not touch commits younger than LOG_RETENTION"
    )
    for v in range(0, 4):
        os.remove(f"{log.log_path}/{filenames.delta_file(v)}")
    DeltaLog.clear_cache()
    return DeltaLog.for_table(tmp_table)


def test_earliest_reproducible_commit_after_cleanup(tmp_table):
    log = checkpointed_log_with_cleaned_head(tmp_table)
    # versions 0-3 are gone; earliest rebuildable state is the checkpoint
    assert log.history.get_earliest_reproducible_commit() == 6
    assert log.history.get_earliest_delta_file() == 4


def test_travel_to_cleaned_version_fails(tmp_table):
    log = checkpointed_log_with_cleaned_head(tmp_table)
    with pytest.raises((VersionNotFoundError, DeltaAnalysisError)):
        log.history.check_version_exists(2)


def test_travel_to_checkpoint_covered_version(tmp_table):
    log = checkpointed_log_with_cleaned_head(tmp_table)
    snap = log.get_snapshot_at(7)
    assert snap.version == 7
    assert len(snap.all_files) == 8


def test_full_history_intact_log(tmp_table):
    log = bootstrap(tmp_table, n_commits=3)
    hist = log.history.get_history()
    assert [h.version for h in hist] == [2, 1, 0]


def test_history_limit(tmp_table):
    log = bootstrap(tmp_table, n_commits=5)
    hist = log.history.get_history(limit=2)
    assert [h.version for h in hist] == [4, 3]


def test_history_stops_at_cleaned_versions(tmp_table):
    log = checkpointed_log_with_cleaned_head(tmp_table)
    hist = log.history.get_history()
    # newest-first, stops where the log was cleaned (v3 and below gone)
    assert [h.version for h in hist] == [9, 8, 7, 6, 5, 4]


# -- API-level time travel ---------------------------------------------------


def api_table(tmp_table):
    data = pa.table({"id": [1, 2], "value": ["a", "b"]})
    t = DeltaTable.create(tmp_table, data=data)
    t.delta_log.store.write  # touch
    import pyarrow as _pa

    for i in range(2):
        from delta_tpu.commands.write import WriteIntoDelta

        WriteIntoDelta(
            t.delta_log, "append",
            _pa.table({"id": [10 + i], "value": [f"v{i}"]}),
        ).run()
    return t


def test_to_arrow_version_as_of(tmp_table):
    t = api_table(tmp_table)
    assert t.version == 2
    assert t.to_arrow(version=0).num_rows == 2
    assert t.to_arrow(version=1).num_rows == 3
    assert t.to_arrow().num_rows == 4


def test_to_arrow_timestamp_as_of(tmp_table):
    t = api_table(tmp_table)
    log = t.delta_log
    for v in range(3):
        set_commit_time(log, v, (10 + v) * HOUR_MS)
    DeltaLog.clear_cache()
    t = DeltaTable.for_path(tmp_table)
    got = t.to_arrow(timestamp=11 * HOUR_MS + 1)
    assert got.num_rows == 3  # version 1


def test_to_arrow_timestamp_string_form(tmp_table):
    t = api_table(tmp_table)
    log = t.delta_log
    import datetime as dt

    base = dt.datetime(2024, 5, 1, tzinfo=dt.timezone.utc)
    for v in range(3):
        set_commit_time(log, v, int(base.timestamp() * 1000) + v * HOUR_MS)
    DeltaLog.clear_cache()
    t = DeltaTable.for_path(tmp_table)
    got = t.to_arrow(timestamp="2024-05-01 01:30:00")
    assert got.num_rows == 3


def test_version_and_timestamp_both_rejected(tmp_table):
    t = api_table(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        t.to_arrow(version=1, timestamp=10 * HOUR_MS)


def test_time_travel_sees_old_schema(tmp_table):
    """Schema is part of the snapshot: travel before an ADD COLUMNS must
    yield the old schema (reference: time travel reads the pinned
    snapshot's metadata, not the latest)."""
    t = api_table(tmp_table)
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.schema.types import LongType, StructField

    add_columns(t.delta_log, [StructField("extra", LongType())])
    old = t.to_arrow(version=2)
    new = t.to_arrow()
    assert "extra" not in old.column_names
    assert "extra" in new.column_names


def test_get_changes_tailing(tmp_table):
    log = bootstrap(tmp_table, n_commits=4)
    changes = list(log.get_changes(2))
    assert [v for v, _ in changes] == [2, 3]
    # each change carries that commit's actions
    assert any(
        getattr(a, "path", None) == "f-3" for _, acts in changes for a in acts
    )


def test_timestamp_option_parsing_forms():
    """One parser for every timestamp option surface: epoch ms, ISO-8601
    naive (= UTC), explicit offsets, and the 'Z' suffix (normalized before
    fromisoformat, which only accepts 'Z' natively from Python 3.11)."""
    from delta_tpu.utils.timeparse import timestamp_option_to_ms

    base = 1_714_564_800_000  # 2024-05-01T12:00:00Z
    assert timestamp_option_to_ms(base) == base
    assert timestamp_option_to_ms(str(base)) == base
    assert timestamp_option_to_ms("2024-05-01 12:00:00") == base
    assert timestamp_option_to_ms("2024-05-01T12:00:00Z") == base
    assert timestamp_option_to_ms("2024-05-01T14:00:00+02:00") == base
    import pytest

    from delta_tpu.utils.errors import DeltaAnalysisError

    with pytest.raises(DeltaAnalysisError):
        timestamp_option_to_ms("not-a-time")
    with pytest.raises(DeltaAnalysisError):
        timestamp_option_to_ms(True)
