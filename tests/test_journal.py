"""Workload journal + layout advisor (`delta_tpu/obs/journal.py`,
`delta_tpu/obs/advisor.py`): persistent per-table JSONL segments recording
scans/commits/DML routing, the predicate fingerprint, segment
rotation/sweep bounds, blackout inertness, the advisor's evidence-backed
recommendations (and their survival across a process "restart"), the HTTP
``/advisor`` route, the flight-recorder embeds, and the offline dump tool.
"""
import json
import os
import threading

import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.obs import journal
from delta_tpu.obs.advisor import advise
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_journal():
    journal.reset()
    telemetry.reset_all()
    yield
    journal.reset()
    telemetry.clear_events()


def _ids(n, extra_col=True):
    cols = {"id": pa.array(range(n), pa.int64())}
    if extra_col:
        cols["v"] = pa.array(range(n), pa.int64())
    return pa.table(cols)


def _dir_bytes(jdir):
    return sum(os.path.getsize(os.path.join(jdir, f))
               for f in os.listdir(jdir))


# -- recording hooks ---------------------------------------------------------


def test_scan_entries_carry_report_and_fingerprint(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    t.to_arrow(filters=["v = 7"])
    t.to_arrow(filters=["v > 3", "id = 1"])
    journal.flush()
    scans = journal.read_entries(t.delta_log.log_path, kinds=["scan"])
    assert len(scans) == 2
    first = scans[0]
    assert first["report"]["filesTotal"] == 1
    assert first["report"]["rowsOut"] == 1
    assert first["fingerprint"]["columns"] == ["v"]
    assert first["fingerprint"]["key"] == "eq(v,?)"
    [c] = first["fingerprint"]["conjuncts"]
    assert c["prunable"] is True and c["partition"] is False
    second = scans[1]
    assert second["fingerprint"]["columns"] == ["id", "v"]
    assert set(second["fingerprint"]["prunableColumns"]) == {"id", "v"}
    assert first.get("ts")


def test_fingerprint_normalizes_literals_and_splits_residual():
    from delta_tpu.expr.parser import parse_predicate

    fp1 = journal.predicate_fingerprint(parse_predicate("v = 5"))
    fp2 = journal.predicate_fingerprint(parse_predicate("v = 900"))
    assert fp1["key"] == fp2["key"] == "eq(v,?)"
    # arithmetic over columns is NOT min/max-evaluable without rewrite
    # synthesis: it lands in the residual split with its shape preserved
    fp3 = journal.predicate_fingerprint(
        parse_predicate("price * qty > 1000 AND id = 3"))
    assert fp3["prunableColumns"] == ["id"]
    assert set(fp3["residualColumns"]) == {"price", "qty"}
    shapes = {c["shape"] for c in fp3["conjuncts"]}
    assert "gt(mul(price,qty),?)" in shapes and "eq(id,?)" in shapes
    # partition-only conjuncts are flagged
    fp4 = journal.predicate_fingerprint(
        parse_predicate("p = 'x'"), partition_cols=["p"])
    assert fp4["conjuncts"][0]["partition"] is True
    assert journal.predicate_fingerprint(None) is None


def test_fingerprint_or_of_residual_shapes_is_not_prunable():
    """skipping_predicate recurses through OR, so an unsupported
    disjunction rewrites to Or(NULL, NULL) — NOT a bare Literal(None) root.
    Three-valued logic: an OR with an unknowable branch can never exclude a
    row group, so the conjunct must land in the residual split (else the
    advisor blames layout for a shape problem and recommends a Z-ORDER
    that cannot help)."""
    from delta_tpu.expr.parser import parse_predicate

    fp = journal.predicate_fingerprint(
        parse_predicate("a + b = 1 OR c + d = 2"))
    assert fp["conjuncts"][0]["prunable"] is False
    assert fp["prunableColumns"] == []
    assert set(fp["residualColumns"]) == {"a", "b", "c", "d"}
    # an OR of two genuinely evaluable comparisons CAN exclude
    fp2 = journal.predicate_fingerprint(parse_predicate("v = 1 OR v = 2"))
    assert fp2["conjuncts"][0]["prunable"] is True
    # ...but one unknowable branch poisons the whole OR
    fp3 = journal.predicate_fingerprint(
        parse_predicate("v = 1 OR a + b = 2"))
    assert fp3["conjuncts"][0]["prunable"] is False
    # AND excludes through either side, even nested inside the conjunct
    fp4 = journal.predicate_fingerprint(
        parse_predicate("(v = 1 AND a + b = 2) OR v = 3"))
    assert fp4["conjuncts"][0]["prunable"] is True


def test_commit_and_dml_entries(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    t.update({"v": "v + 1"}, "id = 3")
    t.delete("id = 7")
    journal.flush()
    entries = journal.read_entries(t.delta_log.log_path)
    commits = [e for e in entries if e["kind"] == "commit"]
    assert len(commits) == 3  # create + update + delete
    assert all(e["outcome"] == "committed" for e in commits)
    assert commits[1]["stats"]["operation"] == "UPDATE"
    assert commits[1]["stats"]["attempts"] == 1
    dmls = [e for e in entries if e["kind"] == "dml"]
    assert [e["op"] for e in dmls] == ["update", "delete"]
    assert dmls[0]["mode"] == "rewrite"
    assert dmls[0]["metrics"]["numUpdatedRows"] == 1
    assert dmls[0]["version"] == 1


def test_conflict_commits_journaled(tmp_table):
    """An aborted commit (genuine logical conflict) still leaves a journal
    entry — contention analysis needs the failures."""
    from delta_tpu.commands import operations as ops
    from delta_tpu.utils import errors

    t = DeltaTable.create(tmp_table, data=_ids(20))
    log = t.delta_log
    txn = log.start_transaction()
    txn.read_whole_table()
    removes = [f.remove() for f in txn.snapshot.all_files]
    # interleaving writer deletes the same files first -> our delete hits
    # a concurrent-delete-delete conflict on retry
    t.delete()
    with pytest.raises(errors.DeltaConcurrentModificationException):
        txn.commit(removes, ops.Delete(predicate=[]))
    journal.flush()
    commits = journal.read_entries(log.log_path, kinds=["commit"])
    conflicted = [e for e in commits if e["outcome"] == "conflict"]
    assert len(conflicted) == 1
    assert conflicted[0]["stats"]["attempts"] >= 1


def test_merge_dml_entry_carries_decision_and_audit(tmp_table):
    t = DeltaTable.create(tmp_table, data=pa.table({
        "id": pa.array(range(100), pa.int64()),
        "x": pa.array(range(100), pa.int64()),
    }))
    src = pa.table({"id": pa.array([3, 500], pa.int64()),
                    "x": pa.array([-1, -2], pa.int64())})
    (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
     .when_matched_update_all().when_not_matched_insert_all().execute())
    journal.flush()
    entries = journal.read_entries(t.delta_log.log_path)
    [merge] = [e for e in entries if e["kind"] == "dml" and e["op"] == "merge"]
    assert merge["decision"]  # host / resident / device-cold / ...
    if merge["audit"] is not None:
        assert isinstance(merge["audit"]["miss"], bool)
        assert merge["audit"]["actualMs"] >= 0
    # the router audit itself is journaled too (hook in obs/router_audit)
    routers = [e for e in entries if e["kind"] == "router"]
    assert any(e["audit"]["op"] == "merge.join" for e in routers)


# -- blackout + enablement ---------------------------------------------------


def test_blackout_writes_zero_bytes_and_advise_reports_no_history(tmp_table):
    with conf.set_temporarily(delta__tpu__telemetry__enabled=False):
        t = DeltaTable.create(tmp_table, data=_ids(50))
        t.to_arrow(filters=["v = 1"])
        t.update({"v": "v + 1"}, "id = 3")
        journal.flush()
        jdir = journal.journal_dir(t.delta_log.log_path)
        assert not os.path.isdir(jdir), "blackout must write ZERO journal bytes"
        rep = t.advise()
        assert rep.status == "no history"
        assert rep.recommendations == []
        assert "blackout" in rep.facts["reason"] or "disabled" in rep.facts["reason"]
    # journal.enabled=false behaves identically with telemetry on
    with conf.set_temporarily(delta__tpu__journal__enabled=False):
        t.to_arrow(filters=["v = 2"])
        journal.flush()
        assert not os.path.isdir(jdir)
        assert t.advise().status == "no history"


def test_object_store_paths_never_journal():
    assert journal.enabled("s3://bucket/tbl/_delta_log") is False
    assert journal.enabled("/local/tbl/_delta_log") is True
    # record_* are no-ops, not errors, for remote tables
    journal.record_dml("s3://bucket/tbl/_delta_log", "merge", decision="host")
    assert journal.flush() == 0


# -- segment rotation + sweep ------------------------------------------------


def test_segment_rotation_and_sweep_bounds(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(50))
    log_path = t.delta_log.log_path
    jdir = journal.journal_dir(log_path)
    with conf.set_temporarily(**{
        "delta.tpu.journal.segmentBytes": 400,
        "delta.tpu.journal.maxBytes": 2000,
    }):
        for i in range(60):
            journal.record_dml(log_path, "update", mode="dv",
                               metrics={"numUpdatedRows": i})
            journal.flush(log_path)  # one write per entry -> forced rotations
        segs = sorted(os.listdir(jdir))
        assert len(segs) > 1, "segmentBytes bound must rotate segments"
        # every closed segment respects the size bound (+ one entry slop)
        for s in segs[:-1]:
            assert os.path.getsize(os.path.join(jdir, s)) <= 600
        assert _dir_bytes(jdir) <= 2000 + 600, "maxBytes sweep must bound the dir"
        assert telemetry.counters("journal.segments.swept")[
            "journal.segments.swept"] >= 1
    # entries survive in the retained tail, oldest swept first
    entries = journal.read_entries(log_path, kinds=["dml"])
    assert entries, "sweep must never empty the journal"
    assert entries[-1]["metrics"]["numUpdatedRows"] == 59


def test_sweep_drops_aged_segments(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.record_dml(log_path, "update", mode="dv", metrics={})
    journal.flush(log_path)
    jdir = journal.journal_dir(log_path)
    [seg] = [n for n in os.listdir(jdir) if n.endswith(".jsonl")]
    old = os.path.join(jdir, "journal-0000000000001-1-000001.jsonl")
    with open(old, "w", encoding="utf-8") as f:
        f.write('{"kind":"dml","op":"old"}\n')
    past = 1_000_000  # epoch 1970: far past any retention window
    os.utime(old, (past, past))
    assert journal.sweep(jdir) == 1
    assert not os.path.exists(old)
    assert os.path.exists(os.path.join(jdir, seg))


def test_read_entries_limit_zero_returns_nothing(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    journal.flush()
    log_path = t.delta_log.log_path
    assert journal.read_entries(log_path, limit=0) == []
    assert len(journal.read_entries(log_path, limit=1)) == 1
    assert journal.read_entries(log_path, limit=None)


def test_partition_survival_counts_perfect_pruning(tmp_table):
    """filesAfterPartition=0 is perfect pruning (survival 0.0), not missing
    data — the falsy-zero regression."""
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.flush()
    journal._record(log_path, {
        "kind": "scan",
        "report": {"filesTotal": 100, "filesAfterPartition": 0},
    })
    journal.flush()
    rep = advise(tmp_table)
    assert rep.facts["partition"]["meanPartitionSurvival"] == 0.0


def test_retry_fraction_counts_each_commit_once(tmp_table):
    """A conflict entry that also retried must not double-count toward the
    contention fraction."""
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.flush()
    for i in range(10):
        if i < 3:  # conflicted AND retried: one contended commit, not two
            journal.record_commit(log_path, {"attempts": 2}, outcome="conflict")
        else:
            journal.record_commit(log_path, {"attempts": 1})
    rep = advise(tmp_table)
    cf = rep.facts["commits"]
    # 3 contended of 10 synthetic + 1 real create commit
    assert cf["retryFraction"] == pytest.approx(3 / 11, abs=1e-4)


def test_cleanup_sweeps_journal_even_when_disabled(tmp_table):
    """A table that STOPPED journaling still sheds its history through
    metadata cleanup."""
    from delta_tpu.log.cleanup import cleanup_expired_logs

    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.flush()
    jdir = journal.journal_dir(log_path)
    old = os.path.join(jdir, "journal-0000000000001-1-000001.jsonl")
    with open(old, "w", encoding="utf-8") as f:
        f.write('{"kind":"dml","op":"ancient"}\n')
    os.utime(old, (1_000_000, 1_000_000))
    journal.reset()
    with conf.set_temporarily(delta__tpu__journal__enabled=False):
        cleanup_expired_logs(t.delta_log, t.delta_log.update())
    assert not os.path.exists(old)


def test_read_entries_skips_torn_lines(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.record_dml(log_path, "update", mode="dv", metrics={})
    journal.flush(log_path)
    jdir = journal.journal_dir(log_path)
    [seg] = [n for n in os.listdir(jdir) if n.endswith(".jsonl")]
    before = len(journal.read_entries(log_path))
    with open(os.path.join(jdir, seg), "a", encoding="utf-8") as f:
        f.write('{"kind":"dml","truncated')  # torn tail write
    entries = journal.read_entries(log_path)
    assert len(entries) == before  # the torn line is skipped, not fatal


def test_buffer_cap_drops_not_grows(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.flush()
    # fill past the cap without flushing: drops are counted, memory bounded
    with conf.set_temporarily(**{"delta.tpu.journal.flushEntries": 10 ** 9,
                                 "delta.tpu.journal.flushIntervalMs": 10 ** 9}):
        for i in range(journal.MAX_BUFFERED + 50):
            journal.record_dml(log_path, "update", mode="dv", metrics={})
    assert telemetry.counters("journal.entriesDropped")[
        "journal.entriesDropped"] == 50
    assert journal.flush(log_path) == journal.MAX_BUFFERED


def test_concurrent_recording_loses_nothing(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.flush()
    N, K = 8, 40

    def worker(w):
        for i in range(K):
            journal.record_dml(log_path, "update", mode="dv",
                               metrics={"w": w, "i": i})

    ts = [threading.Thread(target=worker, args=(w,)) for w in range(N)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    journal.flush()
    dmls = journal.read_entries(log_path, kinds=["dml"])
    assert len(dmls) == N * K
    seen = {(e["metrics"]["w"], e["metrics"]["i"]) for e in dmls}
    assert len(seen) == N * K


# -- advisor -----------------------------------------------------------------


def _skewed_workload(path, scans=6):
    """The acceptance shape: a table whose queries repeatedly filter on a
    non-layout column where pruning never fires (wide-range values in every
    file — min/max stats exclude nothing)."""
    import numpy as np

    rng = np.random.RandomState(3)
    t = DeltaTable.create(path, data=pa.table({
        "id": pa.array(range(2000), pa.int64()),
        # every file spans the whole value domain -> stats never exclude
        "v": pa.array(rng.permutation(2000).astype("int64")),
    }))
    t.write(pa.table({
        "id": pa.array(range(2000, 4000), pa.int64()),
        "v": pa.array(rng.permutation(2000).astype("int64")),
    }), mode="append")
    for i in range(scans):
        t.to_arrow(filters=[f"v = {i * 7}"])
    return t


def test_advisor_recommends_zorder_with_cited_evidence(tmp_table):
    t = _skewed_workload(tmp_table)
    rep = t.advise()
    assert rep.status == "ok"
    assert rep.entries > 0
    zorder = [r for r in rep.recommendations if r.kind == "ZORDER"]
    assert zorder, f"expected a ZORDER rec, got {rep.recommendations}"
    top = zorder[0]
    assert top.target == "v"
    assert top.evidence["filterCount"] == 6
    assert top.evidence["pruningMissRate"] == 1.0
    assert "execute_z_order_by('v')" in top.action
    # ranked first: the strongest evidence leads
    assert rep.recommendations[0].kind == "ZORDER"
    # facts cite the never-pruned fingerprint with the layout reason
    [nv] = [g for g in rep.facts["neverPruned"] if g["columns"] == ["v"]]
    assert nv["scans"] == 6 and nv["prunable"] is True
    assert "layout" in nv["reason"]
    json.dumps(rep.to_dict())  # JSON-able end to end


def test_advisor_recommendation_survives_process_restart(tmp_table):
    """Acceptance: the journal re-reads from disk by a fresh DeltaLog —
    in-memory state dropped, caches cleared, same recommendation."""
    _skewed_workload(tmp_table)
    journal.flush()
    journal.reset()          # forget every in-memory buffer/segment handle
    DeltaLog.clear_cache()   # fresh DeltaLog on next resolution
    rep = advise(tmp_table)
    assert rep.status == "ok"
    top = [r for r in rep.recommendations if r.kind == "ZORDER"][0]
    assert top.target == "v"
    assert top.evidence["filterCount"] == 6
    assert top.evidence["pruningMissRate"] == 1.0


def test_advisor_no_zorder_when_pruning_works(tmp_table):
    """Sorted data prunes (files exclude by min/max): no ZORDER rec — the
    advisor must not recommend re-layout for a layout that works."""
    t = DeltaTable.create(tmp_table, data=pa.table({
        "id": pa.array(range(2000), pa.int64()),
        "v": pa.array(range(2000), pa.int64()),   # sorted: tight per-file stats
    }))
    t.write(pa.table({
        "id": pa.array(range(2000, 4000), pa.int64()),
        "v": pa.array(range(2000, 4000), pa.int64()),
    }), mode="append")
    for i in range(6):
        t.to_arrow(filters=[f"v = {i * 7}"])  # hits file 1, file 2 pruned
    rep = t.advise()
    assert rep.status == "ok"
    assert not [r for r in rep.recommendations if r.kind == "ZORDER"]
    assert rep.facts["columns"]["v"]["missRate"] == 0.0


def test_advisor_flags_residual_only_shapes(tmp_table):
    """neverPruned splits by reason: a shape predicate synthesis can lower
    but that never excluded anything is 'synthesizedLayout' (clustering
    WOULD help it now); one synthesis has no sound rewrite for (division
    by a zero-crossing column interval) stays 'shape'."""
    t = DeltaTable.create(tmp_table, data=pa.table({
        "price": pa.array([float(i) for i in range(100)], pa.float64()),
        "qty": pa.array(range(100), pa.int64()),
    }))
    for _ in range(3):
        t.to_arrow(filters=["price * qty > 1000"])
        t.to_arrow(filters=["qty / price > 2"])
    rep = t.advise()
    [g] = [g for g in rep.facts["neverPruned"]
           if g["fingerprint"].startswith("gt(mul")]
    assert g["prunable"] is True
    assert g["reason"].startswith("synthesizedLayout")
    [g2] = [g2 for g2 in rep.facts["neverPruned"]
            if g2["fingerprint"].startswith("gt(div")]
    assert g2["prunable"] is False
    assert g2["reason"].startswith("shape")


def test_row_group_facts_ignore_unpredicated_scans(tmp_table):
    """``rowGroupsTotal`` is populated only for predicated scans (footers
    are consulted only under a predicate/position hint) — unfiltered
    full-table scans must not dilute rowGroupsPerScannedFile toward 0 and
    fabricate a ROW_GROUP_SIZE recommendation."""
    from delta_tpu.expr.parser import parse_predicate

    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    for _ in range(10):  # full scans: footers untouched
        journal.record_scan(log_path, report_dict={
            "filesScanned": 10, "rowGroupsTotal": 0})
    for _ in range(4):   # predicated, 2 row groups per file, never pruned
        journal.record_scan(log_path, report_dict={
            "filesScanned": 10, "rowGroupsTotal": 20,
            "filesPruned": 0, "rowGroupsPruned": 0},
            predicate=parse_predicate("v = 1"))
    rep = advise(tmp_table)
    rgf = rep.facts["rowGroups"]
    assert rgf["rowGroupsPerScannedFile"] == 2.0
    assert rgf["filesScanned"] == 40
    assert not [r for r in rep.recommendations if r.kind == "ROW_GROUP_SIZE"]


def test_sweep_ages_out_the_newest_segment(tmp_table):
    """Age expiry reaches the NEWEST segment too — a table that stopped
    journaling must shed its final segment through the cleanup sweep —
    while this process's own active segment stays exempt."""
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.flush()
    jdir = journal.journal_dir(log_path)
    [seg] = [n for n in os.listdir(jdir) if n.endswith(".jsonl")]
    past = (1_000_000, 1_000_000)
    os.utime(os.path.join(jdir, seg), past)
    # the segment is this process's active file: exempt even when stale
    assert journal.sweep(jdir) == 0
    assert os.path.exists(os.path.join(jdir, seg))
    # a fresh process (no active handle) sweeps it
    journal.reset()
    assert journal.sweep(jdir) == 1
    assert not os.path.exists(os.path.join(jdir, seg))


def test_read_entries_sorts_by_timestamp_across_segments(tmp_table):
    """Two processes journaling the same table interleave in time while
    each appends to its own active segment — segment-name order alone
    would time-scramble the advisor's 'recent window' (limit / recent-half
    trends). Entries stable-sort by their recorded ts."""
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.flush()
    jdir = journal.journal_dir(log_path)
    # simulate process A's long-lived segment (name sorts FIRST) holding
    # entries written both before and after process B's whole segment
    with open(os.path.join(jdir, "journal-0000000000001-1-000001.jsonl"),
              "w", encoding="utf-8") as f:
        f.write('{"kind":"dml","op":"a-early","ts":1000}\n')
        f.write('{"kind":"dml","op":"a-late","ts":4000}\n')
    with open(os.path.join(jdir, "journal-0000000000002-2-000001.jsonl"),
              "w", encoding="utf-8") as f:
        f.write('{"kind":"dml","op":"b-mid","ts":2000}\n')
    entries = journal.read_entries(log_path, kinds=["dml"])
    assert [e["op"] for e in entries] == ["a-early", "b-mid", "a-late"]
    # the recent window is genuinely recent
    assert [e["op"] for e in journal.read_entries(
        log_path, kinds=["dml"], limit=1)] == ["a-late"]


def test_advisor_zorder_not_masked_by_partition_pruning(tmp_table):
    """``filesPruned`` counts BOTH pruning tiers — on a partitioned table
    every scan partition-prunes something, which must not mask a data
    column whose min/max stats never exclude anything (the headline
    acceptance scenario on a partitioned table)."""
    from delta_tpu.expr.parser import parse_predicate

    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    for _ in range(4):  # partition tier halves the files; stats tier: nothing
        journal.record_scan(log_path, report_dict={
            "filesTotal": 10, "filesAfterPartition": 5, "filesScanned": 5,
            "rowGroupsTotal": 5, "rowGroupsPruned": 0,
            "rowGroupsLateSkipped": 0},
            predicate=parse_predicate("date = 1 AND v = 2"),
            partition_cols=["date"])
    rep = advise(tmp_table)
    assert rep.facts["columns"]["v"]["missRate"] == 1.0
    assert [r for r in rep.recommendations
            if r.kind == "ZORDER" and r.target == "v"]
    # ...but the stats tier firing DOES count as pruned
    journal.record_scan(log_path, report_dict={
        "filesTotal": 10, "filesAfterPartition": 5, "filesScanned": 2,
        "rowGroupsTotal": 2},
        predicate=parse_predicate("date = 1 AND v = 2"),
        partition_cols=["date"])
    rep = advise(tmp_table)
    assert rep.facts["columns"]["v"]["missRate"] < 1.0


def test_never_pruned_partition_filter_gets_partition_reason(tmp_table):
    """A pure partition filter that never excludes a partition IS pushed
    down — the reason must point at value distribution, not clustering or
    rewrite synthesis."""
    from delta_tpu.expr.parser import parse_predicate

    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    for _ in range(3):
        journal.record_scan(log_path, report_dict={
            "filesTotal": 4, "filesAfterPartition": 4, "filesScanned": 4},
            predicate=parse_predicate("region = 'eu'"),
            partition_cols=["region"])
    rep = advise(tmp_table)
    [g] = [g for g in rep.facts["neverPruned"] if g["columns"] == ["region"]]
    assert g["partition"] is True
    assert g["reason"].startswith("partition:")
    # and no ZORDER rec for a column that's already the partition layout
    assert not [r for r in rep.recommendations if r.kind == "ZORDER"]


def test_sweep_size_pressure_spares_each_pids_newest_segment(tmp_table):
    """Segment names embed the creating pid and a process appends only to
    its newest segment — size pressure must never delete a concurrent
    writer's possibly-active file, only settled (non-newest-per-pid)
    segments."""
    t = DeltaTable.create(tmp_table, data=_ids(10))
    jdir = journal.journal_dir(t.delta_log.log_path)
    journal.reset()  # no in-process active handle
    os.makedirs(jdir, exist_ok=True)
    line = json.dumps({"kind": "dml", "op": "x", "ts": 1}) + "\n"
    segs = ["journal-0000000000001-111-000001.jsonl",
            "journal-0000000000002-111-000002.jsonl",
            "journal-0000000000003-222-000001.jsonl"]
    for n in segs:
        with open(os.path.join(jdir, n), "w", encoding="utf-8") as f:
            f.write(line * 10)
    with conf.set_temporarily(**{"delta.tpu.journal.maxBytes": 1}):
        assert journal.sweep(jdir) == 1
    left = sorted(n for n in os.listdir(jdir) if n.endswith(".jsonl"))
    # pid 111's older segment swept; each pid's newest survives
    assert left == [segs[1], segs[2]]


def test_advisor_commit_contention_recommendation(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    ts0 = 1_700_000_000_000
    for i in range(12):
        journal.record_commit(log_path, {
            "operation": "WRITE", "attempts": 3 if i % 2 else 1,
            "commitVersion": i,
        })
    # pin timestamps into two 60s windows for the window detector
    journal.flush()
    entries = journal.read_entries(log_path, kinds=["commit"])
    assert len(entries) >= 12
    rep = advise(tmp_table)
    cf = rep.facts["commits"]
    assert cf["retried"] == 6
    assert cf["retryFraction"] >= 0.2
    [rec] = [r for r in rep.recommendations if r.kind == "COMMIT_CONTENTION"]
    assert rec.evidence["commits"] == cf["commits"]
    assert "group commit" in rec.action


def test_advisor_calibration_and_hbm_recommendations(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    for i in range(6):
        journal.record_router(log_path, {
            "op": "merge.join", "decision": "host", "miss": i % 2 == 0,
            "predictedMs": {"host": 1.0}, "actualMs": 2.0,
        })
        journal.record_dml(log_path, "merge", decision="device-cold",
                           router={}, audit=None)
    rep = advise(tmp_table)
    kinds = {r.kind: r for r in rep.recommendations}
    assert "CALIBRATION" in kinds
    assert kinds["CALIBRATION"].evidence["missRate"] == 0.5
    assert "HBM_BUDGET" in kinds
    assert kinds["HBM_BUDGET"].evidence["coldDeviceMerges"] == 6
    assert rep.facts["keyCache"]["hitRate"] == 0.0


def test_advisor_empty_table_no_history(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(5))
    # nothing journaled for a DIFFERENT table path
    other = tmp_table + "_other"
    DeltaTable.create(other, data=_ids(5))
    journal.reset()
    import shutil

    shutil.rmtree(journal.journal_dir(
        DeltaTable.for_path(other).delta_log.log_path), ignore_errors=True)
    rep = advise(other)
    assert rep.status == "no history"
    assert rep.entries == 0
    assert rep.recommendations == []


# -- surfaces: doctor cross-link, HTTP route, dump tool, flight recorder -----


def test_doctor_report_cross_links_advisor(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(10))
    d = t.doctor().to_dict()
    assert "advise" in d["advisor"] and "/advisor" in d["advisor"]
    ad = t.advise().to_dict()
    assert "doctor" in ad["doctor"].lower()


def test_advisor_http_route(tmp_table):
    import urllib.request

    from delta_tpu.obs.server import ObsServer

    t = _skewed_workload(tmp_table, scans=4)
    journal.flush()
    server = ObsServer(0)
    try:
        host, port = server.address
        url = f"http://{host}:{port}/advisor?path={urllib.request.quote(tmp_table)}"
        with urllib.request.urlopen(url) as resp:
            assert resp.status == 200
            served = json.loads(resp.read())
        assert served["status"] == "ok"
        assert any(r["kind"] == "ZORDER" and r["target"] == "v"
                   for r in served["recommendations"])
        # missing ?path= is a 400, and the route is advertised on 404s
        req = urllib.request.Request(f"http://{host}:{port}/advisor")
        try:
            urllib.request.urlopen(req)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
        try:
            urllib.request.urlopen(f"http://{host}:{port}/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert "/advisor" in json.loads(e.read())["routes"]
    finally:
        server.stop()


def test_journal_dump_tool(tmp_table, capsys):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.journal_dump import main

    t = DeltaTable.create(tmp_table, data=_ids(20))
    t.to_arrow(filters=["v = 3"])
    journal.flush()
    assert main([tmp_table, "--kind", "scan"]) == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert len(lines) == 1 and lines[0]["kind"] == "scan"
    assert main([tmp_table, "--summary"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["segments"] >= 1 and summary["byKind"]["scan"] == 1
    assert main([tmp_table, "--advise"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["status"] == "ok"


def test_flight_recorder_embeds_scan_report_and_last_audit(tmp_path):
    """Satellite: incidents show WHAT the query was doing — the in-flight
    ScanReport and the last router-audit record ride into the file."""
    from delta_tpu.obs import flight_recorder, router_audit, scan_report

    router_audit.clear_audits()
    router_audit.record_audit("merge.join", "/t", "host",
                              {"host": 0.1, "device": 0.5}, 0.2,
                              units={"targetRows": 10})
    flight_recorder.install()
    inc_dir = str(tmp_path / "incidents")
    with conf.set_temporarily(**{"delta.tpu.obs.incidentDir": inc_dir}):
        token = scan_report.start_report("/t", 3)
        scan_report.contribute(bytes_read=123)
        try:
            with pytest.raises(ValueError):
                with telemetry.record_operation("delta.scan", path="/t"):
                    raise ValueError("mid-scan failure")
        finally:
            scan_report.finish_report(token, completed=False)
    [f] = flight_recorder.incident_files(inc_dir)
    incident = json.loads(open(f).read())
    assert incident["scanReport"]["bytesRead"] == 123
    assert incident["scanReport"]["version"] == 3
    assert incident["routerAudit"]["op"] == "merge.join"
    assert incident["routerAudit"]["decision"] == "host"
    router_audit.clear_audits()


def test_bench_snapshot_carries_journal_counters(tmp_table):
    t = DeltaTable.create(tmp_table, data=_ids(30))
    t.to_arrow(filters=["v = 1"])
    journal.flush()
    snap = telemetry.bench_snapshot(include=("journal", "advisor"))
    assert snap["counters"].get("journal.entries", 0) >= 1
    advise(tmp_table)
    snap = telemetry.bench_snapshot(include=("journal", "advisor"))
    assert snap["counters"].get("advisor.runs", 0) >= 1


# -- review-fix regressions --------------------------------------------------


def test_advisor_empty_table_scans_do_not_fabricate_zorder(tmp_table):
    """Scans over a zero-file table carry no pruning evidence: pruning
    could not possibly have fired, so repeated filters against an empty
    table must not manufacture a 100%-miss ZORDER/PARTITION case."""
    from delta_tpu.schema.types import LongType, StructType

    t = DeltaTable.create(tmp_table, StructType().add("v", LongType()))
    for i in range(4):
        t.to_arrow(filters=[f"v = {i}"])
    journal.flush()
    scans = journal.read_entries(t.delta_log.log_path, kinds=["scan"])
    assert scans and all(
        (s["report"].get("filesTotal") or 0) == 0 for s in scans)
    rep = t.advise()
    assert not [r for r in rep.recommendations
                if r.kind in ("ZORDER", "PARTITION")], rep.recommendations
    assert not rep.facts.get("neverPruned")


def test_record_hooks_never_raise_when_writer_cannot_start(
        tmp_table, monkeypatch):
    """The commit hook runs after version N is durably on disk and the
    conflict hook sits on the exception path — a journaling failure (e.g.
    Thread.start at interpreter shutdown) must stay invisible to the
    caller; the buffered entry still lands on the next flush."""
    t = DeltaTable.create(tmp_table, data=_ids(5))
    journal.flush()

    def boom():
        raise RuntimeError("can't start new thread")

    monkeypatch.setattr(journal, "_ensure_writer", boom)
    journal.record_commit(t.delta_log.log_path, {"attempts": 1},
                          outcome="committed")  # must not raise
    monkeypatch.undo()
    journal.flush()
    commits = journal.read_entries(t.delta_log.log_path, kinds=["commit"])
    assert any(c["stats"].get("attempts") == 1 for c in commits)


def test_buffered_entries_flush_at_interpreter_exit(tmp_table):
    """A short-lived process (scan + exit inside the flush interval) must
    not lose its buffered entries with the daemon writer thread — the
    atexit drain writes them synchronously."""
    import subprocess
    import sys
    import textwrap

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {repo!r})
        import pyarrow as pa
        from delta_tpu.api.tables import DeltaTable
        from delta_tpu.utils.config import conf

        conf.set("delta.tpu.journal.flushIntervalMs", 60000)
        conf.set("delta.tpu.journal.flushEntries", 1000)
        t = DeltaTable.create({tmp_table!r}, data=pa.table(
            {{"id": pa.array(range(10), pa.int64())}}))
        t.to_arrow(filters=["id = 3"])
        # exit WITHOUT flushing: nothing aged, nothing hit the count
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   timeout=300)
    entries = journal.read_entries(os.path.join(tmp_table, "_delta_log"),
                                   kinds=["scan"])
    assert entries, "atexit drain lost the buffered scan entry"
    assert entries[0]["fingerprint"]["key"] == "eq(id,?)"


def test_sweep_size_pressure_reclaims_grace_stale_pid_segments(tmp_table):
    """The newest-per-pid exemption only holds while a segment is recently
    written (a live writer touches its file at least every flush interval)
    — one immune segment per dead CI/cron pid would make the maxBytes cap
    unenforceable. Grace-stale segments yield to size pressure."""
    import time as time_mod

    t = DeltaTable.create(tmp_table, data=_ids(10))
    jdir = journal.journal_dir(t.delta_log.log_path)
    journal.reset()  # no in-process active handle
    os.makedirs(jdir, exist_ok=True)
    line = json.dumps({"kind": "dml", "op": "x", "ts": 1}) + "\n"
    stale = time_mod.time() - 3600  # long past any grace window
    segs = ["journal-0000000000001-111-000001.jsonl",
            "journal-0000000000002-222-000001.jsonl",
            "journal-0000000000003-333-000001.jsonl"]
    for n in segs:
        p = os.path.join(jdir, n)
        with open(p, "w", encoding="utf-8") as f:
            f.write(line * 10)
        os.utime(p, (stale, stale))
    # freshly-written newest-per-pid segment: spared even under pressure
    fresh = os.path.join(jdir, "journal-0000000000004-444-000001.jsonl")
    with open(fresh, "w", encoding="utf-8") as f:
        f.write(line * 10)
    with conf.set_temporarily(**{"delta.tpu.journal.maxBytes": 1}):
        assert journal.sweep(jdir) == 3
    left = sorted(n for n in os.listdir(jdir) if n.endswith(".jsonl"))
    assert left == [os.path.basename(fresh)]


def test_unwritable_journal_dir_drops_without_inflating_segment_counter(
        tmp_table):
    """Every failed batch re-enters the rotation branch; segments.written
    must count files that actually landed, not attempts."""
    t = DeltaTable.create(tmp_table, data=_ids(10))
    log_path = t.delta_log.log_path
    journal.flush()
    journal.reset()
    jdir = journal.journal_dir(log_path)
    import shutil

    shutil.rmtree(jdir, ignore_errors=True)
    with open(jdir, "w", encoding="utf-8") as f:
        f.write("not a directory")  # makedirs(jdir) now raises
    try:
        before = telemetry.counters("journal.segments.written").get(
            "journal.segments.written", 0)
        for _ in range(3):
            journal.record_dml(log_path, "update", mode="dv", metrics={})
            journal.flush(log_path)
        after = telemetry.counters("journal.segments.written").get(
            "journal.segments.written", 0)
        assert after == before
        assert telemetry.counters("journal.entriesDropped").get(
            "journal.entriesDropped", 0) >= 3
    finally:
        os.remove(jdir)
