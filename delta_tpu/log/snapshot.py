"""Snapshot: immutable table state at a version.

Reference: ``Snapshot.scala:55-410``. The reference reconstructs state as a
50-partition Spark Dataset replay of per-action JVM objects; here the
reconstruction is **columnar end to end**:

* the whole segment (checkpoint Parquet + delta JSON) decodes directly to
  SoA columns in C++ (``delta_tpu.log.columnar``) — no per-action Python
  object is ever built on this path;
* last-writer-wins is one vectorized winner computation (host scatter, or
  the device kernel ``delta_tpu.ops.replay_kernel`` for the sharded path);
* :class:`AddFile` / :class:`RemoveFile` dataclasses are materialized
  *lazily*, only for the rows a caller actually touches
  (``Snapshot.all_files`` et al.).

The object-per-action host replay (``delta_tpu.log.replay.LogReplay``)
remains the correctness oracle and serves the small-N transactional paths.
"""
from __future__ import annotations

import logging
from functools import cached_property
from typing import Any, Dict, List, Optional, Sequence, Tuple, TYPE_CHECKING

import numpy as np

from delta_tpu.log.columnar import SegmentColumns, decode_segment
from delta_tpu.protocol.actions import (
    Action,
    AddFile,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
)
from delta_tpu.storage.logstore import FileStatus, LogStore
from delta_tpu.utils.config import DeltaConfigs

if TYPE_CHECKING:
    from delta_tpu.log.deltalog import DeltaLog

__all__ = ["LogSegment", "Snapshot", "InitialSnapshot"]

logger = logging.getLogger(__name__)


class LogSegment:
    """The files that define a version: checkpoint parts + contiguous deltas
    after it (``SnapshotManagement.scala:394-421``)."""

    def __init__(
        self,
        log_path: str,
        version: int,
        deltas: Sequence[FileStatus],
        checkpoint_files: Sequence[FileStatus] = (),
        checkpoint_version: Optional[int] = None,
        last_commit_timestamp: int = 0,
    ):
        self.log_path = log_path
        self.version = version
        self.deltas = list(deltas)
        self.checkpoint_files = list(checkpoint_files)
        self.checkpoint_version = checkpoint_version
        self.last_commit_timestamp = last_commit_timestamp

    def __eq__(self, other: Any) -> bool:
        """Segment equivalence for early-exit update
        (``SnapshotManagement.scala:286-330``)."""
        if not isinstance(other, LogSegment):
            return False
        return (
            self.log_path == other.log_path
            and self.version == other.version
            and [f.path for f in self.deltas] == [f.path for f in other.deltas]
            and [f.path for f in self.checkpoint_files] == [f.path for f in other.checkpoint_files]
        )

    @staticmethod
    def empty(log_path: str) -> "LogSegment":
        return LogSegment(log_path, -1, [])

    def __repr__(self) -> str:
        return (
            f"LogSegment(v={self.version}, ckpt={self.checkpoint_version}, "
            f"deltas={[f.name for f in self.deltas]})"
        )


class Snapshot:
    def __init__(
        self,
        delta_log: "DeltaLog",
        version: int,
        segment: LogSegment,
        min_file_retention_timestamp: Optional[int] = None,
        timestamp: Optional[int] = None,
    ):
        self.delta_log = delta_log
        self.version = version
        self.segment = segment
        self.timestamp = timestamp if timestamp is not None else segment.last_commit_timestamp
        self._min_file_retention_timestamp = min_file_retention_timestamp

    # -- state reconstruction -------------------------------------------

    @property
    def store(self) -> LogStore:
        return self.delta_log.store

    def min_file_retention_timestamp(self) -> int:
        if self._min_file_retention_timestamp is not None:
            return self._min_file_retention_timestamp
        retention = DeltaConfigs.TOMBSTONE_RETENTION.from_metadata(self.metadata)
        return self.delta_log.clock() - retention

    @cached_property
    def _columnar(self) -> SegmentColumns:
        """Columnar decode of the whole segment (``Snapshot.scala:88-111``
        equivalent, minus the per-action objects).

        Corruption recovery (≈ ``Checkpoints.scala:152-175`` /
        ``SnapshotManagement.scala:118-126``): a checkpoint part that fails
        to decode (truncated / garbage parquet) is excluded and the segment
        recomputed from the listing — falling back to an earlier complete
        checkpoint, or a full JSON replay from version 0. The corrupt
        version is memoized on the DeltaLog so later listings skip it (and
        ``update()``'s segment-equality early-exit keeps working)."""
        from delta_tpu.utils import telemetry

        segment = self.segment
        while True:
            try:
                with telemetry.record_operation(
                    "delta.snapshot.stateReconstruction",
                    {"version": self.version,
                     "checkpointParts": len(segment.checkpoint_files),
                     "deltas": len(segment.deltas)},
                    path=self.delta_log.data_path,
                ) as sev:
                    cols = decode_segment(
                        self.store,
                        [f.path for f in segment.checkpoint_files],
                        [f.path for f in segment.deltas],
                    )
                    sev.data["numActions"] = len(cols.size)
                    return cols
            except Exception as e:
                if segment.checkpoint_version is None:
                    raise
                # attribute the failure: only exclude the checkpoint when its
                # parquet itself is unreadable — a corrupt delta JSON must
                # surface, not burn through every good checkpoint
                if self._checkpoint_readable(segment):
                    raise
                from delta_tpu.log import snapshot_management as sm

                excluded = self.delta_log.mark_corrupt_checkpoint(
                    segment.checkpoint_version
                )
                logger.warning(
                    "checkpoint at version %s failed to decode (%s: %s); "
                    "recovering from the log listing",
                    segment.checkpoint_version, type(e).__name__, e,
                )
                retry = sm.get_log_segment_for_version(
                    self.store, segment.log_path,
                    version_to_load=self.version,
                    excluded_checkpoints=excluded,
                )
                if retry is None or retry.checkpoint_version in excluded:
                    raise
                segment = retry
                self.segment = retry

    def _checkpoint_readable(self, segment: LogSegment) -> bool:
        """Can every checkpoint part's parquet footer be opened?"""
        import io

        import pyarrow.parquet as pq

        try:
            for f in segment.checkpoint_files:
                pq.ParquetFile(io.BytesIO(self.store.read_bytes(f.path)))
            return True
        # delta-lint: ignore[crash-except] -- read-only readability probe: no
        # state to clean up; a pierced crash aborts the cold build as intended
        except Exception:
            return False

    @cached_property
    def _winner(self) -> np.ndarray:
        """Last-action-per-path boolean row mask over the columnar stream."""
        return self._columnar.winner_mask()

    @cached_property
    def _other_state(self) -> Tuple[Optional[Protocol], Optional[Metadata], Dict[str, SetTransaction]]:
        proto: Optional[Protocol] = None
        meta: Optional[Metadata] = None
        txns: Dict[str, SetTransaction] = {}
        for a in self._columnar.other_actions:
            if isinstance(a, Protocol):
                proto = a
            elif isinstance(a, Metadata):
                meta = a
            elif isinstance(a, SetTransaction):
                txns[a.app_id] = a
        return proto, meta, txns

    # -- reconciled state ------------------------------------------------

    @cached_property
    def protocol(self) -> Protocol:
        p = self._other_state[0]
        return p if p is not None else Protocol()

    @cached_property
    def metadata(self) -> Metadata:
        m = self._other_state[1]
        return m if m is not None else Metadata()

    @cached_property
    def set_transactions(self) -> Dict[str, SetTransaction]:
        return dict(self._other_state[2])

    def transaction_version(self, app_id: str) -> int:
        t = self.set_transactions.get(app_id)
        return t.version if t else -1

    @cached_property
    def _alive_mask(self) -> np.ndarray:
        alive, _ = self._columnar.replay(winner=self._winner)
        return alive

    @cached_property
    def all_files(self) -> List[AddFile]:
        """Active AddFiles sorted by path (deterministic scan order).
        Materializes dataclasses for exactly the surviving rows."""
        files = self._columnar.materialize(self._alive_mask)
        return sorted(files, key=lambda a: a.path)

    @cached_property
    def _alive_row_by_path(self) -> Dict[str, int]:
        rows = np.nonzero(self._alive_mask)[0]
        return dict(zip(self._columnar.paths_for(rows), rows.tolist()))

    def files_for_paths(self, paths: Sequence[str]) -> List[AddFile]:
        """Materialize AddFiles for exactly the given (alive) paths, sorted
        by path — the selective alternative to ``all_files`` when a resident
        plan already knows which few files survive (`ops/state_cache`)."""
        by_path = self._alive_row_by_path
        rows = np.asarray(sorted(by_path[p] for p in paths), np.int64)
        return sorted(self._columnar.materialize(rows), key=lambda a: a.path)

    def _tombstone_mask(self, cutoff_ms: int) -> np.ndarray:
        _, tomb = self._columnar.replay(cutoff_ms, winner=self._winner)
        return tomb

    @cached_property
    def tombstones(self) -> List[RemoveFile]:
        cutoff = self.min_file_retention_timestamp()
        return list(self._columnar.materialize(self._tombstone_mask(cutoff)))

    def tombstones_newer_than(self, cutoff_ms: int) -> List[RemoveFile]:
        """Un-expired tombstones against a caller-supplied horizon — VACUUM
        must apply its own retention, not the snapshot's clock-cached one."""
        return list(self._columnar.materialize(self._tombstone_mask(cutoff_ms)))

    @property
    def num_of_files(self) -> int:
        return int(self._alive_mask.sum())

    @property
    def size_in_bytes(self) -> int:
        return int(self._columnar.size[self._alive_mask].sum())

    @property
    def num_of_metadata(self) -> int:
        return 1 if self._other_state[1] is not None else 0

    @property
    def num_of_protocol(self) -> int:
        return 1 if self._other_state[0] is not None else 0

    @property
    def num_of_removes(self) -> int:
        # len() of the cached list: consistent with checkpoint_actions() even
        # when the clock-derived retention cutoff advances between accesses
        return len(self.tombstones)

    @property
    def num_of_set_transactions(self) -> int:
        return len(self.set_transactions)

    @property
    def schema(self):
        return self.metadata.schema

    @property
    def partition_columns(self) -> List[str]:
        return self.metadata.partition_columns

    def checkpoint_actions(self) -> List[Action]:
        """The complete reconciled state, the content of a checkpoint
        (``InMemoryLogReplay.scala:71-77``): protocol, metadata, txns,
        retained tombstones, active files, ``dataChange=False`` normalized."""
        from dataclasses import replace as _dc_replace

        out: List[Action] = []
        proto, meta, txns = self._other_state
        if proto is not None:
            out.append(proto)
        if meta is not None:
            out.append(meta)
        out.extend(txns.values())
        out.extend(_dc_replace(r, data_change=False) for r in self.tombstones)
        out.extend(a.with_data_change(False) for a in self.all_files)
        return out

    def checkpoint_size_estimate(self) -> int:
        return (
            self.num_of_files
            + self.num_of_removes
            + self.num_of_set_transactions
            + self.num_of_metadata
            + self.num_of_protocol
        )

    # -- columnar export for the device path -----------------------------

    def files_arrays(self, stats_columns: Optional[Sequence[str]] = None):
        """Export AddFile metadata as numpy columns for the device scan planner
        (path dictionary stays on host; hashes/sizes/stats go to HBM).
        See ``delta_tpu.ops.pruning``."""
        from delta_tpu.ops.state_export import arrays_from_columns, files_to_arrays

        arr = arrays_from_columns(
            self._columnar, self._alive_mask, self.metadata, stats_columns,
            sort_by_path=True,
        )
        if arr is not None:
            return arr
        return files_to_arrays(self.all_files, self.metadata, stats_columns)

    def __repr__(self) -> str:
        return f"Snapshot(version={self.version}, files={self.num_of_files})"


class InitialSnapshot(Snapshot):
    """Snapshot of a table that has no commits yet
    (``Snapshot.scala:392-410``)."""

    def __init__(self, delta_log: "DeltaLog", metadata: Optional[Metadata] = None):
        super().__init__(
            delta_log,
            version=-1,
            segment=LogSegment.empty(delta_log.log_path),
            min_file_retention_timestamp=0,
            timestamp=-1,
        )
        self._initial_metadata = metadata or Metadata(
            configuration=DeltaConfigs.merge_global_configs({})
        )

    @cached_property
    def _columnar(self) -> SegmentColumns:
        return decode_segment(self.store, [], [])

    @cached_property
    def metadata(self) -> Metadata:
        return self._initial_metadata

    @cached_property
    def protocol(self) -> Protocol:
        return Protocol()
