"""LogStore: atomic read/write/list of transaction-log files.

Contract (reference: ``storage/LogStore.scala:30-43``):
  1. Atomic visibility of writes — readers never see a partial file.
  2. Mutual exclusion — at most one writer can create a given log entry.
  3. Consistent listing — once a file is written, listings must include it.

The reference implements this over Hadoop FileSystems (HDFS rename, S3
single-driver in-JVM locks, Azure rename). Here the backends are:

* :class:`LocalLogStore` — POSIX. Mutual exclusion + atomic visibility via
  write-temp-then-``link(2)`` (hard link fails with ``EEXIST`` if the target
  exists, and the linked file is complete by construction). This is strictly
  stronger than the reference's local story and safe for concurrent
  *processes*, not just threads.
- :class:`ObjectStoreLogStore` — S3-semantics emulation: no atomic
  create-if-absent, so mutual exclusion comes from an in-process path lock +
  a listing/read-after-write cache, matching ``S3SingleDriverLogStore.scala``
  (single-writer-driver mode, ``isPartialWriteVisible=False``).
* :class:`MemoryLogStore` — in-memory store with fault-injection hooks for
  concurrency tests (the analogue of the reference's fake filesystems in
  ``LogStoreSuite.scala:293-339``).

Stores are pluggable per scheme via :func:`register_log_store` /
:func:`get_log_store` (≈ ``spark.delta.logStore.class``,
``storage/LogStore.scala:152-172``).
"""
from __future__ import annotations

import io
import os
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, Iterator, List, Optional
from urllib.parse import urlparse

from delta_tpu.utils.errors import DeltaIOError
from delta_tpu.utils.telemetry import bump_counter

__all__ = [
    "FileStatus",
    "LogStore",
    "LocalLogStore",
    "MemoryLogStore",
    "ObjectStoreLogStore",
    "register_log_store",
    "get_log_store",
    "split_scheme",
]


def _record_io(op: str, nbytes: int = 0) -> None:
    """Per-request store telemetry: ``logstore.<op>.calls`` (+ ``.bytes``
    where a size is known) — the request-count/egress numbers an operator
    needs to price a backend (S3 GET/PUT/LIST bills per request)."""
    bump_counter(f"logstore.{op}.calls")
    if nbytes:
        bump_counter(f"logstore.{op}.bytes", nbytes)


@dataclass(frozen=True)
class FileStatus:
    path: str  # absolute path (no scheme for local)
    size: int
    modification_time: int  # millis since epoch

    @property
    def name(self) -> str:
        return self.path.rsplit("/", 1)[-1]


class LogStore:
    """Abstract base; see module docstring for the contract."""

    def read(self, path: str) -> List[str]:
        """Read the whole file as a list of lines (no trailing newlines)."""
        return list(self.read_iter(path))

    def read_iter(self, path: str) -> Iterator[str]:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, lines: Iterable[str], overwrite: bool = False) -> None:
        """Atomically write ``lines`` (newline-terminated on disk).

        Raises ``FileExistsError`` if ``path`` exists and ``overwrite`` is
        False — that error is the OCC commit-conflict signal
        (``OptimisticTransaction.scala:672-674``).
        """
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        raise NotImplementedError

    def list_from(self, path: str) -> Iterator[FileStatus]:
        """List files in path's parent whose name is >= path's name,
        sorted lexicographically (``storage/LogStore.scala:109-115``)."""
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str) -> bool:
        raise NotImplementedError

    def is_partial_write_visible(self, path: str) -> bool:
        """Whether a concurrent reader may observe a half-written file; when
        True, non-log writers (e.g. checkpoints) must go through
        temp-file+rename (``Checkpoints.scala:271-303``)."""
        return True

    # -- convenience ----------------------------------------------------

    def mkdirs(self, path: str) -> None:
        pass

    def resolve_path(self, path: str) -> str:
        return path


# ---------------------------------------------------------------------------
# Local POSIX store
# ---------------------------------------------------------------------------

class LocalLogStore(LogStore):
    """POSIX filesystem store.

    Mutual exclusion: the log file is staged to a unique temp name in the same
    directory and published with ``os.link`` (atomic create-if-absent across
    processes). Atomic visibility: the published file is complete before the
    link exists. This collapses the reference's HDFS (rename-based,
    ``HDFSLogStore.scala:46-90``) and Local (synchronized rename,
    ``LocalLogStore.scala:43-48``) stores into one stronger primitive.
    """

    def read_iter(self, path: str) -> Iterator[str]:
        p = _strip_scheme(path)
        try:
            f = open(p, "r", encoding="utf-8", newline="")
        except FileNotFoundError:
            raise
        _record_io("read")
        with f:
            for line in f:
                yield line.rstrip("\r\n")

    def read_bytes(self, path: str) -> bytes:
        with open(_strip_scheme(path), "rb") as f:
            data = f.read()
        _record_io("read", len(data))
        return data

    def write(self, path: str, lines: Iterable[str], overwrite: bool = False) -> None:
        data = ("".join(line + "\n" for line in lines)).encode("utf-8")
        self.write_bytes(path, data, overwrite=overwrite)

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        p = _strip_scheme(path)
        parent = os.path.dirname(p)
        os.makedirs(parent, exist_ok=True)
        _record_io("write", len(data))
        # Both branches stage to a dot-tmp and clean it in a finally: a
        # writer dying between staging and publish must not strand temp
        # files for every future exception path — only a hard process crash
        # can, and those aged orphans are swept by log/cleanup.py.
        if overwrite:
            tmp = os.path.join(parent, f".{os.path.basename(p)}.{uuid.uuid4().hex}.tmp")
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, p)  # atomic overwrite
            finally:
                try:
                    os.unlink(tmp)  # no-op after a successful replace
                except OSError:
                    pass
            return
        tmp = os.path.join(parent, f".{os.path.basename(p)}.{uuid.uuid4().hex}.tmp")
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            try:
                os.link(tmp, p)  # atomic create-if-absent
            except FileExistsError:
                raise FileExistsError(p)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def list_from(self, path: str) -> Iterator[FileStatus]:
        p = _strip_scheme(path)
        parent = os.path.dirname(p)
        start = os.path.basename(p)
        if not os.path.isdir(parent):
            raise FileNotFoundError(parent)
        _record_io("list")
        names = sorted(n for n in os.listdir(parent) if n >= start)
        for n in names:
            full = os.path.join(parent, n)
            try:
                st = os.stat(full)
            except FileNotFoundError:
                continue
            yield FileStatus(full, st.st_size, int(st.st_mtime * 1000))

    def exists(self, path: str) -> bool:
        return os.path.exists(_strip_scheme(path))

    def delete(self, path: str) -> bool:
        try:
            os.unlink(_strip_scheme(path))
            return True
        except FileNotFoundError:
            return False

    def mkdirs(self, path: str) -> None:
        os.makedirs(_strip_scheme(path), exist_ok=True)

    def is_partial_write_visible(self, path: str) -> bool:
        # link-publish means readers never see partial log files, but plain
        # data/checkpoint writers still need temp+rename, so keep True to force
        # the rename path in checkpoint writes (parity with HDFSLogStore).
        return True


# ---------------------------------------------------------------------------
# In-memory store (tests, fault injection)
# ---------------------------------------------------------------------------

class MemoryLogStore(LogStore):
    """In-memory store with hooks for injecting races and failures.

    ``before_write`` / ``after_write`` / ``before_list`` callbacks let tests
    interleave concurrent writers deterministically — the role the reference's
    ``TrackingRenameFileSystem`` and fake filesystems play
    (``LogStoreSuite.scala:293-339``).
    """

    def __init__(self):
        self._files: Dict[str, bytes] = {}
        self._mtimes: Dict[str, int] = {}
        self._lock = threading.RLock()
        self.before_write: Optional[Callable[[str], None]] = None
        self.after_write: Optional[Callable[[str], None]] = None
        self.before_list: Optional[Callable[[str], None]] = None
        self.write_count = 0
        self.list_count = 0

    def read_iter(self, path: str) -> Iterator[str]:
        data = self.read_bytes(path)
        for line in io.StringIO(data.decode("utf-8")):
            yield line.rstrip("\r\n")

    def read_bytes(self, path: str) -> bytes:
        with self._lock:
            if path not in self._files:
                raise FileNotFoundError(path)
            data = self._files[path]
        _record_io("read", len(data))
        return data

    def write(self, path: str, lines: Iterable[str], overwrite: bool = False) -> None:
        data = ("".join(line + "\n" for line in lines)).encode("utf-8")
        self.write_bytes(path, data, overwrite=overwrite)

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        if self.before_write:
            self.before_write(path)
        _record_io("write", len(data))
        with self._lock:
            if not overwrite and path in self._files:
                raise FileExistsError(path)
            self._files[path] = data
            self._mtimes[path] = int(time.time() * 1000)
            self.write_count += 1
        if self.after_write:
            self.after_write(path)

    def list_from(self, path: str) -> Iterator[FileStatus]:
        if self.before_list:
            self.before_list(path)
        _record_io("list")
        parent, _, start = path.rpartition("/")
        with self._lock:
            self.list_count += 1
            if not any(p.rpartition("/")[0] == parent for p in self._files):
                raise FileNotFoundError(parent)
            entries = [
                (p, len(d), self._mtimes[p])
                for p, d in self._files.items()
                if p.rpartition("/")[0] == parent and p.rpartition("/")[2] >= start
            ]
        for p, size, mtime in sorted(entries):
            yield FileStatus(p, size, mtime)

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._files

    def delete(self, path: str) -> bool:
        with self._lock:
            if path in self._files:
                del self._files[path]
                self._mtimes.pop(path, None)
                return True
            return False

    def set_mtime(self, path: str, mtime_ms: int) -> None:
        """Test helper — the analogue of the reference's ManualClock mtime
        manipulation in retention tests (``DeltaRetentionSuiteBase.scala``)."""
        with self._lock:
            self._mtimes[path] = mtime_ms


# ---------------------------------------------------------------------------
# Object-store-semantics store (S3-style: no atomic create)
# ---------------------------------------------------------------------------

class ObjectStoreLogStore(LogStore):
    """Wraps a base store but refuses to rely on atomic create-if-absent,
    emulating S3: mutual exclusion via an in-process per-path lock plus a
    write cache for read-after-write consistency within this process —
    the semantics of ``S3SingleDriverLogStore.scala:48-251``. Correct only
    when all concurrent writers share this process (single-driver mode).
    """

    # Striped locks: bounded memory regardless of how many distinct paths are
    # written over the process lifetime (the reference's per-path map relies on
    # cache expiry instead, S3SingleDriverLogStore.scala:206).
    _LOCK_STRIPES = 64
    _path_locks = [threading.Lock() for _ in range(_LOCK_STRIPES)]

    #: Max entries kept for read-after-write listing consistency. Old entries
    #: are evicted FIFO — by then the base store's listing includes them.
    WRITE_CACHE_MAX = 4096

    def __init__(self, base: Optional[LogStore] = None):
        from collections import OrderedDict

        self._base = base or LocalLogStore()
        self._write_cache: "OrderedDict[str, FileStatus]" = OrderedDict()
        self._cache_lock = threading.Lock()

    @classmethod
    def _lock_for(cls, path: str) -> threading.Lock:
        return cls._path_locks[hash(path) % cls._LOCK_STRIPES]

    def read_iter(self, path: str) -> Iterator[str]:
        return self._base.read_iter(path)

    def read_bytes(self, path: str) -> bytes:
        return self._base.read_bytes(path)

    def write(self, path: str, lines: Iterable[str], overwrite: bool = False) -> None:
        data = ("".join(line + "\n" for line in lines)).encode("utf-8")
        self.write_bytes(path, data, overwrite=overwrite)

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        lock = self._lock_for(path)
        with lock:
            if not overwrite and (self.exists(path)):
                raise FileExistsError(path)
            # Emulate a PUT: overwrite unconditionally at the base layer.
            self._base.write_bytes(path, data, overwrite=True)
            with self._cache_lock:
                self._write_cache[path] = FileStatus(path, len(data), int(time.time() * 1000))
                while len(self._write_cache) > self.WRITE_CACHE_MAX:
                    self._write_cache.popitem(last=False)

    def list_from(self, path: str) -> Iterator[FileStatus]:
        # Merge base listing with the write cache (read-after-write), as
        # S3SingleDriverLogStore.mergeFileIterators does.
        parent, _, start = _strip_scheme(path).replace(os.sep, "/").rpartition("/")
        with self._cache_lock:
            cached = {
                s.path: s
                for s in self._write_cache.values()
                if _strip_scheme(s.path).replace(os.sep, "/").rpartition("/")[0] == parent
                and s.name >= start
            }
        listed: Dict[str, FileStatus] = {}
        try:
            for s in self._base.list_from(path):
                listed[s.path] = s
        except FileNotFoundError:
            if not cached:
                raise
        merged = {**cached, **listed}
        for p in sorted(merged, key=lambda x: merged[x].name):
            yield merged[p]

    def exists(self, path: str) -> bool:
        with self._cache_lock:
            if path in self._write_cache:
                return True
        return self._base.exists(path)

    def delete(self, path: str) -> bool:
        with self._cache_lock:
            self._write_cache.pop(path, None)
        return self._base.delete(path)

    def mkdirs(self, path: str) -> None:
        self._base.mkdirs(path)

    def is_partial_write_visible(self, path: str) -> bool:
        return False  # S3SingleDriverLogStore.scala:194


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Callable[[], LogStore]] = {}
_INSTANCES: Dict[str, LogStore] = {}
_REG_LOCK = threading.Lock()


def register_log_store(scheme: str, factory: Callable[[], LogStore]) -> None:
    with _REG_LOCK:
        _REGISTRY[scheme] = factory
        _INSTANCES.pop(scheme, None)


def get_log_store(path: str = "") -> LogStore:
    scheme = split_scheme(path)[0] or "file"
    cache_key = scheme
    with _REG_LOCK:
        factory = _REGISTRY.get(scheme)
    if factory is None and scheme in ("s3", "s3a", "s3n", "gs"):
        # Network object store: requires an endpoint — never silently fall
        # back to local disk for a cloud scheme.
        from delta_tpu.utils.config import conf

        endpoint = conf.get("delta.tpu.storage.objectStore.endpoint")
        if not endpoint:
            raise DeltaIOError(
                f"Path {path!r} uses object-store scheme {scheme!r} but no "
                "endpoint is configured. Set session conf "
                "'delta.tpu.storage.objectStore.endpoint' to the store's URL "
                "(conditional-PUT commits; see delta_tpu.storage.http_store), "
                "or register a custom store for this scheme via "
                "register_log_store()."
            )
        dialect = (conf.get("delta.tpu.storage.objectStore.dialect")
                   or ("gcs" if scheme == "gs" else "s3"))
        cache_key = f"{scheme}|{endpoint}|{dialect}"

        def factory(endpoint=endpoint, dialect=dialect):
            from delta_tpu.storage.http_store import HttpObjectLogStore

            return HttpObjectLogStore(endpoint, dialect=dialect)

    with _REG_LOCK:
        if cache_key not in _INSTANCES:
            if factory is None:
                if scheme in ("file", ""):
                    factory = LocalLogStore
                else:
                    raise DeltaIOError(f"No LogStore registered for scheme {scheme!r}")
            _INSTANCES[cache_key] = factory()
        return _INSTANCES[cache_key]


def split_scheme(path: str):
    if "://" in path:
        parsed = urlparse(path)
        return parsed.scheme, path
    return "", path


def _strip_scheme(path: str) -> str:
    if path.startswith("file://"):
        return path[len("file://"):]
    return path
