"""OPTIMIZE — compaction and Z-ORDER clustering.

The reference ships no OPTIMIZE command in this version (Z-order tags exist
in the format only, `actions/actions.scala:270-291`); the rebuild provides
both modes because the perf baseline measures them:

* **compaction**: bin-pack small files per partition up to a target size and
  rewrite them as one file;
* **Z-ORDER BY (cols)**: re-sort the selected partitions by the on-device
  Morton key (`ops/zorder.py`) and re-split, giving compact per-file min/max
  boxes for data skipping.

Both commit as rearrange-only transactions (`dataChange=False`), so
concurrent appends don't conflict and streams ignore the rewrite — the same
reason `WriteIntoDelta.scala:129-131` flips dataChange for rearrangeOnly.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple, Union

import pyarrow as pa

from delta_tpu.commands import operations as ops
from delta_tpu.commands.dml_common import Timer
from delta_tpu.exec import write as write_exec
from delta_tpu.exec.scan import read_files_as_table
from delta_tpu.expr import ir
from delta_tpu.expr import partition as partition_expr
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.ops.zorder import morton_order
from delta_tpu.protocol.actions import Action, AddFile
from delta_tpu.utils.errors import DeltaAnalysisError
from delta_tpu.utils import errors

__all__ = ["OptimizeCommand", "OptimizeBudgetExceeded"]

DEFAULT_MIN_FILE_SIZE = 256 * 1024 * 1024  # files below this are compactable
DEFAULT_TARGET_ROWS = 1 << 22


class OptimizeBudgetExceeded(errors.DeltaError):
    """The selected rewrite set exceeds ``max_rewrite_bytes``. Raised
    BEFORE any data is read or written — the cost-capped invocation path
    (`delta_tpu/autopilot`) turns this into a journaled SKIPPED outcome
    instead of an over-budget background rewrite."""

    def __init__(self, est_bytes: int, cap_bytes: int, files: int):
        super().__init__(
            f"OPTIMIZE would rewrite {est_bytes} bytes across {files} "
            f"files, over the {cap_bytes}-byte budget")
        self.est_bytes = est_bytes
        self.cap_bytes = cap_bytes
        self.files = files


class OptimizeCommand:
    def __init__(
        self,
        delta_log,
        predicate: Optional[Union[str, ir.Expression]] = None,
        z_order_by: Sequence[str] = (),
        min_file_size: int = DEFAULT_MIN_FILE_SIZE,
        target_rows: int = DEFAULT_TARGET_ROWS,
        purge: bool = False,
        max_rewrite_bytes: Optional[int] = None,
        workers: Optional[int] = None,
        distribute: bool = False,
    ):
        self.delta_log = delta_log
        self.predicate = (
            parse_predicate(predicate) if isinstance(predicate, str) else predicate
        )
        self.z_order_by = list(z_order_by)
        self.min_file_size = min_file_size
        self.target_rows = target_rows
        # purge mode (modern Delta's REORG TABLE ... APPLY (PURGE)): rewrite
        # exactly the files carrying deletion vectors, materializing the
        # deletes and dropping the DVs — size-based selection is bypassed
        self.purge = purge
        # cost cap (programmatic maintenance path): the total size of the
        # files selected for rewrite is bounded up front — an over-budget
        # job raises OptimizeBudgetExceeded before any IO
        self.max_rewrite_bytes = max_rewrite_bytes
        # sharded execution (parallel/executor): bin-pack groups rewrite on
        # `workers` LPT-seeded work-stealing workers (None = the
        # delta.tpu.distributed.optimize.workers conf, default 1 —
        # sequential, byte-identical to the classic loop). `distribute`
        # additionally splits the groups across jax.distributed hosts
        # (byte-weighted LPT); each host commits its disjoint rearrange-only
        # slice, funneled through the group-commit coordinator.
        self.workers = workers
        self.distribute = distribute
        # the last run's executor evidence (per-worker timings, steals,
        # skew) — the sharded-scan bench and the MULTICHIP artifact read it
        self.shard_report = None
        self.metrics: Dict[str, int] = {}

    def _resolve_workers(self) -> int:
        if self.workers is not None:
            return max(int(self.workers), 1)
        from delta_tpu.utils.config import conf

        got = conf.get("delta.tpu.distributed.optimize.workers")
        return max(int(got), 1) if got is not None else 1

    def run(self) -> int:
        from delta_tpu.utils.telemetry import record_operation

        with record_operation("delta.dml.optimize", path=self.delta_log.data_path):
            return self.delta_log.with_new_transaction(self._body)

    def _body(self, txn) -> int:
        metadata = txn.metadata
        pcols = metadata.partition_columns
        if self.predicate is not None:
            conjuncts = ir.split_conjuncts(self.predicate)
            if not all(partition_expr.is_partition_predicate(c, pcols) for c in conjuncts):
                raise DeltaAnalysisError(
                    "OPTIMIZE predicate must reference only partition columns"
                )
        for c in self.z_order_by:
            names = [f.name.lower() for f in metadata.schema.fields]
            if c.lower() not in names:
                raise errors.zorder_column_not_in_schema(c)
            if c.lower() in [p.lower() for p in pcols]:
                raise errors.zorder_on_partition_column(c)

        timer = Timer()
        # filter_files evaluates the partition predicate exactly
        candidates = txn.filter_files(
            [self.predicate] if self.predicate is not None else None
        )

        by_partition: Dict[Tuple, List[AddFile]] = defaultdict(list)
        for f in candidates:
            key = tuple(sorted((f.partition_values or {}).items()))
            by_partition[key].append(f)

        # plan first (selection is metadata-only), so the cost cap can
        # abort an over-budget job before ANY file is read or written
        groups: List[Tuple[Tuple, List[AddFile]]] = []
        # None-safe ordering: null partition values sort first
        for key, files in sorted(
            by_partition.items(),
            key=lambda kv: [(c, v is not None, v or "") for c, v in kv[0]],
        ):
            if self.z_order_by:
                group = files  # Z-order rewrites every selected file
            elif self.purge:
                group = [f for f in files if f.deletion_vector is not None]
                if not group:
                    continue
            else:
                group = [f for f in files if (f.size or 0) < self.min_file_size]
                if len(group) < 2:
                    continue  # nothing to compact
            groups.append((key, group))
        if self.max_rewrite_bytes is not None:
            est = sum(f.size or 0 for _, g in groups for f in g)
            if est > self.max_rewrite_bytes:
                raise OptimizeBudgetExceeded(
                    est, self.max_rewrite_bytes,
                    sum(len(g) for _, g in groups))

        # multi-host mode: every host plans the SAME group list from the
        # same snapshot, then takes its disjoint byte-weighted LPT slice —
        # deterministic, no scheduler RPC. Each host commits only its own
        # rearranged files, so the per-host transactions are disjoint
        # rearrange-only commits that cannot conflict.
        fan_in = False
        slice_info = None
        if self.distribute:
            from delta_tpu.parallel.distributed import (
                host_shard_indices, process_info)

            proc, n_procs = process_info()
            if n_procs > 1:
                gsizes = [sum(f.size or 0 for f in g) for _k, g in groups]
                mine = host_shard_indices(
                    len(groups), proc, n_procs, sizes=gsizes)
                groups = [groups[i] for i in mine]
                # this host's slice of the groups, as a span: the stitched
                # trace shows one delta.dist.hostSlice lane per process
                slice_info = {
                    "proc": proc, "nProcs": n_procs, "groups": len(groups),
                    "sliceBytes": sum(
                        f.size or 0 for _k, g in groups for f in g),
                }
                # narrow the recorded read set to THIS host's slice: the
                # commit's validity depends only on its own files surviving
                # (the reference's OPTIMIZE pins its read files the same
                # way), so a peer host's rearrange-only removes must not
                # fail us with a delete-read conflict
                keep = {f.path for _k, g in groups for f in g}
                for p in [p for p in txn.read_files if p not in keep]:
                    del txn.read_files[p]
                from delta_tpu.utils.config import conf

                fan_in = conf.get_bool(
                    "delta.tpu.distributed.singleWriterFanIn", True)

        removes: List[Action] = []
        adds: List[Action] = []

        def _rewrite(group: List[AddFile]):
            table = read_files_as_table(
                self.delta_log.data_path, group, metadata
            )
            if self.z_order_by:
                cols = [
                    np_col(table, c) for c in self.z_order_by
                ]
                perm = morton_order(cols)
                table = table.take(pa.array(perm))
            new_adds = write_exec.write_files(
                self.delta_log.data_path,
                table,
                metadata,
                data_change=False,
                target_file_rows=self.target_rows,
            )
            return new_adds, [f.remove(data_change=False) for f in group]

        if groups:
            import contextlib

            from delta_tpu.parallel.executor import run_sharded
            from delta_tpu.utils import telemetry

            telemetry.bump_counter("dist.optimize.groups", len(groups))
            slice_span = (
                telemetry.record_operation("delta.dist.hostSlice", slice_info)
                if slice_info is not None else contextlib.nullcontext())
            with slice_span:
                report = run_sharded(
                    [g for _k, g in groups],
                    _rewrite,
                    sizes=[sum(f.size or 0 for f in g) for _k, g in groups],
                    workers=self._resolve_workers(),
                    label="optimize",
                )
            self.shard_report = report
            # results are index-ordered, so adds/removes land in the exact
            # order the classic sequential loop produced them
            for new_adds, new_removes in report.results:
                adds.extend(new_adds)
                removes.extend(new_removes)

        self.metrics.update(
            numRemovedFiles=len(removes),
            numAddedFiles=len(adds),
            numRemovedBytes=sum(f.size or 0 for _k, g in groups for f in g),
            numAddedBytes=sum(a.size or 0 for a in adds
                              if isinstance(a, AddFile)),
            timeMs=timer.lap_ms(),
        )
        txn.report_metrics(**self.metrics)
        pred_sql = [self.predicate.sql()] if self.predicate is not None else []
        if self.purge:
            op = ops.Reorg(predicate=pred_sql)
        else:
            op = ops.Optimize(
                predicate=pred_sql, z_order_by=self.z_order_by or None,
            )
        if fan_in:
            # single-writer fan-in: every host's commit funnels through the
            # group-commit coordinator (PR 9), so the log sees one ordered
            # writer instead of n_procs racing _do_commit_retry loops
            from delta_tpu.utils.config import conf
            from delta_tpu.utils import telemetry

            telemetry.bump_counter("dist.commit.fanin")
            with telemetry.record_operation(
                "delta.dist.commit.fanIn",
                {"adds": len(adds), "removes": len(removes)},
            ):
                with conf.set_temporarily(
                    **{"delta.tpu.commit.group.enabled": True}
                ):
                    version = txn.commit(removes + adds, op)
        else:
            version = txn.commit(removes + adds, op)
        # file rewrite: bump the resident key-cache epoch so a stale HBM
        # slab can never serve a post-OPTIMIZE MERGE (ops/key_cache.py)
        if removes or adds:
            from delta_tpu.ops.column_cache import ColumnCache
            from delta_tpu.ops.key_cache import KeyCache

            KeyCache.instance().bump_epoch(self.delta_log.log_path)
            ColumnCache.instance().bump_epoch(self.delta_log.log_path)
        # feed the table-health doctor: maintenance recency as gauges, work
        # done as counters (obs/metric_names.py catalog)
        from delta_tpu.utils import telemetry

        telemetry.set_gauge("table.maintenance.lastOptimizeVersion", version,
                            path=self.delta_log.data_path)
        if removes:
            telemetry.bump_counter("maintenance.optimize.filesCompacted",
                                   len(removes))
        if adds:
            telemetry.bump_counter("maintenance.optimize.filesWritten",
                                   len(adds))
        return version


def np_col(table: pa.Table, name: str):
    """Column as numpy for ranking; NULLs substitute the column minimum so
    rank_u16's argsort stays total (NULLs cluster with the smallest value)."""
    import pyarrow.compute as pc

    col = None
    for c in table.column_names:
        if c.lower() == name.lower():
            col = table.column(c)
            break
    if col.null_count == len(col):
        # all-null: every rank is equal, contribute a constant dimension
        import numpy as np

        return np.zeros(len(col), np.int64)
    if col.null_count:
        col = pc.fill_null(col, pc.min(col))
    return col.to_numpy(zero_copy_only=False)
