"""Device-resident scan column cache + jitted residual path
(`ops/column_cache.py`, `expr/jaxeval.compile_residual`): result identity
with the Arrow path across the predicate matrix (strings, IN, temporals,
NULLs, partitions, DVs, schema evolution), rewrite-epoch invalidation
(OPTIMIZE / UPDATE / DELETE-rewrite / RESTORE can never be served stale
lanes), LRU + HBM-budget eviction, router pricing/audit, and the
``columnCache.*`` / ``scan.device.*`` observability."""
import datetime as dt

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.scan import scan_to_table
from delta_tpu.expr import ir, jaxeval
from delta_tpu.expr.parser import parse_predicate
from delta_tpu.obs import hbm_ledger
from delta_tpu.ops.column_cache import ColumnCache, ResidentColumn
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_cache():
    ColumnCache.reset()
    yield
    ColumnCache.reset()


FORCE = {"delta.tpu.read.deviceResidual.mode": "force"}
OFF = {"delta.tpu.read.deviceResidual.mode": "off"}


def _mk_table(path, files=3, n=400, partition=False, seed=7):
    log = DeltaLog.for_table(path)
    rng = np.random.RandomState(seed)
    for i in range(files):
        tbl = pa.table({
            "id": np.arange(i * n, (i + 1) * n, dtype=np.int64),
            "cat": pa.array(rng.choice(
                ["alpha", "beta", "gamma", None], n).tolist()),
            "x": rng.rand(n),
            "d": pa.array([dt.date(2024, 1, 1) + dt.timedelta(days=int(v))
                           for v in rng.randint(0, 400, n)]),
            "ts": pa.array([dt.datetime(2024, 1, 1)
                            + dt.timedelta(seconds=int(v))
                            for v in rng.randint(0, 86400 * 30, n)],
                           pa.timestamp("us")),
            "p": np.full(n, i % 2, dtype=np.int32),
        })
        WriteIntoDelta(log, "append", tbl,
                       partition_columns=["p"] if partition else ()).run()
    return log


def _both(log, pred):
    with conf.set_temporarily(**OFF):
        host = scan_to_table(log.update(), [pred]).sort_by("id")
    with conf.set_temporarily(**FORCE):
        dev = scan_to_table(log.update(), [pred]).sort_by("id")
    return host, dev


# -- result identity: device mask vs Arrow path -----------------------------


IDENTITY_PREDS = [
    "cat = 'alpha' AND x > 0.5",
    "cat != 'beta'",
    "cat <=> 'gamma'",
    "cat IN ('beta', 'gamma')",
    "cat IN ('nosuchvalue')",
    "cat IS NULL",
    "cat IS NOT NULL AND id < 300",
    "d >= '2024-06-01'",
    "ts < '2024-01-15 12:30:00'",
    "year(d) = 2024 AND month(ts) = 1",
    "to_date(ts) = '2024-01-15'",
    "hour(ts) >= 12",
    "id > 900 OR cat = 'missingvalue'",
    "x BETWEEN 0.2 AND 0.4",
]


@pytest.mark.parametrize("pred", IDENTITY_PREDS)
def test_device_scan_identity(tmp_table, pred):
    log = _mk_table(tmp_table)
    host, dev = _both(log, pred)
    assert host.equals(dev), pred


def test_device_scan_engages_and_counts(tmp_table):
    log = _mk_table(tmp_table)
    c0 = dict(telemetry.counters())
    host, dev = _both(log, "cat = 'alpha'")
    assert host.equals(dev)
    c1 = telemetry.counters()
    assert c1.get("scan.device.engaged", 0) > c0.get("scan.device.engaged", 0)
    assert c1.get("columnCache.misses", 0) > c0.get("columnCache.misses", 0)
    # warm pass: same lanes serve from residency
    with conf.set_temporarily(**FORCE):
        scan_to_table(log.update(), ["cat = 'alpha'"])
    c2 = telemetry.counters()
    assert c2.get("columnCache.hits", 0) > c1.get("columnCache.hits", 0)
    assert c2.get("columnCache.misses", 0) == c1.get("columnCache.misses", 0)
    assert hbm_ledger.totals()["columnCache"] > 0
    assert ColumnCache.instance().resident_bytes() > 0


def test_device_scan_report_attribution(tmp_table):
    from delta_tpu.obs.scan_report import last_scan_report

    log = _mk_table(tmp_table)
    with conf.set_temporarily(**FORCE):
        scan_to_table(log.update(), ["cat = 'alpha'"])
    rep = last_scan_report()
    assert rep is not None and rep.device_residual == "device"
    d = rep.to_dict()
    assert d["deviceResidual"] == "device"
    assert d["bytesDeviceSurvivor"] > 0


def test_device_mask_skips_all_false_row_groups(tmp_table):
    """A row group whose footer stats cover the value but whose rows never
    match skips decode entirely on the device path (stats can't see gaps;
    the mask can)."""
    log = DeltaLog.for_table(tmp_table)
    with conf.set_temporarily(**{"delta.tpu.write.rowGroupRows": 100}):
        WriteIntoDelta(log, "append", pa.table({
            "id": np.arange(0, 600, 2, dtype=np.int64),  # evens only
            "v": np.ones(300),
        })).run()
    c0 = dict(telemetry.counters())
    host, dev = _both(log, "id = 51")  # inside group 0's range, never present
    assert host.num_rows == dev.num_rows == 0
    c1 = telemetry.counters()
    assert c1.get("scan.rowgroups.deviceSkipped", 0) \
        > c0.get("scan.rowgroups.deviceSkipped", 0)
    assert c1.get("scan.bytes.deviceSkipped", 0) \
        > c0.get("scan.bytes.deviceSkipped", 0)


def test_identity_with_typed_partition_column(tmp_table):
    log = _mk_table(tmp_table, partition=True)
    for pred in ["p = 0 AND cat = 'alpha'", "p = 1 OR x < 0.1"]:
        host, dev = _both(log, pred)
        assert host.equals(dev), pred


def test_identity_with_deletion_vectors(tmp_table):
    from delta_tpu.commands.alter import set_table_properties
    from delta_tpu.commands.delete import DeleteCommand

    log = _mk_table(tmp_table)
    set_table_properties(log, {"delta.tpu.enableDeletionVectors": "true"})
    with conf.set_temporarily(**{"delta.tpu.deletionVectors.enabled": True}):
        DeleteCommand(log, "id % 7 = 0").run()
    host, dev = _both(log, "cat = 'alpha' AND x > 0.3")
    assert host.equals(dev)
    assert not any(v % 7 == 0 for v in dev.column("id").to_pylist())


def test_identity_after_schema_evolution(tmp_table):
    """Files that predate a column bind an all-invalid lane: NULL semantics
    must match the host's appended-null columns exactly."""
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.schema.types import StringType, StructField

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(100, dtype=np.int64), "v": np.ones(100)})).run()
    add_columns(log, [StructField("tag", StringType())])
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(100, 200, dtype=np.int64), "v": np.ones(100),
        "tag": pa.array(["new"] * 100)})).run()
    for pred in ["tag = 'new'", "tag IS NULL", "tag != 'new' OR id < 20"]:
        host, dev = _both(log, pred)
        assert host.equals(dev), pred


def test_mode_off_never_engages(tmp_table):
    log = _mk_table(tmp_table, files=1)
    c0 = dict(telemetry.counters())
    with conf.set_temporarily(**OFF):
        scan_to_table(log.update(), ["cat = 'alpha'"])
    c1 = telemetry.counters()
    for k in ("scan.device.engaged", "scan.device.declined",
              "scan.device.fallback"):
        assert c1.get(k, 0) == c0.get(k, 0)
    assert ColumnCache.instance().resident_bytes() == 0


def test_auto_mode_declines_on_slow_link_and_audits(tmp_table):
    from delta_tpu.obs import router_audit
    from delta_tpu.parallel import link

    log = _mk_table(tmp_table, files=1)
    link.reset()
    c0 = dict(telemetry.counters())
    try:
        with conf.set_temporarily(**{
            "delta.tpu.read.deviceResidual.mode": "auto",
            "delta.tpu.link.uploadMBps": 0.0001,
            "delta.tpu.link.downloadMBps": 0.0001,
        }):
            host = scan_to_table(log.update(), ["cat = 'alpha'"])
    finally:
        link.reset()
    c1 = telemetry.counters()
    assert c1.get("scan.device.declined", 0) > c0.get(
        "scan.device.declined", 0)
    assert c1.get("scan.device.engaged", 0) == c0.get(
        "scan.device.engaged", 0)
    last = router_audit.last_audit()
    assert last is not None and last.op == "scan.residual"
    assert last.decision == "host"
    assert host.num_rows > 0


def test_host_fallback_on_uncompilable_residual(tmp_table):
    """A residual with no device lowering (string ordering) falls back to
    the host path — identical results, fallback counter bumped."""
    log = _mk_table(tmp_table, files=1)
    c0 = dict(telemetry.counters())
    host, dev = _both(log, "cat > 'b'")
    assert host.equals(dev)
    assert telemetry.counters().get("scan.device.fallback", 0) \
        > c0.get("scan.device.fallback", 0)


# -- rewrite invalidation (epoch bump) --------------------------------------


def _resident_after_scan(log):
    with conf.set_temporarily(**FORCE):
        scan_to_table(log.update(), ["cat = 'alpha'"])
    cache = ColumnCache.instance()
    assert cache.resident_bytes() > 0
    return cache


def test_optimize_bumps_epoch_and_drops_lanes(tmp_table):
    from delta_tpu.commands.optimize import OptimizeCommand

    log = _mk_table(tmp_table)
    cache = _resident_after_scan(log)
    epoch0 = cache.epoch(log.log_path)
    c0 = dict(telemetry.counters())
    OptimizeCommand(log, min_file_size=1 << 30).run()
    assert cache.epoch(log.log_path) == epoch0 + 1
    assert cache.resident_bytes() == 0
    assert telemetry.counters().get("columnCache.invalidations", 0) \
        > c0.get("columnCache.invalidations", 0)
    host, dev = _both(log, "cat = 'alpha'")
    assert host.equals(dev)


def test_update_rewrite_cannot_serve_stale_lane(tmp_table):
    """After an UPDATE rewrite, a device scan must see the NEW values —
    the pre-rewrite lanes can never mask a post-rewrite scan."""
    from delta_tpu.commands.update import UpdateCommand

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(100, dtype=np.int64),
        "cat": pa.array(["old"] * 100)})).run()
    cache = ColumnCache.instance()
    with conf.set_temporarily(**FORCE):
        t0 = scan_to_table(log.update(), ["cat = 'old'"])
    assert t0.num_rows == 100 and cache.resident_bytes() > 0
    epoch0 = cache.epoch(log.log_path)
    UpdateCommand(log, {"cat": "'new'"}, "id < 50").run()
    assert cache.epoch(log.log_path) == epoch0 + 1
    with conf.set_temporarily(**FORCE):
        t_new = scan_to_table(log.update(), ["cat = 'new'"]).sort_by("id")
        t_old = scan_to_table(log.update(), ["cat = 'old'"]).sort_by("id")
    assert t_new.column("id").to_pylist() == list(range(50))
    assert t_old.column("id").to_pylist() == list(range(50, 100))


def test_delete_rewrite_cannot_serve_stale_lane(tmp_table):
    from delta_tpu.commands.delete import DeleteCommand

    log = _mk_table(tmp_table, files=2)
    cache = _resident_after_scan(log)
    epoch0 = cache.epoch(log.log_path)
    DeleteCommand(log, "id < 100").run()  # rewrite mode (no DV conf)
    assert cache.epoch(log.log_path) == epoch0 + 1
    with conf.set_temporarily(**FORCE):
        t = scan_to_table(log.update(), ["id < 200"])
    assert min(t.column("id").to_pylist()) >= 100
    host, dev = _both(log, "cat = 'beta'")
    assert host.equals(dev)


def test_restore_cannot_serve_stale_lane(tmp_table):
    from delta_tpu.commands.restore import RestoreCommand

    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(100, dtype=np.int64),
        "cat": pa.array(["v0"] * 100)})).run()
    v0 = log.update().version
    WriteIntoDelta(log, "append", pa.table({
        "id": np.arange(100, 200, dtype=np.int64),
        "cat": pa.array(["v1"] * 100)})).run()
    cache = ColumnCache.instance()
    with conf.set_temporarily(**FORCE):
        t = scan_to_table(log.update(), ["cat IN ('v0', 'v1')"])
    assert t.num_rows == 200 and cache.resident_bytes() > 0
    epoch0 = cache.epoch(log.log_path)
    RestoreCommand(log, version=v0).run()
    assert cache.epoch(log.log_path) == epoch0 + 1
    with conf.set_temporarily(**FORCE):
        t = scan_to_table(log.update(), ["cat IN ('v0', 'v1')"])
    assert t.num_rows == 100
    assert set(t.column("cat").to_pylist()) == {"v0"}


def test_register_refused_when_epoch_moved():
    """A decode racing a rewrite is served but never cached: register under
    a stale epoch is refused, and a slipped-in stale entry is dropped by
    the get-side guard."""
    cache = ColumnCache.instance()
    lp = "/tbl/_delta_log"
    e = ResidentColumn(lp, "part-0.parquet", "c",
                       np.arange(8, dtype=np.int64), np.ones(8, bool),
                       None, epoch=cache.epoch(lp))
    cache.bump_epoch(lp)
    assert cache.register(e) is False
    assert cache.get(lp, "part-0.parquet", "c") is None
    # belt-and-braces: force a stale entry in and read through the guard
    e2 = ResidentColumn(lp, "part-1.parquet", "c",
                        np.arange(8, dtype=np.int64), np.ones(8, bool),
                        None, epoch=0)
    with cache._lock:
        cache._entries[(lp, "part-1.parquet", "c")] = e2
    assert cache.get(lp, "part-1.parquet", "c") is None
    assert not e2.is_resident


# -- eviction ----------------------------------------------------------------


def test_lru_eviction_under_max_bytes():
    cache = ColumnCache.instance()
    lp = "/tbl/_delta_log"
    entries = [
        ResidentColumn(lp, f"part-{i}.parquet", "c",
                       np.arange(4096, dtype=np.int64), np.ones(4096, bool),
                       None, epoch=0)
        for i in range(4)
    ]
    one = entries[0].nbytes
    c0 = dict(telemetry.counters())
    with conf.set_temporarily(**{
            "delta.tpu.columnCache.maxBytes": one * 2}):
        for e in entries:
            cache.register(e)
    assert cache.resident_bytes() <= one * 2
    # LRU order: the earliest-registered entries lost residency first
    assert not entries[0].is_resident and not entries[1].is_resident
    assert entries[3].is_resident
    assert telemetry.counters().get("columnCache.evictions", 0) \
        > c0.get("columnCache.evictions", 0)


def test_hbm_budget_pressure_applies_to_column_cache():
    cache = ColumnCache.instance()
    lp = "/tbl/_delta_log"
    e = ResidentColumn(lp, "part-0.parquet", "c",
                       np.arange(4096, dtype=np.int64), np.ones(4096, bool),
                       None, epoch=0)
    cache.register(e)
    assert hbm_ledger.column_cache_allowance() is None  # no budget set
    with conf.set_temporarily(**{"delta.tpu.device.hbmBudgetBytes": 16}):
        assert hbm_ledger.column_cache_allowance() is not None
        assert hbm_ledger.over_budget()
        assert hbm_ledger.maybe_relieve()
    assert cache.resident_bytes() == 0
    assert not e.is_resident


def test_residency_gauge_published():
    from delta_tpu.obs import fleet

    cache = ColumnCache.instance()
    lp = "/tbl/_delta_log"
    e = ResidentColumn(lp, "part-0.parquet", "c",
                       np.arange(64, dtype=np.int64), np.ones(64, bool),
                       None, epoch=0)
    cache.register(e)
    label = fleet.table_label("/tbl")
    g = telemetry.gauges("columnCache.residentBytes")
    assert any(dict(k[1]).get("table") == label and v == e.nbytes
               for k, v in g.items())
    cache.bump_epoch(lp)
    g = telemetry.gauges("columnCache.residentBytes")
    assert any(dict(k[1]).get("table") == label and v == 0
               for k, v in g.items())


# -- compile_residual lowering ----------------------------------------------


TYPES = None


def _types():
    from delta_tpu.schema.types import (DateType, DecimalType, DoubleType,
                                        IntegerType, StringType,
                                        TimestampType)

    return {"a": IntegerType(), "s": StringType(), "d": DateType(),
            "ts": TimestampType(), "x": DoubleType(),
            "m": DecimalType(10, 2)}


def test_residual_string_literals_become_code_binds():
    plan = jaxeval.compile_residual(
        parse_predicate("s = 'foo' AND s != 'bar'"), _types(), ())
    assert len(plan.str_binds) == 2
    assert {b[2] for b in plan.str_binds} == {"foo", "bar"}
    assert all(b[1] == "s" for b in plan.str_binds)
    assert plan.refs == frozenset({"s"})


def test_residual_temporal_literals_become_epoch_ints():
    plan = jaxeval.compile_residual(
        parse_predicate("d >= '2024-01-01'"), _types(), ())
    assert plan.expr.sql() == "(d >= 19723)"
    plan = jaxeval.compile_residual(
        parse_predicate("ts < '2024-06-01 12:00:00'"), _types(), ())
    us = int(dt.datetime(2024, 6, 1, 12,
                         tzinfo=dt.timezone.utc).timestamp() * 1_000_000)
    assert plan.expr.sql() == f"(ts < {us})"


def test_residual_date_vs_timestamp_midnight_combine():
    # date literal against a timestamp lane coerces at midnight UTC
    plan = jaxeval.compile_residual(
        parse_predicate("ts >= '2024-03-05'"), _types(), ())
    us = int(dt.datetime(2024, 3, 5,
                         tzinfo=dt.timezone.utc).timestamp() * 1_000_000)
    assert plan.expr.sql() == f"(ts >= {us})"


@pytest.mark.parametrize("bad", [
    "s < 'm'",                 # string ordering has no code semantics
    "upper(s) = 'A'",          # string function
    "m > 5",                   # decimal stays on host
    "d = ts",                  # mixed temporal compare
    "a = 'five'",              # string literal vs numeric lane
])
def test_residual_gates_raise(bad):
    with pytest.raises(jaxeval.NotDeviceCompilable):
        jaxeval.compile_residual(parse_predicate(bad), _types(), ())


def test_residual_string_partition_column_gated():
    from delta_tpu.schema.types import StringType

    with pytest.raises(jaxeval.NotDeviceCompilable):
        jaxeval.compile_residual(parse_predicate("pc = 'x'"),
                                 {"pc": StringType()}, ("pc",))


def test_civil_kernel_matches_python_calendar():
    """The Hinnant civil-from-days lowering must agree with datetime for
    dates across eras, leap years, and the epoch boundary."""
    import jax.numpy as jnp

    from delta_tpu.utils.jaxcompat import enable_x64

    days = np.array(
        [-719162, -1, 0, 1, 59, 60, 19723, 20514,
         (dt.date(2000, 2, 29) - dt.date(1970, 1, 1)).days,
         (dt.date(2100, 3, 1) - dt.date(1970, 1, 1)).days,
         (dt.date(1900, 2, 28) - dt.date(1970, 1, 1)).days],
        dtype=np.int32)
    expect = [dt.date(1970, 1, 1) + dt.timedelta(days=int(v)) for v in days]
    for fn_name, attr in (("year", "year"), ("month", "month"),
                          ("day", "day")):
        plan = jaxeval.compile_residual(
            parse_predicate(f"{fn_name}(d) >= -99999"), _types(), ())
        kernel = jaxeval.compile_expr(plan.expr.children[0])
        with enable_x64():
            env = {"d": jaxeval.DeviceColumn(jnp.asarray(days),
                                             jnp.ones(len(days), bool))}
            got = np.asarray(kernel(env).values)
        assert got.tolist() == [getattr(e, attr) for e in expect], fn_name


def test_residual_plan_is_jit_cache_key():
    """Two scans with the same predicate shape share one jitted kernel:
    the rewritten expression hashes stably."""
    p1 = jaxeval.compile_residual(parse_predicate("a > 5"), _types(), ())
    p2 = jaxeval.compile_residual(parse_predicate("a > 5"), _types(), ())
    assert hash(p1.expr) == hash(p2.expr)
    from delta_tpu.ops.column_cache import _mask_kernel

    assert _mask_kernel(p1.expr) is _mask_kernel(p2.expr)
