"""Change Data Feed: write-side capture + read-side reconstruction.

The reference carries the ``cdc`` action but blocks writing it
(``actions/actions.scala:151-156``); this engine implements the feature the
modern-Delta way. Covers: insert/delete/update/merge capture, preimage/
postimage pairs, reconstruction of append and full-file-delete commits
without CDC files, deletion-vector diff reconstruction, version ranges, and
the protocol gate (CDF needs writer v4).
"""
import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.exec.cdf import (
    CHANGE_TYPE_COL,
    COMMIT_TIMESTAMP_COL,
    COMMIT_VERSION_COL,
)
from delta_tpu.protocol.actions import AddCDCFile
from delta_tpu.utils.errors import DeltaAnalysisError, DeltaUnsupportedOperationError

CDF_PROPS = {"delta.enableChangeDataFeed": "true"}


def make_table(path, n=10, cdf=True, extra_props=None):
    props = dict(CDF_PROPS) if cdf else {}
    props.update(extra_props or {})
    data = pa.table({
        "id": pa.array(range(n), pa.int64()),
        "value": pa.array([f"v{i}" for i in range(n)]),
    })
    return DeltaTable.create(path, data=data, configuration=props or None)


def changes(t, start, end=None):
    got = t.table_changes(start, end)
    return sorted(
        got.to_pylist(),
        key=lambda r: (r[COMMIT_VERSION_COL], r[CHANGE_TYPE_COL], r.get("id") or 0),
    )


def by_type(rows):
    out = {}
    for r in rows:
        out.setdefault(r[CHANGE_TYPE_COL], []).append(r)
    return out


# -- basic capture ------------------------------------------------------------


def test_create_reconstructs_inserts(tmp_table):
    t = make_table(tmp_table, n=3)
    rows = changes(t, 0)
    assert len(rows) == 3
    assert all(r[CHANGE_TYPE_COL] == "insert" for r in rows)
    assert all(r[COMMIT_VERSION_COL] == 0 for r in rows)


def test_delete_captures_deleted_rows(tmp_table):
    t = make_table(tmp_table)
    t.delete("id < 3")
    rows = changes(t, 1)
    assert [r["id"] for r in rows] == [0, 1, 2]
    assert all(r[CHANGE_TYPE_COL] == "delete" for r in rows)
    # the commit carries an AddCDCFile action
    _, acts = next(iter(t.delta_log.get_changes(1)))
    assert any(isinstance(a, AddCDCFile) for a in acts)


def test_update_captures_pre_and_postimage(tmp_table):
    t = make_table(tmp_table)
    t.update({"value": "'X'"}, "id = 4")
    rows = by_type(changes(t, 1))
    assert [r["value"] for r in rows["update_preimage"]] == ["v4"]
    assert [r["value"] for r in rows["update_postimage"]] == ["X"]


def test_merge_captures_all_change_kinds(tmp_table):
    t = make_table(tmp_table)
    src = pa.table({"id": pa.array([2, 3, 100], pa.int64()),
                    "value": pa.array(["U2", "DEL", "N100"])})
    (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
       .when_matched_update_all("s.value != 'DEL'")
       .when_matched_delete("s.value = 'DEL'")
       .when_not_matched_insert_all()
       .execute())
    rows = by_type(changes(t, 1))
    assert [r["id"] for r in rows["insert"]] == [100]
    assert [r["id"] for r in rows["delete"]] == [3]
    assert [r["value"] for r in rows["update_preimage"]] == ["v2"]
    assert [r["value"] for r in rows["update_postimage"]] == ["U2"]


def test_merge_skips_files_with_no_fired_clause(tmp_table):
    """A file whose matched rows all fall through every clause condition is
    left in place: no remove+add rewrite, and no spurious delete+insert
    change rows for rows that never logically changed."""
    t = make_table(tmp_table, n=5)
    from delta_tpu.commands.write import WriteIntoDelta

    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array(range(1000, 1005), pa.int64()),
        "value": pa.array([f"w{i}" for i in range(5)]),
    })).run()
    files_before = {f.path for f in t.delta_log.update().all_files}
    # id=2 (first file): update fires; id=1000 (second file): matched but
    # the clause condition is false — second file must stay untouched
    src = pa.table({"id": pa.array([2, 1000], pa.int64()),
                    "value": pa.array(["U2", "NOOP"])})
    (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
       .when_matched_update_all("s.value != 'NOOP'")
       .execute())
    files_after = {f.path for f in t.delta_log.update().all_files}
    # the second file survives the merge verbatim
    second = [p for p in files_before if p in files_after]
    assert len(second) == 1
    rows = by_type(changes(t, 2))
    assert [r["id"] for r in rows["update_preimage"]] == [2]
    assert [r["id"] for r in rows["update_postimage"]] == [2]
    assert "insert" not in rows and "delete" not in rows
    # table contents intact
    got = t.to_arrow()
    vals = dict(zip(got.column("id").to_pylist(), got.column("value").to_pylist()))
    assert vals[2] == "U2" and vals[1000] == "w0" and got.num_rows == 10


def test_append_reconstructed_without_cdc_files(tmp_table):
    t = make_table(tmp_table, n=2)
    WriteIntoDelta(t.delta_log, "append",
                   pa.table({"id": pa.array([10], pa.int64()),
                             "value": pa.array(["new"])})).run()
    _, acts = next(iter(t.delta_log.get_changes(1)))
    assert not any(isinstance(a, AddCDCFile) for a in acts)
    rows = changes(t, 1)
    assert [(r["id"], r[CHANGE_TYPE_COL]) for r in rows] == [(10, "insert")]


def test_whole_table_delete_reconstructed_from_removes(tmp_table):
    t = make_table(tmp_table, n=4)
    t.delete()  # case 1: file-level removes, no CDC written
    rows = changes(t, 1)
    assert len(rows) == 4
    assert all(r[CHANGE_TYPE_COL] == "delete" for r in rows)


# -- deletion-vector interplay ------------------------------------------------


def test_dv_delete_without_cdf_reconstructs_from_dv_diff(tmp_table):
    t = make_table(
        tmp_table, cdf=False,
        extra_props={"delta.tpu.enableDeletionVectors": "true"},
    )
    t.delete("id < 4")
    t.delete("id = 7")  # second DV on the same file: diff must isolate id=7
    rows1 = changes(t, 1, 1)
    assert sorted(r["id"] for r in rows1) == [0, 1, 2, 3]
    rows2 = changes(t, 2, 2)
    assert [r["id"] for r in rows2] == [7]
    assert all(r[CHANGE_TYPE_COL] == "delete" for r in rows1 + rows2)


def test_dv_plus_cdf_uses_cdc_files(tmp_table):
    t = make_table(
        tmp_table, extra_props={"delta.tpu.enableDeletionVectors": "true"}
    )
    t.update({"value": "'Z'"}, "id >= 8")
    rows = by_type(changes(t, 1))
    assert sorted(r["id"] for r in rows["update_preimage"]) == [8, 9]
    assert [r["value"] for r in rows["update_postimage"]] == ["Z", "Z"]
    _, acts = next(iter(t.delta_log.get_changes(1)))
    assert any(isinstance(a, AddCDCFile) for a in acts)


# -- ranges & errors ----------------------------------------------------------


def test_version_range_selection(tmp_table):
    t = make_table(tmp_table, n=2)
    t.delete("id = 0")        # v1
    t.update({"value": "'u'"}, "id = 1")  # v2
    assert all(r[COMMIT_VERSION_COL] == 1 for r in changes(t, 1, 1))
    both = changes(t, 1, 2)
    assert {r[COMMIT_VERSION_COL] for r in both} == {1, 2}
    assert {r[COMMIT_VERSION_COL] for r in changes(t, 2)} == {2}


def test_commit_timestamps_present(tmp_table):
    t = make_table(tmp_table)
    t.delete("id = 1")
    rows = changes(t, 1)
    assert all(r[COMMIT_TIMESTAMP_COL] > 0 for r in rows)


def test_start_after_end_rejected(tmp_table):
    t = make_table(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        t.table_changes(5, 2)


def test_cdc_write_blocked_without_property(tmp_table):
    """Matches the reference's gate (actions.scala:151-156): committing cdc
    actions to a non-CDF table fails."""
    t = make_table(tmp_table, cdf=False)
    cdc = AddCDCFile(path="_change_data/x.parquet", partition_values={}, size=1)
    with pytest.raises(DeltaUnsupportedOperationError):
        t.delta_log.with_new_transaction(
            lambda txn: txn.commit([cdc], __import__(
                "delta_tpu.commands.operations", fromlist=["x"]
            ).Write(mode="Append"))
        )


def test_cdf_table_requires_writer_v4(tmp_table):
    t = make_table(tmp_table)
    assert t.delta_log.update().protocol.min_writer_version >= 4


def test_cdc_files_do_not_affect_table_state(tmp_table):
    t = make_table(tmp_table)
    t.delete("id < 5")
    t.update({"value": "'q'"}, "id = 9")
    assert t.to_arrow().num_rows == 5
    # CDC files are not part of all_files
    for f in t.delta_log.update().all_files:
        assert not f.path.startswith("_change_data")


# -- streaming CDF source -----------------------------------------------------


def test_streaming_cdf_source_tails_changes(tmp_table):
    from delta_tpu.streaming.source import DeltaCDFSource

    t = make_table(tmp_table, n=4)
    src = DeltaCDFSource(t.delta_log)
    start = src.initial_offset()
    end = src.latest_offset(start)
    batch = src.get_batch(None, end)
    assert batch.num_rows == 4  # initial snapshot as inserts
    assert set(batch.column(CHANGE_TYPE_COL).to_pylist()) == {"insert"}

    t.delete("id = 2")
    t.update({"value": "'u'"}, "id = 3")
    cur = end
    rows = []
    while True:
        nxt = src.latest_offset(cur)
        if nxt is None:
            break
        rows.extend(src.get_batch(cur, nxt).to_pylist())
        cur = nxt
    kinds = sorted(r[CHANGE_TYPE_COL] for r in rows)
    assert kinds == ["delete", "update_postimage", "update_preimage"]
    versions = {r[COMMIT_VERSION_COL] for r in rows}
    assert versions == {1, 2}


def test_streaming_cdf_source_ignores_hygiene(tmp_table):
    """Updates/deletes never raise on the CDF source (they ARE the data),
    unlike the row source's ignoreChanges contract."""
    from delta_tpu.streaming.source import DeltaCDFSource, DeltaSource

    t = make_table(tmp_table, n=4)
    t.update({"value": "'u'"}, "id = 1")
    plain = DeltaSource(t.delta_log, starting_version=0)
    with pytest.raises(Exception):
        for _ in plain._changes_from(1, -1):
            pass
    cdf_src = DeltaCDFSource(t.delta_log, starting_version=0)
    assert [f.version for f in cdf_src._changes_from(1, -1)] == [1]


def test_cdf_start_beyond_latest_rejected(tmp_table):
    t = make_table(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        t.table_changes(100)


def test_cdf_cleaned_start_version_is_data_loss(tmp_table):
    """Retention-cleaned commits must surface as an error, not a silently
    shorter feed."""
    import os
    from delta_tpu.protocol import filenames

    t = make_table(tmp_table, n=2)
    t.delete("id = 0")      # v1
    t.delete("id = 1")      # v2
    t.delta_log.checkpoint()
    os.remove(f"{t.delta_log.log_path}/{filenames.delta_file(0)}")
    os.remove(f"{t.delta_log.log_path}/{filenames.delta_file(1)}")
    from delta_tpu.log.deltalog import DeltaLog

    DeltaLog.clear_cache()
    t2 = DeltaTable.for_path(tmp_table)
    with pytest.raises(DeltaAnalysisError):
        t2.table_changes(0)
    assert t2.table_changes(2).num_rows >= 1  # retained range still works


def test_streaming_cdf_schema_change_still_fatal(tmp_table):
    """The CDF source waives change/delete hygiene but NOT schema drift."""
    from delta_tpu.commands.alter import add_columns
    from delta_tpu.schema.types import LongType, StructField
    from delta_tpu.streaming.source import DeltaCDFSource
    from delta_tpu.utils.errors import DeltaIllegalStateError

    t = make_table(tmp_table, n=2)
    src = DeltaCDFSource(t.delta_log, starting_version=0)
    add_columns(t.delta_log, [StructField("extra", LongType())])
    with pytest.raises(DeltaIllegalStateError):
        for _ in src._changes_from(1, -1):
            pass


def test_streaming_cdf_admission_caps_commits_per_trigger(tmp_table):
    from delta_tpu.streaming.source import DeltaCDFSource

    t = make_table(tmp_table, n=4)
    for i in range(4):
        t.delete(f"id = {i}")  # v1..v4
    src = DeltaCDFSource(t.delta_log, starting_version=0,
                         max_files_per_trigger=2)
    start = src.initial_offset()
    end1 = src.latest_offset(start)
    assert end1.reservoir_version <= 2, "cap must bound commits per batch"
    end2 = src.latest_offset(end1)
    assert end2.reservoir_version > end1.reservoir_version


def test_streaming_cdf_snapshot_rows_carry_real_timestamp(tmp_table):
    from delta_tpu.streaming.source import DeltaCDFSource

    t = make_table(tmp_table, n=2)
    src = DeltaCDFSource(t.delta_log)
    end = src.latest_offset(src.initial_offset())
    batch = src.get_batch(None, end)
    assert all(ts > 0 for ts in batch.column(COMMIT_TIMESTAMP_COL).to_pylist())
