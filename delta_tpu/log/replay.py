"""Action reconciliation ("log replay").

Pure semantics, matching ``PROTOCOL.md`` "Action Reconciliation" and the
reference's ``actions/InMemoryLogReplay.scala:35-78``:

* latest ``Protocol`` wins;
* latest ``Metadata`` wins;
* latest ``SetTransaction`` per ``appId`` wins;
* last ``AddFile`` per path wins; a ``RemoveFile`` tombstones an Add;
* an ``AddFile`` after a ``RemoveFile`` un-tombstones the path;
* tombstones older than ``min_file_retention_timestamp`` are dropped from
  the output state (they only exist so VACUUM and concurrent readers can
  see recently-deleted files).

This host-side replay is the correctness reference; the device-sharded
replay kernel (``delta_tpu.ops.replay_kernel``) computes the same fixpoint
as a segmented sort + last-wins reduce and is validated against this one.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from delta_tpu.protocol.actions import (
    Action,
    AddCDCFile,
    AddFile,
    CommitInfo,
    Metadata,
    Protocol,
    RemoveFile,
    SetTransaction,
)

__all__ = ["LogReplay"]


class LogReplay:
    def __init__(self, min_file_retention_timestamp: int = 0):
        self.min_file_retention_timestamp = min_file_retention_timestamp
        self.current_protocol: Optional[Protocol] = None
        self.current_metadata: Optional[Metadata] = None
        self.current_version: int = -1
        self.transactions: Dict[str, SetTransaction] = {}
        self.active_files: Dict[str, AddFile] = {}
        self._tombstones: Dict[str, RemoveFile] = {}

    def append(self, version: int, actions: Iterable[Action]) -> None:
        """Replay one commit's actions. Versions must be fed in order."""
        assert self.current_version == -1 or version == self.current_version + 1, (
            f"Attempted to replay version {version} after {self.current_version}"
        )
        self.current_version = version
        for a in actions:
            if isinstance(a, SetTransaction):
                self.transactions[a.app_id] = a
            elif isinstance(a, Metadata):
                self.current_metadata = a
            elif isinstance(a, Protocol):
                self.current_protocol = a
            elif isinstance(a, AddFile):
                canonical = canonicalize_path(a.path)
                # Add wins over any prior state of the path.
                self.active_files[canonical] = (
                    a if a.path == canonical else _with_path(a, canonical)
                )
                self._tombstones.pop(canonical, None)
            elif isinstance(a, RemoveFile):
                canonical = canonicalize_path(a.path)
                self.active_files.pop(canonical, None)
                self._tombstones[canonical] = (
                    a if a.path == canonical else _remove_with_path(a, canonical)
                )
            elif isinstance(a, (CommitInfo, AddCDCFile)):
                pass  # not part of reconciled state
            elif a is None:
                pass
            else:
                raise ValueError(f"Unknown action during replay: {a!r}")

    # -- outputs ---------------------------------------------------------

    def get_tombstones(self, cutoff_ms: Optional[int] = None) -> List[RemoveFile]:
        """Un-expired tombstones (InMemoryLogReplay.scala:66-69). Callers with
        their own retention horizon (VACUUM) pass ``cutoff_ms``."""
        if cutoff_ms is None:
            cutoff_ms = self.min_file_retention_timestamp
        return [
            r for r in self._tombstones.values() if r.delete_timestamp > cutoff_ms
        ]

    def checkpoint_actions(self) -> List[Action]:
        """The complete reconciled state, the content of a checkpoint
        (InMemoryLogReplay.scala:71-77): protocol, metadata, txns, tombstones,
        active files (with ``dataChange=False`` normalization)."""
        out: List[Action] = []
        if self.current_protocol is not None:
            out.append(self.current_protocol)
        if self.current_metadata is not None:
            out.append(self.current_metadata)
        out.extend(self.transactions.values())
        out.extend(
            _remove_no_datachange(r) for r in self.get_tombstones()
        )
        out.extend(a.with_data_change(False) for a in self.active_files.values())
        return out


def canonicalize_path(path: str) -> str:
    """Normalize a file path for replay identity (≈ ``Snapshot.canonicalizePath``).

    Relative paths stay as-is (they are relative to the table root and
    percent-decoded by scan time, not here); absolute URIs are kept whole so
    shallow-cloned / converted tables still reconcile correctly."""
    # Strip a redundant "./" prefix; leave everything else untouched. Path
    # identity in the log is exact-string based apart from this.
    while path.startswith("./"):
        path = path[2:]
    return path


def _with_path(a: AddFile, path: str) -> AddFile:
    from dataclasses import replace

    return replace(a, path=path)


def _remove_with_path(r: RemoveFile, path: str) -> RemoveFile:
    from dataclasses import replace

    return replace(r, path=path)


def _remove_no_datachange(r: RemoveFile) -> RemoveFile:
    from dataclasses import replace

    return replace(r, data_change=False)
