"""Deletion vectors: row-level tombstones for data files.

A *beyond-reference* feature (the reference at 0.9 always rewrites whole
files for DML — ``commands/MergeIntoCommand.scala:456-561``,
``commands/DeleteCommand.scala:137-171``): instead of rewriting a 128MB file
to delete 1% of its rows, the engine marks those row positions in a bitmap
attached to the ``AddFile``. DML then writes only *new* rows; readers drop
marked rows at scan time.

Modeled on the modern Delta protocol's deletion-vector descriptors (storage
type, inline vs out-of-line payload, cardinality), but the bitmap encoding
is this engine's own (the real spec uses RoaringBitmapArray): zlib-compressed
deltas of sorted uint32 row positions. Tables that carry DVs are protected by
a protocol bump — (3, 7), mirroring the versions the Delta DV feature
shipped under — so the 0.9 reference refuses them cleanly instead of
silently resurrecting deleted rows.

Row positions are **physical** row indexes in the file as written (0-based),
independent of any DV already applied: a new DV for a file must be the union
of the old positions and the newly-deleted ones.
"""
from __future__ import annotations

import base64
import os
import uuid
import zlib
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "DeletionVectorDescriptor",
    "encode_bitmap",
    "decode_bitmap",
    "write_deletion_vector",
    "read_deletion_vector",
    "INLINE_THRESHOLD_BYTES",
]

# payloads up to this size live inline (base85 in the log JSON); larger ones
# go to a sidecar file under the table dir
INLINE_THRESHOLD_BYTES = 4096

STORAGE_INLINE = "i"
STORAGE_FILE = "u"

_MAGIC = b"DTDV1\x00"


@dataclass(frozen=True)
class DeletionVectorDescriptor:
    """The ``deletionVector`` JSON object carried on Add/RemoveFile."""

    storage_type: str  # "i" inline | "u" sidecar file
    path_or_inline_dv: str  # base85 payload | relative sidecar path
    size_in_bytes: int  # encoded payload size
    cardinality: int  # number of deleted rows

    def to_dict(self) -> Dict[str, Any]:
        return {
            "storageType": self.storage_type,
            "pathOrInlineDv": self.path_or_inline_dv,
            "sizeInBytes": self.size_in_bytes,
            "cardinality": self.cardinality,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DeletionVectorDescriptor":
        return DeletionVectorDescriptor(
            storage_type=d["storageType"],
            path_or_inline_dv=d["pathOrInlineDv"],
            size_in_bytes=int(d.get("sizeInBytes", 0)),
            cardinality=int(d.get("cardinality", 0)),
        )

    @property
    def sidecar_path(self) -> Optional[str]:
        return self.path_or_inline_dv if self.storage_type == STORAGE_FILE else None


def encode_bitmap(rows: np.ndarray) -> bytes:
    """Sorted unique uint32 positions -> compressed payload."""
    rows = np.unique(np.asarray(rows, dtype=np.uint32))
    # delta-encode: runs and near-adjacent deletions compress to almost
    # nothing; random scatters still shrink well under zlib
    deltas = np.diff(rows, prepend=rows[:1]).astype(np.uint32) if rows.size else rows
    if rows.size:
        deltas[0] = rows[0]
    return _MAGIC + zlib.compress(deltas.tobytes(), level=1)


def decode_bitmap(payload: bytes) -> np.ndarray:
    if not payload.startswith(_MAGIC):
        raise ValueError("Not a deletion-vector payload (bad magic)")
    deltas = np.frombuffer(zlib.decompress(payload[len(_MAGIC):]), dtype=np.uint32)
    return np.cumsum(deltas, dtype=np.uint64).astype(np.uint32)


def write_deletion_vector(
    rows: np.ndarray,
    data_path: str,
    inline_threshold: Optional[int] = None,
) -> DeletionVectorDescriptor:
    """Encode ``rows`` and store the payload inline or as a sidecar file."""
    if inline_threshold is None:
        inline_threshold = INLINE_THRESHOLD_BYTES
    rows = np.unique(np.asarray(rows, dtype=np.uint32))
    payload = encode_bitmap(rows)
    if len(payload) <= inline_threshold:
        return DeletionVectorDescriptor(
            storage_type=STORAGE_INLINE,
            path_or_inline_dv=base64.b85encode(payload).decode("ascii"),
            size_in_bytes=len(payload),
            cardinality=int(rows.size),
        )
    rel = f"deletion_vector_{uuid.uuid4()}.bin"
    abs_path = os.path.join(data_path, rel)
    tmp = abs_path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, abs_path)
    finally:
        try:
            os.unlink(tmp)  # no-op after a successful replace
        except OSError:
            pass
    return DeletionVectorDescriptor(
        storage_type=STORAGE_FILE,
        path_or_inline_dv=rel,
        size_in_bytes=len(payload),
        cardinality=int(rows.size),
    )


def dv_sidecar_path(dv: dict, data_path: str):
    """Absolute sidecar path for a ``deletionVector`` JSON dict, or None for
    inline/absent payloads. The single resolution rule (plain join, no
    unquote — sidecar paths are stored raw) shared by the read path below
    and pre-checks like RESTORE's vacuumed-sidecar guard."""
    if not dv or dv.get("storageType") != STORAGE_FILE:
        return None
    rel = dv.get("pathOrInlineDv")
    if rel is None:
        return None  # malformed descriptor: tolerated, the read path errors
    return os.path.join(data_path, rel)


def read_deletion_vector(
    descriptor: DeletionVectorDescriptor, data_path: str
) -> np.ndarray:
    """Deleted physical row positions (sorted uint32)."""
    if descriptor.storage_type == STORAGE_INLINE:
        payload = base64.b85decode(descriptor.path_or_inline_dv)
    elif descriptor.storage_type == STORAGE_FILE:
        sidecar = dv_sidecar_path(
            {"storageType": descriptor.storage_type,
             "pathOrInlineDv": descriptor.path_or_inline_dv},
            data_path,
        )
        with open(sidecar, "rb") as f:
            payload = f.read()
    else:
        raise ValueError(f"Unknown deletion-vector storage type: {descriptor.storage_type!r}")
    return decode_bitmap(payload)
