"""DESCRIBE HISTORY + timestamp→version resolution for time travel.

Reference: ``DeltaHistoryManager.scala:46-538``. Commit timestamps come from
file modification times and can regress (clock skew, copied files); they are
*monotonized* by clamping each commit's timestamp to be strictly greater than
its predecessor's — the same adjustment the reference applies
(``DeltaHistoryManager.monotonizeCommitTimestamps``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from delta_tpu.protocol import filenames
from delta_tpu.protocol.actions import CommitInfo, actions_from_lines
from delta_tpu.utils.errors import (
    DeltaFileNotFoundError,
    TemporallyUnstableInputError,
    TimestampEarlierThanCommitRetentionError,
    VersionNotFoundError,
)

__all__ = ["DeltaHistoryManager", "Commit"]


@dataclass(frozen=True)
class Commit:
    version: int
    timestamp: int  # monotonized millis


class DeltaHistoryManager:
    def __init__(self, delta_log):
        self.delta_log = delta_log

    # -- DESCRIBE HISTORY (DeltaHistoryManager.scala:62-101) -------------

    def get_history(self, limit: Optional[int] = None) -> List[CommitInfo]:
        """Newest-first CommitInfo per commit, with version/timestamp filled."""
        latest = self.delta_log.update().version
        if latest < 0:
            return []
        start = 0 if limit is None else max(0, latest - limit + 1)
        out: List[CommitInfo] = []
        for v in range(latest, start - 1, -1):
            path = f"{self.delta_log.log_path}/{filenames.delta_file(v)}"
            try:
                actions = actions_from_lines(self.delta_log.store.read_iter(path))
            except FileNotFoundError:
                break  # older versions cleaned up
            ci = next((a for a in actions if isinstance(a, CommitInfo)), None)
            if ci is None:
                ci = CommitInfo(version=v)
            elif ci.version is None:
                ci = ci.with_version_timestamp(v)
            out.append(ci)
        return out

    # -- commit listing with monotonized timestamps ----------------------

    def get_commits(self, start: int = 0, end: Optional[int] = None) -> List[Commit]:
        prefix = f"{self.delta_log.log_path}/{filenames.check_version_prefix(start)}"
        commits: List[Commit] = []
        try:
            statuses = list(self.delta_log.store.list_from(prefix))
        except FileNotFoundError:
            return []
        for fs in statuses:
            if filenames.is_delta_file(fs.name):
                v = filenames.delta_version(fs.name)
                if end is not None and v > end:
                    break
                commits.append(Commit(v, fs.modification_time))
        return _monotonize(commits)

    # -- timestamp → version (DeltaHistoryManager.scala:112-145) ---------

    def get_active_commit_at_time(
        self,
        timestamp_ms: int,
        can_return_last_commit: bool = False,
        must_be_recreatable: bool = True,
        can_return_earliest_commit: bool = False,
    ) -> Commit:
        latest_version = self.delta_log.update().version
        if latest_version < 0:
            raise DeltaFileNotFoundError(f"No commits found at {self.delta_log.log_path}")
        earliest = (
            self.get_earliest_reproducible_commit() if must_be_recreatable
            else self.get_earliest_delta_file()
        )
        commits = self.get_commits(earliest, latest_version)
        # last commit with timestamp <= requested
        chosen: Optional[Commit] = None
        for c in commits:
            if c.timestamp <= timestamp_ms:
                chosen = c
            else:
                break
        if chosen is None:
            if can_return_earliest_commit and commits:
                return commits[0]
            if commits:
                raise TimestampEarlierThanCommitRetentionError(
                    f"The provided timestamp ({timestamp_ms}) is before the earliest "
                    f"version available ({commits[0].timestamp}, version {commits[0].version})."
                )
            raise DeltaFileNotFoundError("No commits found")
        if commits and timestamp_ms > commits[-1].timestamp and not can_return_last_commit:
            raise TemporallyUnstableInputError(timestamp_ms, commits[-1].timestamp, commits[-1].version)
        return chosen

    def get_earliest_delta_file(self) -> int:
        prefix = f"{self.delta_log.log_path}/{filenames.check_version_prefix(0)}"
        for fs in self.delta_log.store.list_from(prefix):
            if filenames.is_delta_file(fs.name):
                return filenames.delta_version(fs.name)
        raise DeltaFileNotFoundError(f"No delta files found in {self.delta_log.log_path}")

    def get_earliest_reproducible_commit(self) -> int:
        """Earliest version whose state can be rebuilt: either version 0 with a
        contiguous chain, or covered by a complete checkpoint
        (``DeltaHistoryManager.getEarliestReproducibleCommit``)."""
        from delta_tpu.log.checkpoints import CheckpointInstance, latest_complete_checkpoint

        prefix = f"{self.delta_log.log_path}/{filenames.check_version_prefix(0)}"
        deltas: List[int] = []
        candidates: List[CheckpointInstance] = []
        for fs in self.delta_log.store.list_from(prefix):
            if filenames.is_delta_file(fs.name):
                deltas.append(filenames.delta_version(fs.name))
            elif filenames.is_checkpoint_file(fs.name) and fs.size > 0:
                part = filenames.checkpoint_part(fs.name)
                candidates.append(
                    CheckpointInstance(filenames.checkpoint_version(fs.name), part[1] if part else None)
                )
        if deltas and deltas[0] == 0:
            # contiguous from zero?
            if deltas == list(range(deltas[0], deltas[-1] + 1)):
                return 0
        ckpt = None
        # earliest complete checkpoint from which the chain is contiguous
        complete = sorted({c.version for c in candidates
                           if latest_complete_checkpoint([x for x in candidates if x.version == c.version])})
        for v in complete:
            following = [d for d in deltas if d > v]
            if not following or following == list(range(v + 1, following[-1] + 1)):
                ckpt = v
                break
        if ckpt is None:
            raise DeltaFileNotFoundError(
                f"No recreatable commits found at {self.delta_log.log_path}"
            )
        return ckpt

    def check_version_exists(self, version: int, must_be_recreatable: bool = True) -> None:
        earliest = (
            self.get_earliest_reproducible_commit() if must_be_recreatable
            else self.get_earliest_delta_file()
        )
        latest = self.delta_log.update().version
        if version < earliest or version > latest:
            raise VersionNotFoundError(version, earliest, latest)


def _monotonize(commits: List[Commit]) -> List[Commit]:
    """Clamp timestamps strictly increasing
    (``DeltaHistoryManager.monotonizeCommitTimestamps``)."""
    out: List[Commit] = []
    prev = None
    for c in commits:
        ts = c.timestamp
        if prev is not None and ts <= prev:
            ts = prev + 1
        out.append(Commit(c.version, ts))
        prev = ts
    return out
