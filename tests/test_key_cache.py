"""HBM-resident MERGE join keys (`ops/key_cache.py`): build/advance
lifecycle, deletion-vector validity (grow, shrink, re-add), probe parity
with the host join, and the resident path wired through MergeIntoCommand
(forced mode; parity against the host-pinned merge on a table copy)."""
import shutil

import numpy as np
import pyarrow as pa
import pytest

from delta_tpu import DeltaLog
from delta_tpu.commands.merge import MergeClause, MergeIntoCommand
from delta_tpu.commands.write import WriteIntoDelta
from delta_tpu.expr import ir
from delta_tpu.ops.key_cache import KeyCache, _pack_lanes
from delta_tpu.utils.config import conf


@pytest.fixture(autouse=True)
def _fresh_cache():
    KeyCache.reset()
    yield
    KeyCache.reset()


KEY_EXPRS = (ir.Column("k"),)
SIG = "test-k"


def _mk_table(path, lo=0, hi=200, files=4):
    log = DeltaLog.for_table(path)
    per = (hi - lo) // files
    rng = np.random.RandomState(5)
    for i in range(files):
        keys = np.arange(lo + i * per, lo + (i + 1) * per, dtype=np.int64)
        WriteIntoDelta(log, "append", pa.table({
            "k": keys, "v": rng.rand(per),
        })).run()
    return log


def _entry(log, **kw):
    snap = log.update()
    return KeyCache.instance().get(
        snap, SIG, ["k"], list(KEY_EXPRS), **kw)


def _source(keys, vals=None):
    keys = np.asarray(keys, np.int64)
    return pa.table({
        "k": keys,
        "v": np.asarray(vals if vals is not None else np.zeros(len(keys))),
    })


def _merge(log, source, mode="force"):
    with conf.set_temporarily(**{
        "delta.tpu.merge.devicePath.mode": mode,
        "delta.tpu.deletionVectors.enabled": True,
    }):
        cmd = MergeIntoCommand(
            log, source, "t.k = s.k",
            [MergeClause("update", assignments=None)],
            [MergeClause("insert", assignments=None)],
            source_alias="s", target_alias="t",
        )
        cmd.run()
    return cmd


# -- entry lifecycle --------------------------------------------------------


def test_build_and_probe_matches_membership(tmp_table):
    log = _mk_table(tmp_table)
    e = _entry(log)
    assert e is not None and e.num_rows == 200
    probe = e.probe_async(np.array([5, 150, 500], np.int64),
                          np.array([True, True, True]))
    res = probe.result()
    assert res.s_matched.tolist() == [True, True, False]
    assert res.t_bits.sum() == 2
    assert not res.any_multi


def test_probe_null_keys_never_match(tmp_table):
    log = _mk_table(tmp_table)
    e = _entry(log)
    res = e.probe_async(np.array([5, 0], np.int64),
                        np.array([True, False])).result()
    assert res.s_matched.tolist() == [True, False]


def test_tail_advance_append_and_remove(tmp_table):
    from delta_tpu.commands.delete import DeleteCommand

    log = _mk_table(tmp_table)
    e1 = _entry(log)
    v1 = e1.version
    # append a new file
    WriteIntoDelta(log, "append", pa.table({
        "k": np.arange(500, 550, dtype=np.int64), "v": np.zeros(50)})).run()
    # delete a whole file's rows (file removal, no DV since whole-file)
    e2 = _entry(log)
    assert e2 is e1 and e2.version > v1
    res = e2.probe_async(np.array([510], np.int64), np.array([True])).result()
    assert res.s_matched.tolist() == [True]


def test_dv_deleted_rows_do_not_match(tmp_table):
    """A row logically deleted via deletion vector must not count as a
    match — else its key's NOT MATCHED insert would be skipped. (The table
    property must be on BEFORE the entry builds: a rewrite-path delete
    would instead bump the key-cache epoch and force a rebuild.)"""
    from delta_tpu.commands.alter import set_table_properties
    from delta_tpu.commands.delete import DeleteCommand

    log = _mk_table(tmp_table)
    set_table_properties(log, {"delta.tpu.enableDeletionVectors": "true"})
    e = _entry(log)
    with conf.set_temporarily(**{"delta.tpu.deletionVectors.enabled": True}):
        DeleteCommand(log, "k = 42").run()
    e2 = _entry(log)
    assert e2 is e
    res = e2.probe_async(np.array([42, 43], np.int64),
                         np.array([True, True])).result()
    assert res.s_matched.tolist() == [False, True]


def test_dv_shrink_revives_rows(tmp_table):
    """_set_dv recomputes validity exactly: removing the DV (RESTORE shape)
    brings rows back."""
    log = _mk_table(tmp_table, files=1)
    e = _entry(log)
    path = next(iter(e.slabs))
    e.ensure_resident()
    e._set_dv(path, np.array([3, 7], np.int64))
    res = e.probe_async(np.array([3], np.int64), np.array([True])).result()
    assert res.s_matched.tolist() == [False]
    e._set_dv(path, np.empty(0, np.int64))
    res = e.probe_async(np.array([3], np.int64), np.array([True])).result()
    assert res.s_matched.tolist() == [True]


def test_probe_sorted_kernel_fuzz_parity():
    """Direct slab fuzz of the sorted-slab probe kernel vs a numpy oracle:
    random keys with duplicates, kills, DV masks, null source rows — and
    both coarse-fine download paths (sparse hot blocks -> device gather;
    dense -> full live-prefix fetch)."""
    from delta_tpu.ops.key_cache import ResidentJoinKeys

    rng = np.random.RandomState(7)
    n = 20000  # capacity 32768 -> 8 blocks of 4096
    keys = rng.randint(0, 15000, n).astype(np.int64)  # dense duplicates
    e = ResidentJoinKeys("log", "mid", 0, "sig", ["k"])
    half = n // 2
    e._append_file("f1", keys[:half], np.ones(half, bool))
    e._append_file("f2", keys[half:], np.ones(n - half, bool))
    # DV-mask some of f2, kill nothing (validity path)
    dv_pos = rng.choice(n - half, 500, replace=False).astype(np.int64)
    assert e._set_dv("f2", dv_pos)
    valid = np.ones(n, bool)
    valid[half + dv_pos] = False

    for label, s_keys, s_ok in [
        ("sparse", np.arange(100, 200, dtype=np.int64),
         np.ones(100, bool)),  # clusters into few blocks
        ("dense", rng.randint(0, 15000, 3000).astype(np.int64),
         rng.rand(3000) > 0.1),
        ("misses", np.arange(100000, 100050, dtype=np.int64),
         np.ones(50, bool)),
    ]:
        res = e.probe_async(s_keys, s_ok).result()
        valid_keys = set(keys[valid].tolist())
        exp_s = np.array([ok and (k in valid_keys)
                          for k, ok in zip(s_keys.tolist(), s_ok)], bool)
        src_member = set(s_keys[exp_s].tolist())
        exp_t = np.array([v and (k in src_member)
                          for k, v in zip(keys.tolist(), valid)], bool)
        assert (res.s_matched == exp_s).all(), label
        assert (res.t_bits == exp_t).all(), label
        # multi: some valid slab row matched by >=2 source rows
        matched_counts = {}
        for k, ok in zip(s_keys[s_ok & exp_s].tolist(), [1] * int(exp_s.sum())):
            matched_counts[k] = matched_counts.get(k, 0) + 1
        exp_multi = any(c >= 2 for c in matched_counts.values())
        assert res.any_multi == exp_multi, label


def test_probe_many_above_max_misses_no_overflow():
    """Source keys above the slab maximum (inserts) fall into NO block's
    candidate window — the padding tail must not swallow them into the
    boundary block and trip the overflow tiers."""
    from delta_tpu.ops.key_cache import ResidentJoinKeys

    n = 20000
    e = ResidentJoinKeys("log", "mid", 0, "sig", ["k"])
    e._append_file("f", np.arange(n, dtype=np.int64) * 2, np.ones(n, bool))
    s = np.concatenate([
        np.arange(50000, 60000, dtype=np.int64),  # 10k above-max misses
        np.array([10, 20], np.int64),
    ])
    res = e.probe_async(s, np.ones(len(s), bool)).result()
    assert res.s_matched[-2:].tolist() == [True, True]
    assert not res.s_matched[:-2].any()
    assert res.t_bits.sum() == 2


def test_probe_after_kill_and_append_resorts(tmp_table):
    """Key appends invalidate the sorted view; kills do not. Both must
    still probe correctly afterwards."""
    from delta_tpu.ops.key_cache import ResidentJoinKeys

    e = ResidentJoinKeys("log", "mid", 0, "sig", ["k"])
    e._append_file("a", np.array([10, 20, 30], np.int64), np.ones(3, bool))
    e.ensure_resident()
    r = e.probe_async(np.array([20], np.int64), np.array([True])).result()
    assert r.s_matched.tolist() == [True]
    assert not e._sort_stale
    e._kill_file("a")  # validity flip only: no resort needed
    assert not e._sort_stale
    r = e.probe_async(np.array([20], np.int64), np.array([True])).result()
    assert r.s_matched.tolist() == [False]
    e._append_file("b", np.array([40, 20], np.int64), np.ones(2, bool))
    assert e._sort_stale  # key rows changed
    r = e.probe_async(np.array([20, 10, 40], np.int64),
                      np.ones(3, bool)).result()
    assert r.s_matched.tolist() == [True, False, True]
    assert not e._sort_stale


def test_set_dv_out_of_range_positions_signal_rebuild(tmp_table):
    """DV positions beyond the slab's recorded row count mean the slab and
    the file disagree; masking them would let deleted rows keep matching
    (suppressing NOT MATCHED inserts). _set_dv must refuse (r4 advisor)."""
    log = _mk_table(tmp_table, files=1)
    e = _entry(log)
    rows = e.num_rows
    assert e._set_dv(next(iter(e.slabs)),
                     np.array([0, rows + 5], np.int64)) is False
    # in-range still succeeds
    assert e._set_dv(next(iter(e.slabs)), np.array([0], np.int64)) is True
    # and a DV for an unknown file is likewise a consistency failure
    assert e._set_dv("no-such-file", np.array([0], np.int64)) is False


def test_failed_advance_poisons_version(tmp_table, monkeypatch):
    """A mid-tail failure leaves half-applied mirrors; the entry must not
    stay probe-able at its old version (r4 advisor: stale-version probe of
    a half-advanced slab produced spurious NOT MATCHED inserts)."""
    from delta_tpu.ops import key_cache as kc_mod

    log = _mk_table(tmp_table, files=2)
    e = _entry(log)
    v0 = e.version
    # grow the log, then make the key read fail mid-advance
    WriteIntoDelta(log, "append", pa.table({
        "k": np.arange(500, 520, dtype=np.int64), "v": np.zeros(20),
    })).run()
    snap = log.update()
    orig_file_keys = kc_mod._file_keys
    monkeypatch.setattr(kc_mod, "_file_keys",
                        lambda *a, **k: None)
    assert KeyCache.instance()._advance(e, snap, ["k"], list(KEY_EXPRS)) is False
    assert e.version not in (v0, snap.version)
    # a thread that cached `e` before the failure now fails its guard
    assert e.probe_async(np.array([5], np.int64), np.array([True]),
                         expected_version=v0) is None

    # an EXCEPTION mid-apply (not a clean False) must poison too — it
    # propagates past get()'s pop-on-failure, so the poisoned version is
    # the only thing stopping a stale-version probe
    monkeypatch.setattr(kc_mod, "_file_keys", orig_file_keys)
    e2 = _entry(log)  # rebuilds at snap.version
    assert e2 is not None and e2.version == snap.version
    v1 = e2.version
    WriteIntoDelta(log, "append", pa.table({
        "k": np.arange(600, 610, dtype=np.int64), "v": np.zeros(10),
    })).run()
    snap2 = log.update()

    def boom(*a, **k):
        raise ValueError("corrupt")

    monkeypatch.setattr(kc_mod, "_file_keys", boom)
    with pytest.raises(ValueError):
        KeyCache.instance()._advance(e2, snap2, ["k"], list(KEY_EXPRS))
    assert e2.version not in (v1, snap2.version)
    assert e2.probe_async(np.array([5], np.int64), np.array([True]),
                          expected_version=v1) is None


def test_metadata_change_invalidates(tmp_table):
    from delta_tpu.commands.alter import set_table_properties

    log = _mk_table(tmp_table)
    e1 = _entry(log)
    set_table_properties(log, {"delta.appendOnly": "false"})
    e2 = _entry(log)
    assert e2 is not e1 and e2.version == log.update().version


def test_composite_pack_parity():
    tab = pa.table({"a": pa.array([1, 2, None], pa.int64()),
                    "b": pa.array([10, -3, 5], pa.int64())})
    from delta_tpu.expr.vectorized import evaluate

    packed = _pack_lanes(tab, [ir.Column("a"), ir.Column("b")], evaluate)
    keys, ok = packed
    assert ok.tolist() == [True, True, False]
    assert keys[0] == (1 << 32) | 10
    assert keys[1] == (2 << 32) | (np.int64(-3) & 0xFFFFFFFF)


# -- resident path through MERGE -------------------------------------------


def _copy_table(src_path, dst_path):
    shutil.copytree(src_path, dst_path)
    return DeltaLog.for_table(dst_path)


def test_resident_merge_parity(tmp_path):
    """Forced resident merge == host-pinned merge, end to end (DV mode)."""
    import pyarrow.compute as pc

    from delta_tpu.exec.scan import scan_to_table

    a_path, b_path = str(tmp_path / "a"), str(tmp_path / "b")
    log_a = _mk_table(a_path)
    _copy_table(a_path, b_path)
    log_b = DeltaLog.for_table(b_path)

    sig_exprs = None  # built by the command's signature, seeded below
    # seed the resident entry for table a using the merge's own key exprs
    snap = log_a.update()
    cmd_probe = MergeIntoCommand(
        log_a, _source([1]), "t.k = s.k",
        [MergeClause("update", assignments=None)],
        [MergeClause("insert", assignments=None)],
        source_alias="s", target_alias="t",
    )
    cond = cmd_probe._resolve(cmd_probe.condition, ["k", "v"], ["k", "v"])
    equi, _res = cmd_probe._split_equi_keys(cond)
    t_exprs = [t for t, _ in equi]
    sig = MergeIntoCommand._key_signature(t_exprs)
    e = KeyCache.instance().get(snap, sig, ["k"], t_exprs)
    assert e is not None

    src_keys = [5, 50, 150, 400, 401]  # 3 updates, 2 inserts
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    cmd_a = _merge(log_a, _source(src_keys, vals), mode="force")
    cmd_b = _merge(log_b, _source(src_keys, vals), mode="off")
    assert cmd_a._device_join is not None
    assert cmd_a._join_path == "resident"
    assert cmd_a.metrics["numTargetRowsUpdated"] == 3
    assert cmd_a.metrics["numTargetRowsInserted"] == 2
    for k in ("numTargetRowsUpdated", "numTargetRowsInserted",
              "numTargetRowsCopied"):
        assert cmd_a.metrics[k] == cmd_b.metrics[k], k

    ta = scan_to_table(log_a.update()).sort_by("k")
    tb = scan_to_table(log_b.update()).sort_by("k")
    assert ta.column("k").to_pylist() == tb.column("k").to_pylist()
    assert ta.column("v").to_pylist() == tb.column("v").to_pylist()


def test_resident_merge_after_dv_round(tmp_path):
    """Second resident merge after the first created DVs: deleted rows must
    not block inserts, updated values must land (the CDC steady state)."""
    from delta_tpu.exec.scan import scan_to_table

    a_path = str(tmp_path / "a")
    log = _mk_table(a_path)
    snap = log.update()
    cmd0 = MergeIntoCommand(
        log, _source([1]), "t.k = s.k",
        [MergeClause("update", assignments=None)],
        [MergeClause("insert", assignments=None)],
        source_alias="s", target_alias="t",
    )
    cond = cmd0._resolve(cmd0.condition, ["k", "v"], ["k", "v"])
    equi, _ = cmd0._split_equi_keys(cond)
    t_exprs = [t for t, _ in equi]
    sig = MergeIntoCommand._key_signature(t_exprs)
    KeyCache.instance().get(snap, sig, ["k"], t_exprs)

    cmd1 = _merge(log, _source([10, 20, 300], [1.0, 2.0, 3.0]))
    assert cmd1._join_path == "resident"
    # second merge: hits rows now carrying DVs + the fresh insert file
    cmd2 = _merge(log, _source([10, 300, 301], [7.0, 8.0, 9.0]))
    assert cmd2._join_path == "resident"
    assert cmd2.metrics["numTargetRowsUpdated"] == 2
    assert cmd2.metrics["numTargetRowsInserted"] == 1
    t = scan_to_table(log.update())
    got = dict(zip(t.column("k").to_pylist(), t.column("v").to_pylist()))
    assert got[10] == 7.0 and got[300] == 8.0 and got[301] == 9.0
    assert t.num_rows == 202  # 200 original + 300 + 301


def test_resident_multi_match_errors(tmp_path):
    from delta_tpu.utils.errors import DeltaUnsupportedOperationError

    log = _mk_table(str(tmp_path / "a"))
    snap = log.update()
    e = KeyCache.instance().get(
        snap, MergeIntoCommand._key_signature([ir.Column("k")]),
        ["k"], [ir.Column("k")])
    assert e is not None
    with pytest.raises(DeltaUnsupportedOperationError, match="multiple source"):
        _merge(log, _source([5, 5], [1.0, 2.0]))


def test_background_build_after_merge(tmp_table):
    import time

    log = _mk_table(tmp_table)
    with conf.set_temporarily(**{"delta.tpu.merge.residentKeys.minRows": "1"}):
        cmd = _merge(log, _source([5, 400], [1.0, 2.0]), mode="auto")
        sig = None
        # the command recorded + consumed the candidate; poll the cache
        for _ in range(100):
            entries = list(KeyCache.instance()._entries.values())
            if entries:
                break
            time.sleep(0.05)
    assert entries, "background build after an eligible merge"
    assert entries[0].version == log.update().version


def test_probe_absent_key_sharing_lo_with_member(tmp_table):
    """A member key Z and an absent key Y with searchsorted lo(Y) == lo(Z)
    must not race in the mark scatter: Z stays matched (round-4 review —
    mixed True/False scatter to one index has unspecified winner on XLA)."""
    log = DeltaLog.for_table(tmp_table)
    WriteIntoDelta(log, "append", pa.table({
        "k": np.array([100, 200, 300], np.int64), "v": np.zeros(3)})).run()
    e = _entry(log)
    # many interleaved probes: absent keys just below each member key share
    # the member's lo; order inside the scatter must not matter
    s = np.array([99, 100, 199, 200, 299, 300, 150, 250], np.int64)
    res = e.probe_async(s, np.ones(len(s), bool)).result()
    assert res.s_matched.tolist() == [False, True, False, True, False, True,
                                      False, False]
    assert res.t_bits.tolist() == [True, True, True]


def test_batched_advance_append_plus_dv_same_file(tmp_table):
    """A file appended AND DV-masked within one tail batch: the flush must
    apply the row scatter before the kills (append captures pre-DV
    validity)."""
    from delta_tpu.commands.alter import set_table_properties
    from delta_tpu.commands.delete import DeleteCommand

    log = _mk_table(tmp_table, files=1)
    set_table_properties(log, {"delta.tpu.enableDeletionVectors": "true"})
    e1 = _entry(log)
    e1.ensure_resident()
    # in one tail window: append a file, then DV-delete some of its rows
    WriteIntoDelta(log, "append", pa.table({
        "k": np.arange(1000, 1050, dtype=np.int64), "v": np.zeros(50)})).run()
    with conf.set_temporarily(**{"delta.tpu.deletionVectors.enabled": True}):
        DeleteCommand(log, "k = 1010").run()
    e2 = _entry(log)
    assert e2 is e1 and e2.is_resident
    res = e2.probe_async(np.array([1010, 1011], np.int64),
                         np.array([True, True])).result()
    assert res.s_matched.tolist() == [False, True]


# -- rewrite invalidation (epoch bump) --------------------------------------


def test_optimize_bumps_epoch_and_drops_entry(tmp_table):
    """OPTIMIZE rewrites files: the resident entry must be dropped (never
    advanced-through or served) and the table's epoch must move."""
    from delta_tpu.commands.optimize import OptimizeCommand

    log = _mk_table(tmp_table)
    e = _entry(log)
    assert e is not None
    kc = KeyCache.instance()
    epoch0 = kc.epoch(log.log_path)
    OptimizeCommand(log, min_file_size=1 << 30).run()
    assert kc.epoch(log.log_path) == epoch0 + 1
    assert kc.peek(log.log_path, SIG) is None
    # a rebuild at the post-rewrite snapshot serves correct members
    e2 = _entry(log)
    assert e2 is not e and e2.version == log.update().version
    res = e2.probe_async(np.array([5, 500], np.int64),
                         np.ones(2, bool)).result()
    assert res.s_matched.tolist() == [True, False]


def test_stale_entry_cannot_serve_after_rewrite(tmp_table):
    """Even if a buggy path re-inserts a pre-rewrite entry, the epoch guard
    refuses to serve it, and version-poisoning fails any in-flight holder's
    expected-version probe — a stale resident cache can never serve a
    post-rewrite MERGE."""
    from delta_tpu.commands.optimize import OptimizeCommand

    log = _mk_table(tmp_table)
    e = _entry(log)
    v0 = e.version
    kc = KeyCache.instance()
    OptimizeCommand(log, min_file_size=1 << 30).run()
    # the bump poisoned the dropped entry: in-flight holders fail their guard
    assert e.probe_async(np.array([5], np.int64), np.array([True]),
                         expected_version=v0) is None
    # simulate a buggy re-insert of the stale entry
    with kc._lock:
        kc._entries[(log.log_path, SIG)] = e
    assert kc.get(log.update(), SIG, ["k"], list(KEY_EXPRS),
                  build_if_missing=False) is None


def test_update_rewrite_bumps_epoch_dv_mark_does_not(tmp_table):
    """UPDATE in rewrite mode invalidates; UPDATE in DV mode advances the
    entry incrementally (the CDC steady state must not lose residency)."""
    from delta_tpu.commands.alter import set_table_properties
    from delta_tpu.commands.update import UpdateCommand

    log = _mk_table(tmp_table)
    kc = KeyCache.instance()
    epoch0 = kc.epoch(log.log_path)
    # rewrite mode (no DV property): epoch bumps
    UpdateCommand(log, {"v": "0.5"}, "k = 10").run()
    assert kc.epoch(log.log_path) == epoch0 + 1
    # DV mode: no bump, existing entry advances in place
    set_table_properties(log, {"delta.tpu.enableDeletionVectors": "true"})
    e = _entry(log)
    with conf.set_temporarily(**{"delta.tpu.deletionVectors.enabled": True}):
        UpdateCommand(log, {"v": "0.7"}, "k = 11").run()
    assert kc.epoch(log.log_path) == epoch0 + 1
    e2 = _entry(log)
    assert e2 is e and e2.version == log.update().version


def test_concurrent_resident_merges_chaos(tmp_path):
    """Two threads merging DISJOINT key sets into one table with the
    resident lane forced: OCC retries serialize the commits, the lane
    advances through both tails, and the final table state is exactly the
    union — no lost updates, no phantom inserts (the advance-vs-probe race
    the entry lock + expected-version guard protect)."""
    import threading

    from delta_tpu.exec.scan import scan_to_table

    path = str(tmp_path / "c")
    log = _mk_table(path, files=4)
    snap = log.update()
    sig = MergeIntoCommand._key_signature([ir.Column("k")])
    e = KeyCache.instance().get(snap, sig, ["k"], [ir.Column("k")])
    e.ensure_resident()

    errors_seen = []

    def worker(base):
        try:
            for rnd in range(3):
                src = _source([base + rnd * 2, 1000 + base + rnd],
                              [float(base + rnd), float(base + rnd) + 0.5])
                for attempt in range(8):
                    try:
                        _merge(log, src)
                        break
                    except Exception as exc:
                        name = type(exc).__name__
                        if "Concurrent" in name or "Commit" in name:
                            continue  # OCC conflict: retry
                        raise
                else:
                    raise RuntimeError("merge retries exhausted")
        except Exception as exc:
            errors_seen.append(exc)

    t1 = threading.Thread(target=worker, args=(0,))
    t2 = threading.Thread(target=worker, args=(100,))
    t1.start(); t2.start()
    t1.join(30); t2.join(30)
    assert not errors_seen, errors_seen

    t = scan_to_table(log.update())
    got = dict(zip(t.column("k").to_pylist(), t.column("v").to_pylist()))
    # updates landed (last writer per key within each thread's sequence)
    for base in (0, 100):
        for rnd in range(3):
            assert got[base + rnd * 2] == float(base + rnd), (base, rnd)
            assert got[1000 + base + rnd] == float(base + rnd) + 0.5
    assert t.num_rows == 200 + 6  # 200 original + 3 inserts per thread


# -- device-memory soft budget (ISSUE 7: obs/hbm_ledger pressure) ------------


def test_hbm_budget_pressure_evicts_lru_first():
    """With delta.tpu.device.hbmBudgetBytes set, KeyCache eviction prices
    itself against budget - stateCache - scratch and drops device copies in
    LRU order — least-recently-used entries lose residency first, the MRU
    survivor keeps it, and scratch growth tightens the allowance further."""
    import gc

    from delta_tpu.obs import hbm_ledger
    from delta_tpu.ops.key_cache import ResidentJoinKeys

    gc.collect()
    hbm_ledger.reset()
    cache = KeyCache.instance()
    entries = []
    for i in range(3):
        e = ResidentJoinKeys(f"/hbm-log-{i}", "mid", 0, "sig", ["k"])
        e.h_keys = np.arange(10, dtype=np.int64)
        e.h_valid = np.ones(10, bool)
        e.h_nullok = np.ones(10, bool)
        e.h_min, e.h_max = 0, 9
        e.num_rows = 10
        e.ensure_resident()
        assert cache.register(e), f"entry {i} failed to register"
        entries.append(e)
    per_entry = entries[0].device_bytes
    assert hbm_ledger.totals()["keyCache"] == 3 * per_entry
    # budget fits ONE entry (plus slack): the two least-recently-registered
    # lose their device copies, the most recent keeps residency
    with conf.set_temporarily(**{
        "delta.tpu.device.hbmBudgetBytes": per_entry + per_entry // 2,
    }):
        cache._evict(keep=None)
        assert [e.is_resident for e in entries] == [False, False, True]
        assert hbm_ledger.totals()["keyCache"] == per_entry
        # scratch pressure shrinks the allowance below one entry: the last
        # resident copy goes too (host mirrors keep serving)
        hbm_ledger.adjust("scratch", per_entry)
        cache._evict(keep=None)
        assert [e.is_resident for e in entries] == [False, False, False]
        assert hbm_ledger.totals()["keyCache"] == 0
        hbm_ledger.adjust("scratch", -per_entry)
    # without a budget the default keyCache.maxBytes (1 GiB) evicts nothing
    entries[0].ensure_resident()
    cache._evict(keep=None)
    assert entries[0].is_resident
    hbm_ledger.reset()
