"""Capacity testing + synthetic scenario traces.

:func:`capacity_replay` replays a :class:`~delta_tpu.replay.trace.WorkloadTrace`
time-compressed (10x / 100x) against the LIVE scraper/SLO plane: every scan
event's measured planning latency feeds the real
``delta.scan.planning.duration_ms`` histogram under the table's hashed
fleet label, and the time-series scraper snapshots + evaluates the SLO
objectives at the compressed timestamps — a burn that would take an hour of
real traffic pre-fires in seconds, BEFORE the traffic arrives. The replay
deliberately writes into the live metric rings (that is the point); run it
against a staging process or follow with ``timeseries.reset()`` +
``slo.reset()`` when the rings must stay pristine.

The synthetic generators (:func:`zipf_hot_key_storm`, :func:`cdc_burst`,
:func:`contention_flood`) emit deterministic (seeded) traces in the SAME
serialized format `replay/trace` produces from the journal, so shadow runs,
capacity replays, torture, and bench all draw from one scenario library.
"""
from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

from delta_tpu.utils import telemetry

from delta_tpu.replay.trace import TraceEvent, WorkloadTrace

__all__ = ["SCENARIOS", "capacity_replay", "cdc_burst", "contention_flood",
           "zipf_hot_key_storm"]


# ---------------------------------------------------------------------------
# Capacity replay
# ---------------------------------------------------------------------------


def capacity_replay(trace: WorkloadTrace, speed: float = 10.0,
                    scrape_every: int = 8,
                    now_ms: Optional[int] = None) -> Dict[str, Any]:
    """Replay ``trace``'s scan latencies at ``speed``x against the live
    scraper/SLO plane. Event N lands at simulated time
    ``now + (ts_N - ts_0) / speed``; every ``scrape_every`` events the
    scraper snapshots and the SLO objectives evaluate at that simulated
    clock. Returns the fired objectives + alerts attributed to the trace's
    table."""
    from delta_tpu.obs import fleet, slo, timeseries

    speed = max(float(speed), 1e-6)
    label = fleet.table_label(trace.path) if trace.path else ""
    scans = [e for e in trace.events if e.kind == "scan"]
    start = int(now_ms if now_ms is not None else time.time() * 1000)
    scrapes = 0
    if scans:
        t0 = scans[0].ts
        # baseline snapshot BEFORE any observation: window queries diff the
        # latest sample against the oldest retained one, so observations
        # recorded before the first scrape would vanish into the baseline
        timeseries.scrape_once(now_ms=start - 1, evaluate_slo=False)
        scrapes += 1
        sim = start
        for i, ev in enumerate(scans):
            sim = start + int((ev.ts - t0) / speed)
            telemetry.observe("delta.scan.planning.duration_ms",
                              float(ev.planning_ms), table=label)
            if (i + 1) % max(1, int(scrape_every)) == 0:
                timeseries.scrape_once(now_ms=sim, evaluate_slo=True)
                scrapes += 1
        timeseries.scrape_once(now_ms=sim + 1, evaluate_slo=True)
        scrapes += 1
    alerts = [a for a in slo.active_alerts()
              if not label or a.get("table") in (label, None)]
    telemetry.bump_counter("replay.capacity.runs")
    return {
        "path": trace.path,
        "source": trace.source,
        "speed": speed,
        "events": len(scans),
        "scrapes": scrapes,
        "simulatedMs": (int((scans[-1].ts - scans[0].ts) / speed)
                        if scans else 0),
        "originalMs": (scans[-1].ts - scans[0].ts) if scans else 0,
        "alerts": alerts,
        "objectives": sorted({a["objective"] for a in alerts}),
    }


# ---------------------------------------------------------------------------
# Synthetic scenario library
# ---------------------------------------------------------------------------


def _zipf_index(rng: random.Random, n: int, skew: float = 1.2) -> int:
    """Cheap zipf-ish draw over [0, n): inverse-power transform of a
    uniform sample — no scipy, deterministic under the seed."""
    u = rng.random()
    return min(n - 1, int(n * (u ** skew) * u))


def zipf_hot_key_storm(path: str = "synthetic://zipf", scans: int = 120,
                       keys: int = 50, seed: int = 7,
                       interval_ms: int = 30_000,
                       hot_planning_ms: float = 900.0) -> WorkloadTrace:
    """A skewed point-lookup storm: zipf-distributed ``k = <key>`` scans
    where the hottest keys also carry pathological planning latency — the
    shape that burns the ``scanPlanningP99`` objective under load."""
    rng = random.Random(seed)
    events: List[TraceEvent] = []
    for i in range(scans):
        key = _zipf_index(rng, keys)
        hot = key < max(1, keys // 10)
        events.append(TraceEvent(
            ts=i * interval_ms, kind="scan", predicate=f"k = {key}",
            fingerprint="eq(k,?)",
            planning_ms=(hot_planning_ms * (0.8 + 0.4 * rng.random())
                         if hot else 5.0 + 10.0 * rng.random()),
            payload={"hotKey": hot},
        ))
    return WorkloadTrace(path=path, built_at_ms=0, events=events,
                         source="synthetic:zipfHotKeyStorm")


def cdc_burst(path: str = "synthetic://cdc", bursts: int = 4,
              writes_per_burst: int = 25, seed: int = 11,
              interval_ms: int = 60_000) -> WorkloadTrace:
    """Change-data-capture apply bursts: trains of MERGE-shaped dml +
    commit events with trailing verification scans — the workload the
    merge-on-read delta store (ROADMAP item 3) will be sized against."""
    rng = random.Random(seed)
    events: List[TraceEvent] = []
    ts = 0
    for b in range(bursts):
        ts = b * bursts * interval_ms
        for w in range(writes_per_burst):
            ts += int(interval_ms / writes_per_burst)
            events.append(TraceEvent(
                ts=ts, kind="dml",
                payload={"op": "MERGE", "rows": 1 + _zipf_index(rng, 500)}))
            events.append(TraceEvent(
                ts=ts + 1, kind="commit",
                payload={"outcome": "committed", "attempts": 1}))
        events.append(TraceEvent(
            ts=ts + 2, kind="scan", predicate=f"v >= {rng.randrange(1000)}",
            fingerprint="ge(v,?)",
            planning_ms=20.0 + 30.0 * rng.random()))
    return WorkloadTrace(path=path, built_at_ms=0, events=events,
                         source="synthetic:cdcBurst")


def contention_flood(path: str = "synthetic://contention", writers: int = 8,
                     rounds: int = 12, seed: int = 13,
                     interval_ms: int = 10_000) -> WorkloadTrace:
    """Concurrent-writer pile-up: every round, ``writers`` commits race and
    most retry or lose — the trace the commit-retry-rate SLO and the group
    commit coordinator are torture-tested against."""
    rng = random.Random(seed)
    events: List[TraceEvent] = []
    for r in range(rounds):
        base = r * interval_ms
        for w in range(writers):
            won = w == r % writers
            attempts = 1 if won else 1 + _zipf_index(rng, 4)
            events.append(TraceEvent(
                ts=base + w, kind="commit",
                payload={"outcome": ("committed" if won or attempts < 4
                                     else "conflict"),
                         "attempts": attempts, "writer": w}))
        events.append(TraceEvent(
            ts=base + writers, kind="scan", predicate=None,
            planning_ms=15.0 + 20.0 * rng.random()))
    return WorkloadTrace(path=path, built_at_ms=0, events=events,
                         source="synthetic:contentionFlood")


#: name → generator; torture and bench both resolve scenarios through this
SCENARIOS = {
    "zipfHotKeyStorm": zipf_hot_key_storm,
    "cdcBurst": cdc_burst,
    "contentionFlood": contention_flood,
}
