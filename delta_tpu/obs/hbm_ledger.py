"""Device-memory ledger — HBM accounting for the engine's resident state.

PR 6 put three kinds of engine state in HBM: the MERGE key-cache slabs
(`ops/key_cache`), the scan-planning state cache (`ops/state_cache`), and
transient join scratch (probe source uploads); the device scan path added a
fourth, the hot-column lanes of `ops/column_cache`. None of it was measured
originally — an operator diagnosing device OOM had no number, and nothing
connected the caches' independent byte budgets. This module is the single
ledger:

* each component's live device bytes, published as
  ``device.hbm.{keyCache,stateCache,scratch,columnCache}Bytes`` gauges
  (gated on ``delta.tpu.telemetry.enabled``; the internal tallies always
  run — budget enforcement must survive a telemetry blackout);
* a process-wide soft budget ``delta.tpu.device.hbmBudgetBytes`` (unset =
  unlimited).  When set, each LRU cache prices itself against
  ``budget - everyone else`` (:func:`key_cache_allowance`,
  :func:`column_cache_allowance`) so growth anywhere turns into eviction
  *pressure* instead of OOM — soft: a transient slab mid-build may
  overshoot until it registers;
* the numbers behind the doctor's 8th dimension ("device residency
  pressure", `obs/doctor._dim_device`) with its EVICT remedy.

Accounting is delta-based at the residency transitions (device arrays
built / dropped), so the ledger needs no walk of either cache.
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict, Optional

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["Account", "adjust", "totals", "budget_bytes",
           "device_totals", "worst_device",
           "key_cache_allowance", "column_cache_allowance", "over_budget",
           "maybe_relieve", "reset"]

_LOCK = threading.Lock()
_BYTES: Dict[str, int] = {"keyCache": 0, "stateCache": 0, "scratch": 0,
                          "columnCache": 0}
# per-device breakdown (component -> device index -> bytes): sharded
# residency (ops/state_cache sharded lanes) accounts each device's slice,
# so one hot device can't hide under the mesh-wide aggregate
_DEVICES: Dict[str, Dict[int, int]] = {}

# gauge names are constants from the obs/metric_names catalog — mapped here
# so every component publishes through a registered name
_GAUGE = {
    "keyCache": "device.hbm.keyCacheBytes",
    "stateCache": "device.hbm.stateCacheBytes",
    "scratch": "device.hbm.scratchBytes",
    "columnCache": "device.hbm.columnCacheBytes",
}


def adjust(component: str, delta_bytes: int,
           device: Optional[int] = None) -> None:
    """Add ``delta_bytes`` (may be negative) to a component's ledger entry.
    Callers are the residency transitions themselves (alloc/upload = +,
    drop/free = -); the ledger clamps at zero so a double-free can never
    drive the total negative. With ``device`` the delta also lands in that
    device's breakdown, published as the same gauge with a ``device=<i>``
    label next to the unlabeled aggregate."""
    dvalue = None
    with _LOCK:
        _BYTES[component] = max(0, _BYTES[component] + int(delta_bytes))
        value = _BYTES[component]
        if device is not None:
            d = _DEVICES.setdefault(component, {})
            d[int(device)] = max(0, d.get(int(device), 0) + int(delta_bytes))
            dvalue = d[int(device)]
    if conf.get_bool("delta.tpu.telemetry.enabled", True):
        telemetry.set_gauge(_GAUGE[component], value)
        if dvalue is not None:
            telemetry.set_gauge(_GAUGE[component], dvalue, device=str(device))


def _charge(component: str, items, rest: int, sign: int) -> None:
    """Apply an Account's (per-device items, unattributed rest) charge with
    ``sign`` = +1 (on) / -1 (off and the gc-finalizer backstop). Module
    function + plain values only, so the finalizer never pins its owner."""
    for dev, b in items:
        adjust(component, sign * b, device=dev)
    if rest:
        adjust(component, sign * rest)


class Account:
    """Delta-based residency accounting for ONE device-resident object —
    the shared pattern behind `ops/key_cache.ResidentJoinKeys` and
    `ops/state_cache.ResidentState`: idempotent :meth:`on` at the
    residency transition (with a gc-finalizer backstop, so an object that
    dies resident still returns its bytes), :meth:`off` at the drop.
    Callers hold their own entry lock; the ledger lock stays a leaf."""

    __slots__ = ("component", "bytes", "_final", "_per_device", "_rest")

    def __init__(self, component: str):
        self.component = component
        self.bytes = 0
        self._final = None
        self._per_device = ()
        self._rest = 0

    def on(self, owner, nbytes: int,
           per_device: Optional[Dict[int, int]] = None) -> None:
        """Account ``nbytes`` resident; ``per_device`` attributes slices to
        device indices (sharded residency) — any remainder stays in the
        unattributed aggregate."""
        if self.bytes:
            return
        self.bytes = int(nbytes)
        items = tuple(sorted(
            (int(d), int(b)) for d, b in (per_device or {}).items() if b
        ))
        self._per_device = items
        self._rest = self.bytes - sum(b for _, b in items)
        _charge(self.component, items, self._rest, 1)
        # the callback must not reference `owner` (it would never collect):
        # module function + captured plain values only
        self._final = weakref.finalize(owner, _charge, self.component,
                                       items, self._rest, -1)

    def off(self) -> None:
        if not self.bytes:
            return
        _charge(self.component, self._per_device, self._rest, -1)
        self.bytes = 0
        self._per_device = ()
        self._rest = 0
        if self._final is not None:
            self._final.detach()
            self._final = None


def totals() -> Dict[str, int]:
    """Current per-component bytes plus their sum under ``"total"``."""
    with _LOCK:
        out = dict(_BYTES)
    out["total"] = sum(out.values())
    return out


def device_totals() -> Dict[int, int]:
    """Per-device resident bytes summed across components (only devices
    that ever held attributed residency appear)."""
    out: Dict[int, int] = {}
    with _LOCK:
        for d in _DEVICES.values():
            for dev, b in d.items():
                out[dev] = out.get(dev, 0) + b
    return out


def worst_device() -> Optional[tuple]:
    """(device index, bytes) of the most-loaded device, or None when no
    per-device residency is attributed — what the doctor's device dimension
    flags, so a single hot device can't hide under the mesh-wide mean."""
    per = device_totals()
    if not per:
        return None
    dev = max(per, key=lambda i: (per[i], -i))
    return dev, per[dev]


def budget_bytes() -> Optional[int]:
    """The configured soft budget, or None (unlimited)."""
    b = conf.get("delta.tpu.device.hbmBudgetBytes")
    try:
        return int(b) if b is not None else None
    except (TypeError, ValueError):
        return None


def _allowance(component: str) -> Optional[int]:
    budget = budget_bytes()
    if budget is None:
        return None
    with _LOCK:
        other = sum(v for k, v in _BYTES.items() if k != component)
    return max(0, budget - other)


def key_cache_allowance() -> Optional[int]:
    """How many HBM bytes the KeyCache may hold under the soft budget:
    ``budget - everyone else`` (floored at 0), or None when no budget is
    set. `ops/key_cache.KeyCache._evict` takes the min of this and its
    own ``delta.tpu.keyCache.maxBytes``."""
    return _allowance("keyCache")


def column_cache_allowance() -> Optional[int]:
    """Same contract for the scan ColumnCache: ``budget - everyone else``
    or None. `ops/column_cache.ColumnCache._evict` takes the min of this
    and ``delta.tpu.columnCache.maxBytes``."""
    return _allowance("columnCache")


def over_budget() -> bool:
    budget = budget_bytes()
    return budget is not None and totals()["total"] > budget


def maybe_relieve() -> bool:
    """Apply eviction pressure when over the soft budget: run the KeyCache's
    LRU eviction under the (now tighter) allowance. Returns True when
    pressure was applied. Never called with cache/entry locks held."""
    if not over_budget():
        return False
    from delta_tpu.ops.column_cache import ColumnCache
    from delta_tpu.ops.key_cache import KeyCache

    KeyCache.instance()._evict(keep=None)
    ColumnCache.instance()._evict(keep=None)
    return True


def reset() -> None:
    """Zero the ledger (tests; the caches re-account as they re-build)."""
    with _LOCK:
        for k in _BYTES:
            _BYTES[k] = 0
        _DEVICES.clear()
