"""Remaining SchemaUtilsSuite scenario families — duplicate detection at
every nesting depth (double-nested structs, arrays-of-arrays, map keys AND
values), dots/backtick-quoted names as NON-duplicates, case-sensitivity
variants, normalize-ordering, and merge upcast matrices — re-expressed
against `schema/schema_utils.py` (reference:
`schema/SchemaUtilsSuite.scala`, 1,311 LoC)."""
import pytest

from delta_tpu.schema import schema_utils as su
from delta_tpu.schema.types import (
    ArrayType,
    ByteType,
    DoubleType,
    IntegerType,
    LongType,
    MapType,
    NullType,
    ShortType,
    StringType,
    StructField,
    StructType,
)
from delta_tpu.utils.errors import DeltaAnalysisError, SchemaMismatchError


def S(*fields):
    return StructType([StructField(n, t) for n, t in fields])


# ---------------------------------------------------------------------------
# duplicate detection at depth
# ---------------------------------------------------------------------------


def _dup(schema):
    with pytest.raises(DeltaAnalysisError):
        su.check_column_name_duplication(schema, "in test")


def _ok(schema):
    su.check_column_name_duplication(schema, "in test")


def test_duplicate_top_level():
    _dup(S(("a", IntegerType()), ("b", StringType()), ("a", LongType())))


def test_duplicate_top_level_case_insensitive():
    _dup(S(("abc", IntegerType()), ("ABC", LongType())))


def test_duplicate_in_nested_struct():
    _dup(S(("top", S(("x", IntegerType()), ("X", LongType())))))


def test_duplicate_in_double_nested_struct():
    inner = S(("d", IntegerType()), ("D", LongType()))
    _dup(S(("l1", S(("l2", inner)))))


def test_duplicate_in_double_nested_array():
    inner = S(("d", IntegerType()), ("d", LongType()))
    arr = ArrayType(ArrayType(inner))
    _dup(S(("top", arr)))


def test_duplicate_in_nested_array_element():
    _dup(S(("top", ArrayType(S(("e", IntegerType()), ("E", LongType()))))))


def test_duplicate_in_map_value_struct():
    m = MapType(StringType(), S(("v", IntegerType()), ("V", LongType())))
    _dup(S(("top", m)))


def test_duplicate_in_map_key_struct():
    m = MapType(S(("k", IntegerType()), ("K", LongType())), StringType())
    _dup(S(("top", m)))


def test_nested_and_top_level_same_name_not_duplicate():
    """'a' at top level and 'a' inside a struct are distinct columns."""
    _ok(S(("a", IntegerType()), ("s", S(("a", LongType())))))


def test_same_name_in_sibling_structs_not_duplicate():
    _ok(S(("s1", S(("x", IntegerType()))), ("s2", S(("x", LongType())))))


def test_dotted_name_is_not_duplicate_of_nested_path():
    """A flat column literally named 'a.b' (backtick-quoted in SQL) is NOT
    a duplicate of struct a with field b — names compare per level."""
    _ok(S(("a.b", IntegerType()), ("a", S(("b", LongType())))))


def test_dotted_names_duplicate_when_identical():
    _dup(S(("a.b", IntegerType()), ("a.b", LongType())))


# ---------------------------------------------------------------------------
# findColumnPosition / add / drop edges
# ---------------------------------------------------------------------------


def test_find_position_double_nested():
    schema = S(("a", S(("b", S(("c", IntegerType()), ("d", LongType()))))))
    assert su.find_column_position(["a", "b", "d"], schema) == [0, 0, 1]


def test_find_position_array_of_struct():
    schema = S(("arr", ArrayType(S(("x", IntegerType()), ("y", LongType())))))
    pos = su.find_column_position(["arr", "element", "y"], schema)
    assert pos[-1] == 1


def test_find_position_map_sides():
    schema = S(("m", MapType(S(("k", IntegerType())), S(("v", LongType())))))
    assert su.find_column_position(["m", "key", "k"], schema)
    assert su.find_column_position(["m", "value", "v"], schema)


def test_find_position_missing_nested_errors():
    schema = S(("a", S(("b", IntegerType()))))
    with pytest.raises(DeltaAnalysisError):
        su.find_column_position(["a", "zz"], schema)


def test_add_column_preserves_sibling_order():
    schema = S(("a", IntegerType()), ("c", IntegerType()))
    out = su.add_column(schema, StructField("b", LongType()), [1])
    assert [f.name for f in out.fields] == ["a", "b", "c"]


def test_add_then_drop_round_trip_nested():
    schema = S(("s", S(("x", IntegerType()))))
    grown = su.add_column(schema, StructField("y", LongType()), [0, 1])
    names = [f.name for f in grown.fields[0].data_type.fields]
    assert names == ["x", "y"]
    back = su.drop_column_at(grown, [0, 1])[0]
    assert back.to_json() == schema.to_json()


# ---------------------------------------------------------------------------
# mergeSchemas upcast matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("frm,to", [
    (ByteType(), ShortType()),
    (ByteType(), IntegerType()),
    (ShortType(), IntegerType()),
])
def test_merge_upcasts_int_family(frm, to):
    merged = su.merge_schemas(S(("c", frm)), S(("c", to)))
    assert merged.fields[0].data_type == to
    # and the reverse keeps the wider existing type
    merged = su.merge_schemas(S(("c", to)), S(("c", frm)))
    assert merged.fields[0].data_type == to


@pytest.mark.parametrize("frm", [ByteType(), ShortType(), IntegerType()])
def test_merge_to_long_requires_implicit_conversions(frm):
    with pytest.raises(SchemaMismatchError):
        su.merge_schemas(S(("c", frm)), S(("c", LongType())))
    merged = su.merge_schemas(S(("c", frm)), S(("c", LongType())),
                              allow_implicit_conversions=True)
    assert merged.fields[0].data_type == LongType()


def test_merge_null_type_yields_other_side():
    assert su.merge_schemas(
        S(("c", NullType())), S(("c", DoubleType()))
    ).fields[0].data_type == DoubleType()
    assert su.merge_schemas(
        S(("c", DoubleType())), S(("c", NullType()))
    ).fields[0].data_type == DoubleType()


def test_merge_keeps_current_metadata_and_nullability():
    cur = StructType([StructField("c", IntegerType(), False, {"k": "v"})])
    new = StructType([StructField("c", IntegerType(), True, {"other": "x"})])
    merged = su.merge_schemas(cur, new)
    f = merged.fields[0]
    assert f.nullable is False and f.metadata == {"k": "v"}


def test_merge_missing_column_in_data_keeps_schema():
    cur = S(("a", IntegerType()), ("b", LongType()))
    merged = su.merge_schemas(cur, S(("a", IntegerType())))
    assert [f.name for f in merged.fields] == ["a", "b"]


def test_merge_new_columns_append_at_tail_nested():
    cur = S(("s", S(("x", IntegerType()))))
    new = S(("s", S(("x", IntegerType()), ("y", LongType()))),
            ("z", StringType()))
    merged = su.merge_schemas(cur, new)
    assert [f.name for f in merged.fields] == ["s", "z"]
    assert [f.name for f in merged.fields[0].data_type.fields] == ["x", "y"]


def test_merge_case_differs_keeps_current_case():
    merged = su.merge_schemas(S(("Col", IntegerType())),
                              S(("COL", IntegerType())))
    assert merged.fields[0].name == "Col"


def test_merge_incompatible_nested_path_named_in_error():
    cur = S(("s", S(("x", IntegerType()))))
    new = S(("s", S(("x", StringType()))))
    with pytest.raises(SchemaMismatchError, match="[sx]"):
        su.merge_schemas(cur, new)


# ---------------------------------------------------------------------------
# normalize column names (reference: normalize ordering / dots)
# ---------------------------------------------------------------------------


def test_normalize_fixes_case_any_order():
    table = S(("aa", IntegerType()), ("bb", LongType()))
    data = S(("BB", LongType()), ("AA", IntegerType()))
    fixes = dict(su.normalize_column_names(table, data))
    assert fixes == {"BB": "bb", "AA": "aa"}


def test_normalize_handles_dotted_flat_names():
    table = S(("a.b", IntegerType()),)
    data = S(("A.B", IntegerType()),)
    fixes = dict(su.normalize_column_names(table, data))
    assert fixes == {"A.B": "a.b"}


# ---------------------------------------------------------------------------
# read compatibility edges
# ---------------------------------------------------------------------------


def test_read_compat_upcast_not_allowed_for_readers():
    """A reader schema pinned to int cannot read a widened long column."""
    assert not su.is_read_compatible(S(("c", IntegerType())),
                                     S(("c", LongType())))


def test_read_compat_reordered_columns_ok():
    a = S(("x", IntegerType()), ("y", LongType()))
    b = S(("y", LongType()), ("x", IntegerType()))
    assert su.is_read_compatible(a, b)


def test_read_compat_nested_added_nullable_ok():
    a = S(("s", S(("x", IntegerType()))))
    b = S(("s", S(("x", IntegerType()), ("y", LongType()))))
    assert su.is_read_compatible(a, b)
