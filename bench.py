"""Benchmark: snapshot state reconstruction (checkpoint replay) on device.

BASELINE.json config 5: "DeltaLog checkpoint + 10k-version snapshot
stateReconstruction replay". The reference replays the action log as a
50-partition Spark job with per-partition hash maps (`Snapshot.scala:88-111`,
`actions/InMemoryLogReplay.scala:43-65`); here the same reconciliation is one
device sort + segmented reduce. ``vs_baseline`` is the speedup over the
host-side pure-Python replay (the same algorithm the reference's executors
run per partition, minus JVM overheads) on this machine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

import numpy as np


def build_stream(n_versions=10_000, actions_per_commit=20, n_paths=50_000):
    """Synthetic 10k-version log: adds/removes over a bounded path universe."""
    rng = np.random.RandomState(7)
    path_id = rng.randint(0, n_paths, size=n_versions * actions_per_commit).astype(np.int32)
    version = np.repeat(np.arange(n_versions, dtype=np.int64), actions_per_commit)
    pos = np.tile(np.arange(actions_per_commit, dtype=np.int64), n_versions)
    seq = (version << 31) | pos
    is_add = rng.rand(len(path_id)) < 0.85
    size = rng.randint(1, 1 << 24, size=len(path_id)).astype(np.int64)
    del_ts = np.where(is_add, 0, version * 1000).astype(np.int64)
    return path_id, seq, is_add, size, del_ts


def host_replay_ms(path_id, seq, is_add, size):
    """The reference algorithm: sequential hash-map replay (one partition)."""
    t0 = time.perf_counter()
    active = {}
    for i in range(len(path_id)):
        p = path_id[i]
        if is_add[i]:
            active[p] = size[i]
        else:
            active.pop(p, None)
    elapsed = (time.perf_counter() - t0) * 1000
    return elapsed, len(active)


def device_replay_ms(path_id, seq, is_add, size, del_ts):
    import jax

    from delta_tpu.ops import replay_kernel
    from delta_tpu.ops.state_export import ReplayArrays

    arrays = ReplayArrays(
        paths=[],  # dictionary not needed for the kernel
        path_id=path_id,
        seq=seq,
        is_add=is_add,
        size=size,
        deletion_timestamp=del_ts,
    )
    # warm-up: compile
    r = replay_kernel.replay_alive_mask(arrays)
    jax.block_until_ready(r.alive)
    runs = []
    for _ in range(5):
        t0 = time.perf_counter()
        r = replay_kernel.replay_alive_mask(arrays)
        jax.block_until_ready(r.alive)
        runs.append((time.perf_counter() - t0) * 1000)
    return min(runs), int(r.stats.num_files)


def main():
    path_id, seq, is_add, size, del_ts = build_stream()
    host_ms, host_n = host_replay_ms(path_id, seq, is_add, size)
    dev_ms, dev_n = device_replay_ms(path_id, seq, is_add, size, del_ts)
    if host_n != dev_n:
        print(
            f"MISMATCH host={host_n} device={dev_n}", file=sys.stderr
        )
        sys.exit(1)
    print(
        json.dumps(
            {
                "metric": "checkpoint_replay_10k_versions_200k_actions",
                "value": round(dev_ms, 3),
                "unit": "ms",
                "vs_baseline": round(host_ms / dev_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
