"""Error taxonomy, mirroring the reference's user-facing error factory
(``DeltaErrors.scala``) and the public concurrency exception hierarchy
(``io/delta/exceptions/DeltaConcurrentExceptions.scala``, also surfaced to
Python in the reference via ``python/delta/exceptions.py``)."""
from __future__ import annotations

from typing import Iterable, Optional

__all__ = [
    "DeltaError",
    "DeltaAnalysisError",
    "DeltaIllegalArgumentError",
    "DeltaIllegalStateError",
    "DeltaFileNotFoundError",
    "DeltaIOError",
    "DeltaUnsupportedOperationError",
    "DeltaParseError",
    "MetadataChangedException",
    "ProtocolChangedException",
    "ConcurrentWriteException",
    "ConcurrentAppendException",
    "ConcurrentDeleteReadException",
    "ConcurrentDeleteDeleteException",
    "ConcurrentTransactionException",
    "DeltaConcurrentModificationException",
    "InvariantViolationError",
    "SchemaMismatchError",
    "ProtocolError",
    "VersionNotFoundError",
    "TimestampEarlierThanCommitRetentionError",
    "TemporallyUnstableInputError",
]


class DeltaError(Exception):
    """Base for all delta-tpu errors."""


class DeltaAnalysisError(DeltaError):
    pass


class DeltaIllegalArgumentError(DeltaError, ValueError):
    pass


class DeltaIllegalStateError(DeltaError, RuntimeError):
    pass


class DeltaFileNotFoundError(DeltaError, FileNotFoundError):
    pass


class DeltaIOError(DeltaError, IOError):
    pass


class DeltaUnsupportedOperationError(DeltaError, NotImplementedError):
    pass


class InvariantViolationError(DeltaError):
    """Row-level constraint / NOT NULL violation
    (``schema/InvariantViolationException.scala``)."""


class DeltaParseError(DeltaAnalysisError):
    """SQL statement failed to tokenize or parse (≈ Spark ParseException)."""


class SchemaMismatchError(DeltaAnalysisError):
    """Write schema incompatible with table schema
    (``DeltaErrors.failedToMergeFields`` etc.)."""


class ProtocolError(DeltaError):
    """Table requires a newer reader/writer than this client
    (``DeltaErrors.InvalidProtocolVersionException``)."""


class VersionNotFoundError(DeltaAnalysisError):
    def __init__(self, user_version: int, earliest: int, latest: int):
        super().__init__(
            f"Cannot time travel Delta table to version {user_version}. "
            f"Available versions: [{earliest}, {latest}]."
        )
        self.user_version = user_version
        self.earliest = earliest
        self.latest = latest


class TimestampEarlierThanCommitRetentionError(DeltaAnalysisError):
    pass


class TemporallyUnstableInputError(DeltaAnalysisError):
    """Requested timestamp is after the latest commit timestamp."""

    def __init__(self, user_ts, commit_ts, latest_version: int):
        super().__init__(
            f"The provided timestamp ({user_ts}) is after the latest version "
            f"available to this table ({commit_ts}, version {latest_version})."
        )
        self.commit_ts = commit_ts
        self.latest_version = latest_version


# ---------------------------------------------------------------------------
# Concurrency exceptions (conflict-checker verdicts) — names match
# io/delta/exceptions/DeltaConcurrentExceptions.scala so users can map 1:1.
# ---------------------------------------------------------------------------

class DeltaConcurrentModificationException(DeltaError):
    """Base of the OCC conflict hierarchy."""

    def __init__(self, message: str, conflicting_commit: Optional[dict] = None):
        super().__init__(message)
        self.conflicting_commit = conflicting_commit


class ConcurrentWriteException(DeltaConcurrentModificationException):
    """A concurrent transaction wrote new data the current transaction read
    (or the commit file appeared non-atomically)."""


class MetadataChangedException(DeltaConcurrentModificationException):
    """The table metadata changed since the transaction's snapshot."""


class ProtocolChangedException(DeltaConcurrentModificationException):
    """The protocol version changed since the transaction's snapshot."""


class ConcurrentAppendException(DeltaConcurrentModificationException):
    """Files were added by a concurrent commit in a region this txn read."""


class ConcurrentDeleteReadException(DeltaConcurrentModificationException):
    """A concurrent commit deleted a file this transaction read."""


class ConcurrentDeleteDeleteException(DeltaConcurrentModificationException):
    """A concurrent commit deleted a file this transaction also deletes."""


class ConcurrentTransactionException(DeltaConcurrentModificationException):
    """Overlapping SetTransaction appId with a concurrent commit."""


def versions_not_contiguous(versions: Iterable[int]) -> DeltaIllegalStateError:
    return DeltaIllegalStateError(
        f"Versions ({list(versions)}) are not contiguous. This can happen when "
        "files have been manually deleted from the transaction log."
    )


# ---------------------------------------------------------------------------
# Error factories — the user-facing message contract, mirroring the relevant
# subset of ``DeltaErrors.scala`` (message text and remediation advice kept
# 1:1 where the situation exists in this engine).
# ---------------------------------------------------------------------------

_CONCURRENCY_DOC = "https://docs.delta.io/latest/concurrency-control.html"


def _concurrent_msg(base: str, commit: Optional[dict]) -> str:
    """``DeltaErrors.concurrentModificationExceptionMsg`` composition: base
    message + conflicting-commit provenance + doc pointer."""
    import json

    msg = base
    if commit:
        msg += f"\nConflicting commit: {json.dumps(commit, default=str)}"
    return msg + f"\nRefer to {_CONCURRENCY_DOC} for more details."


def concurrent_write_exception(commit: Optional[dict] = None) -> ConcurrentWriteException:
    return ConcurrentWriteException(_concurrent_msg(
        "A concurrent transaction has written new data since the current "
        "transaction read the table. Please try the operation again.",
        commit), commit)


def metadata_changed_exception(commit: Optional[dict] = None) -> MetadataChangedException:
    return MetadataChangedException(_concurrent_msg(
        "The metadata of the Delta table has been changed by a concurrent "
        "update. Please try the operation again.", commit), commit)


def protocol_changed_exception(commit: Optional[dict] = None) -> ProtocolChangedException:
    additional = ""
    if commit and commit.get("version") == 0:
        # DeltaErrors.scala:1164-1171 — empty-directory race hint
        additional = (
            "This happens when multiple writers are writing to an empty "
            "directory. Creating the table ahead of time will avoid this "
            "conflict. "
        )
    return ProtocolChangedException(_concurrent_msg(
        "The protocol version of the Delta table has been changed by a "
        f"concurrent update. {additional}Please try the operation again.",
        commit), commit)


def concurrent_append_exception(
    partition: str, commit: Optional[dict] = None,
    custom_retry: Optional[str] = None,
) -> ConcurrentAppendException:
    return ConcurrentAppendException(_concurrent_msg(
        f"Files were added to {partition} by a concurrent update. "
        + (custom_retry or "Please try the operation again."), commit), commit)


def concurrent_delete_read_exception(
    file: str, commit: Optional[dict] = None
) -> ConcurrentDeleteReadException:
    return ConcurrentDeleteReadException(_concurrent_msg(
        "This transaction attempted to read one or more files that were "
        f"deleted (for example {file}) by a concurrent update. "
        "Please try the operation again.", commit), commit)


def concurrent_delete_delete_exception(
    file: str, commit: Optional[dict] = None
) -> ConcurrentDeleteDeleteException:
    return ConcurrentDeleteDeleteException(_concurrent_msg(
        "This transaction attempted to delete one or more files that were "
        f"deleted (for example {file}) by a concurrent update. "
        "Please try the operation again.", commit), commit)


def concurrent_transaction_exception(
    commit: Optional[dict] = None, app_id: Optional[str] = None,
) -> ConcurrentTransactionException:
    detail = f" (conflicting appId={app_id})" if app_id else ""
    return ConcurrentTransactionException(_concurrent_msg(
        "This error occurs when multiple streaming queries are using the "
        f"same checkpoint to write into this table{detail}. Did you run "
        "multiple instances of the same streaming query at the same time?",
        commit), commit)


def not_a_delta_table(identifier: str, operation: Optional[str] = None) -> DeltaAnalysisError:
    if operation:
        return DeltaAnalysisError(
            f"{identifier} is not a Delta table. {operation} is only "
            "supported for Delta tables."
        )
    return DeltaAnalysisError(f"{identifier} is not a Delta table.")


def modify_append_only_table() -> DeltaUnsupportedOperationError:
    return DeltaUnsupportedOperationError(
        "This table is configured to only allow appends. If you would like "
        "to permit updates or deletes, use 'ALTER TABLE <table_name> SET "
        "TBLPROPERTIES (delta.appendOnly=false)'."
    )


def invalid_protocol_version(
    client_reader: int, client_writer: int, table_reader: int, table_writer: int
) -> ProtocolError:
    return ProtocolError(
        "Delta protocol version "
        f"(reader={table_reader}, writer={table_writer}) is too new for this "
        f"client (supports reader={client_reader}, writer={client_writer}). "
        "Please upgrade to a newer release."
    )


def not_null_invariant_violated(
    column: str, null_rows: Optional[int] = None
) -> InvariantViolationError:
    detail = f" ({null_rows} null rows)" if null_rows else ""
    return InvariantViolationError(
        f"NOT NULL constraint violated for column: {column}{detail}."
    )


def check_constraint_violated(
    name: str, expr_sql: str, values: Optional[dict] = None
) -> InvariantViolationError:
    lines = "".join(f"\n - {c} : {v}" for c, v in (values or {}).items())
    return InvariantViolationError(
        f"CHECK constraint {name} ({expr_sql}) violated by row with values:"
        f"{lines}"
    )


def new_check_constraint_violated(num: int, table: str, expr: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"{num} rows in {table} violate the new CHECK constraint ({expr})"
    )


def replace_where_mismatch(replace_where: str, detail: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Data written out does not match replaceWhere '{replace_where}'.\n"
        f"Invalid data would be written to {detail}."
    )


def unset_nonexistent_property(key: str, table: str) -> DeltaAnalysisError:
    return DeltaAnalysisError(
        f"Attempted to unset non-existent property '{key}' in table {table}"
    )


def retention_period_too_short(retention_hours: float, configured_hours: float):
    return DeltaIllegalArgumentError(
        "Are you sure you would like to vacuum files with such a low "
        f"retention period ({retention_hours} hours)? If you have writers "
        "that are currently writing to this table, there is a risk that you "
        "may corrupt the state of your Delta table.\nIf you are certain "
        "there are no operations being performed on this table, such as "
        "insert/upsert/delete/optimize, then you may turn off this check by "
        "setting delta.tpu.retentionDurationCheck.enabled = false\nIf you "
        "are not sure, please use a value not less than "
        f"{configured_hours} hours."
    )


def missing_part_files(version: int, cause: Exception) -> DeltaIllegalStateError:
    return DeltaIllegalStateError(
        f"Couldn't find all part files of the checkpoint version: {version} "
        f"({cause})"
    )
