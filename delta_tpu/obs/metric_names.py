"""Single catalog of every observability metric name and public entry point.

The AST lint in ``tests/test_telemetry.py`` enforces that (a) every string
constant passed to ``set_gauge`` anywhere in ``delta_tpu/`` appears in
:data:`GAUGES`, (b) every counter bumped from ``delta_tpu/obs/`` (and the
maintenance/conflict counters wired for the doctor) appears in
:data:`COUNTERS`, (c) the INVERSE pass — every constant-string
``bump_counter`` / ``observe`` call site engine-wide resolves to
:data:`COUNTERS` ∪ :data:`ENGINE_COUNTERS` / :data:`HISTOGRAMS` — so no
metric can ship un-cataloged, and (d) each ``obs/`` module's ``__all__``
matches :data:`PUBLIC_API` — so dashboards and the doctor never chase
stringly-typed drift: a renamed gauge fails the suite, not a Grafana panel.

``table.health.*`` gauges are emitted by :func:`delta_tpu.obs.doctor.doctor`
(labeled by table path) and validated against this catalog at publish time.
"""
from __future__ import annotations

__all__ = ["GAUGES", "COUNTERS", "ENGINE_COUNTERS", "HISTOGRAMS",
           "PUBLIC_API", "health_gauge"]

#: Every labeled gauge the engine publishes.
GAUGES = frozenset({
    # -- doctor: table-health gauges (obs/doctor.py, label: path) --------
    "table.health.severity",
    "table.health.files.count",
    "table.health.files.bytes",
    "table.health.checkpoint.commitsSince",
    "table.health.checkpoint.tailBytes",
    "table.health.checkpoint.tailFiles",
    "table.health.smallFiles.count",
    "table.health.smallFiles.bytes",
    "table.health.smallFiles.estReduction",
    "table.health.dv.files",
    "table.health.dv.deletedRows",
    "table.health.dv.deletedPct",
    "table.health.dv.filesPastPurge",
    "table.health.stats.coveragePct",
    "table.health.stats.parsedPct",
    "table.health.partition.count",
    "table.health.partition.gini",
    "table.health.tombstones.count",
    "table.health.tombstones.bytes",
    "table.health.protocol.minReader",
    "table.health.protocol.minWriter",
    # -- doctor: device residency pressure (obs/doctor._dim_device) ------
    "table.health.device.hbmBytes",
    "table.health.device.keyCacheBytes",
    "table.health.device.stateCacheBytes",
    "table.health.device.scratchBytes",
    "table.health.device.budgetBytes",
    "table.health.device.pressure",
    # -- device-memory ledger (obs/hbm_ledger, process-wide) -------------
    "device.hbm.keyCacheBytes",
    "device.hbm.stateCacheBytes",
    "device.hbm.scratchBytes",
    # -- router audit + calibration (obs/router_audit, obs/calibration) --
    "router.missRate",
    "router.calibration",        # label: constant
    # -- streaming consumer lag (streaming/source.py, label: path) -------
    "streaming.source.backlogFiles",
    "streaming.source.backlogBytes",
    "streaming.source.lastBatchVersionLag",
    # -- maintenance recency (commands/optimize.py, vacuum.py) -----------
    "table.maintenance.lastOptimizeVersion",
    "table.maintenance.lastVacuumTimestamp",
})

#: Counters introduced by the obs layer and its doctor feeds.
COUNTERS = frozenset({
    "obs.incidents.written",
    "obs.server.requests",
    "commit.conflicts",
    "maintenance.optimize.filesCompacted",
    "maintenance.optimize.filesWritten",
    "maintenance.vacuum.filesDeleted",
    "maintenance.vacuum.bytesReclaimed",
    # -- robustness layer (utils/retries, storage/faults, txn) -----------
    "storage.retry.attempts",     # one per backoff sleep, any store
    "storage.retry.exhausted",    # gave up: surfaced to the caller
    "faults.injected",            # deterministic fault injector fired
    "commit.reconciled",          # ambiguous commit resolved via txnId
    # -- device MERGE router + resident key cache (commands/merge.py,
    #    ops/key_cache.py) — `auto_used_device` made observable on
    #    production tables via /metrics and flight-recorder incidents
    "merge.device.engaged",       # a device join produced this merge's pairs
    "merge.device.declined",      # link cost model chose the host
    "merge.device.cacheHit",      # engaged from an HBM-resident key lane
    "merge.keyCache.builds",      # cold key-lane builds (inline or bg)
    "merge.keyCache.advances",    # incremental log-tail applications
    "merge.keyCache.invalidations",  # entries dropped by a rewrite epoch bump
    # -- router audit ledger + calibrator (obs/router_audit, obs/calibration)
    "router.audits",              # one per routed decision recorded
    "router.misses",              # hindsight: rejected route predicted faster
    "router.calibration.updates",  # EWMA samples folded into the state
})

#: Every OTHER counter the engine bumps by constant name — the inverse lint
#: (tests/test_telemetry.py) fails on any ``bump_counter`` call site whose
#: name is in neither this set nor :data:`COUNTERS`. Dynamic families
#: (``logstore.{op}.calls``/``.bytes``) are f-strings and out of lint scope.
ENGINE_COUNTERS = frozenset({
    "checkpoint.parts",
    "checkpoint.actions",
    "checkpoint.written",
    "commit.total",
    "commit.retries",
    "convert.stats.fromFooter",
    "convert.stats.fromDecode",
    "footerCache.hits",
    "footerCache.misses",
    "footerCache.evictions",
    "log.update.installed",
    "log.update.unchanged",
    "parquet.files.written",
    "parquet.bytes.written",
    "parquet.rows.written",
    "scan.files.read",
    "scan.bytes.read",
    "scan.bytes.skipped",
    "scan.rowgroups.total",
    "scan.rowgroups.pruned",
    "scan.rowgroups.lateSkipped",
    "stateCache.builds",
    "stateCache.plan.resident",
    "stateCache.plan.fallback.lowering",
    "stateCache.plan.fallback.noentry",
    "stateCache.plan.fallback.version",
    "stateCache.scan.resident",
    "stateCache.scan.fallback.lowering",
    "stateCache.scan.fallback.noentry",
    "stateCache.scan.fallback.version",
    "stateExport.statsLanes.struct",
    "stateExport.statsLanes.json",
    "stateExport.statsLanes.mixed",
    "stateExport.statsLanes.us",
    "streaming.sink.batches",
})

#: Every histogram observed by constant name (``telemetry.observe``).
HISTOGRAMS = frozenset({
    "delta.checkpoint.duration_ms",
    "delta.commit.duration_ms",
    "delta.streaming.sink.batch_ms",
    "delta.streaming.source.batch_ms",
    "router.predicted_ms",
    "router.actual_ms",
})

#: Public surface of each obs module, lint-matched against its ``__all__``.
PUBLIC_API = {
    "doctor": ("HealthDimension", "TableHealthReport", "doctor",
               "SEVERITY_RANK"),
    "scan_report": ("ScanReport", "last_scan_report", "clear_last_report",
                    "start_report", "current_report", "contribute",
                    "finish_report"),
    "server": ("ObsServer", "start_server", "stop_server"),
    "flight_recorder": ("install", "uninstall", "record_incident",
                        "incident_files"),
    "metric_names": ("GAUGES", "COUNTERS", "ENGINE_COUNTERS", "HISTOGRAMS",
                     "PUBLIC_API", "health_gauge"),
    "router_audit": ("RouterAudit", "record_audit", "recent_audits",
                     "clear_audits", "audit_stats"),
    "calibration": ("enabled", "ingest", "state_path", "load_state",
                    "save_state", "apply_state", "current_state", "reset"),
    "hbm_ledger": ("Account", "adjust", "totals", "budget_bytes",
                   "key_cache_allowance", "over_budget", "maybe_relieve",
                   "reset"),
}


def health_gauge(dimension: str, metric: str) -> str:
    """The catalog-checked gauge name for a doctor metric — raises on a name
    that is not registered, so a new metric cannot ship un-cataloged."""
    name = f"table.health.{dimension}.{metric}"
    if name not in GAUGES:
        raise ValueError(f"gauge {name!r} is not registered in "
                         "delta_tpu/obs/metric_names.py")
    return name
