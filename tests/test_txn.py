"""OCC transaction tests — the conflict matrix.

Port of the *semantics* of ``OptimisticTransactionSuite.scala:36-516``
("block/allow concurrent X vs Y") plus commit-pipeline behaviors
(first-commit injection, retry, append-only, blind-append detection).
"""
import threading

import pytest

from tests.conftest import init_metadata

from delta_tpu.commands import operations as ops
from delta_tpu.log.deltalog import DeltaLog
from delta_tpu.protocol.actions import AddFile, Metadata, Protocol, RemoveFile, SetTransaction
from delta_tpu.schema.types import IntegerType, StringType, StructType
from delta_tpu.utils import errors


PART_SCHEMA = StructType().add("id", IntegerType()).add("part", StringType())


def add(path, part=None, data_change=True):
    pv = {} if part is None else {"part": part}
    return AddFile(path, pv, 1, 1, data_change)


def create_table(tmp_table, partitioned=False, configuration=None):
    log = DeltaLog.for_table(tmp_table)
    txn = log.start_transaction()
    if partitioned:
        md = Metadata(schema_string=PART_SCHEMA.to_json(), partition_columns=["part"],
                      configuration=dict(configuration or {}))
    else:
        md = init_metadata(configuration=configuration)
    txn.update_metadata(md)
    txn.commit([], ops.ManualUpdate())
    return log


class TestCommitPipeline:
    def test_first_commit_injects_protocol(self, tmp_table):
        log = create_table(tmp_table)
        snap = log.update()
        assert snap.version == 0
        assert snap.protocol.min_writer_version >= 2
        assert snap.metadata.schema.field_names == ["id", "value"]

    def test_versions_increment(self, tmp_table):
        log = create_table(tmp_table)
        for i in range(3):
            txn = log.start_transaction()
            v = txn.commit([add(f"f{i}")], ops.Write("Append"))
            assert v == i + 1
        assert len(log.update().all_files) == 3

    def test_commit_info_written(self, tmp_table):
        log = create_table(tmp_table)
        txn = log.start_transaction()
        txn.commit([add("f0")], ops.Write("Append"))
        history = log.history.get_history()
        assert history[0].operation == "WRITE"
        assert history[0].is_blind_append is True
        assert history[0].version == 1
        assert history[1].operation == "Manual Update"

    def test_cannot_commit_twice(self, tmp_table):
        log = create_table(tmp_table)
        txn = log.start_transaction()
        txn.commit([add("f0")], ops.Write("Append"))
        with pytest.raises(errors.DeltaIllegalStateError):
            txn.commit([add("f1")], ops.Write("Append"))

    def test_metadata_change_only_once(self, tmp_table):
        log = create_table(tmp_table)
        txn = log.start_transaction()
        txn.update_metadata(init_metadata())
        with pytest.raises(errors.DeltaIllegalStateError):
            txn.update_metadata(init_metadata())

    def test_first_commit_requires_metadata(self, tmp_table):
        log = DeltaLog.for_table(tmp_table)
        txn = log.start_transaction()
        with pytest.raises(errors.DeltaIllegalStateError):
            txn.commit([add("f0")], ops.Write("Append"))

    def test_add_partition_values_must_match_schema(self, tmp_table):
        log = create_table(tmp_table, partitioned=True)
        txn = log.start_transaction()
        with pytest.raises(errors.DeltaIllegalStateError):
            txn.commit([add("f0")], ops.Write("Append"))  # missing part value
        txn2 = log.start_transaction()
        txn2.commit([add("f0", part="a")], ops.Write("Append"))

    def test_append_only_table_blocks_deletes(self, tmp_table):
        log = create_table(tmp_table, configuration={"delta.appendOnly": "true"})
        txn = log.start_transaction()
        txn.commit([add("f0")], ops.Write("Append"))
        txn2 = log.start_transaction()
        with pytest.raises(errors.DeltaUnsupportedOperationError):
            txn2.commit([RemoveFile("f0", deletion_timestamp=1)], ops.Delete())

    def test_checkpoint_written_at_interval(self, tmp_table):
        log = create_table(tmp_table, configuration={"delta.checkpointInterval": "4"})
        for i in range(5):
            log.start_transaction().commit([add(f"f{i}")], ops.Write("Append"))
        from delta_tpu.protocol import filenames

        assert log.store.exists(f"{log.log_path}/{filenames.checkpoint_file_single(4)}")

    def test_txn_version_roundtrip(self, tmp_table):
        log = create_table(tmp_table)
        txn = log.start_transaction()
        assert txn.txn_version("stream-1") == -1
        txn.commit([SetTransaction("stream-1", 7, None), add("f0")], ops.StreamingUpdate("Append", "stream-1", 7))
        txn2 = log.start_transaction()
        assert txn2.txn_version("stream-1") == 7


class TestConflictMatrix:
    """Each test: txn A starts & reads; txn B commits concurrently; A commits."""

    def _two_txns(self, log):
        a = log.start_transaction()
        return a

    def test_allow_disjoint_blind_appends(self, tmp_table):
        log = create_table(tmp_table)
        a = log.start_transaction()
        log.start_transaction().commit([add("b1")], ops.Write("Append"))
        v = a.commit([add("a1")], ops.Write("Append"))
        assert v == 2
        assert len(log.update().all_files) == 2

    def test_read_whole_table_vs_nonblind_append_blocks(self, tmp_table):
        log = create_table(tmp_table)
        log.start_transaction().commit([add("f0")], ops.Write("Append"))
        a = log.start_transaction()
        a.filter_files()  # read (taints whole table via TRUE predicate)
        # B reads too (non-blind) then appends
        b = log.start_transaction()
        b.filter_files()
        b.commit([add("b1")], ops.Write("Append"))
        with pytest.raises(errors.ConcurrentAppendException):
            a.commit([add("a1")], ops.Write("Append"))

    def test_read_whole_table_vs_blind_append_allowed_write_serializable(self, tmp_table):
        # WriteSerializable (default): blind appends never conflict with reads
        log = create_table(tmp_table)
        a = log.start_transaction()
        a.filter_files()
        log.start_transaction().commit([add("b1")], ops.Write("Append"))  # blind
        v = a.commit([add("a1")], ops.Write("Append"))
        assert v == 2

    def test_disjoint_partitions_do_not_conflict(self, tmp_table):
        log = create_table(tmp_table, partitioned=True)
        log.start_transaction().commit([add("f0", part="x")], ops.Write("Append"))
        a = log.start_transaction()
        a.filter_files(["part = 'x'"])
        b = log.start_transaction()
        b.filter_files(["part = 'y'"])
        b.commit([add("b1", part="y")], ops.Write("Append"))
        v = a.commit([add("a1", part="x")], ops.Write("Append"))
        assert v == 3

    def test_same_partition_conflicts(self, tmp_table):
        log = create_table(tmp_table, partitioned=True)
        log.start_transaction().commit([add("f0", part="x")], ops.Write("Append"))
        a = log.start_transaction()
        a.filter_files(["part = 'x'"])
        b = log.start_transaction()
        b.filter_files(["part = 'x'"])
        b.commit([add("b1", part="x")], ops.Write("Append"))
        with pytest.raises(errors.ConcurrentAppendException):
            a.commit([add("a1", part="x")], ops.Write("Append"))

    def test_concurrent_delete_of_read_file(self, tmp_table):
        log = create_table(tmp_table)
        log.start_transaction().commit([add("f0")], ops.Write("Append"))
        a = log.start_transaction()
        a.filter_files()
        assert set(a.read_files) == {"f0"}
        b = log.start_transaction()
        b.filter_files()
        b.commit([RemoveFile("f0", deletion_timestamp=1)], ops.Delete())
        with pytest.raises(errors.ConcurrentDeleteReadException):
            a.commit([add("a1")], ops.Write("Append"))

    def test_concurrent_delete_delete(self, tmp_table):
        log = create_table(tmp_table)
        log.start_transaction().commit([add("f0")], ops.Write("Append"))
        a = log.start_transaction()
        b = log.start_transaction()
        b.commit([RemoveFile("f0", deletion_timestamp=1)], ops.Delete())
        with pytest.raises(errors.ConcurrentDeleteDeleteException):
            a.commit([RemoveFile("f0", deletion_timestamp=2)], ops.Delete())

    def test_metadata_change_conflicts(self, tmp_table):
        log = create_table(tmp_table)
        a = log.start_transaction()
        b = log.start_transaction()
        b.update_metadata(init_metadata(configuration={"delta.checkpointInterval": "20"}))
        b.commit([], ops.SetTableProperties({"delta.checkpointInterval": "20"}))
        with pytest.raises(errors.MetadataChangedException):
            a.commit([add("a1")], ops.Write("Append"))

    def test_protocol_change_conflicts(self, tmp_table):
        log = create_table(tmp_table)
        a = log.start_transaction()
        b = log.start_transaction()
        b.new_protocol = Protocol(1, 3)
        b.commit([], ops.UpgradeProtocol(Protocol(1, 3)))
        with pytest.raises(errors.ProtocolChangedException):
            a.commit([add("a1")], ops.Write("Append"))

    def test_concurrent_set_transaction_conflicts(self, tmp_table):
        log = create_table(tmp_table)
        a = log.start_transaction()
        a.txn_version("app-1")
        b = log.start_transaction()
        b.commit([SetTransaction("app-1", 1, None)], ops.StreamingUpdate("Append", "app-1", 1))
        with pytest.raises(errors.ConcurrentTransactionException):
            a.commit([SetTransaction("app-1", 2, None), add("a1")],
                     ops.StreamingUpdate("Append", "app-1", 2))

    def test_snapshot_isolation_rearrange_only_vs_append(self, tmp_table):
        # dataChange=False commit (OPTIMIZE-style) must not conflict with appends
        log = create_table(tmp_table)
        log.start_transaction().commit([add("f0")], ops.Write("Append"))
        a = log.start_transaction()
        a.filter_files()
        b = log.start_transaction()
        b.filter_files()
        b.commit([add("b1")], ops.Write("Append"))
        v = a.commit(
            [RemoveFile("f0", deletion_timestamp=1, data_change=False),
             add("f0-compacted", data_change=False)],
            ops.Optimize(),
        )
        assert v == 3

    def test_delete_vs_rearrange_of_same_file_conflicts(self, tmp_table):
        log = create_table(tmp_table)
        log.start_transaction().commit([add("f0")], ops.Write("Append"))
        a = log.start_transaction()
        a.filter_files()
        b = log.start_transaction()
        b.filter_files()
        b.commit([RemoveFile("f0", deletion_timestamp=1)], ops.Delete())
        with pytest.raises((errors.ConcurrentDeleteReadException, errors.ConcurrentDeleteDeleteException)):
            a.commit(
                [RemoveFile("f0", deletion_timestamp=2, data_change=False),
                 add("f0-compacted", data_change=False)],
                ops.Optimize(),
            )

    def test_multiple_winning_commits_replayed(self, tmp_table):
        log = create_table(tmp_table)
        a = log.start_transaction()
        for i in range(3):
            log.start_transaction().commit([add(f"b{i}")], ops.Write("Append"))
        v = a.commit([add("a1")], ops.Write("Append"))
        assert v == 4
        assert a.stats.attempts >= 2


class TestConcurrentThreads:
    def test_many_threads_all_commit(self, tmp_table):
        """8 threads × blind appends: all must land, versions unique."""
        log = create_table(tmp_table)
        results = []
        lock = threading.Lock()

        def worker(i):
            txn = log.start_transaction()
            v = txn.commit([add(f"t{i}")], ops.Write("Append"))
            with lock:
                results.append(v)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == list(range(1, 9))
        assert len(log.update().all_files) == 8


class TestConflictMatrixDepth:
    """Further block/allow cases toward OptimisticTransactionSuite's ~25."""

    def test_serializable_table_blocks_even_blind_append_vs_read(self, tmp_table):
        """delta.isolationLevel=Serializable: blind appends DO conflict with
        reads (vs WriteSerializable's exemption, isolationLevels.scala)."""
        log = create_table(
            tmp_table, configuration={"delta.isolationLevel": "Serializable"}
        )
        a = log.start_transaction()
        a.filter_files()
        log.start_transaction().commit([add("b1")], ops.Write("Append"))  # blind
        with pytest.raises(errors.ConcurrentAppendException):
            a.commit([add("a1")], ops.Write("Append"))

    def test_invalid_isolation_level_property_rejected(self, tmp_table):
        log = create_table(tmp_table)
        a = log.start_transaction()
        with pytest.raises(errors.DeltaIllegalArgumentError):
            a.update_metadata(init_metadata(
                configuration={"delta.isolationLevel": "ReadCommitted"}
            ))

    def test_unread_set_transaction_no_conflict(self, tmp_table):
        from delta_tpu.protocol.actions import SetTransaction

        log = create_table(tmp_table)
        a = log.start_transaction()
        a.txn_version("app-A")  # reads only app-A
        log.start_transaction().commit(
            [SetTransaction("app-B", 7)], ops.StreamingUpdate("Append", "app-B", 7)
        )
        v = a.commit([add("a1")], ops.Write("Append"))
        assert v == 2

    def test_winner_removes_unread_file_no_conflict(self, tmp_table):
        log = create_table(tmp_table, partitioned=True)
        log.start_transaction().commit([add("fx", part="x")], ops.Write("Append"))
        log.start_transaction().commit([add("fy", part="y")], ops.Write("Append"))
        a = log.start_transaction()
        a.filter_files(["part = 'y'"])  # reads only partition y
        # winner deletes the x file A never read
        b = log.start_transaction()
        b.commit([AddFile("fx", {"part": "x"}, 1, 1, True).remove()],
                 ops.Delete(["part = 'x'"]))
        v = a.commit([add("a1", part="y")], ops.Write("Append"))
        assert v == 4

    def test_commit_info_only_winner_no_conflict(self, tmp_table):
        log = create_table(tmp_table)
        a = log.start_transaction()
        a.filter_files()
        log.start_transaction().commit([], ops.ManualUpdate())  # empty commit
        v = a.commit([add("a1")], ops.Write("Append"))
        assert v == 2

    def test_dv_readds_of_same_file_conflict(self, tmp_table):
        """Two transactions DV-marking the same file: both stage remove+
        re-add of one path — delete/delete conflict, never a lost update."""
        log = create_table(tmp_table)
        f = add("shared")
        log.start_transaction().commit([f], ops.Write("Append"))
        dv1 = {"storageType": "i", "pathOrInlineDv": "p1", "sizeInBytes": 1,
               "cardinality": 1}
        dv2 = {"storageType": "i", "pathOrInlineDv": "p2", "sizeInBytes": 1,
               "cardinality": 2}
        from dataclasses import replace as _replace

        a = log.start_transaction()
        a.filter_files()
        b = log.start_transaction()
        b.filter_files()
        b.commit([f.remove(), _replace(f, deletion_vector=dv2)],
                 ops.Delete([]))
        with pytest.raises(errors.DeltaConcurrentModificationException):
            a.commit([f.remove(), _replace(f, deletion_vector=dv1)],
                     ops.Delete([]))

    def test_losing_txn_retries_past_multiple_winners(self, tmp_table):
        log = create_table(tmp_table)
        a = log.start_transaction()
        for i in range(3):
            log.start_transaction().commit([add(f"w{i}")], ops.Write("Append"))
        v = a.commit([add("a1")], ops.Write("Append"))
        assert v == 4
        assert len(log.update().all_files) == 4

    def test_protocol_upgrade_winner_blocks_everyone(self, tmp_table):
        from delta_tpu.protocol.actions import Protocol

        log = create_table(tmp_table)
        a = log.start_transaction()
        log.start_transaction().commit(
            [Protocol(1, 3)], ops.UpgradeProtocol(Protocol(1, 3))
        )
        with pytest.raises(errors.ProtocolChangedException):
            a.commit([add("a1")], ops.Write("Append"))

    def test_append_only_table_rejects_dv_readd_as_delete(self, tmp_table):
        """A DV re-add logically deletes rows — appendOnly must refuse it
        even WITHOUT a staged remove (the remove-based check alone would
        miss a bare add-with-DV)."""
        log = create_table(
            tmp_table, configuration={"delta.appendOnly": "true"}
        )
        f = add("f1")
        log.start_transaction().commit([f], ops.Write("Append"))
        from dataclasses import replace as _replace

        dv = {"storageType": "i", "pathOrInlineDv": "p", "sizeInBytes": 1,
              "cardinality": 1}
        a = log.start_transaction()
        with pytest.raises(errors.DeltaUnsupportedOperationError):
            a.commit([_replace(f, deletion_vector=dv)], ops.Delete([]))
