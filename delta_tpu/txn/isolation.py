"""Isolation levels (reference: ``isolationLevels.scala:27-91``)."""
from __future__ import annotations

__all__ = ["Serializable", "WriteSerializable", "SnapshotIsolation", "ALL_LEVELS"]


class IsolationLevel:
    name = ""

    def __repr__(self):
        return self.name


class _Serializable(IsolationLevel):
    """All reads + writes totally ordered with other txns."""

    name = "Serializable"


class _WriteSerializable(IsolationLevel):
    """Default (isolationLevels.scala:75): writes are serializable, but a
    blind append by another txn is allowed to commit concurrently even if we
    would have read it — weaker for reads, stronger availability."""

    name = "WriteSerializable"


class _SnapshotIsolation(IsolationLevel):
    """Used for commits that don't change data (dataChange=False only):
    never conflicts on file contents."""

    name = "SnapshotIsolation"


Serializable = _Serializable()
WriteSerializable = _WriteSerializable()
SnapshotIsolation = _SnapshotIsolation()
ALL_LEVELS = {l.name: l for l in (Serializable, WriteSerializable, SnapshotIsolation)}

