"""Self-calibrating cost model — EWMA re-fit of the link constants.

The router constants in `parallel/link.py` (host join/decode per-row rates,
resident-probe and prune cell rates) were measured on one bench machine; on
different hardware the router silently picks the wrong side and nothing
corrects it. This module closes the loop: the router audit ledger
(`obs/router_audit`) hands each routed decision's attributable samples —
``(constant_name, units_of_work, measured_seconds)`` — to
:func:`ingest`, which EWMA-blends the implied per-unit rate into a running
estimate and, once a constant has ``delta.tpu.router.calibration.minSamples``
observations, installs it as a live override via ``link.set_calibrated`` —
so routing self-corrects on new hardware without a code change.

Strictly opt-in (``delta.tpu.router.calibration.enabled``, default off) and
blackout-gated: with telemetry disabled nothing is fitted or written.

State persists to a small JSON file so calibration survives the process:
``delta.tpu.router.calibration.statePath`` when set, else
``<table log dir>/.router_calibration.json`` next to the log that produced
the samples (local paths only — object-store tables need the conf'd path).
Each ingest seeds constants this process hasn't sampled from the file (the
read is skipped while its mtime is unchanged since our last load/save),
folds the new samples in, re-applies the overrides, and writes it back —
a fresh DeltaLog on the same table resumes exactly where the last process
left off. Delete the file (or flip the conf off and call :func:`reset`) to
return to the shipped defaults.

Hot-path callers (the scan planner audits once per planned query) pass
``flush=False``: the write is then throttled to at most one per
``delta.tpu.router.calibration.flushIntervalMs`` (default 2000), with
deferred state flushed by the next qualifying ingest or :func:`apply_state`
— so calibration never puts a per-query file write on the planning path it
is calibrating.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

from delta_tpu.parallel import link
from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["enabled", "ingest", "state_path", "load_state", "save_state",
           "apply_state", "current_state", "reset"]

STATE_FILE = ".router_calibration.json"
_STATE_VERSION = 1

_LOCK = threading.Lock()
# constant name -> {"value": s_per_unit, "samples": int}
_STATE: Dict[str, Dict[str, float]] = {}
# per-path disk sync bookkeeping (all under _LOCK):
_SYNC_MTIME: Dict[str, int] = {}    # mtime_ns at our last load/save
_LAST_SAVE: Dict[str, float] = {}   # time.monotonic() of our last save
_DIRTY: set = set()                 # paths with unflushed in-memory state


def enabled() -> bool:
    return (conf.get_bool("delta.tpu.router.calibration.enabled", False)
            and conf.get_bool("delta.tpu.telemetry.enabled", True))


def _alpha() -> float:
    try:
        a = float(conf.get("delta.tpu.router.calibration.alpha", 0.2))
    except (TypeError, ValueError):
        a = 0.2
    return min(max(a, 0.01), 1.0)


def _min_samples() -> int:
    try:
        return max(int(conf.get("delta.tpu.router.calibration.minSamples", 3)), 1)
    except (TypeError, ValueError):
        return 3


def _flush_interval_s() -> float:
    try:
        ms = float(conf.get(
            "delta.tpu.router.calibration.flushIntervalMs", 2000))
    except (TypeError, ValueError):
        ms = 2000.0
    return max(ms, 0.0) / 1000.0


def state_path(log_path: Optional[str] = None) -> Optional[str]:
    """Where calibration state persists: the conf'd path wins; else the
    table's log dir (local paths only); else None (in-memory only)."""
    p = conf.get("delta.tpu.router.calibration.statePath")
    if p:
        return str(p)
    if log_path and "://" not in log_path:
        return os.path.join(log_path, STATE_FILE)
    return None


def load_state(path: str) -> Dict[str, Dict[str, float]]:
    """Parse a state file; unknown constants and malformed entries are
    dropped (an old file must never poison routing)."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        out: Dict[str, Dict[str, float]] = {}
        for name, ent in (raw.get("constants") or {}).items():
            if name not in link.CALIBRATABLE:
                continue
            value = float(ent["value"])
            samples = int(ent.get("samples", 1))
            if value > 0.0 and samples > 0:
                out[name] = {"value": value, "samples": samples}
        return out
    except (OSError, ValueError, TypeError, KeyError):
        return {}


def save_state(path: str, state: Dict[str, Dict[str, float]]) -> bool:
    """Atomic-enough JSON write (tmp + rename); best-effort — a read-only
    log dir downgrades persistence, never fails the operation."""
    import uuid

    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # uuid-suffixed like logstore.write_bytes: _persist runs outside
        # _LOCK, so concurrent savers must not share (and finally-unlink)
        # one tmp name out from under each other
        tmp = f"{path}.{uuid.uuid4().hex}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": _STATE_VERSION, "constants": state}, f,
                          indent=1, sort_keys=True)
            os.replace(tmp, path)
        finally:
            try:
                os.unlink(tmp)  # no-op after a successful replace
            except OSError:
                pass
        return True
    except OSError:
        return False


def _seed_locked(path: str) -> None:
    """Merge on-disk constants this process hasn't (or has less-well)
    sampled into ``_STATE`` — skipped entirely while the file's mtime is
    unchanged since our last load/save, so steady-state ingests pay one
    ``stat``, not a JSON parse. Callers hold ``_LOCK``."""
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return
    if _SYNC_MTIME.get(path) == mtime:
        return
    for name, ent in load_state(path).items():
        cur = _STATE.get(name)
        if cur is None or cur["samples"] < ent["samples"]:
            _STATE[name] = dict(ent)
    _SYNC_MTIME[path] = mtime


def _persist(path: str, state: Dict[str, Dict[str, float]]) -> None:
    """Write the state file and record the sync point (the IO runs outside
    ``_LOCK``; only the bookkeeping re-takes it)."""
    if not save_state(path, state):
        return
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        mtime = None
    with _LOCK:
        if mtime is not None:
            _SYNC_MTIME[path] = mtime
        _LAST_SAVE[path] = time.monotonic()
        _DIRTY.discard(path)


def _apply_locked() -> None:
    """Install every sufficiently-sampled constant as a link override and
    publish its gauge. Callers hold ``_LOCK``."""
    min_n = _min_samples()
    for name, ent in _STATE.items():
        if ent["samples"] >= min_n:
            try:
                link.set_calibrated(name, ent["value"])
            except ValueError:
                continue
            telemetry.set_gauge("router.calibration", ent["value"],
                                constant=name)


def apply_state(log_path: Optional[str] = None) -> Dict[str, Dict[str, float]]:
    """Load persisted state (merging constants this process hasn't sampled)
    and install the overrides — the fresh-process resume path. No-op unless
    :func:`enabled`."""
    if not enabled():
        return {}
    path = state_path(log_path)
    with _LOCK:
        if path is not None:
            _seed_locked(path)
        _apply_locked()
        state = {k: dict(v) for k, v in _STATE.items()}
        flush_dirty = path is not None and path in _DIRTY
    if flush_dirty:
        _persist(path, state)
    return state


def ingest(samples: Sequence[Tuple[str, float, float]],
           log_path: Optional[str] = None,
           flush: bool = True) -> Optional[Dict[str, Any]]:
    """Fold observed ``(constant_name, units, seconds)`` samples into the
    EWMA state, install matured overrides, and persist. Returns the updated
    state, or None when calibration is off / no sample was usable.
    ``flush=False`` (hot-path callers) defers the state-file write to the
    flush-interval throttle instead of paying it per call."""
    if not enabled() or not samples:
        return None
    alpha = _alpha()
    path = state_path(log_path)
    used = 0
    with _LOCK:
        if path is not None:
            # seed from disk first so a fresh process continues the fit
            _seed_locked(path)
        for name, units, seconds in samples:
            if name not in link.CALIBRATABLE:
                continue
            try:
                units = float(units)
                seconds = float(seconds)
            except (TypeError, ValueError):
                continue
            if units <= 0 or seconds <= 0:
                continue
            rate = seconds / units
            cur = _STATE.get(name)
            if cur is None:
                _STATE[name] = {"value": rate, "samples": 1}
            else:
                cur["value"] = alpha * rate + (1.0 - alpha) * cur["value"]
                cur["samples"] += 1
            used += 1
        if not used:
            return None
        _apply_locked()
        state = {k: dict(v) for k, v in _STATE.items()}
        last_save = _LAST_SAVE.get(path) if path is not None else None
        do_save = path is not None and (
            flush or last_save is None
            or time.monotonic() - last_save >= _flush_interval_s())
        if path is not None and not do_save:
            _DIRTY.add(path)
    telemetry.bump_counter("router.calibration.updates", used)
    if do_save:
        _persist(path, state)
    return state


def current_state() -> Dict[str, Dict[str, float]]:
    """The in-memory EWMA state (value + sample count per constant)."""
    with _LOCK:
        return {k: dict(v) for k, v in _STATE.items()}


def reset() -> None:
    """Drop in-memory state and the installed link overrides (tests).
    Persisted files are left alone — delete them to reset a deployment."""
    with _LOCK:
        _STATE.clear()
        _SYNC_MTIME.clear()
        _LAST_SAVE.clear()
        _DIRTY.clear()
    link.clear_calibrated()
