"""Sharded work-item executor — LPT assignment, work stealing, supervision.

The DCN partitioner (`parallel/distributed`) decides which *host* owns each
work item; this module is the per-host engine that actually runs a host's
items: scan decode groups, OPTIMIZE bin-pack rewrites, fused-MERGE probe
batches, checkpoint part writes. The reference delegates the same role to
Spark's task scheduler (TaskSchedulerImpl: per-executor queues + speculative
execution); ours is deliberately smaller:

* **deterministic LPT seed** — items are pre-assigned to worker deques by
  size-weighted LPT (`distributed.lpt_assign`), so the steady state does no
  coordination at all;
* **work stealing** — a worker whose deque drains steals the *tail* item of
  the worker with the most remaining bytes (the zipf hot-shard case: one
  deque inherits the head of the distribution and everyone else finishes
  early). Stealing is conf-gated (`delta.tpu.distributed.workStealing.enabled`)
  and counted (`dist.steals`);
* **measured, not asserted** — every item's wall clock is recorded
  (`dist.item.duration_ms`), and the report carries per-worker totals +
  the max/mean byte skew so benches and the MULTICHIP artifact can print
  per-shard timings instead of an "ok" string.

Supervision (fault tolerance — the MapReduce task re-execution model the
column-storage paper assumes of its runtime):

* **per-item retry** — a *transient* ``Exception`` from an item (classified
  by `utils/retries.is_transient` — the convention that transient errors
  fire before an operation's side effects land) retries in place under the
  shared :class:`~delta_tpu.utils.retries.RetryPolicy` read from the
  ``delta.tpu.distributed.retry.*`` confs: bounded attempts AND a total
  deadline. Permanent errors and ``BaseException``s (`SimulatedCrash` is a
  process death) are never retried.
* **poison quarantine** — ``on_failure="quarantine"`` turns an exhausted or
  permanent item failure into a :class:`QuarantinedItem` on the report
  (``dist.items.quarantined``; the failing attempt raised through its item
  span, so the flight recorder holds an incident with the trace id) and the
  job completes with a structured partial result — ``results[j] is None``
  for quarantined ``j`` and the caller decides (OPTIMIZE skips the group,
  MERGE's probe keeps the file). The default ``"raise"`` aborts like the
  pre-supervision executor — but always with finalized per-worker stats
  (the raised error carries the partial report as ``exc.shard_report``).
* **heartbeats + speculation** — each worker stamps a monotonic heartbeat
  when it starts an item; a ``delta-dist-supervisor`` thread marks items
  whose heartbeat age exceeds their *priced* timeout — ``max(``
  ``delta.tpu.distributed.itemTimeoutMs``, measured ms/byte × the item's
  LPT byte estimate × ``speculation.slackFactor)``, not a flat constant —
  and re-dispatches them to an idle worker (``dist.items.speculated``).
  First completion wins; the loser's result is discarded idempotently
  (``dist.speculation.wins`` counts rescues, and the loser's item span
  carries ``discarded=true`` so `analyze_trace` attributes the race).
* **degradation** — if the pool dies under it (worker-spawn faults, pool
  construction failure), the caller's thread finishes every unresolved item
  inline (``dist.degraded.pool``): a sharded job degrades to the sequential
  loop instead of stranding work.

Fault points (`storage/faults.fire`): ``dist.itemExec`` fires per attempt
inside the item span (so injected faults exercise retry/quarantine/crash
paths), ``dist.workerSpawn`` per pool worker at startup (a transient spawn
failure abandons the worker and the job survives on the rest),
``dist.heartbeat`` around heartbeat stamps and supervisor sweeps (a lost
stamp may cost a spurious speculation, never correctness).

Threads come from one pool named ``delta-dist-exec`` plus the
``delta-dist-supervisor`` watchdog (pool-naming lint). Results preserve
item order.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from delta_tpu.parallel.distributed import bytes_skew, lpt_assign, lpt_loads

__all__ = ["ShardReport", "WorkerStats", "QuarantinedItem", "run_sharded",
           "default_workers"]


@dataclass
class WorkerStats:
    items: int = 0
    bytes: int = 0
    busy_s: float = 0.0  # includes FAILED attempts' elapsed time
    stolen: int = 0  # items this worker STOLE from another deque


@dataclass
class QuarantinedItem:
    """One poison item the job completed *around*: its index, the final
    error, how many attempts the retry policy spent, and the trace id the
    flight-recorder incident (when configured) filed under."""

    index: int
    error: str
    attempts: int
    trace_id: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"index": self.index, "error": self.error,
                "attempts": self.attempts, "traceId": self.trace_id}


@dataclass
class ShardReport:
    """What a sharded job actually did — the bench / MULTICHIP evidence."""

    results: List[Any]
    wall_s: float
    workers: int
    steals: int
    skew: float  # max/mean per-worker bytes of the LPT seed assignment
    per_worker: Dict[int, WorkerStats] = field(default_factory=dict)
    retried: int = 0      # transient item attempts that were retried
    speculated: int = 0   # stuck items the supervisor re-dispatched
    rescued: int = 0      # speculative attempts that won the race
    degraded_inline: int = 0  # items finished inline after the pool died
    quarantined: List[QuarantinedItem] = field(default_factory=list)

    def quarantined_indices(self) -> set:
        return {q.index for q in self.quarantined}

    def timings(self) -> List[Dict[str, Any]]:
        """Per-shard timing rows for artifacts (sorted by worker id)."""
        return [
            {
                "worker": w,
                "items": s.items,
                "bytes": s.bytes,
                "busy_s": round(s.busy_s, 6),
                "stolen": s.stolen,
            }
            for w, s in sorted(self.per_worker.items())
        ]


def default_workers() -> int:
    """Worker count for sharded jobs: ``delta.tpu.distributed.workers``
    when set, else min(8, cpu count) — sized like the 8-way state mesh."""
    import os

    from delta_tpu.utils.config import conf

    w = conf.get("delta.tpu.distributed.workers", None)
    if w is not None:
        return max(int(w), 1)
    return max(min(8, os.cpu_count() or 1), 1)


def _retry_policy():
    """The shared item-retry policy from the distributed confs: bounded
    attempts AND a total per-item deadline (`utils/retries.RetryPolicy`)."""
    from delta_tpu.utils.config import conf
    from delta_tpu.utils.retries import RetryPolicy

    return RetryPolicy(
        max_attempts=max(conf.get_int(
            "delta.tpu.distributed.retry.maxAttempts", 3), 1),
        base_delay_s=conf.get_int(
            "delta.tpu.distributed.retry.baseDelayMs", 10) / 1000.0,
        max_delay_s=conf.get_int(
            "delta.tpu.distributed.retry.maxDelayMs", 200) / 1000.0,
        deadline_s=conf.get_int(
            "delta.tpu.distributed.retry.deadlineMs", 10_000) / 1000.0,
    )


class _JobState:
    """Shared mutable state of one pooled job: deques, claims, the
    speculation queue, and the first fatal error. Every mutation happens
    under ``cond``'s lock; completion/quarantine/speculation notify it so
    idle workers wake instead of polling."""

    def __init__(self, n: int, weights: Sequence[int],
                 deques: List[List[int]], stealing: bool,
                 per_worker: Dict[int, WorkerStats]):
        self.n = n
        self.weights = weights
        self.deques = deques
        self.remaining = [sum(weights[j] for j in b) for b in deques]
        self.stealing = stealing
        self.per_worker = per_worker
        self.cond = threading.Condition()
        self.results: List[Any] = [None] * n
        self.done = [False] * n
        self.quarantined: Dict[int, QuarantinedItem] = {}
        self.resolved = 0  # done + quarantined
        self.spec_queue: List[int] = []
        self.spec_marked: set = set()
        self.running: Dict[int, Tuple[int, float]] = {}  # worker -> (item, t0)
        self.stop = False
        self.fatal: List[BaseException] = []
        self.steals = 0
        self.retried = 0
        self.speculated = 0
        self.rescued = 0

    # -- scheduling -------------------------------------------------------

    def take(self, w: int):
        """Next item for worker ``w``: own deque head, else a speculative
        re-dispatch, else the tail of the most-loaded victim. Blocks while
        the job is unfinished but nothing is claimable (a sibling may still
        fail or get speculated); returns None when the job is over."""
        from delta_tpu.utils import telemetry

        with self.cond:
            while True:
                if self.stop or self.resolved >= self.n:
                    return None
                if self.deques[w]:
                    j = self.deques[w].pop(0)
                    self.remaining[w] -= self.weights[j]
                    return j, False, False
                while self.spec_queue:
                    j = self.spec_queue.pop(0)
                    if not self.done[j] and j not in self.quarantined:
                        return j, False, True
                if self.stealing:
                    # steal the tail of the most-loaded deque: the tail
                    # holds that worker's smallest seeded items, so the
                    # victim keeps the head it is already streaming through
                    victim = max(
                        (v for v in range(len(self.deques)) if self.deques[v]),
                        key=lambda v: (self.remaining[v], -v),
                        default=None,
                    )
                    if victim is not None:
                        j = self.deques[victim].pop()
                        self.remaining[victim] -= self.weights[j]
                        self.steals += 1
                        self.per_worker[w].stolen += 1
                        telemetry.bump_counter("dist.steals")
                        return j, True, False
                # job unfinished but nothing claimable: wait for a
                # completion, a speculation mark, or the stop flag (timeout
                # is belt-and-braces against a missed notify)
                self.cond.wait(0.05)

    def abandon_worker(self, w: int) -> None:
        """Worker ``w`` died at spawn: its seeded deque re-dispatches
        through the speculation queue so siblings (or the inline fallback)
        finish the items even with stealing disabled."""
        with self.cond:
            if self.deques[w]:
                self.spec_queue.extend(self.deques[w])
                self.deques[w] = []
                self.remaining[w] = 0
            self.running.pop(w, None)
            self.cond.notify_all()

    # -- outcomes ---------------------------------------------------------

    def commit(self, w: Optional[int], j: int, value: Any,
               speculative: bool) -> bool:
        """First-completion-wins: land ``value`` for item ``j`` unless a
        rival attempt already did. Returns whether this attempt won."""
        from delta_tpu.utils import telemetry

        with self.cond:
            if self.done[j] or j in self.quarantined:
                return False  # the loser's result is discarded idempotently
            self.done[j] = True
            self.results[j] = value
            self.resolved += 1
            if speculative:
                self.rescued += 1
                telemetry.bump_counter("dist.speculation.wins")
            self.cond.notify_all()
            return True

    def quarantine(self, j: int, exc: BaseException, attempts: int) -> None:
        from delta_tpu.utils import telemetry

        with self.cond:
            if self.done[j] or j in self.quarantined:
                return
            self.quarantined[j] = QuarantinedItem(
                index=j, error=f"{type(exc).__name__}: {exc}",
                attempts=attempts,
                trace_id=telemetry.current_trace_id() or "")
            self.resolved += 1
            telemetry.bump_counter("dist.items.quarantined")
            self.cond.notify_all()

    def record_fatal(self, exc: BaseException) -> None:
        with self.cond:
            if not self.fatal:
                self.fatal.append(exc)
            self.stop = True
            self.cond.notify_all()

    def unresolved(self) -> List[int]:
        with self.cond:
            return [j for j in range(self.n)
                    if not self.done[j] and j not in self.quarantined]


def run_sharded(
    items: Sequence,
    fn: Callable[[Any], Any],
    *,
    sizes: Optional[Sequence[int]] = None,
    workers: Optional[int] = None,
    label: str = "job",
    on_failure: str = "raise",
) -> ShardReport:
    """Run ``fn(item)`` for every item over a worker pool with LPT seeding,
    work stealing, and supervision; returns an order-preserving
    :class:`ShardReport`.

    ``sizes`` are per-item byte weights (defaults to uniform). ``workers``
    defaults to :func:`default_workers`; 1 worker runs inline with no pool,
    so the single-shard leg of a scaling bench measures the job, not the
    machinery (retry + quarantine still apply inline).

    ``on_failure`` decides what an item that exhausts its transient
    retries (or fails permanently) does to the job: ``"raise"`` aborts —
    after every worker drained and finalized its stats, with the partial
    report attached to the raised error as ``shard_report`` — while
    ``"quarantine"`` records the poison item on ``report.quarantined``
    (its ``results`` slot stays None) and the job completes. A
    ``BaseException`` that is not an ``Exception`` (e.g.
    :class:`~delta_tpu.storage.faults.SimulatedCrash` — a process death)
    always aborts: no recovery path may swallow a crash.

    The whole job runs inside a ``delta.dist.job`` span; each pool worker
    opens a ``delta.dist.worker`` span (adopting the job's span context —
    pool threads do not inherit contextvars) and each item attempt a
    ``delta.dist.item`` span carrying its index/bytes/stolen/attempt/
    speculative flags, so a distributed trace can attribute the makespan —
    and every retry, speculation race, and quarantine — to a specific
    shard and item (`obs/trace_store.analyze_trace`).
    """
    from delta_tpu.storage import faults
    from delta_tpu.utils import telemetry
    from delta_tpu.utils.config import conf
    from delta_tpu.utils.retries import is_transient

    if on_failure not in ("raise", "quarantine"):
        raise ValueError(f"on_failure must be 'raise' or 'quarantine', "
                         f"got {on_failure!r}")

    n = len(items)
    if workers is None:
        workers = default_workers()
    workers = max(1, min(int(workers), max(n, 1)))
    weights = [int(s or 0) for s in sizes] if sizes is not None else [1] * n
    policy = _retry_policy()
    # pin the fault plan ONCE at job start: a lazily spawned pool thread can
    # dequeue its worker task after the job already resolved (the main thread
    # returns at resolved == n without awaiting never-started tasks), and a
    # live conf read from that stale task would consume script entries from
    # whatever plan the NEXT job installed — cross-job fault leakage
    fault_plan = faults.plan_from_conf()
    telemetry.bump_counter("dist.jobs")
    telemetry.bump_counter("dist.items", n)

    with telemetry.record_operation(
        "delta.dist.job", {"items": n, "workers": workers}, job=label
    ) as job_ev:
        t0 = time.perf_counter()

        state = _JobState(
            n, weights,
            deques=[[] for _ in range(workers)],
            stealing=conf.get_bool(
                "delta.tpu.distributed.workStealing.enabled", True),
            per_worker={w: WorkerStats() for w in range(workers)})

        def _attempt_item(j: int, stolen: bool, speculative: bool,
                          stats: WorkerStats) -> Tuple[str, Any, int]:
            """One item to a terminal outcome: retry transient Exceptions
            under ``policy``, then return ``("ok", won, attempts)`` or
            ``("fail", exc, attempts)``. Fatal BaseExceptions propagate.
            Elapsed time lands on ``stats.busy_s`` even for failed
            attempts, so an abort never leaves torn timings."""
            attempt = 0
            started = time.monotonic()
            while True:
                it0 = time.perf_counter()
                try:
                    try:
                        with telemetry.record_operation(
                            "delta.dist.item",
                            {"index": j, "bytes": weights[j],
                             "stolen": stolen, "attempt": attempt,
                             "speculative": speculative},
                            job=label,
                        ) as item_ev:
                            faults.fire("dist.itemExec", f"{label}#{j}",
                                        plan=fault_plan)
                            value = fn(items[j])
                            won = state.commit(None, j, value, speculative)
                            if speculative or not won:
                                item_ev.data["discarded"] = not won
                    finally:
                        d = time.perf_counter() - it0
                        stats.busy_s += d
                except Exception as exc:  # noqa: BLE001 — classified below;
                    # SimulatedCrash is a BaseException and falls through
                    if not is_transient(exc) \
                            or policy.give_up(attempt, started):
                        return "fail", exc, attempt + 1
                    with state.cond:
                        state.retried += 1
                    telemetry.bump_counter("dist.items.retried")
                    time.sleep(policy.delay(attempt))
                    attempt += 1
                    continue
                if won:
                    stats.items += 1
                    stats.bytes += weights[j]
                    telemetry.observe("dist.item.duration_ms", d * 1e3,
                                      job=label)
                return "ok", won, attempt + 1

        def _settle_failure(j: int, exc: BaseException,
                            attempts: int) -> None:
            """Terminal item failure: quarantine or abort per the policy."""
            if on_failure == "quarantine":
                state.quarantine(j, exc, attempts)
            else:
                raise exc

        # ---- inline path: 1 worker or 1 item — no pool, no supervisor ----
        if workers <= 1 or n <= 1:
            job_ev.data.update(skew=1.0, lptBytes=[sum(weights)])
            stats = state.per_worker.setdefault(0, WorkerStats())
            for j in range(n):
                status, out, attempts = _attempt_item(
                    j, stolen=False, speculative=False, stats=stats)
                if status == "fail":
                    _settle_failure(j, out, attempts)
            report = ShardReport(
                results=state.results,
                wall_s=time.perf_counter() - t0,
                workers=1,
                steals=0,
                skew=1.0,
                per_worker=state.per_worker,
                retried=state.retried,
                quarantined=sorted(state.quarantined.values(),
                                   key=lambda q: q.index),
            )
            if report.quarantined:
                job_ev.data.update(quarantined=len(report.quarantined))
            return report

        # ---- pool path ---------------------------------------------------
        seed = lpt_assign(weights, workers)
        skew = bytes_skew(weights, seed)
        for w, bucket in enumerate(seed):
            state.deques[w] = list(bucket)
        state.remaining = [sum(weights[j] for j in b) for b in state.deques]
        # the per-worker LPT byte shares: what each shard SHOULD cost if
        # bytes predicted time perfectly — analyze_trace diffs the worker
        # spans' measured busy time against exactly these
        job_ev.data.update(
            skew=round(skew, 4), lptBytes=lpt_loads(weights, seed))
        carrier = telemetry.span_context()

        def _stamp_heartbeat(w: int, j: int) -> None:
            # dist.heartbeat fault point: a lost stamp leaves the previous
            # (already-done) entry in place — the supervisor skips done
            # items, so the worst outcome is one spurious speculation
            try:
                faults.fire("dist.heartbeat", f"{label}:{w}",
                            plan=fault_plan)
            except Exception:  # noqa: BLE001 — heartbeat loss is benign
                return
            with state.cond:
                state.running[w] = (j, time.monotonic())

        def _drive(w: int) -> None:
            stats = state.per_worker[w]
            while True:
                taken = state.take(w)
                if taken is None:
                    return
                j, stolen, speculative = taken
                _stamp_heartbeat(w, j)
                try:
                    status, out, attempts = _attempt_item(
                        j, stolen=stolen, speculative=speculative,
                        stats=stats)
                finally:
                    with state.cond:
                        state.running.pop(w, None)
                if status == "fail":
                    _settle_failure(j, out, attempts)

        def _worker(w: int) -> None:
            with telemetry.adopt_span_context(carrier), \
                    telemetry.record_operation(
                        "delta.dist.worker", job=label, worker=str(w)):
                try:
                    faults.fire("dist.workerSpawn", f"{label}:{w}",
                                plan=fault_plan)
                except Exception:  # noqa: BLE001 — transient spawn failure:
                    # this worker is lost, its deque re-dispatches and the
                    # job survives on the remaining workers (or inline)
                    state.abandon_worker(w)
                    return
                try:
                    _drive(w)
                except BaseException as exc:  # propagate the FIRST failure
                    # (re-raised on the caller thread below — including
                    # SimulatedCrash, which must pierce like process death)
                    state.record_fatal(exc)
                    return

        # supervisor: watch heartbeats, speculatively re-dispatch stragglers
        spec_enabled = conf.get_bool(
            "delta.tpu.distributed.speculation.enabled", True)
        floor_ms = conf.get_int("delta.tpu.distributed.itemTimeoutMs",
                                120_000)
        slack = float(conf.get("delta.tpu.distributed.speculation.slackFactor",
                               4.0) or 4.0)
        interval_s = max(conf.get_int(
            "delta.tpu.distributed.supervisor.intervalMs", 25), 1) / 1000.0
        done_evt = threading.Event()

        def _supervise() -> None:
            while not done_evt.wait(interval_s):
                try:
                    faults.fire("dist.heartbeat", f"{label}:supervisor",
                                plan=fault_plan)
                except Exception:  # noqa: BLE001 — a flapping probe skips
                    continue       # one sweep, never kills supervision
                now = time.monotonic()
                # measured throughput prices each item's timeout: bytes
                # predict time, the slack factor absorbs honest variance
                done_bytes = sum(s.bytes for s in state.per_worker.values())
                busy_s = sum(s.busy_s for s in state.per_worker.values())
                ms_per_byte = (busy_s * 1e3 / done_bytes) if done_bytes > 0 \
                    else None
                with state.cond:
                    for w, (j, hb) in list(state.running.items()):
                        if state.done[j] or j in state.quarantined \
                                or j in state.spec_marked:
                            continue
                        timeout_ms = float(floor_ms)
                        if ms_per_byte is not None:
                            timeout_ms = max(
                                timeout_ms,
                                slack * weights[j] * ms_per_byte)
                        if (now - hb) * 1e3 > timeout_ms:
                            state.spec_marked.add(j)
                            state.spec_queue.append(j)
                            state.speculated += 1
                            telemetry.bump_counter("dist.items.speculated")
                            state.cond.notify_all()

        supervisor = None
        if spec_enabled and floor_ms > 0:
            supervisor = threading.Thread(
                target=_supervise, name="delta-dist-supervisor", daemon=True)
            supervisor.start()

        degraded_inline = 0
        try:
            try:
                pool = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="delta-dist-exec")
            except Exception:  # noqa: BLE001 — pool machinery failure (not
                # an item failure: those land in state.fatal): degrade below
                pool = None
            if pool is not None:
                try:
                    futures = [pool.submit(_worker, w)
                               for w in range(workers)]
                    # wait for RESOLUTION, not thread exit: once every item
                    # is done/quarantined the job returns — a speculation
                    # race's loser thread may still be running its doomed
                    # attempt, and waiting for it would forfeit exactly the
                    # wall clock the rescue won (its late result is
                    # discarded idempotently by first-completion-wins)
                    with state.cond:
                        while state.resolved < n and not state.stop:
                            if all(f.done() for f in futures):
                                break  # every worker died: degrade below
                            state.cond.wait(0.05)
                    if state.fatal:
                        # abort path: drain in-flight siblings so every
                        # worker's stats are finalized before the re-raise
                        for f in futures:
                            f.result()
                    else:
                        # normal completion: join every worker that is NOT
                        # mid-item — post-resolution take() returns None, so
                        # they exit promptly. This makes worker spans and
                        # stats deterministic for observers and leaves no
                        # stale worker task behind the return. A worker
                        # still inside its fn is a speculation race's
                        # (possibly wedged) loser: waiting for it would
                        # forfeit exactly the wall clock the rescue won.
                        with state.cond:
                            busy = set(state.running)
                        for w, f in enumerate(futures):
                            if w in busy:
                                continue
                            try:
                                f.result(timeout=1.0)
                            except Exception:  # noqa: BLE001 — join is
                                pass  # best-effort; never fail a done job
                finally:
                    pool.shutdown(wait=False)
            # degradation rung: the pool died under the job (every worker
            # lost at spawn, or the executor itself failed) — finish the
            # unresolved items inline on the caller's thread
            if not state.fatal and state.resolved < n:
                telemetry.bump_counter("dist.degraded.pool")
                stats = state.per_worker[0]
                for j in state.unresolved():
                    degraded_inline += 1
                    status, out, attempts = _attempt_item(
                        j, stolen=False, speculative=False, stats=stats)
                    if status == "fail":
                        _settle_failure(j, out, attempts)
        finally:
            done_evt.set()
            if supervisor is not None:
                supervisor.join(timeout=5)

        report = ShardReport(
            results=state.results,
            wall_s=time.perf_counter() - t0,
            workers=workers,
            steals=state.steals,
            skew=skew,
            per_worker=state.per_worker,
            retried=state.retried,
            speculated=state.speculated,
            rescued=state.rescued,
            degraded_inline=degraded_inline,
            quarantined=sorted(state.quarantined.values(),
                               key=lambda q: q.index),
        )
        job_ev.data.update(
            steals=state.steals, wallMs=int(report.wall_s * 1e3),
            retried=state.retried, speculated=state.speculated,
            rescued=state.rescued, quarantined=len(report.quarantined))
        if state.fatal:
            # abort — but never with torn evidence: every worker drained
            # above, failed-attempt time is on busy_s, and the caller gets
            # the finalized partial report on the exception itself
            exc = state.fatal[0]
            try:
                exc.shard_report = report  # type: ignore[attr-defined]
            except Exception:  # noqa: BLE001 — slotted exceptions: raise bare
                pass
            raise exc
        return report
