"""Operator HTTP endpoint — stdlib-only, opt-in, daemon-threaded.

PR 2 left the telemetry registry pull-by-code; this serves it:

=============  ==============================================================
Route          Payload
=============  ==============================================================
``/metrics``   Prometheus text exposition (``telemetry.prometheus_text``)
``/healthz``   ``{"status": "ok", ...}`` liveness JSON
``/events``    ring-buffer events as JSON; ``?prefix=delta.commit`` filters
               by dotted-boundary op-type prefix, ``?limit=N`` tails
``/trace``     Chrome trace-event JSON of THIS process's ring (open spans
               included, clamped); ``?op=delta.commit`` filters by
               dotted-boundary op prefix, ``?limit=N`` keeps the newest N
               ring events — save and load at https://ui.perfetto.dev
``/traces``    distributed-trace index from the spool directory
               (``delta.tpu.trace.dir``): one row per stitched trace,
               newest first (``?limit=N``, default 20)
``/traces/<id>``  ONE stitched cross-process trace as Perfetto-loadable
               Chrome-trace JSON; ``?analyze=1`` serves the critical-path /
               straggler analysis instead
               (:func:`delta_tpu.obs.trace_store.analyze_trace`)
``/doctor``    ``?path=/data/tbl`` → the table-health report JSON
               (:func:`delta_tpu.obs.doctor.doctor`)
``/router``    router audit ledger: miss stats, installed calibration
               overrides, and the last N audit records (``?limit=N``,
               default 32) — see :mod:`delta_tpu.obs.router_audit`
``/advisor``   ``?path=/data/tbl`` → the workload-journal layout advisor
               report (:func:`delta_tpu.obs.advisor.advise`); ``?limit=N``
               restricts to the last N journal entries
``/autopilot`` maintenance-scheduler status (conf posture, guardrails,
               last run per table — :func:`delta_tpu.autopilot.status`);
               with ``?path=/data/tbl`` also the table's action ledger
               tail (``?limit=N``, default 32)
``/fleet``     table-registry status (:func:`delta_tpu.obs.fleet.
               fleet_status`) plus a ranked sweep: ``?sweep=doctor``
               (default) or ``advisor``, ``?limit=N`` tails the ranking;
               ``?series=<prefix>`` attaches the scraped time series
``/slo``       SLO monitor state (:func:`delta_tpu.obs.slo.status`):
               objectives, burn rates per window, firing + cleared alerts
``/replay``    ``?path=/data/tbl`` → the table's journaled shadow-run
               scorecards (``?limit=N``, default 8) with the latest one
               inlined — see :mod:`delta_tpu.replay.shadow`
=============  ==============================================================

Query parameters degrade, never 500: every numeric param goes through
:func:`_q_int`, so ``?limit=abc`` behaves like an absent param on EVERY
route (the pre-unification ``/events`` handler 500'd on it).

Nothing listens unless :func:`start_server` is called (port argument or
``delta.tpu.obs.port``); the server is a ``ThreadingHTTPServer`` on a daemon
thread bound to 127.0.0.1 by default — an operator surface, not a public
one. Zero dependencies beyond the standard library.
"""
from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["ObsServer", "start_server", "stop_server"]


def _q_int(q, name: str, default: Optional[int] = None) -> Optional[int]:
    """One parser for every numeric query param: absent OR malformed values
    degrade to ``default`` — an operator's typo'd ``?limit=abc`` must serve
    the route's default view, not a 500 (the rule /router and /advisor
    already followed, now shared by construction)."""
    vals = q.get(name)
    if not vals:
        return default
    try:
        return int(vals[0])
    except (TypeError, ValueError):
        return default


class _Handler(BaseHTTPRequestHandler):
    # the engine's logger, not stderr-per-request
    def log_message(self, fmt, *args):  # noqa: D401 — stdlib signature
        telemetry.logger.debug("obs.server %s", fmt % args)

    def _reply(self, status: int, body: bytes, content_type: str) -> None:
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # the client hung up mid-response: counting it is the whole
            # story — re-raising would send the broad do_GET handler off
            # to serve a 500 on the same dead socket and spam the logger
            telemetry.bump_counter("obs.server.clientAborts")
            self.close_connection = True

    def _json(self, payload, status: int = 200) -> None:
        body = json.dumps(payload, default=str).encode("utf-8")
        self._reply(status, body, "application/json; charset=utf-8")

    def do_GET(self) -> None:  # noqa: N802 — stdlib casing
        telemetry.bump_counter("obs.server.requests")
        parsed = urllib.parse.urlsplit(self.path)
        q = urllib.parse.parse_qs(parsed.query)
        try:
            route = parsed.path.rstrip("/") or "/"
            if route == "/metrics":
                self._reply(200, telemetry.prometheus_text().encode("utf-8"),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif route == "/healthz":
                from delta_tpu.exec.rowgroups import footer_cache_info

                self._json({"status": "ok",
                            "events": len(telemetry.recent_events()),
                            "footerCache": footer_cache_info()})
            elif route == "/events":
                prefix = q.get("prefix", [""])[0]
                events = telemetry.recent_events(prefix)
                limit = _q_int(q, "limit")
                if limit is not None:
                    n = max(limit, 0)
                    events = events[-n:] if n else []
                self._json([json.loads(e.to_json()) for e in events])
            elif route == "/trace":
                self._json(telemetry.export_chrome_trace(
                    op_prefix=q.get("op", [""])[0],
                    limit=_q_int(q, "limit")))
            elif route == "/traces" or route.startswith("/traces/"):
                from delta_tpu.obs import trace_store

                tdir = conf.get("delta.tpu.trace.dir")
                if not tdir:
                    self._json(
                        {"error": "delta.tpu.trace.dir is not set — "
                                  "no spool to collect from"}, 400)
                    return
                if route == "/traces":
                    self._json(trace_store.recent_traces(
                        str(tdir), limit=_q_int(q, "limit", 20)))
                    return
                trace_id = route[len("/traces/"):]
                if _q_int(q, "analyze", 0):
                    payload = trace_store.analyze_trace(str(tdir), trace_id)
                else:
                    payload = trace_store.stitch_trace(str(tdir), trace_id)
                if payload is None:
                    self._json(
                        {"error": f"no spooled spans for trace "
                                  f"{trace_id!r}"}, 404)
                    return
                self._json(payload)
            elif route == "/doctor":
                path = q.get("path", [None])[0]
                if not path:
                    self._json({"error": "missing ?path=<table path>"}, 400)
                    return
                from delta_tpu.obs.doctor import doctor

                self._json(doctor(path).to_dict())
            elif route == "/advisor":
                path = q.get("path", [None])[0]
                if not path:
                    self._json({"error": "missing ?path=<table path>"}, 400)
                    return
                limit = _q_int(q, "limit") or None
                from delta_tpu.obs.advisor import advise

                self._json(advise(path, limit=limit).to_dict())
            elif route == "/autopilot":
                from delta_tpu import autopilot as autopilot_mod
                from delta_tpu.obs import journal as journal_mod

                payload = autopilot_mod.status()
                path = q.get("path", [None])[0]
                if path:
                    limit = _q_int(q, "limit", 32)
                    log_path = path.rstrip("/") + "/_delta_log"
                    journal_mod.flush(log_path)
                    payload["ledger"] = journal_mod.read_entries(
                        log_path, kinds=["autopilot"], limit=limit)
                self._json(payload)
            elif route == "/router":
                from delta_tpu.obs import calibration, router_audit
                from delta_tpu.parallel import link

                limit = _q_int(q, "limit", 32)
                self._json({
                    "stats": router_audit.audit_stats(),
                    "calibration": {
                        "enabled": calibration.enabled(),
                        "constants": link.calibrated_constants(),
                        "state": calibration.current_state(),
                    },
                    "audits": router_audit.recent_audits(limit),
                })
            elif route == "/fleet":
                from delta_tpu.obs import fleet, timeseries

                payload = fleet.fleet_status()
                sweep = q.get("sweep", ["doctor"])[0]
                limit = _q_int(q, "limit")
                if sweep in ("doctor", "advisor"):
                    report = (fleet.fleet_doctor() if sweep == "doctor"
                              else fleet.fleet_advise())
                    ranked = report.to_dict()
                    if limit is not None and limit >= 0:
                        ranked["entries"] = ranked["entries"][:limit]
                    payload["sweep"] = ranked
                series_prefix = q.get("series", [None])[0]
                if series_prefix is not None:
                    payload["series"] = timeseries.series_snapshot(
                        series_prefix, limit=_q_int(q, "samples"))
                self._json(payload)
            elif route == "/slo":
                from delta_tpu.obs import slo

                self._json(slo.status())
            elif route == "/replay":
                path = q.get("path", [None])[0]
                if not path:
                    self._json({"error": "missing ?path=<table path>"}, 400)
                    return
                limit = _q_int(q, "limit", 8)
                from delta_tpu.obs import journal as journal_mod

                log_path = path.rstrip("/") + "/_delta_log"
                journal_mod.flush(log_path)
                cards = journal_mod.read_entries(
                    log_path, kinds=["shadow"], limit=limit)
                self._json({
                    "path": path,
                    "shadowRuns": cards,
                    "latest": (cards[-1].get("scorecard")
                               if cards else None),
                })
            else:
                self._json({"error": f"unknown route {route!r}",
                            "routes": ["/metrics", "/healthz", "/events",
                                       "/trace", "/traces", "/traces/<id>",
                                       "/doctor", "/router", "/advisor",
                                       "/autopilot", "/fleet", "/slo",
                                       "/replay"]}, 404)
        except Exception as e:  # noqa: BLE001 — a bad request must not kill the thread
            self._json({"error": f"{type(e).__name__}: {e}"}, 500)


class ObsServer:
    """Daemon-threaded HTTP server over the telemetry registry."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="delta-obs-server",
            daemon=True,
        )
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_SERVER: Optional[ObsServer] = None
_SERVER_LOCK = threading.Lock()


def start_server(port: Optional[int] = None, host: str = "127.0.0.1") -> ObsServer:
    """Start (or return) the process-wide endpoint. ``port=None`` reads
    ``delta.tpu.obs.port`` (0 = ephemeral); raises if neither names a port —
    the server is strictly opt-in. Installs the flight-recorder hook so a
    served process also records incidents when ``incidentDir`` is set."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            return _SERVER
        if port is None:
            port = conf.get("delta.tpu.obs.port")
        if port is None:
            raise ValueError(
                "no port: pass start_server(port=...) or set delta.tpu.obs.port"
            )
        from delta_tpu.obs import flight_recorder

        flight_recorder.install()
        _SERVER = ObsServer(int(port), host)
        return _SERVER


def stop_server() -> None:
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.stop()
            _SERVER = None
