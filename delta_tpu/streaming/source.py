"""Streaming source: initial snapshot + log tailing with admission control.

Mirrors `sources/DeltaSource.scala:57-539`:

* the first read serves the *initial snapshot* as indexed batches
  (`DeltaSourceSnapshot`, files sorted by (modificationTime, path));
* afterwards the source tails the log via `DeltaLog.getChanges`
  (`getFileChanges :183-209`);
* admission control caps a micro-batch by `maxFilesPerTrigger` (default
  1000) and/or `maxBytesPerTrigger` (`AdmissionLimits`);
* hygiene: a commit that removes or rewrites data upstream fails the stream
  unless `ignoreDeletes` (delete-only commits) or `ignoreChanges` (rewrites;
  re-emits updated files) is set (`verifyStreamHygieneAndFilterAddFiles
  :312-355`); metadata (schema) changes always fail the stream;
* `startingVersion` / `startingTimestamp` skip the initial snapshot.
"""
from __future__ import annotations

import itertools
import re
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

import pyarrow as pa

from delta_tpu.protocol.actions import (
    Action,
    AddCDCFile,
    AddFile,
    Metadata,
    Protocol,
    RemoveFile,
)
from delta_tpu.streaming.offset import DeltaSourceOffset
from delta_tpu.utils.errors import DeltaAnalysisError, DeltaIllegalStateError

__all__ = ["IndexedFile", "AdmissionLimits", "DeltaSource", "DeltaCDFSource"]

BASE_INDEX = -1  # offset index meaning "before any file of this version"
# index marking "this version fully consumed" — used when transitioning from
# the initial snapshot to the log tail without re-emitting version V's adds
VERSION_DONE_INDEX = 1 << 30


@dataclass(frozen=True)
class IndexedFile:
    """(version, index, add) — one admissible unit (`DeltaSource.scala:57-74`)."""

    version: int
    index: int
    add: Optional[AddFile]  # None for version sentinels
    is_last: bool = False


class AdmissionLimits:
    """Per-trigger caps (`DeltaSource.scala` AdmissionLimits)."""

    def __init__(self, max_files: Optional[int] = 1000, max_bytes: Optional[int] = None):
        self.files_left = max_files if max_files is not None else float("inf")
        self.bytes_left = max_bytes if max_bytes is not None else float("inf")
        self._admitted_any = False

    def admit(self, add: Optional[AddFile]) -> bool:
        if add is None:
            return True
        size = add.size or 0
        # always admit at least one file so the stream can't stall
        ok = (self.files_left >= 1 and self.bytes_left >= size) or not self._admitted_any
        if ok:
            self.files_left -= 1
            self.bytes_left -= size
            self._admitted_any = True
        return ok


class DeltaSource:
    def __init__(
        self,
        delta_log,
        max_files_per_trigger: Optional[int] = 1000,
        max_bytes_per_trigger: Optional[int] = None,
        ignore_deletes: bool = False,
        ignore_changes: bool = False,
        fail_on_data_loss: bool = True,
        exclude_regex: Optional[str] = None,
        starting_version: Optional[int] = None,
        starting_timestamp: Optional[str] = None,
        filters: Optional[Sequence] = None,
    ):
        self.delta_log = delta_log
        self.max_files = max_files_per_trigger
        self.max_bytes = max_bytes_per_trigger
        self.ignore_deletes = ignore_deletes
        self.ignore_changes = ignore_changes
        self.fail_on_data_loss = fail_on_data_loss
        self.exclude = re.compile(exclude_regex) if exclude_regex else None
        # pushed-down row filter: batches carry only matching rows. The
        # predicate rides into the Parquet decode (row-group skipping +
        # late materialization, exec/rowgroups) and re-applies exactly
        # post-decode. Offsets/admission are unaffected — a filter changes
        # what a batch CONTAINS, never where it ends. Row source only; the
        # CDF source ignores it (change rows are the product there).
        from delta_tpu.expr.parser import parse_predicate as _parse_pred

        self.filters = [
            _parse_pred(f) if isinstance(f, str) else f
            for f in (filters or [])
        ]
        if starting_version is not None and starting_timestamp is not None:
            raise DeltaAnalysisError(
                "Cannot set both startingVersion and startingTimestamp"
            )
        self.starting_version = starting_version
        self.starting_timestamp = starting_timestamp
        snap = delta_log.update()
        self.table_id = snap.metadata.id or ""
        self._initial_schema = snap.metadata.schema_string

    # -- file enumeration -------------------------------------------------

    def _resolve_starting_version(self) -> Optional[int]:
        if self.starting_version is not None:
            if self.starting_version == "latest":
                return self.delta_log.update().version + 1
            return int(self.starting_version)
        if self.starting_timestamp is not None:
            from delta_tpu.utils.timeparse import timestamp_option_to_ms

            return self.delta_log.history.get_active_commit_at_time(
                timestamp_option_to_ms(self.starting_timestamp),
                can_return_last_commit=True, can_return_earliest_commit=True,
            ).version
        return None

    def _initial_snapshot_files(self, version: int) -> List[IndexedFile]:
        """Initial table state as a deterministic indexed sequence
        (`files/DeltaSourceSnapshot.scala`)."""
        if version < 0:
            return []
        snap = self.delta_log.get_snapshot_at(version)
        files = sorted(
            snap.all_files, key=lambda f: (f.modification_time or 0, f.path)
        )
        out = [
            IndexedFile(version, i, f)
            for i, f in enumerate(files)
            if self.exclude is None or not self.exclude.search(f.path)
        ]
        if out:
            out[-1] = IndexedFile(
                out[-1].version, out[-1].index, out[-1].add, is_last=True
            )
        return out

    def _verify_schema_and_protocol(
        self, version: int, actions: Sequence[Action]
    ) -> None:
        """Schema-change + protocol checks — apply to EVERY streaming source
        (the CDF source waives the change/delete errors, never these)."""
        for a in actions:
            if isinstance(a, Metadata):
                if a.schema_string != self._initial_schema:
                    raise DeltaIllegalStateError(
                        f"Detected schema change at version {version}; streaming "
                        "sources don't support schema changes — restart the query"
                    )
            elif isinstance(a, Protocol):
                self.delta_log.assert_protocol_read(a)

    def _verify_hygiene(self, version: int, actions: Sequence[Action]) -> None:
        """`verifyStreamHygieneAndFilterAddFiles` (`DeltaSource.scala:312-355`)."""
        self._verify_schema_and_protocol(version, actions)
        removes = []
        adds_with_change = []
        for a in actions:
            if isinstance(a, RemoveFile) and a.data_change:
                removes.append(a)
            elif isinstance(a, AddFile) and a.data_change:
                adds_with_change.append(a)
        if removes and adds_with_change and not self.ignore_changes:
            raise DeltaIllegalStateError(
                f"Detected a data update at version {version} (e.g. "
                f"{removes[0].path}). This is currently not supported — set "
                "ignoreChanges to re-emit updated files, or restart from a "
                "fresh checkpoint"
            )
        if removes and not adds_with_change and not (
            self.ignore_deletes or self.ignore_changes
        ):
            raise DeltaIllegalStateError(
                f"Detected deleted data (e.g. {removes[0].path}) at version "
                f"{version}. This is currently not supported — set ignoreDeletes "
                "or use a snapshot-only read"
            )

    def _changes_from(self, version: int, start_index: int) -> Iterator[IndexedFile]:
        for v, actions in self.delta_log.get_changes(
            version, fail_on_data_loss=self.fail_on_data_loss
        ):
            self._verify_hygiene(v, actions)
            idx = 0
            adds = [
                a for a in actions
                if isinstance(a, AddFile) and a.data_change
                and (self.exclude is None or not self.exclude.search(a.path))
            ]
            for a in adds:
                f = IndexedFile(v, idx, a, is_last=(idx == len(adds) - 1))
                idx += 1
                if v == version and f.index <= start_index:
                    continue  # already consumed
                yield f
            if not adds and v > version:
                # version sentinel so the offset can advance past data-less
                # commits; v == version is already consumed (re-yielding it
                # would make latest_offset spin forever after e.g. OPTIMIZE)
                yield IndexedFile(v, BASE_INDEX, None, is_last=True)

    # -- offsets ----------------------------------------------------------

    def initial_offset(self) -> DeltaSourceOffset:
        sv = self._resolve_starting_version()
        if sv is not None:
            return DeltaSourceOffset(sv, BASE_INDEX, False, self.table_id)
        version = self.delta_log.update().version
        return DeltaSourceOffset(version, BASE_INDEX, True, self.table_id)

    def latest_offset(self, start: DeltaSourceOffset) -> Optional[DeltaSourceOffset]:
        """End offset for the next micro-batch under the admission limits;
        None when no new data.

        A batch never crosses the initial-snapshot boundary: while the start
        offset is still `isStartingVersion`, only snapshot files are
        admitted, so a crash-recovered `get_batch(None, end)` can always
        re-anchor deterministically at the snapshot version. Draining the
        snapshot emits one empty transition batch that flips the offset into
        tail mode."""
        limits = AdmissionLimits(self.max_files, self.max_bytes)
        last: Optional[IndexedFile] = None
        tail_has_data = False
        for f in self._pending(start):
            if start.is_starting_version and f.version != start.reservoir_version:
                tail_has_data = True
                break
            if not limits.admit(f.add):
                break
            last = f
        if last is None:
            if start.is_starting_version and tail_has_data:
                return DeltaSourceOffset(
                    start.reservoir_version, VERSION_DONE_INDEX, False, self.table_id
                )
            return None
        is_starting = start.is_starting_version and last.version == start.reservoir_version
        return DeltaSourceOffset(last.version, last.index, is_starting, self.table_id)

    def _pending(self, start: DeltaSourceOffset) -> Iterator[IndexedFile]:
        if start.is_starting_version:
            for f in self._initial_snapshot_files(start.reservoir_version):
                if f.index > start.index:
                    yield f
            yield from self._changes_from(start.reservoir_version + 1, BASE_INDEX)
        else:
            yield from self._changes_from(start.reservoir_version, start.index)

    def get_batch(
        self, start: Optional[DeltaSourceOffset], end: DeltaSourceOffset
    ) -> pa.Table:
        """Files in (start, end] decoded to one Arrow table.

        ``start=None`` (batch 0, possibly crash-recovered) anchors on the
        *planned end offset*, never on the table's current version — a
        recovered batch must serve exactly what was planned even if the
        table moved on."""
        from delta_tpu.exec.scan import read_files_as_table
        from delta_tpu.utils import telemetry

        if start is None:
            if end.is_starting_version:
                start = DeltaSourceOffset(
                    end.reservoir_version, BASE_INDEX, True, self.table_id
                )
            else:
                sv = self._resolve_starting_version()
                if sv is not None:
                    start = DeltaSourceOffset(sv, BASE_INDEX, False, self.table_id)
                else:
                    return self.get_batch(end, end)  # transition batch: empty
        from delta_tpu.utils.config import conf as _conf

        # StreamingQueryProgress parity: publish consumer-lag gauges so the
        # doctor and /metrics can see how far this source trails the table.
        # Counting the backlog walks the pending tail past the batch end —
        # skipped entirely under a telemetry blackout.
        track_lag = _conf.get_bool("delta.tpu.telemetry.enabled", True)
        with telemetry.record_operation(
            "delta.streaming.source.getBatch",
            {"endVersion": end.reservoir_version, "endIndex": end.index},
            path=self.delta_log.data_path,
        ) as bev:
            files: List[AddFile] = []
            backlog_files = 0
            backlog_bytes = 0
            pending = self._pending(start)
            overflow: Optional[IndexedFile] = None
            for f in pending:
                if (f.version, f.index) > (end.reservoir_version, end.index):
                    overflow = f
                    break
                if f.add is not None:
                    files.append(f.add)
            backlog_cap = int(_conf.get(
                "delta.tpu.obs.streamingBacklogMaxFiles", 1024) or 0)
            if track_lag and backlog_cap > 0:
                # walk the tail past the batch end for the backlog count —
                # bounded by the cap so a deeply lagging consumer never
                # re-reads its whole remaining log per batch (the count is a
                # floor at the cap). A hygiene failure BEYOND this batch
                # (e.g. an upstream delete two commits later) must not fail
                # THIS batch — it surfaces on the next latest_offset call.
                try:
                    for f in itertools.chain(
                        [overflow] if overflow is not None else [], pending
                    ):
                        if f.add is not None:
                            backlog_files += 1
                            backlog_bytes += f.add.size or 0
                            if backlog_files >= backlog_cap:
                                break
                except Exception:  # noqa: BLE001 — lag is best-effort
                    pass
            snap = self.delta_log.update()
            if track_lag:
                path = self.delta_log.data_path
                telemetry.set_gauge("streaming.source.backlogFiles",
                                    backlog_files, path=path)
                telemetry.set_gauge("streaming.source.backlogBytes",
                                    backlog_bytes, path=path)
                telemetry.set_gauge(
                    "streaming.source.lastBatchVersionLag",
                    max(0, snap.version - end.reservoir_version), path=path,
                )
                bev.data.update(backlogFiles=backlog_files,
                                backlogBytes=backlog_bytes)
            pred = None
            if self.filters:
                from delta_tpu.expr import ir
                from delta_tpu.schema.char_varchar import pad_char_literals

                pred = pad_char_literals(
                    ir.and_all(list(self.filters)), snap.metadata
                )
            table = read_files_as_table(
                self.delta_log.data_path, files, snap.metadata,
                predicate=pred,
            )
            if pred is not None and table.num_rows:
                from delta_tpu.expr.vectorized import filter_table

                table = filter_table(table, pred)
            bev.data.update(numFiles=len(files), numOutputRows=table.num_rows)
        if bev.duration_ms is not None:  # unmeasured (telemetry disabled)
            telemetry.observe(
                "delta.streaming.source.batch_ms", bev.duration_ms,
                path=self.delta_log.data_path,
            )
        return table


class DeltaCDFSource(DeltaSource):
    """Streaming source over the Change Data Feed.

    Batches carry change rows (``_change_type`` / ``_commit_version`` /
    ``_commit_timestamp``) instead of table rows — the streaming face of
    ``exec/cdf.py``. The initial snapshot is served as ``insert`` rows at
    the snapshot version; the tail is one unit per commit (``read_changes``
    resolves each commit's CDC files or reconstructs from file actions).
    Updates/deletes are the *point* of this source, so the base class's
    hygiene errors (`ignoreChanges`/`ignoreDeletes`) do not apply.
    """

    def _verify_hygiene(self, version: int, actions: Sequence[Action]) -> None:
        # changes are data here — but schema drift / protocol upgrades are
        # still fatal, exactly as on the row source
        self._verify_schema_and_protocol(version, actions)

    def _changes_from(self, version: int, start_index: int) -> Iterator[IndexedFile]:
        # one indexed unit per commit: index 0 carries the whole version.
        # The synthetic AddFile sizes the unit for admission control
        # (maxFilesPerTrigger = commits/trigger, maxBytesPerTrigger
        # approximated by the commit's changed bytes).
        for v, actions in self.delta_log.get_changes(
            version, fail_on_data_loss=self.fail_on_data_loss
        ):
            self._verify_hygiene(v, actions)
            if v == version and start_index >= 0:
                continue  # already consumed
            changed = sum(
                (a.size or 0) for a in actions
                if isinstance(a, (AddFile, AddCDCFile))
            )
            yield IndexedFile(
                v, 0, AddFile(path=f"__commit-{v}__", size=changed),
                is_last=True,
            )

    def get_batch(
        self, start: Optional[DeltaSourceOffset], end: DeltaSourceOffset
    ) -> pa.Table:
        from delta_tpu.exec import cdf as cdf_exec
        from delta_tpu.exec.scan import read_files_as_table
        from delta_tpu.utils import telemetry

        if start is None:
            if end.is_starting_version:
                start = DeltaSourceOffset(
                    end.reservoir_version, BASE_INDEX, True, self.table_id
                )
            else:
                sv = self._resolve_starting_version()
                if sv is not None:
                    start = DeltaSourceOffset(sv, BASE_INDEX, False, self.table_id)
                else:
                    return self.get_batch(end, end)
        with telemetry.record_operation(
            "delta.streaming.source.getBatch",
            {"endVersion": end.reservoir_version, "cdf": True},
            path=self.delta_log.data_path,
        ):
            return self._cdf_batch_impl(start, end, cdf_exec, read_files_as_table)

    def _cdf_batch_impl(self, start, end, cdf_exec, read_files_as_table) -> pa.Table:
        snap = self.delta_log.update()
        parts: List[pa.Table] = []
        if start.is_starting_version:
            files = [
                f.add
                for f in self._initial_snapshot_files(start.reservoir_version)
                if f.index > start.index
                and (f.version, f.index) <= (end.reservoir_version, end.index)
                and f.add is not None
            ]
            if files:
                t = read_files_as_table(self.delta_log.data_path, files, snap.metadata)
                t = t.append_column(
                    cdf_exec.CHANGE_TYPE_COL,
                    pa.array(["insert"] * t.num_rows, pa.string()),
                )
                t = t.append_column(
                    cdf_exec.COMMIT_VERSION_COL,
                    pa.array([start.reservoir_version] * t.num_rows, pa.int64()),
                )
                sv = start.reservoir_version
                snap_commits = self.delta_log.history.get_commits(sv, sv)
                snap_ts = snap_commits[0].timestamp if snap_commits else 0
                t = t.append_column(
                    cdf_exec.COMMIT_TIMESTAMP_COL,
                    pa.array([snap_ts] * t.num_rows, pa.int64()),
                )
                parts.append(t)
        # tail versions fully contained in (start, end]
        tail_first = (
            start.reservoir_version + 1
            if start.is_starting_version
            else (start.reservoir_version if start.index < 0
                  else start.reservoir_version + 1)
        )
        if not end.is_starting_version and end.reservoir_version >= tail_first:
            parts.append(
                cdf_exec.read_changes(
                    self.delta_log, tail_first, end.reservoir_version
                )
            )
        if not parts:
            return pa.schema(
                [pa.field(cdf_exec.CHANGE_TYPE_COL, pa.string()),
                 pa.field(cdf_exec.COMMIT_VERSION_COL, pa.int64()),
                 pa.field(cdf_exec.COMMIT_TIMESTAMP_COL, pa.int64())]
            ).empty_table()
        return pa.concat_tables(parts, promote_options="permissive")
