"""Metrics time series — the retention half of the observability plane.

``/metrics`` is a point-in-time snapshot: between two scrapes the registry's
history is gone, so nothing in-process can answer "what was commit p99 over
the last five minutes" — the exact question the SLO burn-rate monitors
(`obs/slo`) ask. This module retains it: a ``delta-obs-scraper`` daemon
snapshots the telemetry registry every ``delta.tpu.obs.scrape.intervalMs``
into bounded in-memory rings (``delta.tpu.obs.scrape.keep`` samples per
series, default 400 — at the 10s default interval the rings span ~67min,
comfortably past the SLO slow window):

* **counters** — the cumulative value per scrape (windowed rates are a
  subtraction, :func:`counter_window`);
* **gauges** — the value per scrape;
* **histograms** — the cumulative bucket counts per scrape, so a windowed
  quantile is the bucket-quantile of ``counts[now] - counts[window_start]``
  (:func:`quantile_window`, sharing ``telemetry.bucket_quantile``).

Window queries are Prometheus-shaped: a window needs two samples — the
baseline is the newest sample at or before ``now - window``, else the
OLDEST retained sample; with a single sample the window is empty. Deltas
therefore never reach before the first scrape: counters and histograms
that predate the scraper (all-time process history) contribute nothing,
and a ring that evicted history under-covers its window instead of
silently widening to all-time (which would let an hour-old incident keep
the "slow" burn hot forever, or fire ratio alerts off lifetime counts the
moment an operator starts the scraper).

Memory is strictly bounded: (series ⨯ keep) samples, each a small tuple;
rings resize in place when ``keep`` changes, and the series map itself is
capped at ``delta.tpu.obs.scrape.maxSeries`` — past it, the series whose
value went stale longest ago are evicted (under table churn the per-table
labeled series would otherwise accumulate for the life of the process). Everything is pull-by-call
except the daemon tick, and the whole module is blackout-inert: with
``delta.tpu.telemetry.enabled=false`` :func:`scrape_once` returns before
touching the registry — zero series entries, zero ring growth, zero SLO
evaluation.

Each scrape ends by driving the SLO monitors (``delta.tpu.obs.slo.enabled``)
so a served process needs exactly one daemon for the whole plane. Queryable
via ``GET /slo``/``/fleet`` (`obs/server`) and ``tools/fleet_dump.py``.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from delta_tpu.utils import telemetry
from delta_tpu.utils.config import conf

__all__ = ["Scraper", "start_scraper", "stop_scraper", "scrape_once",
           "scrape_count", "counter_window", "quantile_window",
           "histogram_labels", "series_snapshot", "reset"]

LabelKey = Tuple[str, Tuple[Tuple[str, str], ...]]

_LOCK = threading.Lock()
#: counter name -> ring of (ts_ms, cumulative value)
_COUNTERS: Dict[str, Deque[Tuple[int, float]]] = {}
#: (gauge name, labels) -> ring of (ts_ms, value)
_GAUGES: Dict[LabelKey, Deque[Tuple[int, float]]] = {}
#: (hist name, labels) -> ring of (ts_ms, bucket_counts, sum, count)
_HISTS: Dict[LabelKey, Deque[Tuple[int, Tuple[int, ...], float, int]]] = {}
_SCRAPES = 0
#: series key -> ts of the last scrape where its VALUE changed (the
#: eviction clock for the maxSeries cap); keys are ("c", name) /
#: ("g", label_key) / ("h", label_key)
_LAST_CHANGE: Dict[tuple, int] = {}
#: evicted series -> the comparator value they were evicted at. The
#: telemetry registry never forgets a series, so an evicted ring would be
#: recreated on the very next scrape; the tombstone (one number, not a
#: ring) keeps it out until its value MOVES again — a dead table's series
#: stays evicted, a quiet-but-live one comes back on its next change.
_EVICTED: Dict[tuple, float] = {}


def _keep() -> int:
    n = conf.get_int("delta.tpu.obs.scrape.keep", 400)
    return n if n > 0 else 400


def _max_series() -> int:
    n = conf.get_int("delta.tpu.obs.scrape.maxSeries", 8192)
    return n if n > 0 else 8192


def _ring(store, key, keep):
    """The ring for ``key`` at maxlen ``keep``; callers hold ``_LOCK``."""
    ring = store.get(key)
    if ring is None:
        ring = store[key] = deque(maxlen=keep)
    elif ring.maxlen != keep:
        ring = store[key] = deque(ring, maxlen=keep)
    return ring


def scrape_once(now_ms: Optional[int] = None,
                evaluate_slo: Optional[bool] = None) -> int:
    """Snapshot the whole telemetry registry into the rings; returns the
    number of series touched (0 under a telemetry blackout — the scrape
    does no registry work at all then). ``now_ms`` is injectable so tests
    can pin window math; ``evaluate_slo`` overrides the
    ``delta.tpu.obs.slo.enabled`` gate."""
    global _SCRAPES
    if not conf.get_bool("delta.tpu.telemetry.enabled", True):
        return 0
    now = int(now_ms if now_ms is not None else time.time() * 1000)
    keep = _keep()
    # registry reads copy under the telemetry lock — each snapshot is
    # internally consistent (never torn mid-bump)
    ctrs = telemetry.counters()
    gags = telemetry.gauges()
    hists = telemetry.histogram_rows()
    with _LOCK:
        for name, value in ctrs.items():
            if _EVICTED.get(("c", name)) == float(value):
                continue  # tombstoned and still not moving
            _EVICTED.pop(("c", name), None)
            ring = _ring(_COUNTERS, name, keep)
            if not ring or ring[-1][1] != float(value):
                _LAST_CHANGE[("c", name)] = now
            else:
                _LAST_CHANGE.setdefault(("c", name), now)
            ring.append((now, float(value)))
        for key, value in gags.items():
            if _EVICTED.get(("g", key)) == float(value):
                continue
            _EVICTED.pop(("g", key), None)
            ring = _ring(_GAUGES, key, keep)
            if not ring or ring[-1][1] != float(value):
                _LAST_CHANGE[("g", key)] = now
            else:
                _LAST_CHANGE.setdefault(("g", key), now)
            ring.append((now, float(value)))
        for name, labels, counts, total, count in hists:
            if _EVICTED.get(("h", (name, labels))) == float(count):
                continue
            _EVICTED.pop(("h", (name, labels)), None)
            ring = _ring(_HISTS, (name, labels), keep)
            if not ring or ring[-1][3] != int(count):
                _LAST_CHANGE[("h", (name, labels))] = now
            else:
                _LAST_CHANGE.setdefault(("h", (name, labels)), now)
            ring.append((now, tuple(counts), float(total), int(count)))
        _evict_stale_series_locked()
        _SCRAPES += 1
        touched = len(ctrs) + len(gags) + len(hists)
        series = len(_COUNTERS) + len(_GAUGES) + len(_HISTS)
    telemetry.bump_counter("obs.scrape.ticks")
    telemetry.set_gauge("obs.scrape.series", series)
    run_slo = (evaluate_slo if evaluate_slo is not None
               else conf.get_bool("delta.tpu.obs.slo.enabled", True))
    if run_slo:
        from delta_tpu.obs import slo

        slo.evaluate(now_ms=now)
    return touched


def _evict_stale_series_locked() -> None:
    """Cap the series map at ``maxSeries`` by dropping the series whose
    value went stale longest ago (dead tables' labeled series stop moving;
    live-but-quiet series outrank them only by recency, which is the best
    signal available without a registry of table lifetimes). Callers hold
    ``_LOCK``."""
    stores = {"c": _COUNTERS, "g": _GAUGES, "h": _HISTS}
    total = sum(len(s) for s in stores.values())
    cap = _max_series()
    if total <= cap:
        return
    by_staleness = sorted(
        _LAST_CHANGE.items(), key=lambda kv: kv[1])  # stalest first
    for (kind, key), _ts in by_staleness[:total - cap]:
        ring = stores[kind].pop(key, None)
        _LAST_CHANGE.pop((kind, key), None)
        if ring:
            # tombstone at the evicted value: the registry still holds the
            # series, so without this the ring is recreated next scrape
            last = ring[-1]
            _EVICTED[(kind, key)] = float(
                last[3] if kind == "h" else last[1])
    if len(_EVICTED) > 4 * cap:
        # the tombstone map must not become its own leak under extreme
        # churn; dropping the oldest costs one re-scrape+re-evict cycle
        for k in list(_EVICTED)[:len(_EVICTED) - 2 * cap]:
            _EVICTED.pop(k, None)


def scrape_count() -> int:
    with _LOCK:
        return _SCRAPES


# ---------------------------------------------------------------------------
# Window queries
# ---------------------------------------------------------------------------


def _window_ends(ring, window_ms: int, now_ms: int):
    """(baseline, latest) samples bracketing the trailing window: latest =
    newest sample, baseline = newest sample at or before ``now - window``,
    else the oldest retained sample. Windows never reach before the first
    scrape — cumulative values that predate the scraper are history, not
    signal (counting them from zero would page on all-time counts the
    moment the scraper starts). baseline None (single sample) = empty
    window."""
    latest = None
    baseline = None
    cutoff = now_ms - window_ms
    for sample in ring:  # rings are small (keep <= a few hundred)
        if sample[0] <= cutoff:
            baseline = sample
        if latest is None or sample[0] >= latest[0]:
            latest = sample
    if baseline is None and len(ring) > 1 and latest is not ring[0]:
        baseline = ring[0]
    if baseline is latest:
        baseline = None  # single usable sample: the window is empty
    return baseline, latest


def counter_window(name: str, window_ms: int,
                   now_ms: Optional[int] = None) -> Dict[str, float]:
    """Counter delta + per-second rate over the trailing window."""
    now = int(now_ms if now_ms is not None else time.time() * 1000)
    with _LOCK:
        ring = _COUNTERS.get(name)
        samples = list(ring) if ring else []
    if not samples:
        return {"delta": 0.0, "ratePerSec": 0.0, "samples": 0}
    baseline, latest = _window_ends(samples, window_ms, now)
    if baseline is None:  # single sample: no delta is computable yet
        return {"delta": 0.0, "ratePerSec": 0.0, "samples": len(samples)}
    delta = max(0.0, latest[1] - baseline[1])
    dt_s = max((latest[0] - baseline[0]) / 1000.0, 1e-9)
    return {"delta": delta, "ratePerSec": delta / dt_s,
            "samples": len(samples)}


def quantile_window(name: str, labels: Tuple[Tuple[str, str], ...],
                    q: float, window_ms: int,
                    now_ms: Optional[int] = None
                    ) -> Tuple[Optional[float], int]:
    """(approximate q-quantile, observation count) of a labeled histogram
    over the trailing window, from cumulative-bucket-count deltas. The
    quantile is None when the window holds no observations; a crossing
    past the last bucket bound reports twice the last bound (conservative
    — "worse than the histogram can resolve" must still compare > any
    threshold)."""
    now = int(now_ms if now_ms is not None else time.time() * 1000)
    with _LOCK:
        ring = _HISTS.get((name, labels))
        samples = list(ring) if ring else []
    if not samples:
        return None, 0
    baseline, latest = _window_ends(samples, window_ms, now)
    if baseline is None:  # single sample: no delta is computable yet
        return None, 0
    _ts, counts_l, _sum_l, count_l = latest
    _bt, counts_b, _sum_b, count_b = baseline
    dcounts = [a - b for a, b in zip(counts_l, counts_b)]
    dcount = count_l - count_b
    if dcount <= 0:
        return None, 0
    value = telemetry.bucket_quantile(dcounts, dcount, q)
    if value is None:  # +Inf bucket crossing
        value = telemetry.HISTOGRAM_BUCKETS[-1] * 2.0
    return value, dcount


def histogram_labels(name: str) -> List[Tuple[Tuple[str, str], ...]]:
    """Every label set the rings hold for histogram ``name``."""
    with _LOCK:
        return [lb for (n, lb) in _HISTS if n == name]


def series_snapshot(prefix: str = "",
                    limit: Optional[int] = None) -> Dict[str, Any]:
    """JSON-able dump of the rings (``/fleet``/``tools/fleet_dump``):
    counters and gauges as ``[[ts, value], ...]``, histograms as
    ``[[ts, count, sum], ...]`` (bucket vectors stay internal — window
    quantiles are served by :func:`quantile_window`). ``limit`` tails each
    series."""
    def _tail(seq):
        # limit <= 0 degrades to "no limit": seq[-(-5):] would DROP the
        # oldest samples while looking like a valid tail, and /fleet feeds
        # the user-controlled ?samples= straight here
        return seq[-limit:] if limit is not None and limit > 0 else seq

    with _LOCK:
        ctrs = {n: _tail([[t, v] for t, v in ring])
                for n, ring in sorted(_COUNTERS.items())
                if not prefix or telemetry._prefix_match(n, prefix)}
        gags = {f"{n}{telemetry._labels_suffix(lb)}":
                _tail([[t, v] for t, v in ring])
                for (n, lb), ring in sorted(_GAUGES.items())
                if not prefix or telemetry._prefix_match(n, prefix)}
        hists = {f"{n}{telemetry._labels_suffix(lb)}":
                 _tail([[t, c, round(s, 3)] for t, _b, s, c in ring])
                 for (n, lb), ring in sorted(_HISTS.items())
                 if not prefix or telemetry._prefix_match(n, prefix)}
        scrapes = _SCRAPES
    return {"scrapes": scrapes, "counters": ctrs, "gauges": gags,
            "histograms": hists}


# ---------------------------------------------------------------------------
# Daemon
# ---------------------------------------------------------------------------


class Scraper:
    """Daemon thread ticking :func:`scrape_once` every
    ``delta.tpu.obs.scrape.intervalMs``. Under a telemetry blackout the
    tick returns immediately — the thread does no registry work."""

    def __init__(self):
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Scraper":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="delta-obs-scraper")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)

    def tick(self) -> None:
        """Wake the daemon for an immediate scrape (tests, operators)."""
        self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                scrape_once()
            except Exception:  # noqa: BLE001 — a bad scrape must not kill
                # the daemon; the next tick retries with fresh state
                telemetry.logger.warning("obs scrape failed", exc_info=True)
            interval = conf.get_int("delta.tpu.obs.scrape.intervalMs", 10_000)
            if interval <= 0:
                interval = 10_000  # a zero/negative conf must not busy-spin
            self._wake.wait(timeout=interval / 1000.0)
            self._wake.clear()


_SCRAPER: Optional[Scraper] = None
_SCRAPER_LOCK = threading.Lock()


def start_scraper() -> Scraper:
    """Start (or return) the process-wide scraper daemon."""
    global _SCRAPER
    with _SCRAPER_LOCK:
        if _SCRAPER is None:
            _SCRAPER = Scraper()
        _SCRAPER.start()
        return _SCRAPER


def stop_scraper() -> None:
    global _SCRAPER
    with _SCRAPER_LOCK:
        if _SCRAPER is not None:
            _SCRAPER.stop()
            _SCRAPER = None


def reset() -> None:
    """Stop the daemon and drop every ring (tests / bench isolation)."""
    global _SCRAPES
    stop_scraper()
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _LAST_CHANGE.clear()
        _EVICTED.clear()
        _SCRAPES = 0
