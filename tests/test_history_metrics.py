"""DESCRIBE HISTORY operationMetrics content per operation
(≈ ``DescribeDeltaHistorySuite``, 911 LoC): each command surfaces its
whitelisted metrics in the commit's CommitInfo, readable through
``DeltaTable.history()``, with values that reconcile with what the
operation actually did.
"""
import pyarrow as pa
import pytest

from delta_tpu.api.tables import DeltaTable
from delta_tpu.commands.write import WriteIntoDelta


def make(tmp_table, n=10, **kw):
    return DeltaTable.create(
        tmp_table,
        data=pa.table({"id": pa.array(range(n), pa.int64()),
                       "v": pa.array([f"v{i}" for i in range(n)])}),
        **kw,
    )


def latest(t):
    h = t.history()[0]
    return h["operation"], h.get("operationMetrics") or {}


def test_write_metrics(tmp_table):
    t = make(tmp_table)
    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array([100], pa.int64()), "v": pa.array(["x"]),
    })).run()
    op, m = latest(t)
    assert op == "WRITE"
    assert int(m["numFiles"]) >= 1
    assert int(m["numOutputRows"]) == 1
    assert int(m["numOutputBytes"]) > 0


def test_delete_metrics_rewrite_path(tmp_table):
    t = make(tmp_table)
    t.delete("id < 3")
    op, m = latest(t)
    assert op == "DELETE"
    assert int(m["numDeletedRows"]) == 3
    assert int(m["numRemovedFiles"]) == 1
    assert int(m["numAddedFiles"]) >= 1


def test_update_metrics(tmp_table):
    t = make(tmp_table)
    t.update({"v": "'u'"}, "id >= 8")
    op, m = latest(t)
    assert op == "UPDATE"
    assert int(m["numUpdatedRows"]) == 2
    assert int(m["numRemovedFiles"]) == 1


def test_merge_metrics_full_set(tmp_table):
    t = make(tmp_table)
    src = pa.table({"id": pa.array([1, 2, 100, 101], pa.int64()),
                    "v": pa.array(["A", "B", "N1", "N2"])})
    (t.alias("t").merge(src, "t.id = s.id", source_alias="s")
     .when_matched_update_all().when_not_matched_insert_all().execute())
    op, m = latest(t)
    assert op == "MERGE"
    assert int(m["numSourceRows"]) == 4
    assert int(m["numTargetRowsUpdated"]) == 2
    assert int(m["numTargetRowsInserted"]) == 2
    assert int(m["numTargetRowsCopied"]) == 8
    assert int(m["numTargetFilesRemoved"]) == 1
    assert "scanTimeMs" in m and "rewriteTimeMs" in m


def test_optimize_and_reorg_metrics(tmp_table):
    t = make(tmp_table, configuration={"delta.tpu.enableDeletionVectors": "true"})
    WriteIntoDelta(t.delta_log, "append", pa.table({
        "id": pa.array([100], pa.int64()), "v": pa.array(["x"]),
    })).run()
    t.optimize().execute_compaction()
    op, m = latest(t)
    assert op == "OPTIMIZE"
    assert int(m["numRemovedFiles"]) == 2 and int(m["numAddedFiles"]) == 1
    t.delete("id = 1")
    t.optimize().execute_purge()
    op, m = latest(t)
    assert op == "REORG"
    assert int(m["numRemovedFiles"]) == 1


def test_streaming_update_metrics_and_op(tmp_table):
    from delta_tpu.streaming.sink import DeltaSink

    sink = DeltaSink(__import__("delta_tpu").DeltaLog.for_table(tmp_table),
                     query_id="q-hist")
    sink.add_batch(0, pa.table({"id": pa.array([1], pa.int64())}))
    t = DeltaTable.for_path(tmp_table)
    op, m = latest(t)
    assert op == "STREAMING UPDATE"


def test_history_entry_shape(tmp_table):
    """Each history row carries the reference's CommitInfo surface:
    version/timestamp/operation/operationParameters (+ metrics)."""
    t = make(tmp_table)
    t.delete("id = 0")
    h = t.history()[0]
    for key in ("version", "timestamp", "operation", "operationParameters"):
        assert key in h, key
    assert h["operationParameters"].get("predicate") is not None
    assert int(h["version"]) == 1


def test_metrics_only_whitelisted_keys(tmp_table):
    """operationMetrics honors the per-operation whitelist
    (`DeltaOperations.scala:344+`) — internal metrics never leak."""
    t = make(tmp_table)
    t.delete("id = 0")
    _, m = latest(t)
    allowed = {"numRemovedFiles", "numAddedFiles", "numDeletedRows",
               "scanTimeMs", "rewriteTimeMs", "executionTimeMs",
               "numCopiedRows", "numAddedChangeFiles"}
    assert set(m) <= allowed, set(m) - allowed


def test_history_metrics_survive_reload(tmp_table):
    from delta_tpu.log.deltalog import DeltaLog

    t = make(tmp_table)
    t.delete("id < 5")
    DeltaLog.clear_cache()
    t2 = DeltaTable.for_path(tmp_table)
    _, m = latest(t2)
    assert int(m["numDeletedRows"]) == 5


def test_ctas_metrics(tmp_table):
    t = make(tmp_table, n=4)
    op, m = latest(t)
    assert op == "CREATE TABLE AS SELECT"
    assert int(m["numFiles"]) >= 1
    assert int(m["numOutputRows"]) == 4


def test_metrics_enabled_false_gates_describe_history(tmp_table):
    """`delta.tpu.history.metricsEnabled=False` suppresses operationMetrics
    END TO END: commits made under the flag carry none in CommitInfo, so
    DESCRIBE HISTORY shows none — while commits made with it on still do."""
    from delta_tpu.commands.describe import describe_history
    from delta_tpu.utils.config import conf

    t = make(tmp_table)  # CTAS with metrics on
    with conf.set_temporarily(delta__tpu__history__metricsEnabled=False):
        t.delete("id < 3")
    rows = describe_history(t.delta_log)
    assert rows[0]["operation"] == "DELETE"
    assert not rows[0].get("operationMetrics")
    # the commit made before the flag flip keeps its metrics
    assert rows[1].get("operationMetrics")
